"""Goal kernels — the reference's goal catalog as vectorized cost functions.

Each reference goal (``analyzer/goals/*.java``) is re-expressed as four
vectorized functions over the :mod:`state` arrays instead of an imperative
``rebalanceForBroker`` loop (ref ``AbstractGoal.java:82-135``):

- ``violation(state, ctx)``      -> scalar residual (0 == satisfied), the
  analog of the goal's success criterion / ``ClusterModelStatsComparator``;
- ``propose(state, ctx, key)``   -> a batch of candidate actions the goal
  wants to try (replaces the sorted-replica candidate walks,
  ``maybeApplyBalancingAction`` ``AbstractGoal.java:230-272``);
- ``delta(state, ctx, cands)``   -> per-candidate change in the residual
  (negative = improvement), evaluated incrementally from the two touched
  broker rows;
- ``accepts(state, ctx, cands)`` -> per-candidate action acceptance when this
  goal was already optimized earlier in the chain (ref
  ``Goal.actionAcceptance`` ``goals/Goal.java:81``) — this is how the
  reference's "later goals must not violate earlier ones" lexicographic
  semantics survive batching.

Most goals are instances of one parametric :class:`IntervalGoal` — "keep a
per-broker metric inside [lower, upper]" — because that is what
Capacity/Distribution goals all are underneath; only rack-awareness and
topic-scoped distribution need bespoke kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.resources import Resource
from ..model.flat import MOVE_INTER_BROKER, MOVE_LEADERSHIP, MOVE_SWAP
from .constraint import BalancingConstraint, SearchConfig
from .state import (Candidates, SearchContext, SearchState, concat_candidates,
                    make_leadership_candidates, make_move_candidates,
                    make_swap_candidates, metric_deltas, metric_values,
                    METRIC_LEADER_COUNT, METRIC_LEADER_NW_IN,
                    METRIC_POTENTIAL_NW_OUT, METRIC_REPLICA_COUNT)

# Candidate priorities are composed as TIER + weight-in-[0,1) + noise. Tiers
# are small multiples of 4.0 so float32 keeps full precision for the weight
# and the 1e-3 tie-break noise (the previous 1e12 offsets had ulp ~1.3e5 and
# silently erased both, collapsing top_k to flat index order).
_TIER_ASSIST = 0.0    # below-average source helping fill a deficit
_TIER_EXCESS = 4.0    # source broker above its upper bound
_TIER_OFFLINE = 8.0   # offline replica: must move (self-healing)
_NEG = -jnp.inf


def _noise(key, shape, scale):
    return scale * jax.random.uniform(key, shape)


def _norm01(x: jax.Array, mask: jax.Array | None = None) -> jax.Array:
    """Scale finite (optionally masked-in) values into [0, 0.99]; everything
    else maps to 0. Keeps weights strictly inside one tier step."""
    if mask is not None:
        x = jnp.where(mask, x, jnp.nan)
    finite = jnp.isfinite(x)
    xmin = jnp.min(jnp.where(finite, x, jnp.inf))
    xmax = jnp.max(jnp.where(finite, x, -jnp.inf))
    span = jnp.maximum(xmax - xmin, 1e-12)
    return jnp.where(finite, (x - xmin) / span * 0.99, 0.0)


def _segment_cum_before(weights: jax.Array, keys: jax.Array,
                        num_segments: int) -> jax.Array:
    """Per-element cumulative weight of EARLIER same-key elements, for
    key-sorted inputs — the "take while cumulative-before < limit" basis
    shared by every bulk-drain quota pass."""
    cum = jnp.cumsum(weights)
    per_key = jax.ops.segment_sum(weights, keys, num_segments=num_segments)
    offset = jnp.cumsum(per_key) - per_key
    return cum - weights - offset[keys]


def _capacity_budget_cap(budget: jax.Array, per_unit_max: jax.Array,
                         constraint: BalancingConstraint,
                         broker_capacity: jax.Array,
                         util: jax.Array) -> jax.Array:
    """Cap per-broker intake budgets (metric units) by every resource's
    capacity headroom divided by the batch-MAX per-unit load — any subset
    with metric weight W then provably carries <= W * per_unit_max[res],
    so one bulk round cannot collectively exceed a capacity hard-goal."""
    for res in range(4):
        headroom = (constraint.capacity_threshold[res]
                    * broker_capacity[:, res] - util[:, res])
        cap_units = jnp.maximum(headroom, 0.0) / jnp.maximum(
            per_unit_max[res], 1e-9)
        budget = jnp.minimum(budget, 0.9 * cap_units)
    return jnp.maximum(budget, 0.0)


def _legal_dest_argmax(state: SearchState, ctx: SearchContext,
                       p: jax.Array, score: jax.Array):
    """(dst[K], ok[K]) — per-candidate best destination from a [K, B1] score,
    masking barred destinations and brokers already hosting the partition
    (the shared idiom behind flow-fallback re-routing and topic-aware
    destination picking)."""
    K, B1 = score.shape
    row = state.rb[p]                                            # [K, R]
    host_mask = jnp.zeros((K, B1), bool).at[
        jnp.arange(K)[:, None], row].set(True, mode="drop")
    masked = jnp.where(host_mask | ~ctx.dest_allowed[None, :], -jnp.inf,
                       score)
    dst = jnp.argmax(masked, axis=1).astype(jnp.int32)
    ok = jnp.isfinite(jnp.max(masked, axis=1))
    return dst, ok


def _top_replica_dest_grid(state: SearchState, ctx: SearchContext, key,
                           cfg: SearchConfig, replica_priority: jax.Array,
                           dest_priority: jax.Array) -> Candidates:
    """Shared candidate generator: top-K replicas x top-D destinations.

    ``replica_priority`` is [P, R] with -inf for non-candidates;
    ``dest_priority`` is [B1] with -inf for barred destinations. Offline
    replicas always float to the top (self-healing must-move semantics, ref
    ``Replica.isCurrentOffline`` handling in every goal's
    ``brokersToBalance``).
    """
    P, R = replica_priority.shape
    K = min(cfg.num_replica_candidates, P * R)
    D = min(cfg.num_dest_candidates, dest_priority.shape[0])
    krep, kdst = jax.random.split(key)

    rp = jnp.where(ctx.movable, replica_priority, _NEG)
    # Offline replicas outrank every goal-specific priority, even when the
    # goal itself would not have short-listed them (self-healing must-move)
    # or the topic is excluded from rebalancing.
    rp = jnp.where(state.offline,
                   _TIER_OFFLINE + jnp.clip(jnp.where(jnp.isfinite(rp), rp,
                                                      0.0), 0.0, 1.0), rp)
    # Priorities are small tier offsets plus [0, 1) weights; noise_scale-sized
    # noise breaks ties within a tier without reordering the weights.
    rp = rp + jnp.where(jnp.isfinite(rp),
                        _noise(krep, rp.shape, cfg.noise_scale), 0.0)
    rvals, ridx = jax.lax.top_k(rp.reshape(-1), K)
    p, r = ridx // R, ridx % R

    dp = jnp.where(ctx.dest_allowed, dest_priority, _NEG)
    dp = dp + jnp.where(jnp.isfinite(dp),
                        _noise(kdst, dp.shape, cfg.noise_scale), 0.0)
    dvals, didx = jax.lax.top_k(dp, D)

    pg = jnp.repeat(p, D)
    rg = jnp.repeat(r, D)
    dg = jnp.tile(didx, K)
    valid = jnp.repeat(jnp.isfinite(rvals), D) & jnp.tile(jnp.isfinite(dvals), K)
    return make_move_candidates(state, ctx, pg, rg, dg.astype(jnp.int32), valid)


def _top_leadership(state: SearchState, ctx: SearchContext, key,
                    cfg: SearchConfig, priority: jax.Array) -> Candidates:
    """Top-K leadership-transfer candidates from a [P, R] priority grid
    (slot r>0 becoming leader)."""
    P, R = priority.shape
    K = min(cfg.num_replica_candidates, P * R)
    slot_ok = (jnp.arange(R)[None, :] > 0) & ctx.leadership_movable[:, None]
    pr = jnp.where(slot_ok, priority, _NEG)
    pr = pr + jnp.where(jnp.isfinite(pr),
                        _noise(key, pr.shape, cfg.noise_scale), 0.0)
    vals, idx = jax.lax.top_k(pr.reshape(-1), K)
    p, r = idx // R, idx % R
    return make_leadership_candidates(state, ctx, p, r, jnp.isfinite(vals))


class GoalKernel:
    """Base goal. Subclasses are stateless; all data flows through args."""

    name: str = "goal"
    hard: bool = False
    uses_topic_counts: bool = False
    uses_topic_leader_counts: bool = False
    #: goals that implement ``bulk_drain`` (the engine's vectorized
    #: excess-shedding prologue) set this True
    supports_bulk_drain: bool = False

    def violation(self, state: SearchState, ctx: SearchContext) -> jax.Array:
        raise NotImplementedError

    def violation_scale(self, state: SearchState,
                        ctx: SearchContext) -> jax.Array:
        """Magnitude the violation's float32 rounding error scales with —
        the total absolute value the penalty sums reduce over. Count-based
        goals return 0: integer arithmetic is exact in float32 well past
        any real cluster size, so their residuals deserve a zero-tolerance
        cutoff. ``GoalResult.satisfied`` turns this into a ulp-aware
        epsilon (a broker landing exactly on a capacity limit must not
        read as VIOLATED by one float32 ulp of a 10^12-byte sum)."""
        return jnp.asarray(0.0)

    def propose(self, state: SearchState, ctx: SearchContext, key,
                cfg: SearchConfig) -> Candidates:
        raise NotImplementedError

    def delta(self, state: SearchState, ctx: SearchContext,
              c: Candidates) -> jax.Array:
        raise NotImplementedError

    def accepts(self, state: SearchState, ctx: SearchContext,
                c: Candidates) -> jax.Array:
        raise NotImplementedError

    def receptive_dest(self, state: SearchState,
                       ctx: SearchContext) -> jax.Array:
        """bool[B1] — brokers that can receive a replica without this
        (previously-optimized) goal likely rejecting the action. A candidate
        *steering* hint for later goals' destination matching; actual
        acceptance is still enforced per candidate. Default: everywhere."""
        return jnp.ones(ctx.broker_alive.shape, bool)

    def collective_guard(self, state: SearchState, ctx: SearchContext,
                         c: Candidates, earlier: jax.Array
                         ) -> jax.Array | None:
        """ok[N] — whether each candidate keeps this goal's bounds when
        applied *together with* every earlier candidate flagged in
        ``earlier`` ([N, N] bool, row i = candidates ranked before i that are
        slated to apply this round).

        This is what lets the engine bulk-apply candidates that share a
        source/destination broker: per-candidate ``accepts``/``delta`` are
        evaluated against the round-start state, so a crowd of individually
        fine actions can collectively overshoot a bound. The guard re-checks
        the bound with the *net* metric flow of earlier candidates included
        (exact prefix accounting, not a heuristic).

        Returning ``None`` opts out: the engine then falls back to treating
        shared-broker pairs as conflicts (at most one candidate per
        source/destination broker per round) — correct but serializing.
        """
        return None

    def bind(self, metadata) -> "GoalKernel":
        """Return the kernel configured against this optimization's
        metadata (topic names, broker sets). Pattern-configured goals
        (MinTopicLeadersPerBroker, BrokerSetAware) resolve their name-level
        config into index-space masks here; everything else returns self.
        """
        return self

    def bind_signature(self):
        """Hashable token describing the bound configuration — part of the
        compiled-chain cache key, so a topic-set change recompiles while
        ordinary re-optimizations reuse the cached chain."""
        return None

    def __repr__(self) -> str:  # pragma: no cover
        return f"<{type(self).__name__} {self.name}>"


def _net_broker_flow(c: Candidates, earlier: jax.Array,
                     d_src: jax.Array, d_dst: jax.Array):
    """(net_src_lo[N], net_dst_hi[N]) — pessimistic bounds on the metric
    change each candidate's source / destination broker accrues from earlier
    candidates in its round group.

    Pessimistic means one-sided: the destination estimate counts only
    *positive* earlier contributions (inflows) and the source estimate only
    *negative* ones (outflows). The set of earlier candidates that actually
    applies is a subset of ``earlier`` (some get guarded out themselves), and
    dropping a candidate can only lower real inflow / raise real outflow —
    so upper-bound checks against ``net_dst_hi`` and lower-bound checks
    against ``net_src_lo`` stay sound under ANY applied subset. Candidates
    that needed an earlier drain to make room are merely deferred a round.

    One [N, N] mask matmul per broker-role pair; N is a few hundred, so this
    rides the MXU for free.
    """
    e = earlier.astype(d_src.dtype)
    same_dd = e * (c.dst[:, None] == c.dst[None, :])
    same_ds = e * (c.dst[:, None] == c.src[None, :])
    same_sd = e * (c.src[:, None] == c.dst[None, :])
    same_ss = e * (c.src[:, None] == c.src[None, :])
    pos = lambda x: jnp.maximum(x, 0.0)
    neg = lambda x: jnp.minimum(x, 0.0)
    net_dst_hi = same_dd @ pos(d_dst) + same_ds @ pos(d_src)
    net_src_lo = same_ss @ neg(d_src) + same_sd @ neg(d_dst)
    return net_src_lo, net_dst_hi


def _net_src_hi(c: Candidates, earlier: jax.Array,
                d_src: jax.Array, d_dst: jax.Array) -> jax.Array:
    """Positive-only earlier inflow on each candidate's *source* broker —
    needed by hard caps because a swap can carry net load INTO its source
    (d_src > 0 when the incoming replica is heavier on this metric)."""
    e = earlier.astype(d_src.dtype)
    same_ss = e * (c.src[:, None] == c.src[None, :])
    same_sd = e * (c.src[:, None] == c.dst[None, :])
    pos = lambda x: jnp.maximum(x, 0.0)
    return same_ss @ pos(d_src) + same_sd @ pos(d_dst)


class IntervalGoal(GoalKernel):
    """Keep ``metric[b]`` within [lower, upper] on every alive broker.

    Parametrization covers (ref classes in analyzer/goals/):
    - CapacityGoal family: upper = capacity * threshold, no lower bound
      (``CapacityGoal.java``);
    - ResourceDistributionGoal family: upper/lower = avg * (t)/(2 - t)
      (``ResourceDistributionGoal.java:55``);
    - Replica/LeaderReplica count distribution, PotentialNwOut,
      LeaderBytesIn — same shape, different metric/bounds.
    """

    #: 'replica' | 'leadership' | 'both'
    actions: str = "replica"
    #: when True the goal only caps the upper side (capacity-style)
    upper_only: bool = False

    def __init__(self, name: str, metric, *, hard: bool,
                 constraint: BalancingConstraint):
        self.name = name
        self.metric = metric
        self.hard = hard
        self.constraint = constraint

    # -- bounds ----------------------------------------------------------
    def bounds(self, state: SearchState, ctx: SearchContext):
        """Return (lower[B1], upper[B1]) arrays (broadcast scalars ok)."""
        raise NotImplementedError

    def _avg_bounds(self, state: SearchState, ctx: SearchContext, t: float,
                    *, integer: bool = False, upper_only: bool = False):
        """avg-over-alive-brokers bounds: [avg*(2-t), avg*t].

        The total includes load still parked on dead brokers — it has to land
        on the alive ones, so the steady-state average accounts for it. With
        ``integer`` the band is widened to at least +-1 unit around the
        average so integer-count goals stay satisfiable on tiny clusters.
        """
        values = metric_values(state, self.metric)
        total = jnp.where(ctx.broker_valid, values, 0.0).sum()
        n = jnp.maximum(ctx.broker_alive.sum(), 1)
        avg = total / n
        upper = avg * t
        lower = (jnp.full_like(avg, -jnp.inf) if upper_only
                 else avg * (2.0 - t))
        if integer:
            upper = jnp.maximum(upper, jnp.ceil(avg))
            if not upper_only:
                lower = jnp.minimum(lower, jnp.floor(avg))
        return lower, upper

    # -- shared machinery ------------------------------------------------
    def _penalty(self, values, lower, upper, alive):
        over = jnp.maximum(values - upper, 0.0)
        under = 0.0 if self.upper_only else jnp.maximum(lower - values, 0.0)
        return jnp.where(alive, over + under, 0.0)

    def violation(self, state, ctx):
        values = metric_values(state, self.metric)
        lower, upper = self.bounds(state, ctx)
        return self._penalty(values, lower, upper, ctx.broker_alive).sum()

    def violation_scale(self, state, ctx):
        which, _res = self.metric
        if which in ("count", "leaders"):
            return jnp.asarray(0.0)     # integer metrics: exact in f32
        values = metric_values(state, self.metric)
        return jnp.where(ctx.broker_valid, jnp.abs(values), 0.0).sum()

    def delta(self, state, ctx, c):
        values = metric_values(state, self.metric)
        lower, upper = self.bounds(state, ctx)
        lo = jnp.broadcast_to(lower, values.shape)
        up = jnp.broadcast_to(upper, values.shape)
        d_src, d_dst = metric_deltas(c, self.metric)
        before = (self._penalty(values[c.src], lo[c.src], up[c.src],
                                ctx.broker_alive[c.src])
                  + self._penalty(values[c.dst], lo[c.dst], up[c.dst],
                                  ctx.broker_alive[c.dst]))
        after = (self._penalty(values[c.src] + d_src, lo[c.src], up[c.src],
                               ctx.broker_alive[c.src])
                 + self._penalty(values[c.dst] + d_dst, lo[c.dst], up[c.dst],
                                 ctx.broker_alive[c.dst]))
        return after - before

    def accepts(self, state, ctx, c):
        """Acceptance when previously optimized: destination must stay within
        the upper limit, or at least remain no more loaded than the source
        ends up (mirrors ResourceDistributionGoal.actionAcceptance's
        no-new-violation rule); symmetrically the source must not sink below
        the lower limit unless it stays above the destination."""
        values = metric_values(state, self.metric)
        lower, upper = self.bounds(state, ctx)
        lo = jnp.broadcast_to(lower, values.shape)
        up = jnp.broadcast_to(upper, values.shape)
        d_src, d_dst = metric_deltas(c, self.metric)
        src_after = values[c.src] + d_src
        dst_after = values[c.dst] + d_dst
        # Metric-neutral actions (d == 0, e.g. a leadership transfer judged by
        # a replica-count goal) are always acceptable: they cannot worsen the
        # goal even when a broker already violates a bound.
        dst_ok = ((d_dst <= 0) | (dst_after <= up[c.dst])
                  | (dst_after <= src_after))
        if self.upper_only:
            src_ok = True
        else:
            src_ok = ((d_src >= 0) | (src_after >= lo[c.src])
                      | (src_after >= dst_after))
        return dst_ok & src_ok

    def collective_guard(self, state, ctx, c, earlier):
        values = metric_values(state, self.metric)
        lower, upper = self.bounds(state, ctx)
        lo = jnp.broadcast_to(lower, values.shape)
        up = jnp.broadcast_to(upper, values.shape)
        d_src, d_dst = metric_deltas(c, self.metric)
        net_src_lo, net_dst_hi = _net_broker_flow(c, earlier, d_src, d_dst)
        src_after = values[c.src] + net_src_lo + d_src   # lowest it can land
        dst_after = values[c.dst] + net_dst_hi + d_dst   # highest it can land
        # Same escape clauses as accepts(): a net-non-increasing destination
        # is always fine, and an already-violating pair may proceed as long
        # as the destination stays at or below where the source lands.
        dst_ok = ((net_dst_hi + d_dst <= 0) | (dst_after <= up[c.dst])
                  | (dst_after <= src_after))
        if self.upper_only:
            src_ok = True
        else:
            src_ok = ((net_src_lo + d_src >= 0) | (src_after >= lo[c.src])
                      | (src_after >= dst_after))
        return dst_ok & src_ok

    def receptive_dest(self, state, ctx):
        values = metric_values(state, self.metric)
        _, upper = self.bounds(state, ctx)
        up = jnp.broadcast_to(jnp.asarray(upper, values.dtype), values.shape)
        # Integer-count metrics need a whole unit of headroom; continuous
        # metrics just need to be strictly below the ceiling.
        if self.metric[0] in ("count", "leaders"):
            return values + 1.0 <= up
        return values < up

    # -- bulk drain ------------------------------------------------------
    @property
    def supports_bulk_drain(self) -> bool:
        # Replica-move goals over additive per-replica metrics: shedding is
        # a pure assignment problem the prefix-sum fill solves exactly.
        # Purely leader-scoped metrics drain via bulk leadership transfers
        # instead — count/disk-neutral, so converged earlier goals cannot
        # veto them. "util"-metric goals with actions="both" (NW_OUT, CPU)
        # deliberately stay on the fine loop: BOTH drain variants measured
        # slower at 10Kx1M — the replica-move drain skews the placement
        # later polish must restore, and the leadership-only drain
        # (placement-neutral, tried round 4) overshoots leadership
        # balance so badly the fine loop doubles its iterations (38 -> 78,
        # warm 56 s -> 86 s) unwinding it. The swap-heavy fine tail wins.
        if self.actions == "replica" and self.metric[0] in ("count", "util"):
            return True
        return (self.actions in ("both", "leadership")
                and self.metric[0] in ("leaders", "leader_nw_in"))

    def _replica_drain_weight(self, ctx: SearchContext,
                              rb: jax.Array) -> jax.Array:
        """f32[P, R] — each replica's contribution to this goal's metric."""
        which, res = self.metric
        P, R = rb.shape
        if which == "count":
            return jnp.ones((P, R), jnp.float32)
        is_leader = (jnp.arange(R) == 0)[None, :]
        return jnp.where(is_leader, ctx.leader_load[:, int(res)][:, None],
                         ctx.follower_load[:, int(res)][:, None])

    def _leadership_drain_weight(self, ctx: SearchContext) -> jax.Array:
        """f32[P] metric shed by transferring partition p's leadership off
        its current leader."""
        which, _res = self.metric
        if which == "leaders":
            return jnp.ones(ctx.partition_valid.shape, jnp.float32)
        assert which == "leader_nw_in", which
        return ctx.leader_load[:, int(Resource.NW_IN)]

    def bulk_drain(self, state: SearchState, ctx: SearchContext, key,
                   cfg: SearchConfig) -> Candidates:
        """Dispatch: replica-move drain for replica-action goals,
        leadership drain for purely leader-scoped metrics (the two
        supports_bulk_drain arms are mutually exclusive)."""
        if self.actions == "replica":
            return self._replica_bulk_drain(state, ctx, key, cfg)
        return self._leadership_bulk_drain(state, ctx, key, cfg,
                                           self._leadership_drain_weight(ctx))

    def _replica_bulk_drain(self, state: SearchState, ctx: SearchContext,
                            key, cfg: SearchConfig) -> Candidates:
        """One round of vectorized excess-shedding: up to ``cfg.drain_batch``
        partition-disjoint move candidates, sources ranked heaviest-first
        within each over-upper (or dead) broker, destinations assigned by a
        prefix-sum fill over receiver budgets. The budgets analytically
        bound aggregate intake for THIS goal's metric, for the replica-
        count ceiling, and — via the batch-max per-unit load — for every
        capacity hard-goal. Earlier SOFT goals' balance bounds are only
        enforced per candidate (round-start values), so a bulk round may
        drift them within one batch; the optimizer's polish passes re-zero
        that drift, which is the documented contract of this fast path.
        Per-candidate legality/acceptance (the engine's eligibility) still
        filters individually; dropped slots retry next round with fresh
        tie-break noise.

        Host-side greedy sheds one replica per step
        (``AbstractGoal.java:98-103``); this is the same policy solved as
        an assignment in O(P·R log) sort work per round."""
        N = cfg.drain_batch
        values = metric_values(state, self.metric)               # [B1]
        lower, upper = self.bounds(state, ctx)
        up = jnp.broadcast_to(jnp.asarray(upper, values.dtype), values.shape)
        alive = ctx.broker_alive
        excess = jnp.where(alive, jnp.maximum(values - up, 0.0), values)
        if self.upper_only:
            deficit = jnp.zeros_like(values)
        else:
            lo = jnp.broadcast_to(jnp.asarray(lower, values.dtype),
                                  values.shape)
            deficit = jnp.where(alive, jnp.maximum(lo - values, 0.0), 0.0)
        # Shed quota per broker: the hard over-upper excess, plus — while
        # under-lower deficits remain beyond what that excess can fill —
        # a pro-rata share of above-average brokers' surplus (the fine
        # loop's "deficit-assist" tier, vectorized).
        n_alive = jnp.maximum(alive.sum(), 1)
        avg = jnp.where(ctx.broker_valid, values, 0.0).sum() / n_alive
        need = jnp.maximum(deficit.sum() - excess.sum(), 0.0)
        pool = jnp.where(alive & (excess <= 0.0),
                         jnp.maximum(values - avg, 0.0), 0.0)
        scale = jnp.minimum(need / jnp.maximum(pool.sum(), 1e-9), 1.0)
        quota = excess + pool * scale

        P, R = state.rb.shape
        B1 = values.shape[0]
        src_b = state.rb
        w = self._replica_drain_weight(ctx, state.rb)            # [P, R]
        # Zero-weight replicas (e.g. followers under a leader-attributed
        # metric) can't reduce anything: taking them floods the batch with
        # moves the delta check rejects and starves real candidates.
        cand = (ctx.movable & ((w > 0.0) | state.offline)
                & ((quota[src_b] > 0.0) | state.offline))

        # Sort candidates by (broker, must-first, weight-desc-with-noise):
        # heaviest replicas shed first, like the reference's sorted-replica
        # walk; noise rotates ties across rounds.
        noise = 1.0 + 0.01 * jax.random.uniform(key, (P, R))
        flat_b = src_b.reshape(-1)
        flat_w = w.reshape(-1)
        flat_cand = cand.reshape(-1)
        flat_must = state.offline.reshape(-1) & flat_cand
        sort_w = jnp.where(flat_cand, flat_w * noise.reshape(-1), -1.0)
        order = jnp.lexsort((-sort_w, ~flat_must, flat_b))
        sb = flat_b[order]
        sw = jnp.where(flat_cand[order], flat_w[order], 0.0)
        smask = flat_cand[order]
        smust = flat_must[order]

        # Shed while the broker's cumulative shed (before this replica)
        # is still below its quota; must-moves shed unconditionally.
        within_before = _segment_cum_before(sw, sb, B1)
        take = smask & ((within_before < quota[sb]) | smust)

        # Partition-disjoint: first taken slot per partition row only.
        sp = (order // R).astype(jnp.int32)
        pos = jnp.arange(P * R, dtype=jnp.int32)
        first_pos = jnp.full((P,), P * R, jnp.int32).at[sp].min(
            jnp.where(take, pos, P * R))
        take = take & (first_pos[sp] == pos)

        grank = (jnp.cumsum(take) - 1).astype(jnp.int32)
        take = take & (grank < N)
        tw = jnp.where(take, sw, 0.0)
        total_w = jnp.maximum(tw.sum(), 1e-9)
        n_take = jnp.maximum(take.sum().astype(jnp.float32), 1.0)

        # Receiver budgets in metric units — on brokers the (possibly
        # steered) destination mask allows — capped by (a) each resource's
        # capacity headroom and (b) the replica-count balance ceiling, both
        # scaled by this batch's mean per-unit load, so one bulk round
        # cannot blow a capacity hard-goal or the count goal in aggregate.
        budget = jnp.where(alive & ctx.dest_allowed & ctx.broker_valid,
                           jnp.maximum(up - values, 0.0), 0.0)
        loads = jnp.where((jnp.arange(R) == 0)[None, :, None],
                          ctx.leader_load[:, None, :],
                          ctx.follower_load[:, None, :])         # [P, R, 4]
        sorted_loads = loads.reshape(-1, 4)[order]               # [P*R, 4]
        # Per-unit load of each taken replica on every resource; the cap
        # divides by the batch MAX (not mean): any subset with metric
        # weight W then provably carries <= W * per_unit_max[res], so a
        # hard CapacityGoal cannot be collectively exceeded even when
        # this-goal-heavy replicas are correlated-heavy on another
        # resource. Soft distribution bounds of earlier goals are NOT
        # capped here — bounded drift there is repaired by the optimizer's
        # polish passes (the documented drain contract).
        ratio = sorted_loads / jnp.maximum(sw, 1e-9)[:, None]    # [P*R, 4]
        per_unit_max = jnp.where(take[:, None], ratio, 0.0).max(axis=0)
        cst = self.constraint
        budget = _capacity_budget_cap(budget, per_unit_max, cst,
                                      ctx.broker_capacity, state.util)
        if self.metric[0] != "count":
            cnt = state.replica_count.astype(jnp.float32)
            cnt_total = jnp.where(ctx.broker_valid, cnt, 0.0).sum()
            cnt_avg = cnt_total / n_alive
            cnt_up = jnp.maximum(cnt_avg * cst.replica_balance_threshold,
                                 jnp.ceil(cnt_avg))
            mean_w = total_w / n_take
            budget = jnp.minimum(budget,
                                 jnp.maximum(cnt_up - cnt, 0.0) * mean_w)
        budget = jnp.maximum(budget, 0.0)

        # Prefix-sum fill over DEFICIT-FIRST receivers: under-lower brokers
        # absorb before merely-below-upper ones (otherwise extra shed lands
        # on whichever broker ids sort first and deficits persist). The
        # replica with cumulative load c lands in the permuted receiver
        # whose budget interval contains c + w/2.
        perm = jnp.argsort(-deficit, stable=True).astype(jnp.int32)
        cumB = jnp.cumsum(budget[perm])
        target = jnp.cumsum(tw) - 0.5 * tw
        pos_in_perm = jnp.searchsorted(cumB, target,
                                       side="left").astype(jnp.int32)
        dst = perm[jnp.minimum(pos_in_perm, B1 - 1)]
        ok = take & (pos_in_perm < B1) & (target < cumB[B1 - 1])

        # Scatter into the fixed-size candidate batch (slot = global rank;
        # invalid rows park in the sentinel slot N).
        slot = jnp.where(ok, grank, N)
        p_out = jnp.zeros((N + 1,), jnp.int32).at[slot].set(sp)
        r_out = jnp.zeros((N + 1,), jnp.int32).at[slot].set(
            (order % R).astype(jnp.int32))
        d_out = jnp.zeros((N + 1,), jnp.int32).at[slot].set(dst)
        # Slot N is the discard row (only not-ok rows land there, and row N
        # is sliced off), so v_out needs no explicit clear.
        v_out = jnp.zeros((N + 1,), bool).at[slot].set(ok)
        return make_move_candidates(state, ctx, p_out[:N], r_out[:N],
                                    d_out[:N], v_out[:N])

    def _leadership_bulk_drain(self, state: SearchState, ctx: SearchContext,
                               key, cfg: SearchConfig,
                               w_all: jax.Array) -> Candidates:
        """Bulk leadership transfers off over-upper leader brokers onto
        each partition's best-headroom follower broker, with two quota
        passes (shed per source, intake per destination) so one round
        cannot overshoot either side. Transfers don't move replicas, so
        count/disk-converged earlier goals accept them freely — this is
        what drains leader-scoped metrics (NW_OUT, CPU, leader counts)
        once replica placement is pinned."""
        N = cfg.drain_batch
        values = metric_values(state, self.metric)               # [B1]
        lower, upper = self.bounds(state, ctx)
        up = jnp.broadcast_to(jnp.asarray(upper, values.dtype), values.shape)
        alive = ctx.broker_alive
        excess = jnp.where(alive, jnp.maximum(values - up, 0.0), values)
        if self.upper_only:
            deficit = jnp.zeros_like(values)
        else:
            lo = jnp.broadcast_to(jnp.asarray(lower, values.dtype),
                                  values.shape)
            deficit = jnp.where(alive, jnp.maximum(lo - values, 0.0), 0.0)
        # Shed quota mirrors the replica drain: over-upper excess, plus a
        # pro-rata share of above-average sources while deficits remain
        # (transfers toward a starving broker usually come from sources
        # within their own bounds).
        n_alive = jnp.maximum(alive.sum(), 1)
        avg = jnp.where(ctx.broker_valid, values, 0.0).sum() / n_alive
        need = jnp.maximum(deficit.sum() - excess.sum(), 0.0)
        pool = jnp.where(alive & (excess <= 0.0),
                         jnp.maximum(values - avg, 0.0), 0.0)
        scale = jnp.minimum(need / jnp.maximum(pool.sum(), 1e-9), 1.0)
        quota = excess + pool * scale
        budget_b = jnp.where(alive & ctx.leader_dest_allowed
                             & ctx.broker_valid,
                             jnp.maximum(up - values, 0.0), 0.0)

        P, R = state.rb.shape
        B1 = values.shape[0]
        src = state.rb[:, 0]                                     # [P]
        w = jnp.maximum(w_all, 0.0)
        # Dead-broker leaders are excluded: a transfer doesn't fix the dead
        # replica (the replica drain / fine loop must relocate it), and
        # such candidates' delta is 0 — they'd pass both quota passes and
        # then be rejected wholesale, starving real transfers of budget.
        can = (ctx.leadership_movable & ctx.partition_valid & alive[src]
               & (quota[src] > 0.0) & (w > 0.0))

        # Destination: the follower slot whose broker has the most intake
        # headroom (receiving slot keeps the full replica; only leadership
        # — and its metric load — moves).
        fb = state.rb                                            # [P, R]
        slot_ok = ((jnp.arange(R) != 0)[None, :] & (fb < B1 - 1)
                   & alive[fb] & ctx.leader_dest_allowed[fb]
                   & ~state.offline)
        dscore = jnp.where(slot_ok, budget_b[fb], -jnp.inf)
        r_sel = jnp.argmax(dscore, axis=1).astype(jnp.int32)
        has_dst = jnp.isfinite(jnp.max(dscore, axis=1))
        can = can & has_dst
        dstb = fb[jnp.arange(P), r_sel]

        noise = 1.0 + 0.01 * jax.random.uniform(key, (P,))
        sort_w = jnp.where(can, w * noise, -1.0)

        # Pass 1 — shed quota per source broker (heaviest transfers first).
        o1 = jnp.lexsort((-sort_w, src))
        sw1 = jnp.where(can[o1], w[o1], 0.0)
        before1 = _segment_cum_before(sw1, src[o1], B1)
        t1_sorted = can[o1] & (before1 < quota[src[o1]])
        take1 = jnp.zeros((P,), bool).at[o1].set(t1_sorted)

        # Aggregate hard-capacity cap, like the replica drain: a transfer
        # lands (leader_load - follower_load) on the destination across
        # all resources.
        dload = jnp.maximum(ctx.leader_load - ctx.follower_load, 0.0)  # [P,4]
        ratio = dload / jnp.maximum(w, 1e-9)[:, None]
        per_unit_max = jnp.where(take1[:, None], ratio, 0.0).max(axis=0)
        budget_b = _capacity_budget_cap(budget_b, per_unit_max,
                                        self.constraint,
                                        ctx.broker_capacity, state.util)

        # Pass 2 — intake budget per destination broker.
        sort_w2 = jnp.where(take1, w * noise, -1.0)
        o2 = jnp.lexsort((-sort_w2, dstb))
        sw2 = jnp.where(take1[o2], w[o2], 0.0)
        before2 = _segment_cum_before(sw2, dstb[o2], B1)
        t2_sorted = take1[o2] & (before2 < budget_b[dstb[o2]])

        grank = (jnp.cumsum(t2_sorted) - 1).astype(jnp.int32)
        ok = t2_sorted & (grank < N)
        slot = jnp.where(ok, grank, N)
        p_out = jnp.zeros((N + 1,), jnp.int32).at[slot].set(
            o2.astype(jnp.int32))
        r_out = jnp.zeros((N + 1,), jnp.int32).at[slot].set(r_sel[o2])
        v_out = jnp.zeros((N + 1,), bool).at[slot].set(ok)
        return make_leadership_candidates(state, ctx, p_out[:N], r_out[:N],
                                          v_out[:N])

    # -- candidate generation -------------------------------------------
    def propose(self, state, ctx, key, cfg):
        values = metric_values(state, self.metric)
        lower, upper = self.bounds(state, ctx)
        lo = jnp.broadcast_to(jnp.asarray(lower, values.dtype), values.shape)
        up = jnp.broadcast_to(jnp.asarray(upper, values.dtype), values.shape)
        alive = ctx.broker_alive
        # Load still parked on dead/invalid brokers also counts as "excess":
        # it must drain to alive brokers (self-healing).
        excess = jnp.where(alive, jnp.maximum(values - up, 0.0), values)
        deficit = (jnp.zeros_like(values) if self.upper_only else
                   jnp.where(alive & jnp.isfinite(lo),
                             jnp.maximum(lo - values, 0.0), 0.0))

        parts = []
        if self.actions in ("replica", "both"):
            kg, key = jax.random.split(key)
            parts.append(self._flow_candidates(state, ctx, kg, cfg, values,
                                               lo, up, excess, deficit))
            if cfg.num_swap_candidates > 0 and self.metric[0] != "count":
                ks, key = jax.random.split(key)
                parts.append(self._swap_candidates(state, ctx, ks, cfg,
                                                   values, lo, up, excess,
                                                   deficit))
        if self.actions in ("leadership", "both"):
            # moving leadership off slot-0's broker to the slot's broker —
            # proposed when EITHER side needs it: the source is over upper,
            # or the destination is starving below lower (a deficit
            # destination's sources are usually within their own bounds;
            # the delta check still keeps only improving transfers).
            src_b = state.rb[:, 0:1]                                # [P, 1]
            dst_b = state.rb                                        # [P, R]
            gain = _norm01(excess)[src_b] + _norm01(deficit)[dst_b]
            prio = jnp.where((excess[src_b] > 0.0) | (deficit[dst_b] > 0.0),
                             gain, _NEG)
            kl, key = jax.random.split(key)
            parts.append(_top_leadership(state, ctx, kl, cfg, prio))
        out = parts[0]
        for extra in parts[1:]:
            out = concat_candidates(out, extra)
        return out

    def _replica_metric_load(self, ctx: SearchContext, p: jax.Array,
                             r: jax.Array) -> jax.Array:
        """f32[N] — how much of this goal's metric arrives at a destination
        when replica (p, r) moves there (== the d_dst component)."""
        which, res = self.metric
        is_leader = (r == 0)
        if which == "util":
            return jnp.where(is_leader, ctx.leader_load[p, int(res)],
                             ctx.follower_load[p, int(res)])
        if which == "count":
            return jnp.ones(p.shape, jnp.float32)
        if which == "leaders":
            return is_leader.astype(jnp.float32)
        if which == "potential":
            return ctx.leader_load[p, Resource.NW_OUT]
        return jnp.where(is_leader, ctx.leader_load[p, Resource.NW_IN], 0.0)

    def _flow_candidates(self, state, ctx, key, cfg, values, lo, up,
                         excess, deficit):
        """Flow-matched move candidates: top-K source replicas, each assigned
        its *own* destination by matching the cumulative outgoing load against
        the cumulative destination headroom (a greedy transportation plan).

        This replaces a K x D cross-product shortlist: with only D distinct
        destinations per iteration the apply pass overshoots them and skips
        the rest of the batch, stalling convergence. Matching by cumulative
        headroom spreads the batch so nearly every candidate is applicable
        in the same iteration.
        """
        P, R = state.rb.shape
        B1 = values.shape[0]
        K = min(cfg.num_replica_candidates, P * R)
        krep, kdst = jax.random.split(key)

        # --- source replicas: offline > excess-broker > deficit-assist tiers
        w = _norm01(self._replica_weight(state, ctx))               # [P, R]
        src_b = state.rb
        any_deficit = deficit.sum() > 0.0
        mid = jnp.where(jnp.isfinite(lo), (lo + up) * 0.5, up * 0.5)
        assist = any_deficit & (values[src_b] > mid[src_b])
        prio = jnp.where(excess[src_b] > 0.0, _TIER_EXCESS + w,
                         jnp.where(assist, _TIER_ASSIST + w, _NEG))
        if self.metric[0] in ("leaders", "leader_nw_in"):
            # Only relocating the *leader* replica (slot 0) changes
            # leader-scoped metrics; follower moves are dead weight.
            prio = jnp.where((jnp.arange(R) == 0)[None, :], prio, _NEG)
        prio = jnp.where(ctx.movable, prio, _NEG)
        prio = jnp.where(state.offline, _TIER_OFFLINE + w, prio)
        prio = prio + jnp.where(jnp.isfinite(prio),
                                _noise(krep, prio.shape, cfg.noise_scale), 0.0)
        vals, idx = jax.lax.top_k(prio.reshape(-1), K)
        p, r = idx // R, idx % R
        sel = jnp.isfinite(vals)

        # --- destination matching by cumulative headroom.
        # Balance goals fill destinations only to the *midpoint* (== the
        # average), not the upper bound: packing a destination to the brim
        # satisfies this goal but leaves zero slack for every later goal in
        # the chain (whose actions this goal must then accept) — the
        # sequential-greedy reference avoids the dead-end by always moving to
        # the least-loaded broker. Capacity-style goals keep the full
        # ceiling. If midpoint headroom is exhausted (everyone above average)
        # fall back to the ceiling headroom.
        ceiling = jnp.where(ctx.dest_allowed, jnp.maximum(up - values, 0.0),
                            0.0)
        if self.upper_only:
            headroom = ceiling
        else:
            to_mid = jnp.where(ctx.dest_allowed,
                               jnp.maximum(mid - values, 0.0), 0.0)
            headroom = jnp.where(to_mid.sum() > 0.0, to_mid, ceiling)
        dprio = jnp.where(ctx.dest_allowed,
                          jnp.where(deficit > 0.0, _TIER_EXCESS, 0.0)
                          + _norm01(headroom, ctx.dest_allowed), _NEG)
        dprio = dprio + jnp.where(jnp.isfinite(dprio),
                                  _noise(kdst, dprio.shape, cfg.noise_scale),
                                  0.0)
        order = jnp.argsort(-dprio)                                  # [B1]
        cum_head = jnp.cumsum(headroom[order])
        load = jnp.where(sel, self._replica_metric_load(ctx, p, r), 0.0)
        cum_load = jnp.cumsum(load) - 0.5 * load                     # midpoints
        slot = jnp.searchsorted(cum_head, cum_load)
        covered = slot < B1
        matched = order[jnp.clip(slot, 0, B1 - 1)]
        # Mandatory (offline) moves get a round-robin destination even when
        # no headroom is left — they must land somewhere alive.
        n_ok = jnp.maximum(ctx.dest_allowed.sum(), 1)
        fallback = order[jnp.arange(K) % n_ok]
        must = state.offline[p, r] & sel
        dst = jnp.where(covered, matched, fallback)
        # The flow matcher is partition-blind: on small clusters it often
        # lands on a broker already hosting the partition, and a mandatory
        # drain can stall on that collision forever. Re-route such
        # candidates to their best *legal* destination.
        hosts_dst = (state.rb[p] == dst[:, None]).any(axis=1)
        alt, alt_ok = _legal_dest_argmax(
            state, ctx, p, jnp.broadcast_to(dprio[None, :], (K, B1)))
        dst = jnp.where(hosts_dst & alt_ok, alt, dst)
        valid = sel & (covered | must) & ctx.dest_allowed[dst]
        return make_move_candidates(state, ctx, p, r, dst.astype(jnp.int32),
                                    valid)

    def _swap_candidates(self, state, ctx, key, cfg, values, lo, up, excess,
                         deficit):
        """Heavy-for-light replica swaps between over-upper and below-average
        brokers (ref ResourceDistributionGoal.java:689,779). Swaps are
        count-neutral, so they fix load imbalance on brokers an earlier
        distribution goal pinned to their replica-count floor/ceiling — the
        lexicographic dead-end single moves cannot escape. The k-th heaviest
        eligible replica pairs with the k-th lightest (largest net transfer
        first); the engine's delta recheck discards overshooting pairs."""
        P, R = state.rb.shape
        K = min(cfg.num_swap_candidates, P * R)
        kh, kl, kshift = jax.random.split(key, 3)
        w = _norm01(self._replica_weight(state, ctx))               # [P, R]
        src_b = state.rb
        # Both sides exchange replicas, so both brokers must be able to
        # receive; offline replicas go through mandatory moves instead.
        # Raw (un-steered) mask: swaps are count/metric-neutral for earlier
        # goals, so a broker the engine steered moves away from (no headroom
        # to *gain* a replica) is still a legitimate swap partner.
        swappable = ctx.movable & ~state.offline & ctx.raw_dest_allowed[src_b]
        leader_scoped = self.metric[0] in ("leaders", "leader_nw_in")
        is_slot0 = (jnp.arange(R) == 0)[None, :]
        mid = jnp.where(jnp.isfinite(lo), (lo + up) * 0.5, up * 0.5)

        # Heavies come from over-upper brokers, or — when the imbalance is
        # deficit-only (everyone under the ceiling, a few below the floor) —
        # from any above-average broker: a heavy-in/light-out exchange is
        # often the only action earlier tightly-packed goals still accept on
        # the deficit broker (e.g. its disk is at the cap).
        any_deficit = deficit.sum() > 0.0
        hmask = swappable & ((excess[src_b] > 0.0)
                             | (any_deficit & (values[src_b] > mid[src_b])))
        lmask = swappable & (values[src_b] < mid[src_b])
        if leader_scoped:
            # Only slot-0 replicas carry the metric out; the incoming side
            # must be a follower or it would haul leadership back in.
            hmask = hmask & is_slot0
            lmask = lmask & ~is_slot0
        hprio = jnp.where(hmask, _TIER_EXCESS + w, _NEG)
        hprio = hprio + jnp.where(jnp.isfinite(hprio),
                                  _noise(kh, hprio.shape, cfg.noise_scale),
                                  0.0)
        # Replicas on *deficit* brokers lead the light side: a deficit broker
        # with no slack on other metrics (e.g. disk at the cap) can only be
        # filled by an exchange, and its own replicas must be the outgoing
        # half of that exchange.
        lprio = jnp.where(lmask,
                          jnp.where(deficit[src_b] > 0.0, _TIER_EXCESS, 0.0)
                          + (0.99 - w), _NEG)
        lprio = lprio + jnp.where(jnp.isfinite(lprio),
                                  _noise(kl, lprio.shape, cfg.noise_scale),
                                  0.0)
        hv, hidx = jax.lax.top_k(hprio.reshape(-1), K)
        lv, lidx = jax.lax.top_k(lprio.reshape(-1), K)
        # Rotate the pairing by a per-iteration random shift: the k-th
        # heaviest meets a different light partner every iteration, so over
        # the pass the generator explores K^2 pairings — the tail of a
        # residual often needs a specific (heavy, light) combination that
        # the default rank-aligned pairing never forms.
        shift = jax.random.randint(kshift, (), 0, K)
        lidx = jnp.roll(lidx, shift)
        lv = jnp.roll(lv, shift)
        p1, r1 = hidx // R, hidx % R
        p2, r2 = lidx // R, lidx % R
        valid = jnp.isfinite(hv) & jnp.isfinite(lv)
        return make_swap_candidates(state, ctx, p1, r1, p2, r2, valid)

    def _replica_weight(self, state: SearchState, ctx: SearchContext):
        """[P, R] preference among movable replicas on source brokers."""
        which, res = self.metric
        R = state.rb.shape[1]
        is_leader = (jnp.arange(R) == 0)[None, :]
        if which == "util":
            load = jnp.where(is_leader[..., None],
                             ctx.leader_load[:, None, :],
                             ctx.follower_load[:, None, :])
            return load[..., int(res)]
        if which == "potential":
            return jnp.broadcast_to(
                ctx.leader_load[:, None, Resource.NW_OUT], state.rb.shape)
        # count-style goals: prefer cheap-to-move (small disk) replicas
        disk = jnp.where(is_leader[..., None], ctx.leader_load[:, None, :],
                         ctx.follower_load[:, None, :])[..., Resource.DISK]
        return -disk


class CapacityGoal(IntervalGoal):
    """Hard cap: util <= capacity * threshold (ref CapacityGoal.java and the
    four resource-specific subclasses)."""

    upper_only = True

    def __init__(self, resource: Resource, constraint: BalancingConstraint):
        name = {Resource.CPU: "CpuCapacityGoal",
                Resource.NW_IN: "NetworkInboundCapacityGoal",
                Resource.NW_OUT: "NetworkOutboundCapacityGoal",
                Resource.DISK: "DiskCapacityGoal"}[resource]
        super().__init__(name, ("util", resource), hard=True,
                         constraint=constraint)
        self.resource = resource
        self.actions = ("both" if resource in (Resource.CPU, Resource.NW_OUT)
                        else "replica")

    def bounds(self, state, ctx):
        thr = self.constraint.cap_threshold(self.resource)
        upper = ctx.broker_capacity[:, int(self.resource)] * thr
        return jnp.full_like(upper, -jnp.inf), upper

    def accepts(self, state, ctx, c):
        # Hard semantics: never push a broker above its capacity ceiling.
        # Both sides are checked — a swap carries net load INTO its source
        # when the incoming replica is heavier on this metric.
        values = metric_values(state, self.metric)
        _, upper = self.bounds(state, ctx)
        d_src, d_dst = metric_deltas(c, self.metric)
        dst_ok = (d_dst <= 0) | (values[c.dst] + d_dst <= upper[c.dst])
        src_ok = (d_src <= 0) | (values[c.src] + d_src <= upper[c.src])
        return dst_ok & src_ok

    def collective_guard(self, state, ctx, c, earlier):
        # Hard cap, so no already-violating escape clause: with net flow
        # included the gaining side(s) must stay under the ceiling outright.
        values = metric_values(state, self.metric)
        _, upper = self.bounds(state, ctx)
        up = jnp.broadcast_to(upper, values.shape)
        d_src, d_dst = metric_deltas(c, self.metric)
        _, net_dst_hi = _net_broker_flow(c, earlier, d_src, d_dst)
        dst_after = values[c.dst] + net_dst_hi + d_dst
        dst_ok = (net_dst_hi + d_dst <= 0) | (dst_after <= up[c.dst])
        src_hi = _net_src_hi(c, earlier, d_src, d_dst)
        src_after = values[c.src] + src_hi + d_src
        src_ok = (src_hi + d_src <= 0) | (src_after <= up[c.src])
        return dst_ok & src_ok


class ResourceDistributionGoal(IntervalGoal):
    """Soft balance: util within avg*(2-t) .. avg*t over alive brokers
    (ref ResourceDistributionGoal.java:55 + the four UsageDistribution
    subclasses)."""

    def __init__(self, resource: Resource, constraint: BalancingConstraint):
        name = {Resource.CPU: "CpuUsageDistributionGoal",
                Resource.NW_IN: "NetworkInboundUsageDistributionGoal",
                Resource.NW_OUT: "NetworkOutboundUsageDistributionGoal",
                Resource.DISK: "DiskUsageDistributionGoal"}[resource]
        super().__init__(name, ("util", resource), hard=False,
                         constraint=constraint)
        self.resource = resource
        self.actions = ("both" if resource in (Resource.CPU, Resource.NW_OUT)
                        else "replica")

    def bounds(self, state, ctx):
        return self._avg_bounds(state, ctx,
                                self.constraint.balance_threshold(self.resource))


class ReplicaCapacityGoal(IntervalGoal):
    """Hard cap on replica count per broker (ref ReplicaCapacityGoal.java,
    max.replicas.per.broker AnalyzerConfig.java:225)."""

    upper_only = True

    def __init__(self, constraint: BalancingConstraint):
        super().__init__("ReplicaCapacityGoal", METRIC_REPLICA_COUNT,
                         hard=True, constraint=constraint)

    def bounds(self, state, ctx):
        upper = jnp.full((ctx.broker_capacity.shape[0],),
                         float(self.constraint.max_replicas_per_broker))
        return jnp.full_like(upper, -jnp.inf), upper

    accepts = CapacityGoal.accepts
    collective_guard = CapacityGoal.collective_guard


class ReplicaDistributionGoal(IntervalGoal):
    """Soft balance of replica counts (ref ReplicaDistributionGoal.java)."""

    def __init__(self, constraint: BalancingConstraint):
        super().__init__("ReplicaDistributionGoal", METRIC_REPLICA_COUNT,
                         hard=False, constraint=constraint)

    def bounds(self, state, ctx):
        return self._avg_bounds(state, ctx,
                                self.constraint.replica_balance_threshold,
                                integer=True)


class LeaderReplicaDistributionGoal(IntervalGoal):
    """Soft balance of leader counts via leadership transfers, falling back
    to relocating leader replicas (ref LeaderReplicaDistributionGoal.java
    tries leadership movement first, then leader-replica movement)."""

    actions = "both"

    def __init__(self, constraint: BalancingConstraint):
        super().__init__("LeaderReplicaDistributionGoal", METRIC_LEADER_COUNT,
                         hard=False, constraint=constraint)

    def bounds(self, state, ctx):
        return self._avg_bounds(
            state, ctx, self.constraint.leader_replica_balance_threshold,
            integer=True)


class LeaderBytesInDistributionGoal(IntervalGoal):
    """Cap leader bytes-in skew: leader NW_IN <= avg * threshold (ref
    LeaderBytesInDistributionGoal.java — upper-side only)."""

    actions = "leadership"
    upper_only = True

    def __init__(self, constraint: BalancingConstraint):
        super().__init__("LeaderBytesInDistributionGoal", METRIC_LEADER_NW_IN,
                         hard=False, constraint=constraint)

    def bounds(self, state, ctx):
        return self._avg_bounds(
            state, ctx, self.constraint.balance_threshold(Resource.NW_IN),
            upper_only=True)


class PotentialNwOutGoal(IntervalGoal):
    """Keep potential (all-leaders) NW_OUT under the capacity ceiling (ref
    PotentialNwOutGoal.java)."""

    upper_only = True

    def __init__(self, constraint: BalancingConstraint):
        super().__init__("PotentialNwOutGoal", METRIC_POTENTIAL_NW_OUT,
                         hard=False, constraint=constraint)

    def bounds(self, state, ctx):
        thr = self.constraint.cap_threshold(Resource.NW_OUT)
        upper = ctx.broker_capacity[:, int(Resource.NW_OUT)] * thr
        return jnp.full_like(upper, -jnp.inf), upper


class RackAwareGoal(GoalKernel):
    """No two replicas of a partition on the same rack (ref
    RackAwareGoal.java; hard)."""

    name = "RackAwareGoal"
    hard = True

    def _dup_mask(self, state: SearchState, ctx: SearchContext) -> jax.Array:
        """bool[P, R] — replica shares a rack with a lower slot's replica."""
        racks = ctx.broker_rack[state.rb]                        # [P, R]
        valid = state.rb < ctx.num_brokers_padded
        R = racks.shape[1]
        same = (racks[:, :, None] == racks[:, None, :])          # [P, R, R]
        lower = jnp.tril(jnp.ones((R, R), bool), k=-1)[None]
        both = valid[:, :, None] & valid[:, None, :]
        return (same & lower & both).any(axis=-1)                # dup vs lower slot

    def violation(self, state, ctx):
        return self._dup_mask(state, ctx).sum().astype(jnp.float32)

    def propose(self, state, ctx, key, cfg):
        dup = self._dup_mask(state, ctx)
        prio = jnp.where(dup, 1.0, _NEG)
        # Prefer emptier destinations (fewer replicas) to also aid balance.
        dest_prio = _norm01(-state.replica_count.astype(jnp.float32))
        return _top_replica_dest_grid(state, ctx, key, cfg, prio, dest_prio)

    def _dup_change(self, state, ctx, p, r, new_broker):
        """(before, after) count of same-rack *pairs* involving replica
        (p, r) when it relocates to ``new_broker`` — counts, not booleans, so
        the delta agrees with the pairwise ``violation`` metric at any
        replication factor (an RF>=3 partition with two co-rack peers loses
        two pairs when the replica leaves)."""
        row = state.rb[p]                                        # [N, R]
        racks = ctx.broker_rack[row]
        valid = row < ctx.num_brokers_padded
        R = racks.shape[-1]
        slots = jnp.arange(R)
        others = valid & (slots != r[..., None])
        my_rack = ctx.broker_rack[state.rb[p, r]]
        new_rack = ctx.broker_rack[new_broker]
        before = ((racks == my_rack[..., None]) & others).sum(axis=-1)
        after = ((racks == new_rack[..., None]) & others).sum(axis=-1)
        return before, after

    def delta(self, state, ctx, c):
        b1, a1 = self._dup_change(state, ctx, c.p, c.r, c.dst)
        d1 = (a1 - b1).astype(jnp.float32)
        is_move = c.kind == MOVE_INTER_BROKER
        is_swap = c.kind == MOVE_SWAP
        # Swap counterpart (a different partition) relocates to src; its
        # pair-count change is independent of the primary's.
        b2, a2 = self._dup_change(state, ctx, c.p2, c.r2, c.src)
        d2 = (a2 - b2).astype(jnp.float32)
        return jnp.where(is_move, d1, jnp.where(is_swap, d1 + d2, 0.0))

    def accepts(self, state, ctx, c):
        # Reference parity (RackAwareGoal.actionAcceptance): an inter-broker
        # move is rejected whenever the destination rack already hosts another
        # replica of the partition — no "was already violating" relaxation.
        _, a1 = self._dup_change(state, ctx, c.p, c.r, c.dst)
        _, a2 = self._dup_change(state, ctx, c.p2, c.r2, c.src)
        is_move = c.kind == MOVE_INTER_BROKER
        is_swap = c.kind == MOVE_SWAP
        return jnp.where(is_move, a1 == 0,
                         jnp.where(is_swap, (a1 == 0) & (a2 == 0), True))

    def collective_guard(self, state, ctx, c, earlier):
        # Rack duplication is a property of a single partition's replica row,
        # and the engine already serializes candidates sharing a partition
        # row — candidates of distinct partitions cannot interact.
        return jnp.ones(c.p.shape, bool)


class TopicReplicaDistributionGoal(GoalKernel):
    """Per-topic replica counts balanced across alive brokers (ref
    TopicReplicaDistributionGoal.java; gap clamping per
    AnalyzerConfig.java:112-131)."""

    name = "TopicReplicaDistributionGoal"
    hard = False
    uses_topic_counts = True

    def __init__(self, constraint: BalancingConstraint):
        self.constraint = constraint

    def _bounds(self, state: SearchState, ctx: SearchContext):
        tc = state.topic_counts                                  # [T, B1]
        total = jnp.where(ctx.broker_valid[None, :], tc, 0).sum(axis=1)
        n = jnp.maximum(ctx.broker_alive.sum(), 1)
        avg = total.astype(jnp.float32) / n                      # [T]
        t = self.constraint.topic_replica_balance_threshold
        gap = jnp.clip(avg * (t - 1.0),
                       float(self.constraint.topic_replica_balance_min_gap),
                       float(self.constraint.topic_replica_balance_max_gap))
        return jnp.maximum(avg - gap, 0.0), avg + gap            # [T], [T]

    def _penalty(self, counts, lower, upper, alive):
        c = counts.astype(jnp.float32)
        pen = jnp.maximum(c - upper, 0.0) + jnp.maximum(lower - c, 0.0)
        return jnp.where(alive, pen, 0.0)

    def violation(self, state, ctx):
        lower, upper = self._bounds(state, ctx)
        pen = self._penalty(state.topic_counts, lower[:, None], upper[:, None],
                            ctx.broker_alive[None, :])
        return pen.sum()

    def propose(self, state, ctx, key, cfg):
        lower, upper = self._bounds(state, ctx)
        tc = state.topic_counts.astype(jnp.float32)              # [T, B1]
        excess = jnp.where(ctx.broker_alive[None, :],
                           jnp.maximum(tc - upper[:, None], 0.0), tc)
        t_of_p = ctx.partition_topic                             # [P]
        src_excess = excess[t_of_p[:, None], state.rb]           # [P, R]
        prio = jnp.where(src_excess > 0.0,
                         _TIER_EXCESS + _norm01(src_excess), _NEG)
        prio = jnp.where(ctx.movable, prio, _NEG)
        prio = jnp.where(state.offline, _TIER_OFFLINE, prio)
        deficit = jnp.where(ctx.broker_alive[None, :],
                            jnp.maximum(lower[:, None] - tc, 0.0), 0.0)

        # Per-candidate TOPIC-AWARE destination: each short-listed replica
        # scores every broker by its own topic's deficit (+ general
        # headroom), masked against brokers already hosting the partition —
        # a topic-agnostic shortlist almost never surfaces the right
        # destination once hundreds of topics each need a specific broker.
        P, R = state.rb.shape
        B1 = tc.shape[1]
        K = min(cfg.num_replica_candidates, P * R)
        krep, kdst, kswap = jax.random.split(key, 3)
        prio = prio + jnp.where(jnp.isfinite(prio),
                                _noise(krep, prio.shape, cfg.noise_scale), 0.0)
        vals, idx = jax.lax.top_k(prio.reshape(-1), K)
        p, r = idx // R, idx % R
        sel = jnp.isfinite(vals)
        count_headroom = _norm01(-state.replica_count.astype(jnp.float32))
        score = (2.0 * _norm01(deficit[t_of_p[p]])            # [K, B1]
                 + count_headroom[None, :]
                 + _noise(kdst, (K, B1), cfg.noise_scale))
        dst, ok = _legal_dest_argmax(state, ctx, p, score)
        out = make_move_candidates(state, ctx, p, r, dst, sel & ok)
        if cfg.num_swap_candidates > 0:
            out = concat_candidates(
                out, self._swap_candidates(state, ctx, kswap, cfg, upper))
        return out

    def _swap_candidates(self, state, ctx, key, cfg, upper):
        """Heavy-for-light topic swaps with *topic-matched* pairing. Once
        earlier resource goals have converged, a plain move of an
        over-represented topic's replica is usually vetoed (it pushes the
        destination's utilization over its tight bound — same bind as
        `ResourceDistributionGoal`'s count-pinned brokers, ref
        ResourceDistributionGoal.java:689). So: each heavy replica (cell
        above upper) picks the destination broker where its own topic is
        scarcest, then trades against that broker's best light replica
        (one per broker per iteration via segment-argmax, noise-rotated so
        partners vary across iterations). Exact cell deltas still reject
        any non-improving pairing."""
        tc = state.topic_counts.astype(jnp.float32)          # [T, B1]
        t_of_p = ctx.partition_topic
        P, R = state.rb.shape
        B1 = tc.shape[1]
        K = min(cfg.num_swap_candidates, P * R)
        src_b = state.rb
        # Raw (un-steered) mask like the resource goals' swap side: swaps
        # are resource-neutral for earlier goals, so steering is moot.
        swappable = ctx.movable & ~state.offline & ctx.raw_dest_allowed[src_b]
        src_over = jnp.maximum(tc - upper[:, None], 0.0)[t_of_p[:, None],
                                                         src_b]
        kh, kl, kd = jax.random.split(key, 3)
        hprio = jnp.where(swappable & (src_over > 0.0),
                          _TIER_EXCESS + _norm01(src_over), _NEG)
        hprio = hprio + jnp.where(jnp.isfinite(hprio),
                                  _noise(kh, hprio.shape, cfg.noise_scale),
                                  0.0)
        hv, hidx = jax.lax.top_k(hprio.reshape(-1), K)
        p1, r1 = hidx // R, hidx % R
        t1 = t_of_p[p1]                                      # [K]

        # One light partner per broker: segment-argmax of a noise-rotated
        # score over in-bounds replicas, keyed by their broker.
        light = (swappable & (src_over <= 0.0)).reshape(-1)
        lraw = jnp.where(light, jax.random.uniform(kl, (P * R,)), -jnp.inf)
        broker_of = src_b.reshape(-1)
        best_val = jax.ops.segment_max(lraw, broker_of, num_segments=B1)
        slots = jnp.arange(P * R, dtype=jnp.int32)
        best_slot = jax.ops.segment_max(
            jnp.where(jnp.isfinite(lraw) & (lraw == best_val[broker_of]),
                      slots, -1),
            broker_of, num_segments=B1)                      # [B1]
        has_light = best_slot >= 0

        # Destination: the broker where this heavy candidate's topic is
        # scarcest (and that can actually offer a partner). Masked against
        # the RAW destination filter — swaps are count/load-neutral, so a
        # broker the steering excluded (e.g. pinned at its replica-count
        # ceiling) is still a legitimate swap destination — and against
        # brokers already hosting the partition.
        row = state.rb[p1]                                   # [K, R]
        hosting = jnp.zeros((K, B1), bool).at[
            jnp.arange(K)[:, None], row].set(True, mode="drop")
        scarcity = _norm01(-tc)[t1]                          # [K, B1]
        score = jnp.where(
            has_light[None, :] & ctx.raw_dest_allowed[None, :] & ~hosting,
            scarcity + _noise(kd, (K, B1), cfg.noise_scale), -jnp.inf)
        dst = jnp.argmax(score, axis=1).astype(jnp.int32)
        ok = jnp.isfinite(jnp.max(score, axis=1))
        partner = best_slot[dst]                             # [K]
        p2, r2 = partner // R, partner % R
        valid = jnp.isfinite(hv) & ok & (partner >= 0)
        return make_swap_candidates(state, ctx, p1, r1, p2, r2, valid)

    def _cell_deltas(self, ctx, c):
        """Per-candidate topic-count deltas on the four (topic, broker)
        cells a move or swap touches. When the swap counterpart shares the
        topic the transfers cancel exactly."""
        is_move = (c.kind == MOVE_INTER_BROKER).astype(jnp.int32)
        is_swap = (c.kind == MOVE_SWAP).astype(jnp.int32)
        m1 = is_move | is_swap          # topic of p: src -> dst
        m2 = is_swap                    # topic of p2: dst -> src
        t1 = ctx.partition_topic[c.p]
        t2 = ctx.partition_topic[c.p2]
        same_t = t1 == t2
        m2_t1 = jnp.where(same_t, m2, 0)
        d_src_t1 = -m1 + m2_t1
        d_dst_t1 = m1 - m2_t1
        m2_t2 = jnp.where(same_t, 0, m2)
        return t1, t2, d_src_t1, d_dst_t1, m2_t2

    def delta(self, state, ctx, c):
        lower, upper = self._bounds(state, ctx)
        t1, t2, d_src_t1, d_dst_t1, m2 = self._cell_deltas(ctx, c)
        tc = state.topic_counts
        alive_s, alive_d = ctx.broker_alive[c.src], ctx.broker_alive[c.dst]

        def pen(t, b, alive, d):
            cell = tc[t, b]
            return (self._penalty(cell + d, lower[t], upper[t], alive)
                    - self._penalty(cell, lower[t], upper[t], alive))
        out = (pen(t1, c.src, alive_s, d_src_t1)
               + pen(t1, c.dst, alive_d, d_dst_t1)
               + pen(t2, c.dst, alive_d, -m2)
               + pen(t2, c.src, alive_s, m2))
        return out

    def accepts(self, state, ctx, c):
        lower, upper = self._bounds(state, ctx)
        t1, t2, d_src_t1, d_dst_t1, m2 = self._cell_deltas(ctx, c)
        tc = state.topic_counts
        # Whichever side *gains* a topic replica must stay within the upper
        # bound or at least not overtake the shrinking side.
        dst_t1_after = tc[t1, c.dst] + d_dst_t1
        ok1 = ((d_dst_t1 <= 0) | (dst_t1_after <= upper[t1])
               | (dst_t1_after <= tc[t1, c.src] + d_src_t1))
        src_t2_after = tc[t2, c.src] + m2
        ok2 = ((m2 <= 0) | (src_t2_after <= upper[t2])
               | (src_t2_after <= tc[t2, c.dst] - m2))
        return ok1 & ok2

    def collective_guard(self, state, ctx, c, earlier):
        # Net flow per (topic, broker) *cell*: candidates interact only when
        # an earlier one moves a replica of the same topic onto/off the same
        # broker. Cell ids (topic * B1 + broker) make that one mask matmul
        # per gaining side, same shape as the broker-metric guards.
        lower, upper = self._bounds(state, ctx)
        t1, t2, d_src_t1, d_dst_t1, m2 = self._cell_deltas(ctx, c)
        B1 = state.util.shape[0]
        tc = state.topic_counts

        # Per-candidate signed deltas on up to 4 cells; net effect on a given
        # cell = sum over earlier candidates' deltas targeting that cell.
        cells = jnp.stack([t1 * B1 + c.src, t1 * B1 + c.dst,
                           t2 * B1 + c.dst, t2 * B1 + c.src])   # [4, N]
        deltas = jnp.stack([d_src_t1, d_dst_t1, -m2, m2]
                           ).astype(jnp.float32)                # [4, N]

        def net_on(cell_ids, sign):
            # [N] — pessimistic one-sided earlier flow on each candidate's
            # cell: positive-only (sign=+1) overestimates inflow for
            # upper-bound checks; negative-only (sign=-1) overestimates
            # outflow for the shrinking side of escape clauses (see
            # _net_broker_flow for why one-sided bounds stay sound under any
            # applied subset).
            acc = jnp.zeros(cell_ids.shape, jnp.float32)
            e = earlier.astype(jnp.float32)
            clip = (lambda x: jnp.maximum(x, 0.0)) if sign > 0 else (
                lambda x: jnp.minimum(x, 0.0))
            for k in range(4):
                acc = acc + (e * (cell_ids[:, None] == cells[k][None, :])
                             ) @ clip(deltas[k])
            return acc

        # Gaining cells checked against the upper bound with worst-case
        # inflow; the escape clause ("stay at or below where the shrinking
        # cell lands") uses the shrinking cell's worst-case *low* estimate so
        # a crowd of same-topic moves can't all ride a stale source count.
        net1 = net_on(cells[1], +1)
        after1 = tc[t1, c.dst].astype(jnp.float32) + net1 + d_dst_t1
        src1_lo = tc[t1, c.src].astype(jnp.float32) + net_on(cells[0], -1) + d_src_t1
        ok1 = ((net1 + d_dst_t1 <= 0) | (after1 <= upper[t1])
               | (after1 <= src1_lo))
        net2 = net_on(cells[3], +1)
        after2 = tc[t2, c.src].astype(jnp.float32) + net2 + m2
        src2_lo = tc[t2, c.dst].astype(jnp.float32) + net_on(cells[2], -1) - m2
        ok2 = ((net2 + m2 <= 0) | (after2 <= upper[t2])
               | (after2 <= src2_lo))
        return ok1 & ok2


class MinTopicLeadersPerBrokerGoal(GoalKernel):
    """Every alive broker must lead at least ``min_count`` partitions of
    each *interested* topic (ref ``MinTopicLeadersPerBrokerGoal.java``, 465
    LoC; hard). Interested topics are configured by name pattern
    (``topics.with.min.leaders.per.broker``); with no interested topics the
    goal is inactive (the reference default).
    """

    name = "MinTopicLeadersPerBrokerGoal"
    hard = True
    uses_topic_counts = True
    uses_topic_leader_counts = True

    def __init__(self, constraint: BalancingConstraint, *,
                 interested_topics: jax.Array | None = None,
                 topic_pattern: str | None = None,
                 min_count: int | None = None):
        self.constraint = constraint
        #: bool[T] — topics the minimum applies to
        self.interested_topics = interested_topics
        #: fnmatch pattern resolved against metadata.topics at bind() time
        #: (ref topics.with.min.leaders.per.broker)
        self.topic_pattern = (topic_pattern if topic_pattern is not None
                              else constraint.topics_with_min_leaders_per_broker)
        self.min_count = (min_count if min_count is not None
                          else constraint.min_topic_leaders_per_broker)
        # An inactive instance (no interested topics — the default-chain
        # case) must not force the engine to build/maintain [T, B1] state.
        self.uses_topic_counts = interested_topics is not None
        self.uses_topic_leader_counts = interested_topics is not None

    def bind(self, metadata) -> "MinTopicLeadersPerBrokerGoal":
        if self.interested_topics is not None or not self.topic_pattern:
            return self
        import fnmatch
        mask = np.array([fnmatch.fnmatch(t, self.topic_pattern)
                         for t in metadata.topics], bool)
        if not mask.any():
            return self
        return MinTopicLeadersPerBrokerGoal(
            self.constraint, interested_topics=jnp.asarray(mask),
            topic_pattern=self.topic_pattern, min_count=self.min_count)

    def bind_signature(self):
        # min_count and topic_pattern are traced into the compiled pass
        # but are NOT derivable from (class, constraint) when passed as
        # explicit overrides — they must be part of the compiled-chain
        # cache identity (the process-wide registry shares chains across
        # optimizer instances on exactly this signature).
        mask = (None if self.interested_topics is None
                else bytes(np.asarray(self.interested_topics).tobytes()))
        return (self.min_count, self.topic_pattern, mask)

    def _deficit(self, state: SearchState, ctx: SearchContext) -> jax.Array:
        """i32[T, B1] — leaders still missing per (topic, broker) cell.
        Only callable on an active instance (interested_topics set)."""
        tlc = state.topic_leader_counts
        d = jnp.maximum(self.min_count - tlc, 0)
        d = jnp.where(ctx.broker_alive[None, :], d, 0)
        return jnp.where(self.interested_topics[:, None], d, 0)

    def violation(self, state, ctx):
        if self.interested_topics is None:   # inactive (no [T, B1] state)
            return jnp.zeros((), jnp.float32)
        return self._deficit(state, ctx).sum().astype(jnp.float32)

    def propose(self, state, ctx, key, cfg):
        if self.interested_topics is None:
            # Inactive: an all-invalid batch keeps the engine's shapes static.
            return _top_leadership(state, ctx, key, cfg,
                                   jnp.full(state.rb.shape, _NEG))
        deficit = self._deficit(state, ctx)                       # [T, B1]
        t_of_p = ctx.partition_topic
        # Leadership transfers: slot r>0 whose broker needs a leader of this
        # topic, from a leader whose broker has surplus.
        tlc = state.topic_leader_counts
        surplus_src = (tlc[t_of_p, state.rb[:, 0]]
                       > self.min_count)[:, None]                 # [P, 1]
        gain = deficit[t_of_p[:, None], state.rb] > 0             # [P, R]
        prio = jnp.where(gain & surplus_src, 1.0, _NEG)
        lead = _top_leadership(state, ctx, key, cfg, prio)
        # Fallback: relocate leader replicas onto deficit brokers.
        rprio = jnp.where((jnp.arange(state.rb.shape[1]) == 0)[None, :]
                          & surplus_src, 1.0, _NEG)
        dest_prio = _norm01(deficit.sum(axis=0).astype(jnp.float32))
        moves = _top_replica_dest_grid(state, ctx, key, cfg, rprio, dest_prio)
        return concat_candidates(lead, moves)

    def _cell_delta(self, state, ctx, c):
        """Signed leadership arriving at dst (+) / leaving src for the
        candidate's primary topic, and the swap counterpart's."""
        is_lead = c.kind == MOVE_LEADERSHIP
        moveswap = (c.kind == MOVE_INTER_BROKER) | (c.kind == MOVE_SWAP)
        d1 = jnp.where(is_lead | (moveswap & (c.r == 0)), 1, 0)
        d2 = jnp.where((c.kind == MOVE_SWAP) & (c.r2 == 0), 1, 0)
        return d1, d2

    def delta(self, state, ctx, c):
        if self.interested_topics is None:
            return jnp.zeros(c.p.shape, jnp.float32)
        t1 = ctx.partition_topic[c.p]
        t2 = ctx.partition_topic[c.p2]
        d1, d2 = self._cell_delta(state, ctx, c)
        tlc = state.topic_leader_counts

        def pen(t, b, d):
            cell = tlc[t, b]
            active = ctx.broker_alive[b] & self.interested_topics[t]
            before = jnp.maximum(self.min_count - cell, 0)
            after = jnp.maximum(self.min_count - (cell + d), 0)
            return jnp.where(active, after - before, 0)
        out = (pen(t1, c.src, -d1) + pen(t1, c.dst, d1)
               + pen(t2, c.dst, -d2) + pen(t2, c.src, d2))
        return out.astype(jnp.float32)

    def accepts(self, state, ctx, c):
        # Hard: the losing cells may not sink below the minimum.
        if self.interested_topics is None:
            return jnp.ones(c.p.shape, bool)
        tlc = state.topic_leader_counts
        t1 = ctx.partition_topic[c.p]
        t2 = ctx.partition_topic[c.p2]
        d1, d2 = self._cell_delta(state, ctx, c)

        def ok(t, b, d):
            interested = self.interested_topics[t] & ctx.broker_alive[b]
            return ~interested | (d >= 0) | (tlc[t, b] + d >= self.min_count)
        return ok(t1, c.src, -d1) & ok(t2, c.dst, -d2)

    def collective_guard(self, state, ctx, c, earlier):
        if self.interested_topics is None:
            return jnp.ones(c.p.shape, bool)
        # Pessimistic (outflow-only) prefix accounting on the losing cells.
        tlc = state.topic_leader_counts
        B1 = state.util.shape[0]
        t1 = ctx.partition_topic[c.p]
        t2 = ctx.partition_topic[c.p2]
        d1, d2 = self._cell_delta(state, ctx, c)
        cells = jnp.stack([t1 * B1 + c.src, t2 * B1 + c.dst])      # losing
        outs = jnp.stack([d1, d2]).astype(jnp.float32)
        e = earlier.astype(jnp.float32)

        def net_out(cell_ids):
            acc = jnp.zeros(cell_ids.shape, jnp.float32)
            for k in range(2):
                acc = acc + (e * (cell_ids[:, None] == cells[k][None, :])
                             ) @ outs[k]
            return acc

        def ok(t, b, cell_ids, d):
            interested = self.interested_topics[t] & ctx.broker_alive[b]
            after = tlc[t, b].astype(jnp.float32) - net_out(cell_ids) - d
            return ~interested | (d <= 0) | (after >= self.min_count)
        return (ok(t1, c.src, cells[0], d1.astype(jnp.float32))
                & ok(t2, c.dst, cells[1], d2.astype(jnp.float32)))


class BrokerSetAwareGoal(GoalKernel):
    """Replicas of a topic must stay inside the topic's broker set (ref
    ``BrokerSetAwareGoal.java``, 331 LoC; hard). ``topic_set[T]`` comes from
    the broker-set resolver + topic mapping policy
    (:mod:`cruise_control_tpu.config.brokersets`); broker_set comes from the
    model (``broker_set`` array). Topics or brokers without a set (-1) are
    unconstrained.
    """

    name = "BrokerSetAwareGoal"
    hard = True

    def __init__(self, constraint: BalancingConstraint, *,
                 topic_set: jax.Array | None = None):
        self.constraint = constraint
        self.topic_set = topic_set     # i32[T] or None

    def bind(self, metadata) -> "BrokerSetAwareGoal":
        """Resolve topic -> broker-set assignments against this model's
        broker sets (name-hash mapping policy, ref
        TopicNameHashBrokerSetMappingPolicy); inactive when the model
        carries no broker sets."""
        if self.topic_set is not None or not metadata.broker_sets:
            return self
        from ..config.brokersets import topic_set_array
        tset = topic_set_array(metadata.topics, metadata.broker_sets)
        return BrokerSetAwareGoal(self.constraint,
                                  topic_set=jnp.asarray(tset))

    def bind_signature(self):
        if self.topic_set is None:
            return None
        return bytes(np.asarray(self.topic_set).tobytes())

    def _mismatch(self, state, ctx) -> jax.Array:
        """bool[P, R] — replica sits outside its topic's broker set."""
        if self.topic_set is None:
            return jnp.zeros(state.rb.shape, bool)
        want = self.topic_set[ctx.partition_topic]                # [P]
        have = ctx.broker_set[state.rb]                           # [P, R]
        valid = state.rb < ctx.num_brokers_padded
        return valid & (want[:, None] >= 0) & (have >= 0) \
            & (have != want[:, None])

    def violation(self, state, ctx):
        return self._mismatch(state, ctx).sum().astype(jnp.float32)

    def propose(self, state, ctx, key, cfg):
        mism = self._mismatch(state, ctx)
        prio = jnp.where(mism, 1.0, _NEG)
        dest_prio = _norm01(-state.replica_count.astype(jnp.float32))
        return _top_replica_dest_grid(state, ctx, key, cfg, prio, dest_prio)

    def _dst_ok(self, ctx, c):
        if self.topic_set is None:
            return jnp.ones(c.p.shape, bool)
        want1 = self.topic_set[ctx.partition_topic[c.p]]
        ok1 = ((want1 < 0) | (ctx.broker_set[c.dst] < 0)
               | (ctx.broker_set[c.dst] == want1))
        want2 = self.topic_set[ctx.partition_topic[c.p2]]
        ok2 = ((want2 < 0) | (ctx.broker_set[c.src] < 0)
               | (ctx.broker_set[c.src] == want2))
        is_move = c.kind == MOVE_INTER_BROKER
        is_swap = c.kind == MOVE_SWAP
        return jnp.where(is_move, ok1,
                         jnp.where(is_swap, ok1 & ok2, True))

    def delta(self, state, ctx, c):
        if self.topic_set is None:
            return jnp.zeros(c.p.shape, jnp.float32)
        mism = self._mismatch(state, ctx)
        before1 = mism[c.p, c.r]
        # after for primary: mismatch iff dst not in topic's set
        want1 = self.topic_set[ctx.partition_topic[c.p]]
        a1 = (want1 >= 0) & (ctx.broker_set[c.dst] >= 0) \
            & (ctx.broker_set[c.dst] != want1)
        want2 = self.topic_set[ctx.partition_topic[c.p2]]
        b2 = mism[c.p2, c.r2]
        a2 = (want2 >= 0) & (ctx.broker_set[c.src] >= 0) \
            & (ctx.broker_set[c.src] != want2)
        is_move = c.kind == MOVE_INTER_BROKER
        is_swap = c.kind == MOVE_SWAP
        d1 = a1.astype(jnp.float32) - before1.astype(jnp.float32)
        d2 = a2.astype(jnp.float32) - b2.astype(jnp.float32)
        return jnp.where(is_move, d1, jnp.where(is_swap, d1 + d2, 0.0))

    def accepts(self, state, ctx, c):
        return self._dst_ok(ctx, c)

    def collective_guard(self, state, ctx, c, earlier):
        # Set membership is a per-replica property; no collective effect.
        return jnp.ones(c.p.shape, bool)

    def receptive_dest(self, state, ctx):
        return jnp.ones(ctx.broker_alive.shape, bool)


class RackAwareDistributionGoal(GoalKernel):
    """Distribute each partition's replicas across racks as evenly as
    possible (ref ``RackAwareDistributionGoal.java``, 449 LoC; hard). The
    relaxation of strict rack-awareness for RF > #racks: at most
    ``ceil(RF / num_alive_racks)`` replicas of a partition per rack.
    """

    name = "RackAwareDistributionGoal"
    hard = True

    def _limit(self, state: SearchState, ctx: SearchContext) -> jax.Array:
        B1 = ctx.broker_rack.shape[0]
        alive_racks = jnp.where(ctx.broker_alive, ctx.broker_rack, -1)
        num_racks = jnp.maximum(_count_distinct(alive_racks, B1), 1)
        rf = (state.rb < ctx.num_brokers_padded).sum(axis=1)      # [P]
        return jnp.ceil(rf / num_racks).astype(jnp.int32)         # [P]

    def _row_penalty(self, racks, valid, limit):
        """Per-partition excess: sum over racks of max(0, n_rack - limit).
        racks [..., R]; the first slot of each rack group carries the
        group's penalty (lower-triangle first-occurrence trick)."""
        R = racks.shape[-1]
        same = (racks[..., :, None] == racks[..., None, :]) \
            & valid[..., :, None] & valid[..., None, :]
        n = same.sum(axis=-1)                                     # [..., R]
        earlier = jnp.tril(jnp.ones((R, R), bool), k=-1)
        first = valid & ~(same & earlier).any(axis=-1)
        excess = jnp.maximum(n - limit[..., None], 0)
        return jnp.where(first, excess, 0).sum(axis=-1)

    def violation(self, state, ctx):
        racks = ctx.broker_rack[state.rb]
        valid = state.rb < ctx.num_brokers_padded
        limit = self._limit(state, ctx)
        return self._row_penalty(racks, valid, limit).sum().astype(jnp.float32)

    def propose(self, state, ctx, key, cfg):
        racks = ctx.broker_rack[state.rb]
        valid = state.rb < ctx.num_brokers_padded
        limit = self._limit(state, ctx)
        same = (racks[:, :, None] == racks[:, None, :]) \
            & valid[:, :, None] & valid[:, None, :]
        n = same.sum(axis=-1)
        prio = jnp.where(valid & (n > limit[:, None]), 1.0, _NEG)
        dest_prio = _norm01(-state.replica_count.astype(jnp.float32))
        return _top_replica_dest_grid(state, ctx, key, cfg, prio, dest_prio)

    def _pen_after(self, state, ctx, p, r, new_broker):
        """Partition p's penalty after slot r relocates to new_broker."""
        rb = state.rb[p]                                          # [N, R]
        R = rb.shape[-1]
        rb2 = jnp.where(jnp.arange(R)[None, :] == r[..., None],
                        new_broker[..., None], rb)
        racks = ctx.broker_rack[rb2]
        valid = rb2 < ctx.num_brokers_padded
        limit = self._limit(state, ctx)[p]
        return self._row_penalty(racks, valid, limit)

    def _side_deltas(self, state, ctx, c):
        racks = ctx.broker_rack[state.rb[c.p]]
        valid = state.rb[c.p] < ctx.num_brokers_padded
        limit = self._limit(state, ctx)[c.p]
        before1 = self._row_penalty(racks, valid, limit)
        after1 = self._pen_after(state, ctx, c.p, c.r, c.dst)
        d1 = (after1 - before1).astype(jnp.float32)
        racks2 = ctx.broker_rack[state.rb[c.p2]]
        valid2 = state.rb[c.p2] < ctx.num_brokers_padded
        limit2 = self._limit(state, ctx)[c.p2]
        before2 = self._row_penalty(racks2, valid2, limit2)
        after2 = self._pen_after(state, ctx, c.p2, c.r2, c.src)
        d2 = (after2 - before2).astype(jnp.float32)
        return d1, d2

    def delta(self, state, ctx, c):
        d1, d2 = self._side_deltas(state, ctx, c)
        is_move = c.kind == MOVE_INTER_BROKER
        is_swap = c.kind == MOVE_SWAP
        return jnp.where(is_move, d1, jnp.where(is_swap, d1 + d2, 0.0))

    def accepts(self, state, ctx, c):
        # Hard: neither side of a swap may push a rack of ITS partition
        # above the limit — per-side, not netted (like RackAwareGoal's
        # per-side a1/a2 check): a big improvement on p2 must not buy a new
        # violation on p.
        d1, d2 = self._side_deltas(state, ctx, c)
        is_move = c.kind == MOVE_INTER_BROKER
        is_swap = c.kind == MOVE_SWAP
        return jnp.where(is_move, d1 <= 0,
                         jnp.where(is_swap, (d1 <= 0) & (d2 <= 0), True))

    def collective_guard(self, state, ctx, c, earlier):
        return jnp.ones(c.p.shape, bool)   # partition-local


def _count_distinct(values: jax.Array, size: int) -> jax.Array:
    """Number of distinct non-negative values below ``size`` — one scatter,
    no quadratic pairwise matrix (values are rack ids, bounded by B1)."""
    ones = jnp.zeros((size,), jnp.int32).at[
        jnp.clip(values, 0, size - 1)].max(
        jnp.where(values >= 0, 1, 0))
    return ones.sum()


class KafkaAssignerEvenRackAwareGoal(RackAwareDistributionGoal):
    """Kafka-assigner mode's strict even-rack placement (ref
    ``kafkaassigner/KafkaAssignerEvenRackAwareGoal.java``, 523 LoC). Same
    even-spread objective as RackAwareDistributionGoal; the reference's
    position-by-position assignment procedure is replaced by the batched
    search reaching the same invariant (<= ceil(RF/num_racks) per rack).
    """

    name = "KafkaAssignerEvenRackAwareGoal"
    hard = True


class KafkaAssignerDiskUsageDistributionGoal(ResourceDistributionGoal):
    """Kafka-assigner mode's minimal-movement disk balancing (ref
    ``kafkaassigner/KafkaAssignerDiskUsageDistributionGoal.java``, 722 LoC):
    disk-usage balance driven primarily by count-neutral swaps.
    """

    def __init__(self, constraint: BalancingConstraint):
        super().__init__(Resource.DISK, constraint)
        self.name = "KafkaAssignerDiskUsageDistributionGoal"


class PreferredLeaderElectionGoal(GoalKernel):
    """Make the original first replica the leader again (ref
    PreferredLeaderElectionGoal.java — used by DemoteBroker and the
    kafka-assigner mode)."""

    name = "PreferredLeaderElectionGoal"
    hard = False

    def violation(self, state, ctx):
        leader_not_preferred = ctx.partition_valid & (state.pos[:, 0] != 0)
        return leader_not_preferred.sum().astype(jnp.float32)

    def propose(self, state, ctx, key, cfg):
        # Candidate: the slot currently holding the preferred replica
        # (pos == 0) for partitions whose leader is not preferred.
        prio = jnp.where((state.pos == 0) & (state.pos[:, 0:1] != 0),
                         1.0, _NEG)
        return _top_leadership(state, ctx, key, cfg, prio)

    def delta(self, state, ctx, c):
        is_lead = c.kind == MOVE_LEADERSHIP
        fixes = (state.pos[c.p, c.r] == 0) & (state.pos[c.p, 0] != 0)
        breaks = state.pos[c.p, 0] == 0
        return jnp.where(is_lead,
                         jnp.where(fixes, -1.0, jnp.where(breaks, 1.0, 0.0)),
                         0.0)

    def accepts(self, state, ctx, c):
        return jnp.ones(c.p.shape, bool)

    def collective_guard(self, state, ctx, c, earlier):
        # Preferred-leader status is per-partition; partition-row exclusivity
        # (engine) is the only interaction.
        return jnp.ones(c.p.shape, bool)


def default_goals(constraint: BalancingConstraint | None = None
                  ) -> list[GoalKernel]:
    """The reference's default goal chain in priority order
    (``config/cruisecontrol.properties:96``)."""
    cst = constraint or BalancingConstraint()
    return [
        RackAwareGoal(),
        MinTopicLeadersPerBrokerGoal(cst),   # inactive until topics configured
        ReplicaCapacityGoal(cst),
        CapacityGoal(Resource.DISK, cst),
        CapacityGoal(Resource.NW_IN, cst),
        CapacityGoal(Resource.NW_OUT, cst),
        CapacityGoal(Resource.CPU, cst),
        ReplicaDistributionGoal(cst),
        PotentialNwOutGoal(cst),
        ResourceDistributionGoal(Resource.DISK, cst),
        ResourceDistributionGoal(Resource.NW_IN, cst),
        ResourceDistributionGoal(Resource.NW_OUT, cst),
        ResourceDistributionGoal(Resource.CPU, cst),
        TopicReplicaDistributionGoal(cst),
        LeaderReplicaDistributionGoal(cst),
        LeaderBytesInDistributionGoal(cst),
    ]


GOAL_REGISTRY = {
    "RackAwareGoal": lambda cst: RackAwareGoal(),
    "ReplicaCapacityGoal": ReplicaCapacityGoal,
    "DiskCapacityGoal": lambda cst: CapacityGoal(Resource.DISK, cst),
    "NetworkInboundCapacityGoal": lambda cst: CapacityGoal(Resource.NW_IN, cst),
    "NetworkOutboundCapacityGoal": lambda cst: CapacityGoal(Resource.NW_OUT, cst),
    "CpuCapacityGoal": lambda cst: CapacityGoal(Resource.CPU, cst),
    "ReplicaDistributionGoal": ReplicaDistributionGoal,
    "PotentialNwOutGoal": PotentialNwOutGoal,
    "DiskUsageDistributionGoal": lambda cst: ResourceDistributionGoal(Resource.DISK, cst),
    "NetworkInboundUsageDistributionGoal": lambda cst: ResourceDistributionGoal(Resource.NW_IN, cst),
    "NetworkOutboundUsageDistributionGoal": lambda cst: ResourceDistributionGoal(Resource.NW_OUT, cst),
    "CpuUsageDistributionGoal": lambda cst: ResourceDistributionGoal(Resource.CPU, cst),
    "TopicReplicaDistributionGoal": TopicReplicaDistributionGoal,
    "LeaderReplicaDistributionGoal": LeaderReplicaDistributionGoal,
    "LeaderBytesInDistributionGoal": LeaderBytesInDistributionGoal,
    "PreferredLeaderElectionGoal": lambda cst: PreferredLeaderElectionGoal(),
    "MinTopicLeadersPerBrokerGoal": MinTopicLeadersPerBrokerGoal,
    "BrokerSetAwareGoal": BrokerSetAwareGoal,
    "RackAwareDistributionGoal": lambda cst: RackAwareDistributionGoal(),
    "KafkaAssignerEvenRackAwareGoal":
        lambda cst: KafkaAssignerEvenRackAwareGoal(),
    "KafkaAssignerDiskUsageDistributionGoal":
        KafkaAssignerDiskUsageDistributionGoal,
}

#: Kafka-assigner mode's minimal goal set (ref analyzer/kafkaassigner/,
#: triggered by the kafka_assigner=true request parameter).
KAFKA_ASSIGNER_GOALS = ["KafkaAssignerEvenRackAwareGoal",
                        "KafkaAssignerDiskUsageDistributionGoal"]

#: Documented relaxations of registered hard goals: a chain carrying one
#: of the alternatives satisfies the requirement for the strict form
#: (RackAwareDistributionGoal relaxes one-replica-per-rack to
#: ceil(RF/num_racks) — RackAwareDistributionGoal.java; the
#: kafka-assigner rack goal likewise supersedes it). Consumed by the
#: off-chain hard-goal audit and the self.healing.goals startup check.
HARD_GOAL_ALTERNATIVES = {
    "RackAwareGoal": ("RackAwareDistributionGoal",
                      "KafkaAssignerEvenRackAwareGoal"),
}


def short_goal_name(name: str) -> str:
    """Canonical short form of a goal name: the reference accepts both
    fully-qualified class names and simple names everywhere
    (ParameterUtils.getGoals) — normalize once, here."""
    return name.rsplit(".", 1)[-1]


def goals_by_name(names: list[str],
                  constraint: BalancingConstraint | None = None
                  ) -> list[GoalKernel]:
    cst = constraint or BalancingConstraint()
    out = []
    for n in names:
        short = short_goal_name(n)
        if short not in GOAL_REGISTRY:
            raise ValueError(f"unknown goal {n!r}")
        out.append(GOAL_REGISTRY[short](cst))
    return out
