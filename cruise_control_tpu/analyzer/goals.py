"""Goal kernels — the reference's goal catalog as vectorized cost functions.

Each reference goal (``analyzer/goals/*.java``) is re-expressed as four
vectorized functions over the :mod:`state` arrays instead of an imperative
``rebalanceForBroker`` loop (ref ``AbstractGoal.java:82-135``):

- ``violation(state, ctx)``      -> scalar residual (0 == satisfied), the
  analog of the goal's success criterion / ``ClusterModelStatsComparator``;
- ``propose(state, ctx, key)``   -> a batch of candidate actions the goal
  wants to try (replaces the sorted-replica candidate walks,
  ``maybeApplyBalancingAction`` ``AbstractGoal.java:230-272``);
- ``delta(state, ctx, cands)``   -> per-candidate change in the residual
  (negative = improvement), evaluated incrementally from the two touched
  broker rows;
- ``accepts(state, ctx, cands)`` -> per-candidate action acceptance when this
  goal was already optimized earlier in the chain (ref
  ``Goal.actionAcceptance`` ``goals/Goal.java:81``) — this is how the
  reference's "later goals must not violate earlier ones" lexicographic
  semantics survive batching.

Most goals are instances of one parametric :class:`IntervalGoal` — "keep a
per-broker metric inside [lower, upper]" — because that is what
Capacity/Distribution goals all are underneath; only rack-awareness and
topic-scoped distribution need bespoke kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.resources import Resource
from ..model.flat import MOVE_INTER_BROKER, MOVE_LEADERSHIP
from .constraint import BalancingConstraint, SearchConfig
from .state import (Candidates, SearchContext, SearchState, concat_candidates,
                    make_leadership_candidates, make_move_candidates,
                    metric_deltas, metric_values,
                    METRIC_LEADER_COUNT, METRIC_LEADER_NW_IN,
                    METRIC_POTENTIAL_NW_OUT, METRIC_REPLICA_COUNT)

_BIG = 1e12
_NEG = -jnp.inf


def _noise(key, shape, scale):
    return scale * jax.random.uniform(key, shape)


def _normalized(w: jax.Array) -> jax.Array:
    """Scale weights into [-1, 1] so they compose with the _BIG tier offsets
    without the tie-break noise (absolute magnitude ~cfg.noise_scale)
    swamping them."""
    return w / (jnp.abs(w).max() + 1.0)


def _top_replica_dest_grid(state: SearchState, ctx: SearchContext, key,
                           cfg: SearchConfig, replica_priority: jax.Array,
                           dest_priority: jax.Array) -> Candidates:
    """Shared candidate generator: top-K replicas x top-D destinations.

    ``replica_priority`` is [P, R] with -inf for non-candidates;
    ``dest_priority`` is [B1] with -inf for barred destinations. Offline
    replicas always float to the top (self-healing must-move semantics, ref
    ``Replica.isCurrentOffline`` handling in every goal's
    ``brokersToBalance``).
    """
    P, R = replica_priority.shape
    K = min(cfg.num_replica_candidates, P * R)
    D = min(cfg.num_dest_candidates, dest_priority.shape[0])
    krep, kdst = jax.random.split(key)

    rp = jnp.where(ctx.movable, replica_priority, _NEG)
    # Offline replicas outrank every goal-specific priority, even when the
    # goal itself would not have short-listed them (self-healing must-move)
    # or the topic is excluded from rebalancing.
    rp = jnp.where(state.offline,
                   2.0 * _BIG + jnp.maximum(jnp.where(jnp.isfinite(rp), rp,
                                                      0.0), 0.0), rp)
    # Priorities are tier offsets (multiples of _BIG) plus normalized [-1, 1]
    # weights; absolute noise_scale-sized noise breaks ties within a tier
    # without reordering the weights.
    rp = rp + jnp.where(jnp.isfinite(rp),
                        _noise(krep, rp.shape, cfg.noise_scale), 0.0)
    rvals, ridx = jax.lax.top_k(rp.reshape(-1), K)
    p, r = ridx // R, ridx % R

    dp = jnp.where(ctx.dest_allowed, dest_priority, _NEG)
    dp = dp + jnp.where(jnp.isfinite(dp),
                        _noise(kdst, dp.shape, cfg.noise_scale), 0.0)
    dvals, didx = jax.lax.top_k(dp, D)

    pg = jnp.repeat(p, D)
    rg = jnp.repeat(r, D)
    dg = jnp.tile(didx, K)
    valid = jnp.repeat(jnp.isfinite(rvals), D) & jnp.tile(jnp.isfinite(dvals), K)
    return make_move_candidates(state, ctx, pg, rg, dg.astype(jnp.int32), valid)


def _top_leadership(state: SearchState, ctx: SearchContext, key,
                    cfg: SearchConfig, priority: jax.Array) -> Candidates:
    """Top-K leadership-transfer candidates from a [P, R] priority grid
    (slot r>0 becoming leader)."""
    P, R = priority.shape
    K = min(cfg.num_replica_candidates, P * R)
    slot_ok = (jnp.arange(R)[None, :] > 0) & ctx.leadership_movable[:, None]
    pr = jnp.where(slot_ok, priority, _NEG)
    pr = pr + jnp.where(jnp.isfinite(pr),
                        _noise(key, pr.shape, cfg.noise_scale), 0.0)
    vals, idx = jax.lax.top_k(pr.reshape(-1), K)
    p, r = idx // R, idx % R
    return make_leadership_candidates(state, ctx, p, r, jnp.isfinite(vals))


class GoalKernel:
    """Base goal. Subclasses are stateless; all data flows through args."""

    name: str = "goal"
    hard: bool = False
    uses_topic_counts: bool = False

    def violation(self, state: SearchState, ctx: SearchContext) -> jax.Array:
        raise NotImplementedError

    def propose(self, state: SearchState, ctx: SearchContext, key,
                cfg: SearchConfig) -> Candidates:
        raise NotImplementedError

    def delta(self, state: SearchState, ctx: SearchContext,
              c: Candidates) -> jax.Array:
        raise NotImplementedError

    def accepts(self, state: SearchState, ctx: SearchContext,
                c: Candidates) -> jax.Array:
        raise NotImplementedError

    def __repr__(self) -> str:  # pragma: no cover
        return f"<{type(self).__name__} {self.name}>"


class IntervalGoal(GoalKernel):
    """Keep ``metric[b]`` within [lower, upper] on every alive broker.

    Parametrization covers (ref classes in analyzer/goals/):
    - CapacityGoal family: upper = capacity * threshold, no lower bound
      (``CapacityGoal.java``);
    - ResourceDistributionGoal family: upper/lower = avg * (t)/(2 - t)
      (``ResourceDistributionGoal.java:55``);
    - Replica/LeaderReplica count distribution, PotentialNwOut,
      LeaderBytesIn — same shape, different metric/bounds.
    """

    #: 'replica' | 'leadership' | 'both'
    actions: str = "replica"
    #: when True the goal only caps the upper side (capacity-style)
    upper_only: bool = False

    def __init__(self, name: str, metric, *, hard: bool,
                 constraint: BalancingConstraint):
        self.name = name
        self.metric = metric
        self.hard = hard
        self.constraint = constraint

    # -- bounds ----------------------------------------------------------
    def bounds(self, state: SearchState, ctx: SearchContext):
        """Return (lower[B1], upper[B1]) arrays (broadcast scalars ok)."""
        raise NotImplementedError

    def _avg_bounds(self, state: SearchState, ctx: SearchContext, t: float,
                    *, integer: bool = False, upper_only: bool = False):
        """avg-over-alive-brokers bounds: [avg*(2-t), avg*t].

        The total includes load still parked on dead brokers — it has to land
        on the alive ones, so the steady-state average accounts for it. With
        ``integer`` the band is widened to at least +-1 unit around the
        average so integer-count goals stay satisfiable on tiny clusters.
        """
        values = metric_values(state, self.metric)
        total = jnp.where(ctx.broker_valid, values, 0.0).sum()
        n = jnp.maximum(ctx.broker_alive.sum(), 1)
        avg = total / n
        upper = avg * t
        lower = (jnp.full_like(avg, -jnp.inf) if upper_only
                 else avg * (2.0 - t))
        if integer:
            upper = jnp.maximum(upper, jnp.ceil(avg))
            if not upper_only:
                lower = jnp.minimum(lower, jnp.floor(avg))
        return lower, upper

    # -- shared machinery ------------------------------------------------
    def _penalty(self, values, lower, upper, alive):
        over = jnp.maximum(values - upper, 0.0)
        under = 0.0 if self.upper_only else jnp.maximum(lower - values, 0.0)
        return jnp.where(alive, over + under, 0.0)

    def violation(self, state, ctx):
        values = metric_values(state, self.metric)
        lower, upper = self.bounds(state, ctx)
        return self._penalty(values, lower, upper, ctx.broker_alive).sum()

    def delta(self, state, ctx, c):
        values = metric_values(state, self.metric)
        lower, upper = self.bounds(state, ctx)
        lo = jnp.broadcast_to(lower, values.shape)
        up = jnp.broadcast_to(upper, values.shape)
        d_src, d_dst = metric_deltas(c, self.metric)
        before = (self._penalty(values[c.src], lo[c.src], up[c.src],
                                ctx.broker_alive[c.src])
                  + self._penalty(values[c.dst], lo[c.dst], up[c.dst],
                                  ctx.broker_alive[c.dst]))
        after = (self._penalty(values[c.src] + d_src, lo[c.src], up[c.src],
                               ctx.broker_alive[c.src])
                 + self._penalty(values[c.dst] + d_dst, lo[c.dst], up[c.dst],
                                 ctx.broker_alive[c.dst]))
        return after - before

    def accepts(self, state, ctx, c):
        """Acceptance when previously optimized: destination must stay within
        the upper limit, or at least remain no more loaded than the source
        ends up (mirrors ResourceDistributionGoal.actionAcceptance's
        no-new-violation rule); symmetrically the source must not sink below
        the lower limit unless it stays above the destination."""
        values = metric_values(state, self.metric)
        lower, upper = self.bounds(state, ctx)
        lo = jnp.broadcast_to(lower, values.shape)
        up = jnp.broadcast_to(upper, values.shape)
        d_src, d_dst = metric_deltas(c, self.metric)
        src_after = values[c.src] + d_src
        dst_after = values[c.dst] + d_dst
        # Metric-neutral actions (d == 0, e.g. a leadership transfer judged by
        # a replica-count goal) are always acceptable: they cannot worsen the
        # goal even when a broker already violates a bound.
        dst_ok = ((d_dst <= 0) | (dst_after <= up[c.dst])
                  | (dst_after <= src_after))
        if self.upper_only:
            src_ok = True
        else:
            src_ok = ((d_src >= 0) | (src_after >= lo[c.src])
                      | (src_after >= dst_after))
        return dst_ok & src_ok

    # -- candidate generation -------------------------------------------
    def propose(self, state, ctx, key, cfg):
        values = metric_values(state, self.metric)
        lower, upper = self.bounds(state, ctx)
        lo = jnp.broadcast_to(jnp.asarray(lower, values.dtype), values.shape)
        up = jnp.broadcast_to(jnp.asarray(upper, values.dtype), values.shape)
        alive = ctx.broker_alive
        excess = jnp.where(alive, jnp.maximum(values - up, 0.0), 0.0)
        deficit = (jnp.zeros_like(values) if self.upper_only else
                   jnp.where(alive, jnp.maximum(lo - values, 0.0), 0.0))
        any_deficit = deficit.sum() > 0
        # Load still parked on dead/invalid brokers also counts as "excess":
        # it must drain to alive brokers (self-healing).
        excess = jnp.where(alive, excess, values)

        parts = []
        if self.actions in ("replica", "both"):
            w = _normalized(self._replica_weight(state, ctx))       # [P, R]
            src_b = state.rb                                        # [P, R]
            src_excess = excess[src_b]
            src_above_avg = values[src_b] > ((lo[src_b] + up[src_b]) * 0.5)
            prio = jnp.where(src_excess > 0.0, _BIG + w,
                             jnp.where(any_deficit & src_above_avg, w, _NEG))
            if self.metric[0] in ("leaders", "leader_nw_in"):
                # Only relocating the *leader* replica (slot 0) changes
                # leader-scoped metrics; follower moves are dead weight.
                R = state.rb.shape[1]
                prio = jnp.where((jnp.arange(R) == 0)[None, :], prio, _NEG)
            dest_prio = (jnp.where(deficit > 0.0, _BIG, 0.0)
                         + _normalized(up - values))
            kg, key = jax.random.split(key)
            parts.append(_top_replica_dest_grid(state, ctx, kg, cfg, prio,
                                                dest_prio))
        if self.actions in ("leadership", "both"):
            # moving leadership off slot-0's broker to the slot's broker
            src_b = state.rb[:, 0:1]                                # [P, 1]
            dst_b = state.rb                                        # [P, R]
            gain = _normalized(excess)[src_b] + _normalized(deficit)[dst_b]
            prio = jnp.where(excess[src_b] > 0.0, gain, _NEG)
            kl, key = jax.random.split(key)
            parts.append(_top_leadership(state, ctx, kl, cfg, prio))
        out = parts[0]
        for extra in parts[1:]:
            out = concat_candidates(out, extra)
        return out

    def _replica_weight(self, state: SearchState, ctx: SearchContext):
        """[P, R] preference among movable replicas on source brokers."""
        which, res = self.metric
        R = state.rb.shape[1]
        is_leader = (jnp.arange(R) == 0)[None, :]
        if which == "util":
            load = jnp.where(is_leader[..., None],
                             ctx.leader_load[:, None, :],
                             ctx.follower_load[:, None, :])
            return load[..., int(res)]
        if which == "potential":
            return jnp.broadcast_to(
                ctx.leader_load[:, None, Resource.NW_OUT], state.rb.shape)
        # count-style goals: prefer cheap-to-move (small disk) replicas
        disk = jnp.where(is_leader[..., None], ctx.leader_load[:, None, :],
                         ctx.follower_load[:, None, :])[..., Resource.DISK]
        return -disk


class CapacityGoal(IntervalGoal):
    """Hard cap: util <= capacity * threshold (ref CapacityGoal.java and the
    four resource-specific subclasses)."""

    upper_only = True

    def __init__(self, resource: Resource, constraint: BalancingConstraint):
        name = {Resource.CPU: "CpuCapacityGoal",
                Resource.NW_IN: "NetworkInboundCapacityGoal",
                Resource.NW_OUT: "NetworkOutboundCapacityGoal",
                Resource.DISK: "DiskCapacityGoal"}[resource]
        super().__init__(name, ("util", resource), hard=True,
                         constraint=constraint)
        self.resource = resource
        self.actions = ("both" if resource in (Resource.CPU, Resource.NW_OUT)
                        else "replica")

    def bounds(self, state, ctx):
        thr = self.constraint.cap_threshold(self.resource)
        upper = ctx.broker_capacity[:, int(self.resource)] * thr
        return jnp.full_like(upper, -jnp.inf), upper

    def accepts(self, state, ctx, c):
        # Hard semantics: never push a broker above its capacity ceiling
        # (additions only; removals always fine).
        values = metric_values(state, self.metric)
        _, upper = self.bounds(state, ctx)
        _, d_dst = metric_deltas(c, self.metric)
        return (d_dst <= 0) | (values[c.dst] + d_dst <= upper[c.dst])


class ResourceDistributionGoal(IntervalGoal):
    """Soft balance: util within avg*(2-t) .. avg*t over alive brokers
    (ref ResourceDistributionGoal.java:55 + the four UsageDistribution
    subclasses)."""

    def __init__(self, resource: Resource, constraint: BalancingConstraint):
        name = {Resource.CPU: "CpuUsageDistributionGoal",
                Resource.NW_IN: "NetworkInboundUsageDistributionGoal",
                Resource.NW_OUT: "NetworkOutboundUsageDistributionGoal",
                Resource.DISK: "DiskUsageDistributionGoal"}[resource]
        super().__init__(name, ("util", resource), hard=False,
                         constraint=constraint)
        self.resource = resource
        self.actions = ("both" if resource in (Resource.CPU, Resource.NW_OUT)
                        else "replica")

    def bounds(self, state, ctx):
        return self._avg_bounds(state, ctx,
                                self.constraint.balance_threshold(self.resource))


class ReplicaCapacityGoal(IntervalGoal):
    """Hard cap on replica count per broker (ref ReplicaCapacityGoal.java,
    max.replicas.per.broker AnalyzerConfig.java:225)."""

    upper_only = True

    def __init__(self, constraint: BalancingConstraint):
        super().__init__("ReplicaCapacityGoal", METRIC_REPLICA_COUNT,
                         hard=True, constraint=constraint)

    def bounds(self, state, ctx):
        upper = jnp.full((ctx.broker_capacity.shape[0],),
                         float(self.constraint.max_replicas_per_broker))
        return jnp.full_like(upper, -jnp.inf), upper

    def accepts(self, state, ctx, c):
        values = metric_values(state, self.metric)
        _, upper = self.bounds(state, ctx)
        _, d_dst = metric_deltas(c, self.metric)
        return (d_dst <= 0) | (values[c.dst] + d_dst <= upper[c.dst])


class ReplicaDistributionGoal(IntervalGoal):
    """Soft balance of replica counts (ref ReplicaDistributionGoal.java)."""

    def __init__(self, constraint: BalancingConstraint):
        super().__init__("ReplicaDistributionGoal", METRIC_REPLICA_COUNT,
                         hard=False, constraint=constraint)

    def bounds(self, state, ctx):
        return self._avg_bounds(state, ctx,
                                self.constraint.replica_balance_threshold,
                                integer=True)


class LeaderReplicaDistributionGoal(IntervalGoal):
    """Soft balance of leader counts via leadership transfers, falling back
    to relocating leader replicas (ref LeaderReplicaDistributionGoal.java
    tries leadership movement first, then leader-replica movement)."""

    actions = "both"

    def __init__(self, constraint: BalancingConstraint):
        super().__init__("LeaderReplicaDistributionGoal", METRIC_LEADER_COUNT,
                         hard=False, constraint=constraint)

    def bounds(self, state, ctx):
        return self._avg_bounds(
            state, ctx, self.constraint.leader_replica_balance_threshold,
            integer=True)


class LeaderBytesInDistributionGoal(IntervalGoal):
    """Cap leader bytes-in skew: leader NW_IN <= avg * threshold (ref
    LeaderBytesInDistributionGoal.java — upper-side only)."""

    actions = "leadership"
    upper_only = True

    def __init__(self, constraint: BalancingConstraint):
        super().__init__("LeaderBytesInDistributionGoal", METRIC_LEADER_NW_IN,
                         hard=False, constraint=constraint)

    def bounds(self, state, ctx):
        return self._avg_bounds(
            state, ctx, self.constraint.balance_threshold(Resource.NW_IN),
            upper_only=True)


class PotentialNwOutGoal(IntervalGoal):
    """Keep potential (all-leaders) NW_OUT under the capacity ceiling (ref
    PotentialNwOutGoal.java)."""

    upper_only = True

    def __init__(self, constraint: BalancingConstraint):
        super().__init__("PotentialNwOutGoal", METRIC_POTENTIAL_NW_OUT,
                         hard=False, constraint=constraint)

    def bounds(self, state, ctx):
        thr = self.constraint.cap_threshold(Resource.NW_OUT)
        upper = ctx.broker_capacity[:, int(Resource.NW_OUT)] * thr
        return jnp.full_like(upper, -jnp.inf), upper


class RackAwareGoal(GoalKernel):
    """No two replicas of a partition on the same rack (ref
    RackAwareGoal.java; hard)."""

    name = "RackAwareGoal"
    hard = True

    def _dup_mask(self, state: SearchState, ctx: SearchContext) -> jax.Array:
        """bool[P, R] — replica shares a rack with a lower slot's replica."""
        racks = ctx.broker_rack[state.rb]                        # [P, R]
        valid = state.rb < ctx.num_brokers_padded
        R = racks.shape[1]
        same = (racks[:, :, None] == racks[:, None, :])          # [P, R, R]
        lower = jnp.tril(jnp.ones((R, R), bool), k=-1)[None]
        both = valid[:, :, None] & valid[:, None, :]
        return (same & lower & both).any(axis=-1)                # dup vs lower slot

    def violation(self, state, ctx):
        return self._dup_mask(state, ctx).sum().astype(jnp.float32)

    def propose(self, state, ctx, key, cfg):
        dup = self._dup_mask(state, ctx)
        prio = jnp.where(dup, 1.0, _NEG)
        # Prefer emptier destinations (fewer replicas) to also aid balance.
        dest_prio = _normalized(-state.replica_count.astype(jnp.float32))
        return _top_replica_dest_grid(state, ctx, key, cfg, prio, dest_prio)

    def _dup_change(self, state, ctx, c):
        """(before, after) duplicate status of the candidate replica."""
        racks = ctx.broker_rack[state.rb[c.p]]                   # [N, R]
        valid = state.rb[c.p] < ctx.num_brokers_padded
        R = racks.shape[-1]
        slots = jnp.arange(R)
        others = valid & (slots != c.r[..., None])
        my_rack = ctx.broker_rack[state.rb[c.p, c.r]]
        dst_rack = ctx.broker_rack[c.dst]
        before = ((racks == my_rack[..., None]) & others).any(axis=-1)
        after = ((racks == dst_rack[..., None]) & others).any(axis=-1)
        return before, after

    def delta(self, state, ctx, c):
        before, after = self._dup_change(state, ctx, c)
        is_move = c.kind == MOVE_INTER_BROKER
        d = after.astype(jnp.float32) - before.astype(jnp.float32)
        return jnp.where(is_move, d, 0.0)

    def accepts(self, state, ctx, c):
        before, after = self._dup_change(state, ctx, c)
        is_move = c.kind == MOVE_INTER_BROKER
        return jnp.where(is_move, ~after | before, True)


class TopicReplicaDistributionGoal(GoalKernel):
    """Per-topic replica counts balanced across alive brokers (ref
    TopicReplicaDistributionGoal.java; gap clamping per
    AnalyzerConfig.java:112-131)."""

    name = "TopicReplicaDistributionGoal"
    hard = False
    uses_topic_counts = True

    def __init__(self, constraint: BalancingConstraint):
        self.constraint = constraint

    def _bounds(self, state: SearchState, ctx: SearchContext):
        tc = state.topic_counts                                  # [T, B1]
        total = jnp.where(ctx.broker_valid[None, :], tc, 0).sum(axis=1)
        n = jnp.maximum(ctx.broker_alive.sum(), 1)
        avg = total.astype(jnp.float32) / n                      # [T]
        t = self.constraint.topic_replica_balance_threshold
        gap = jnp.clip(avg * (t - 1.0),
                       float(self.constraint.topic_replica_balance_min_gap),
                       float(self.constraint.topic_replica_balance_max_gap))
        return jnp.maximum(avg - gap, 0.0), avg + gap            # [T], [T]

    def _penalty(self, counts, lower, upper, alive):
        c = counts.astype(jnp.float32)
        pen = jnp.maximum(c - upper, 0.0) + jnp.maximum(lower - c, 0.0)
        return jnp.where(alive, pen, 0.0)

    def violation(self, state, ctx):
        lower, upper = self._bounds(state, ctx)
        pen = self._penalty(state.topic_counts, lower[:, None], upper[:, None],
                            ctx.broker_alive[None, :])
        return pen.sum()

    def propose(self, state, ctx, key, cfg):
        lower, upper = self._bounds(state, ctx)
        tc = state.topic_counts.astype(jnp.float32)              # [T, B1]
        excess = jnp.where(ctx.broker_alive[None, :],
                           jnp.maximum(tc - upper[:, None], 0.0), tc)
        t_of_p = ctx.partition_topic                             # [P]
        src_excess = excess[t_of_p[:, None], state.rb]           # [P, R]
        prio = jnp.where(src_excess > 0.0, _normalized(src_excess), _NEG)
        deficit = jnp.where(ctx.broker_alive[None, :],
                            jnp.maximum(lower[:, None] - tc, 0.0), 0.0)
        # Destination shortlist is topic-agnostic ([B1]); per-topic fit is
        # resolved by delta scoring over the K x D grid.
        dest_prio = (_normalized(deficit.sum(axis=0))
                     + 1e-3 * _normalized(-state.replica_count.astype(jnp.float32)))
        return _top_replica_dest_grid(state, ctx, key, cfg, prio, dest_prio)

    def delta(self, state, ctx, c):
        lower, upper = self._bounds(state, ctx)
        t = ctx.partition_topic[c.p]
        lo, up = lower[t], upper[t]
        src_c = state.topic_counts[t, c.src]
        dst_c = state.topic_counts[t, c.dst]
        alive_s, alive_d = ctx.broker_alive[c.src], ctx.broker_alive[c.dst]
        is_move = (c.kind == MOVE_INTER_BROKER).astype(jnp.int32)
        before = (self._penalty(src_c, lo, up, alive_s)
                  + self._penalty(dst_c, lo, up, alive_d))
        after = (self._penalty(src_c - is_move, lo, up, alive_s)
                 + self._penalty(dst_c + is_move, lo, up, alive_d))
        return after - before

    def accepts(self, state, ctx, c):
        lower, upper = self._bounds(state, ctx)
        t = ctx.partition_topic[c.p]
        is_move = c.kind == MOVE_INTER_BROKER
        dst_after = state.topic_counts[t, c.dst] + 1
        src_after = state.topic_counts[t, c.src] - 1
        ok = (dst_after <= upper[t]) | (dst_after <= src_after)
        return jnp.where(is_move, ok, True)


class PreferredLeaderElectionGoal(GoalKernel):
    """Make the original first replica the leader again (ref
    PreferredLeaderElectionGoal.java — used by DemoteBroker and the
    kafka-assigner mode)."""

    name = "PreferredLeaderElectionGoal"
    hard = False

    def violation(self, state, ctx):
        leader_not_preferred = ctx.partition_valid & (state.pos[:, 0] != 0)
        return leader_not_preferred.sum().astype(jnp.float32)

    def propose(self, state, ctx, key, cfg):
        # Candidate: the slot currently holding the preferred replica
        # (pos == 0) for partitions whose leader is not preferred.
        prio = jnp.where((state.pos == 0) & (state.pos[:, 0:1] != 0),
                         1.0, _NEG)
        return _top_leadership(state, ctx, key, cfg, prio)

    def delta(self, state, ctx, c):
        is_lead = c.kind == MOVE_LEADERSHIP
        fixes = (state.pos[c.p, c.r] == 0) & (state.pos[c.p, 0] != 0)
        breaks = state.pos[c.p, 0] == 0
        return jnp.where(is_lead,
                         jnp.where(fixes, -1.0, jnp.where(breaks, 1.0, 0.0)),
                         0.0)

    def accepts(self, state, ctx, c):
        return jnp.ones(c.p.shape, bool)


def default_goals(constraint: BalancingConstraint | None = None
                  ) -> list[GoalKernel]:
    """The reference's default goal chain in priority order
    (``config/cruisecontrol.properties:96``)."""
    cst = constraint or BalancingConstraint()
    return [
        RackAwareGoal(),
        ReplicaCapacityGoal(cst),
        CapacityGoal(Resource.DISK, cst),
        CapacityGoal(Resource.NW_IN, cst),
        CapacityGoal(Resource.NW_OUT, cst),
        CapacityGoal(Resource.CPU, cst),
        ReplicaDistributionGoal(cst),
        PotentialNwOutGoal(cst),
        ResourceDistributionGoal(Resource.DISK, cst),
        ResourceDistributionGoal(Resource.NW_IN, cst),
        ResourceDistributionGoal(Resource.NW_OUT, cst),
        ResourceDistributionGoal(Resource.CPU, cst),
        TopicReplicaDistributionGoal(cst),
        LeaderReplicaDistributionGoal(cst),
        LeaderBytesInDistributionGoal(cst),
    ]


GOAL_REGISTRY = {
    "RackAwareGoal": lambda cst: RackAwareGoal(),
    "ReplicaCapacityGoal": ReplicaCapacityGoal,
    "DiskCapacityGoal": lambda cst: CapacityGoal(Resource.DISK, cst),
    "NetworkInboundCapacityGoal": lambda cst: CapacityGoal(Resource.NW_IN, cst),
    "NetworkOutboundCapacityGoal": lambda cst: CapacityGoal(Resource.NW_OUT, cst),
    "CpuCapacityGoal": lambda cst: CapacityGoal(Resource.CPU, cst),
    "ReplicaDistributionGoal": ReplicaDistributionGoal,
    "PotentialNwOutGoal": PotentialNwOutGoal,
    "DiskUsageDistributionGoal": lambda cst: ResourceDistributionGoal(Resource.DISK, cst),
    "NetworkInboundUsageDistributionGoal": lambda cst: ResourceDistributionGoal(Resource.NW_IN, cst),
    "NetworkOutboundUsageDistributionGoal": lambda cst: ResourceDistributionGoal(Resource.NW_OUT, cst),
    "CpuUsageDistributionGoal": lambda cst: ResourceDistributionGoal(Resource.CPU, cst),
    "TopicReplicaDistributionGoal": TopicReplicaDistributionGoal,
    "LeaderReplicaDistributionGoal": LeaderReplicaDistributionGoal,
    "LeaderBytesInDistributionGoal": LeaderBytesInDistributionGoal,
    "PreferredLeaderElectionGoal": lambda cst: PreferredLeaderElectionGoal(),
}


def goals_by_name(names: list[str],
                  constraint: BalancingConstraint | None = None
                  ) -> list[GoalKernel]:
    cst = constraint or BalancingConstraint()
    out = []
    for n in names:
        short = n.rsplit(".", 1)[-1]
        if short not in GOAL_REGISTRY:
            raise ValueError(f"unknown goal {n!r}")
        out.append(GOAL_REGISTRY[short](cst))
    return out
