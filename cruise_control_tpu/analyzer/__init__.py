"""Analyzer — the TPU-native rebuild of Cruise Control's goal optimizer.

Reference layer: ``cruise-control/.../analyzer/`` (``GoalOptimizer.java``,
``goals/*``). The greedy per-replica search is replaced by batched candidate
scoring on device; see :mod:`engine` for the search loop and :mod:`goals`
for the goal catalog.
"""

from .constraint import (BalancingConstraint, PopulationConfig,
                         SearchConfig)
from .goals import (GOAL_REGISTRY, CapacityGoal, GoalKernel,
                    LeaderBytesInDistributionGoal,
                    LeaderReplicaDistributionGoal,
                    PotentialNwOutGoal, PreferredLeaderElectionGoal,
                    RackAwareGoal, ReplicaCapacityGoal,
                    ReplicaDistributionGoal, ResourceDistributionGoal,
                    TopicReplicaDistributionGoal, default_goals, goals_by_name)
from .optimizer import (GoalResult, OptimizationFailureError,
                        OptimizerResult, TpuGoalOptimizer)
from .options import (DefaultOptimizationOptionsGenerator,
                      OptimizationOptions,
                      OptimizationOptionsGenerator)
from .tuning import (SuccessiveHalvingTuner, TunedConfigStore, autotune,
                     plan_quality, shape_bucket)

__all__ = [
    "BalancingConstraint", "PopulationConfig", "SearchConfig",
    "SuccessiveHalvingTuner", "TunedConfigStore", "autotune",
    "plan_quality", "shape_bucket", "GoalKernel", "CapacityGoal",
    "RackAwareGoal", "ReplicaCapacityGoal", "ReplicaDistributionGoal",
    "ResourceDistributionGoal", "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal", "PotentialNwOutGoal",
    "PreferredLeaderElectionGoal", "TopicReplicaDistributionGoal",
    "default_goals", "goals_by_name", "GOAL_REGISTRY",
    "TpuGoalOptimizer", "OptimizerResult", "GoalResult",
    "OptimizationOptions", "OptimizationOptionsGenerator",
    "DefaultOptimizationOptionsGenerator",
    "OptimizationFailureError",
]
