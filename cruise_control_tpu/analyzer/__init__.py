"""Analyzer — the TPU-native rebuild of Cruise Control's goal optimizer.

Reference layer: ``cruise-control/.../analyzer/`` (``GoalOptimizer.java``,
``goals/*``). The greedy per-replica search is replaced by batched candidate
scoring on device; see :mod:`engine` for the search loop and :mod:`goals`
for the goal catalog.
"""

from .constraint import BalancingConstraint, SearchConfig
from .goals import (GOAL_REGISTRY, CapacityGoal, GoalKernel,
                    LeaderBytesInDistributionGoal,
                    LeaderReplicaDistributionGoal,
                    PotentialNwOutGoal, PreferredLeaderElectionGoal,
                    RackAwareGoal, ReplicaCapacityGoal,
                    ReplicaDistributionGoal, ResourceDistributionGoal,
                    TopicReplicaDistributionGoal, default_goals, goals_by_name)
from .optimizer import (GoalResult, OptimizationFailureError,
                        OptimizerResult, TpuGoalOptimizer)
from .options import (DefaultOptimizationOptionsGenerator,
                      OptimizationOptions,
                      OptimizationOptionsGenerator)

__all__ = [
    "BalancingConstraint", "SearchConfig", "GoalKernel", "CapacityGoal",
    "RackAwareGoal", "ReplicaCapacityGoal", "ReplicaDistributionGoal",
    "ResourceDistributionGoal", "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal", "PotentialNwOutGoal",
    "PreferredLeaderElectionGoal", "TopicReplicaDistributionGoal",
    "default_goals", "goals_by_name", "GOAL_REGISTRY",
    "TpuGoalOptimizer", "OptimizerResult", "GoalResult",
    "OptimizationOptions", "OptimizationOptionsGenerator",
    "DefaultOptimizationOptionsGenerator",
    "OptimizationFailureError",
]
