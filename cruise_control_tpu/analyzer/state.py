"""Search state: the analyzer's device-resident view of the cluster.

The reference mutates a ``ClusterModel`` object graph in place while goals
run (``relocateReplica`` ``ClusterModel.java:380``). Here the optimization
state is a pytree of arrays with *incrementally maintained* broker
aggregates: applying a move touches two rows of each aggregate instead of
re-reducing the whole model, which is what makes scoring thousands of
candidate actions per step cheap on the MXU-adjacent vector units.

Terminology:
- ``B1 = padded_brokers + 1``: broker-indexed arrays carry one trailing
  sentinel row so scatter-updates for empty replica slots land in a discard
  row (same trick as ``model/flat.py``).
- A *candidate* is one potential balancing action (ref
  ``BalancingAction.java:20``), represented as a struct-of-arrays so the
  whole batch is scored with elementwise vector math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..core.resources import NUM_RESOURCES, Resource
from ..model.flat import (MOVE_INTER_BROKER, MOVE_LEADERSHIP, MOVE_SWAP,
                          FlatClusterModel, replica_loads)

# Metric selectors: which per-broker aggregate a goal balances/caps.
METRIC_CPU = ("util", Resource.CPU)
METRIC_NW_IN = ("util", Resource.NW_IN)
METRIC_NW_OUT = ("util", Resource.NW_OUT)
METRIC_DISK = ("util", Resource.DISK)
METRIC_REPLICA_COUNT = ("count", None)
METRIC_LEADER_COUNT = ("leaders", None)
METRIC_POTENTIAL_NW_OUT = ("potential", None)
METRIC_LEADER_NW_IN = ("leader_nw_in", None)


@struct.dataclass
class SearchContext:
    """Immutable per-optimization inputs (loads, topology, option masks)."""

    leader_load: jax.Array        # f32[P, 4]
    follower_load: jax.Array      # f32[P, 4]
    partition_topic: jax.Array    # i32[P]
    partition_valid: jax.Array    # bool[P]
    broker_capacity: jax.Array    # f32[B1, 4] (sentinel row: 0)
    broker_rack: jax.Array        # i32[B1] (sentinel: -1)
    broker_set: jax.Array         # i32[B1] (sentinel: -1; -1 = unassigned)
    broker_alive: jax.Array       # bool[B1]
    broker_valid: jax.Array       # bool[B1]
    dest_allowed: jax.Array       # bool[B1] — may receive replicas
    leader_dest_allowed: jax.Array  # bool[B1] — may receive leadership
    # Un-steered copy of dest_allowed. The engine's steer_ctx narrows
    # dest_allowed toward brokers earlier goals can accept *gaining* replicas
    # on; metric-neutral actions (swaps) must ignore that narrowing — a
    # count-packed broker is a perfectly good swap partner — so their
    # generator reads the raw mask.
    raw_dest_allowed: jax.Array     # bool[B1]
    movable: jax.Array            # bool[P, R] — replica may be relocated
    leadership_movable: jax.Array  # bool[P] — leadership may be transferred

    @property
    def num_brokers_padded(self) -> int:
        return self.broker_capacity.shape[0] - 1


@struct.dataclass
class SearchState:
    """Mutable (functionally-updated) optimization state."""

    rb: jax.Array              # i32[P, R] replica -> broker (sentinel = empty)
    pos: jax.Array             # i32[P, R] original assignment position of the
    #                            replica in this slot (slot 0 = current leader;
    #                            pos tracks Kafka's preferred-leader order)
    offline: jax.Array         # bool[P, R] replica must move (dead broker/disk)
    util: jax.Array            # f32[B1, 4]
    replica_count: jax.Array   # i32[B1]
    leader_count: jax.Array    # i32[B1]
    potential_nw_out: jax.Array  # f32[B1]
    leader_nw_in: jax.Array    # f32[B1]
    topic_counts: jax.Array | None  # i32[T, B1] or None (only when a
    #                                 topic-scoped goal is in the chain)
    topic_leader_counts: jax.Array | None  # i32[T, B1] or None (only for
    #                                 MinTopicLeadersPerBrokerGoal chains)
    moves_applied: jax.Array   # i32 scalar — total actions applied so far


@struct.dataclass
class Candidates:
    """A batch of N candidate balancing actions (struct-of-arrays).

    Delta fields are *signed from the destination's perspective*: applying a
    candidate adds ``-d`` to the source row and ``+d`` to the destination row
    of the corresponding aggregate. For swaps (kind MOVE_SWAP) the second
    replica (``p2``, ``r2``) — a replica of a different partition hosted on
    ``dst`` — travels to ``src`` in the same action; non-swap candidates
    carry ``p2 == p``/``r2 == r`` as an inert placeholder.
    """

    p: jax.Array            # i32[N] partition row
    r: jax.Array            # i32[N] replica slot
    p2: jax.Array           # i32[N] swap counterpart partition (== p otherwise)
    r2: jax.Array           # i32[N] swap counterpart slot (== r otherwise)
    src: jax.Array          # i32[N] source broker (for leadership: slot-0 broker)
    dst: jax.Array          # i32[N] destination broker
    kind: jax.Array         # i32[N] MOVE_INTER_BROKER | MOVE_LEADERSHIP | MOVE_SWAP
    valid: jax.Array        # bool[N] generated-slot validity
    must: jax.Array         # bool[N] moves an offline replica (mandatory)
    d_util_src: jax.Array   # f32[N, 4]
    d_util_dst: jax.Array   # f32[N, 4]
    d_cnt: jax.Array        # i32[N] replica-count delta (0/1; swaps: 0)
    d_lead: jax.Array       # i32[N] leader-count delta (signed for swaps)
    d_pot: jax.Array        # f32[N] potential-NW_OUT delta (signed for swaps)
    d_lni: jax.Array        # f32[N] leader-NW_IN delta (signed for swaps)


def init_state(model: FlatClusterModel, *,
               with_topic_counts: int | None = None,
               with_topic_leader_counts: bool = False) -> SearchState:
    """Build the search state from a flat model (one full reduction; all
    subsequent updates are incremental)."""
    P, R = model.replica_broker.shape
    B = model.num_brokers_padded
    B1 = B + 1
    # Fresh buffers: the engine's passes donate the state, and the caller's
    # model must survive to be diffed against the optimized placement.
    rb = jnp.array(model.replica_broker, copy=True)
    loads = replica_loads(model)                                   # [P, R, 4]
    flat_idx = rb.reshape(-1)
    util = jnp.zeros((B1, NUM_RESOURCES), jnp.float32)
    util = util.at[flat_idx].add(loads.reshape(-1, NUM_RESOURCES))
    util = util.at[B].set(0.0)

    valid = model.replica_valid
    counts = jnp.zeros((B1,), jnp.int32).at[flat_idx].add(1).at[B].set(0)
    leaders = jnp.zeros((B1,), jnp.int32).at[rb[:, 0]].add(
        jnp.where(model.partition_valid, 1, 0)).at[B].set(0)
    pot = jnp.where(valid, model.leader_load[:, None, Resource.NW_OUT], 0.0)
    potential = jnp.zeros((B1,), jnp.float32).at[flat_idx].add(
        pot.reshape(-1)).at[B].set(0.0)
    lni = jnp.where(model.partition_valid,
                    model.leader_load[:, Resource.NW_IN], 0.0)
    leader_nw_in = jnp.zeros((B1,), jnp.float32).at[rb[:, 0]].add(lni).at[B].set(0.0)

    topic_counts = None
    topic_leader_counts = None
    if with_topic_leader_counts and with_topic_counts is None:
        raise ValueError("with_topic_leader_counts requires the topic count "
                         "(pass with_topic_counts=num_topics)")
    if with_topic_counts is not None:
        T = with_topic_counts
        idx = model.partition_topic[:, None] * B1 + rb                # [P, R]
        tc = jnp.zeros((T * B1,), jnp.int32).at[idx.reshape(-1)].add(
            jnp.where(valid, 1, 0).reshape(-1), mode="drop")
        topic_counts = tc.reshape(T, B1).at[:, B].set(0)
        if with_topic_leader_counts:
            lidx = model.partition_topic * B1 + rb[:, 0]              # [P]
            tlc = jnp.zeros((T * B1,), jnp.int32).at[lidx].add(
                jnp.where(model.partition_valid, 1, 0), mode="drop")
            topic_leader_counts = tlc.reshape(T, B1).at[:, B].set(0)

    pos = jnp.array(model.replica_pref_pos, copy=True)
    # A replica hosted on a dead (or padding) broker is offline whether or
    # not the model builder flagged it (ref Replica.isCurrentOffline derives
    # from broker state) — offline replicas are the must-move set that
    # drives self-healing.
    alive1 = jnp.concatenate([model.broker_alive & model.broker_valid,
                              jnp.zeros((1,), bool)])
    offline = model.replica_offline | (valid & ~alive1[rb])
    return SearchState(rb=rb, pos=pos, offline=offline,
                       util=util, replica_count=counts, leader_count=leaders,
                       potential_nw_out=potential, leader_nw_in=leader_nw_in,
                       topic_counts=topic_counts,
                       topic_leader_counts=topic_leader_counts,
                       moves_applied=jnp.zeros((), jnp.int32))


def build_context(model: FlatClusterModel, *,
                  excluded_partitions: jax.Array | None = None,
                  excluded_brokers_for_replica_move: jax.Array | None = None,
                  excluded_brokers_for_leadership: jax.Array | None = None
                  ) -> SearchContext:
    """Assemble the immutable context. Exclusion masks follow
    ``OptimizationOptions`` semantics (ref analyzer/OptimizationOptions.java):
    replicas of excluded topics never move *unless offline*; excluded brokers
    never receive replicas / leadership."""
    P, R = model.replica_broker.shape
    B = model.num_brokers_padded

    def _pad1(arr, fill):
        return jnp.concatenate([arr, jnp.full((1,) + arr.shape[1:], fill,
                                              arr.dtype)], axis=0)

    alive = _pad1(model.broker_alive & model.broker_valid, False)
    bvalid = _pad1(model.broker_valid, False)
    capacity = _pad1(model.broker_capacity, 0.0)
    rack = _pad1(model.broker_rack, -1)
    bset = _pad1(model.broker_set, -1)

    # Brokers with broken disks stay alive (healthy replicas keep serving)
    # but may not RECEIVE replicas (ref ClusterModel BAD_DISKS broker state;
    # new replicas would land on a half-dead broker).
    dest = alive & ~_pad1(model.broker_broken_disk, True)
    if excluded_brokers_for_replica_move is not None:
        dest = dest & ~_pad1(excluded_brokers_for_replica_move, True)
    lead_dest = alive & ~_pad1(model.broker_demoted, True)
    if excluded_brokers_for_leadership is not None:
        lead_dest = lead_dest & ~_pad1(excluded_brokers_for_leadership, True)

    # ``movable`` is the *static* exclusion mask: real slot, topic not
    # excluded. The offline exception ("excluded topics still heal") is
    # dynamic — an offline replica becomes immovable again once relocated —
    # so it is resolved against ``state.offline`` in base_legality/propose,
    # not frozen here.
    slot_valid = model.replica_valid
    if excluded_partitions is None:
        excluded_partitions = jnp.zeros((P,), bool)
    movable = slot_valid & ~excluded_partitions[:, None]
    leadership_movable = model.partition_valid & ~excluded_partitions

    return SearchContext(
        leader_load=model.leader_load, follower_load=model.follower_load,
        partition_topic=model.partition_topic,
        partition_valid=model.partition_valid,
        broker_capacity=capacity, broker_rack=rack, broker_set=bset,
        broker_alive=alive,
        broker_valid=bvalid, dest_allowed=dest,
        leader_dest_allowed=lead_dest, raw_dest_allowed=dest,
        movable=movable,
        leadership_movable=leadership_movable)


# ---------------------------------------------------------------------------
# Metric access (the vectorized Load.expectedUtilizationFor of the goals)
# ---------------------------------------------------------------------------

def metric_values(state: SearchState, metric) -> jax.Array:
    """f32[B1] — current value of the balanced metric on every broker."""
    which, res = metric
    if which == "util":
        return state.util[:, int(res)]
    if which == "count":
        return state.replica_count.astype(jnp.float32)
    if which == "leaders":
        return state.leader_count.astype(jnp.float32)
    if which == "potential":
        return state.potential_nw_out
    if which == "leader_nw_in":
        return state.leader_nw_in
    raise ValueError(f"unknown metric {metric}")


def metric_deltas(cand: Candidates, metric):
    """(d_src, d_dst) f32[N] — metric change on source/destination rows."""
    which, res = metric
    if which == "util":
        return cand.d_util_src[..., int(res)], cand.d_util_dst[..., int(res)]
    if which == "count":
        d = cand.d_cnt.astype(jnp.float32)
        return -d, d
    if which == "leaders":
        d = cand.d_lead.astype(jnp.float32)
        return -d, d
    if which == "potential":
        return -cand.d_pot, cand.d_pot
    if which == "leader_nw_in":
        return -cand.d_lni, cand.d_lni
    raise ValueError(f"unknown metric {metric}")


# ---------------------------------------------------------------------------
# Candidate construction
# ---------------------------------------------------------------------------

def make_move_candidates(state: SearchState, ctx: SearchContext,
                         p: jax.Array, r: jax.Array, dst: jax.Array,
                         valid: jax.Array) -> Candidates:
    """Inter-broker replica relocation candidates (ref ActionType
    INTER_BROKER_REPLICA_MOVEMENT)."""
    src = state.rb[p, r]
    is_leader = (r == 0)
    load = jnp.where(is_leader[..., None], ctx.leader_load[p],
                     ctx.follower_load[p])                       # [N, 4]
    d_pot = ctx.leader_load[p, Resource.NW_OUT]
    d_lni = jnp.where(is_leader, ctx.leader_load[p, Resource.NW_IN], 0.0)
    kind = jnp.full(p.shape, MOVE_INTER_BROKER, jnp.int32)
    return Candidates(
        p=p, r=r, p2=p, r2=r, src=src, dst=dst, kind=kind, valid=valid,
        must=state.offline[p, r] & valid,
        d_util_src=-load, d_util_dst=load,
        d_cnt=jnp.ones(p.shape, jnp.int32),
        d_lead=is_leader.astype(jnp.int32),
        d_pot=d_pot, d_lni=d_lni)


def make_leadership_candidates(state: SearchState, ctx: SearchContext,
                               p: jax.Array, r: jax.Array,
                               valid: jax.Array) -> Candidates:
    """Leadership transfer candidates: slot ``r`` becomes the leader (ref
    ActionType LEADERSHIP_MOVEMENT; model swap per relocateLeadership)."""
    src = state.rb[p, 0]
    dst = state.rb[p, r]
    dload = ctx.leader_load[p] - ctx.follower_load[p]            # [N, 4]
    kind = jnp.full(p.shape, MOVE_LEADERSHIP, jnp.int32)
    zero = jnp.zeros(p.shape, jnp.float32)
    return Candidates(
        p=p, r=r, p2=p, r2=r, src=src, dst=dst, kind=kind, valid=valid,
        must=jnp.zeros(p.shape, bool),
        d_util_src=-dload, d_util_dst=dload,
        d_cnt=jnp.zeros(p.shape, jnp.int32),
        d_lead=jnp.ones(p.shape, jnp.int32),
        d_pot=zero, d_lni=ctx.leader_load[p, Resource.NW_IN])


def make_swap_candidates(state: SearchState, ctx: SearchContext,
                         p1: jax.Array, r1: jax.Array,
                         p2: jax.Array, r2: jax.Array,
                         valid: jax.Array) -> Candidates:
    """Inter-broker replica *swap* candidates (ref ActionType
    INTER_BROKER_REPLICA_SWAP; ResourceDistributionGoal.java:689,779).

    Replica (p1, r1) on broker ``src`` trades places with replica (p2, r2)
    on broker ``dst``. Counts are unchanged on both sides — swaps are how
    load imbalances get fixed on brokers already pinned to their replica-
    count floor/ceiling by an earlier distribution goal.
    """
    src = state.rb[p1, r1]
    dst = state.rb[p2, r2]
    lead1 = (r1 == 0)
    lead2 = (r2 == 0)
    load1 = jnp.where(lead1[..., None], ctx.leader_load[p1],
                      ctx.follower_load[p1])                      # [N, 4]
    load2 = jnp.where(lead2[..., None], ctx.leader_load[p2],
                      ctx.follower_load[p2])
    net = load1 - load2              # arrives at dst; leaves src
    pot1 = ctx.leader_load[p1, Resource.NW_OUT]
    pot2 = ctx.leader_load[p2, Resource.NW_OUT]
    lni1 = jnp.where(lead1, ctx.leader_load[p1, Resource.NW_IN], 0.0)
    lni2 = jnp.where(lead2, ctx.leader_load[p2, Resource.NW_IN], 0.0)
    kind = jnp.full(p1.shape, MOVE_SWAP, jnp.int32)
    return Candidates(
        p=p1, r=r1, p2=p2, r2=r2, src=src, dst=dst, kind=kind, valid=valid,
        must=jnp.zeros(p1.shape, bool),
        d_util_src=-net, d_util_dst=net,
        d_cnt=jnp.zeros(p1.shape, jnp.int32),
        d_lead=lead1.astype(jnp.int32) - lead2.astype(jnp.int32),
        d_pot=pot1 - pot2,
        d_lni=lni1 - lni2)


def concat_candidates(a: Candidates, b: Candidates) -> Candidates:
    return jax.tree.map(lambda x, y: jnp.concatenate([x, y], axis=0), a, b)


# ---------------------------------------------------------------------------
# Legality (base constraints every action must satisfy; goal acceptance is
# layered on top by the engine)
# ---------------------------------------------------------------------------

def base_legality(state: SearchState, ctx: SearchContext,
                  c: Candidates) -> jax.Array:
    """bool[N]. Re-evaluable against a *changed* state: includes staleness
    checks (slot still holds the broker captured at proposal time), so the
    apply scan can safely re-test each candidate after earlier applies."""
    row = state.rb[c.p]                                          # [N, R]
    slot_broker = state.rb[c.p, c.r]
    is_move = c.kind == MOVE_INTER_BROKER

    hosts_dst = (row == c.dst[..., None]).any(axis=-1)
    # Offline replicas are movable even when their topic is excluded from
    # rebalancing (self-healing exception, evaluated against the *current*
    # offline mask so a healed replica goes back to immovable).
    movable = ctx.movable[c.p, c.r] | state.offline[c.p, c.r]
    move_ok = (movable
               & (slot_broker == c.src)
               & ctx.dest_allowed[c.dst]
               & ~hosts_dst
               & (c.dst != c.src)
               # relocating the leader replica implies moving leadership too
               & jnp.where(c.r == 0, ctx.leader_dest_allowed[c.dst], True))

    lead_ok = ((c.r > 0)
               & ctx.leadership_movable[c.p]
               & (state.rb[c.p, 0] == c.src)
               & (slot_broker == c.dst)
               & ctx.leader_dest_allowed[c.dst]
               & ~state.offline[c.p, c.r])   # offline replica can't lead

    # Swap: (p, r) on src trades with (p2, r2) on dst. Both brokers must be
    # allowed destinations, neither partition may already have a replica on
    # the incoming broker, and a leader slot may only land where leadership
    # is allowed.
    row2 = state.rb[c.p2]                                        # [N, R]
    hosts_src2 = (row2 == c.src[..., None]).any(axis=-1)
    movable2 = ctx.movable[c.p2, c.r2] | state.offline[c.p2, c.r2]
    swap_ok = (movable
               & movable2
               & (c.p != c.p2)
               & (slot_broker == c.src)
               & (state.rb[c.p2, c.r2] == c.dst)
               & (c.src != c.dst)
               & ctx.dest_allowed[c.dst]
               & ctx.dest_allowed[c.src]
               & ~hosts_dst
               & ~hosts_src2
               & jnp.where(c.r == 0, ctx.leader_dest_allowed[c.dst], True)
               & jnp.where(c.r2 == 0, ctx.leader_dest_allowed[c.src], True))

    is_lead = c.kind == MOVE_LEADERSHIP
    return c.valid & jnp.where(is_move, move_ok,
                               jnp.where(is_lead, lead_ok, swap_ok))


# ---------------------------------------------------------------------------
# Applying candidates (the pure relocateReplica / relocateLeadership / swap)
# ---------------------------------------------------------------------------

def apply_group(state: SearchState, ctx: SearchContext, c: Candidates,
                do: jax.Array) -> SearchState:
    """Apply a *partition-disjoint group* of candidates at once (vectorized).

    Precondition (arranged by the engine's pending-set rounds): among
    candidates with ``do=True``, all partition rows (``p`` and swap
    counterpart ``p2``) are distinct — so every replica-slot row is written
    by at most one candidate. Sources and destinations MAY be shared freely:
    broker aggregates are updated with scatter-*adds*, which stay exact
    under any amount of src/dst sharing (collective bound overshoot is the
    engine's guard problem, not a correctness issue here). Plain scatters
    replace the reference's one-mutation-at-a-time
    ``relocateReplica``/``relocateLeadership`` calls.
    """
    p, r = c.p, c.r
    is_move = (c.kind == MOVE_INTER_BROKER) & do
    is_lead = (c.kind == MOVE_LEADERSHIP) & do
    is_swap = (c.kind == MOVE_SWAP) & do

    rb, pos, off = state.rb, state.pos, state.offline
    # Non-applied candidates may share a partition row with an applied one
    # (they sit in other groups / failed re-validation); their writes are
    # routed out of bounds and dropped so they cannot clobber real updates
    # with stale gathered values.
    P = rb.shape[0]
    pw = jnp.where(do, p, P)
    cur_slot = rb[p, r]
    cur0 = rb[p, 0]
    # Slot r: move/swap writes dst; leadership swaps in the old leader broker.
    new_slot = jnp.where(is_move | is_swap, c.dst, cur0)
    # Slot 0: leadership swaps in slot r's broker; a *leader-replica* move or
    # swap (r == 0) must also land in slot 0 or the second scatter would undo
    # it.
    new0 = jnp.where(is_lead, cur_slot,
                     jnp.where((is_move | is_swap) & (r == 0), c.dst, cur0))
    rb = (rb.at[pw, r].set(new_slot, mode="drop")
          .at[pw, 0].set(new0, mode="drop"))
    # Swap counterpart: replica (p2, r2) travels to src. p2 rows are distinct
    # from every applied candidate's p row within a group (engine grouping).
    p2w = jnp.where(is_swap, c.p2, P)
    rb = rb.at[p2w, c.r2].set(c.src, mode="drop")

    pos_r, pos_0 = pos[p, r], pos[p, 0]
    pos = (pos.at[pw, r].set(jnp.where(is_lead, pos_0, pos_r), mode="drop")
           .at[pw, 0].set(jnp.where(is_lead, pos_r, pos_0), mode="drop"))

    off_r, off_0 = off[p, r], off[p, 0]
    new_off_r = jnp.where(is_move | is_swap, False,
                          jnp.where(is_lead, off_0, off_r))
    new_off_0 = jnp.where(is_lead, off_r,
                          jnp.where((is_move | is_swap) & (r == 0), False,
                                    off_0))
    off = (off.at[pw, r].set(new_off_r, mode="drop")
           .at[pw, 0].set(new_off_0, mode="drop")
           .at[p2w, c.r2].set(False, mode="drop"))

    # Aggregates: zero deltas for non-applied candidates make their scatter
    # contributions no-ops, so no sentinel routing is needed.
    dof = do[:, None]
    util = (state.util.at[c.src].add(jnp.where(dof, c.d_util_src, 0.0))
            .at[c.dst].add(jnp.where(dof, c.d_util_dst, 0.0)))
    dcnt = jnp.where(is_move, c.d_cnt, 0)
    counts = (state.replica_count.at[c.src].add(-dcnt)
              .at[c.dst].add(dcnt))
    dlead = jnp.where(do, c.d_lead, 0)
    leaders = (state.leader_count.at[c.src].add(-dlead)
               .at[c.dst].add(dlead))
    dpot = jnp.where(is_move | is_swap, c.d_pot, 0.0)
    potential = (state.potential_nw_out.at[c.src].add(-dpot)
                 .at[c.dst].add(dpot))
    dlni = jnp.where(do, c.d_lni, 0.0)
    lni = (state.leader_nw_in.at[c.src].add(-dlni)
           .at[c.dst].add(dlni))

    topic_counts = state.topic_counts
    if topic_counts is not None:
        B1 = state.util.shape[0]
        t = ctx.partition_topic[p]
        tc_delta = jnp.where(is_move | is_swap, 1, 0)
        flat = topic_counts.reshape(-1)
        flat = (flat.at[t * B1 + c.src].add(-tc_delta)
                .at[t * B1 + c.dst].add(tc_delta))
        # Swap counterpart topic travels the other way.
        t2 = ctx.partition_topic[c.p2]
        tc2 = jnp.where(is_swap, 1, 0)
        flat = (flat.at[t2 * B1 + c.dst].add(-tc2)
                .at[t2 * B1 + c.src].add(tc2))
        topic_counts = flat.reshape(topic_counts.shape)

    topic_leader_counts = state.topic_leader_counts
    if topic_leader_counts is not None:
        B1 = state.util.shape[0]
        t1 = ctx.partition_topic[p]
        t2 = ctx.partition_topic[c.p2]
        # Leadership of p lands on dst for: leadership transfers, and
        # leader-replica (r == 0) moves/swaps. Swap counterparts with
        # r2 == 0 haul p2's leadership to src.
        d1 = jnp.where(is_lead | ((is_move | is_swap) & (r == 0)), 1, 0)
        d2 = jnp.where(is_swap & (c.r2 == 0), 1, 0)
        flat = topic_leader_counts.reshape(-1)
        flat = (flat.at[t1 * B1 + c.src].add(-d1)
                .at[t1 * B1 + c.dst].add(d1)
                .at[t2 * B1 + c.dst].add(-d2)
                .at[t2 * B1 + c.src].add(d2))
        topic_leader_counts = flat.reshape(topic_leader_counts.shape)

    return state.replace(rb=rb, pos=pos, offline=off, util=util,
                         replica_count=counts, leader_count=leaders,
                         potential_nw_out=potential, leader_nw_in=lni,
                         topic_counts=topic_counts,
                         topic_leader_counts=topic_leader_counts,
                         moves_applied=state.moves_applied
                         + do.sum(dtype=jnp.int32))


def to_model(state: SearchState, template: FlatClusterModel) -> FlatClusterModel:
    """Re-wrap the optimized assignment as a FlatClusterModel. ``pos`` IS
    the per-slot preferred-order position, so writing it back keeps
    preferred-leader drift readable from (and re-optimizable on) the final
    model."""
    return template.replace(replica_broker=state.rb,
                            replica_offline=state.offline,
                            replica_pref_pos=state.pos)
