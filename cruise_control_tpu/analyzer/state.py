"""Search state: the analyzer's device-resident view of the cluster.

The reference mutates a ``ClusterModel`` object graph in place while goals
run (``relocateReplica`` ``ClusterModel.java:380``). Here the optimization
state is a pytree of arrays with *incrementally maintained* broker
aggregates: applying a move touches two rows of each aggregate instead of
re-reducing the whole model, which is what makes scoring thousands of
candidate actions per step cheap on the MXU-adjacent vector units.

Terminology:
- ``B1 = padded_brokers + 1``: broker-indexed arrays carry one trailing
  sentinel row so scatter-updates for empty replica slots land in a discard
  row (same trick as ``model/flat.py``).
- A *candidate* is one potential balancing action (ref
  ``BalancingAction.java:20``), represented as a struct-of-arrays so the
  whole batch is scored with elementwise vector math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..core.resources import NUM_RESOURCES, Resource
from ..model.flat import (MOVE_INTER_BROKER, MOVE_LEADERSHIP, FlatClusterModel,
                          replica_loads)

# Metric selectors: which per-broker aggregate a goal balances/caps.
METRIC_CPU = ("util", Resource.CPU)
METRIC_NW_IN = ("util", Resource.NW_IN)
METRIC_NW_OUT = ("util", Resource.NW_OUT)
METRIC_DISK = ("util", Resource.DISK)
METRIC_REPLICA_COUNT = ("count", None)
METRIC_LEADER_COUNT = ("leaders", None)
METRIC_POTENTIAL_NW_OUT = ("potential", None)
METRIC_LEADER_NW_IN = ("leader_nw_in", None)


@struct.dataclass
class SearchContext:
    """Immutable per-optimization inputs (loads, topology, option masks)."""

    leader_load: jax.Array        # f32[P, 4]
    follower_load: jax.Array      # f32[P, 4]
    partition_topic: jax.Array    # i32[P]
    partition_valid: jax.Array    # bool[P]
    broker_capacity: jax.Array    # f32[B1, 4] (sentinel row: 0)
    broker_rack: jax.Array        # i32[B1] (sentinel: -1)
    broker_alive: jax.Array       # bool[B1]
    broker_valid: jax.Array       # bool[B1]
    dest_allowed: jax.Array       # bool[B1] — may receive replicas
    leader_dest_allowed: jax.Array  # bool[B1] — may receive leadership
    movable: jax.Array            # bool[P, R] — replica may be relocated
    leadership_movable: jax.Array  # bool[P] — leadership may be transferred

    @property
    def num_brokers_padded(self) -> int:
        return self.broker_capacity.shape[0] - 1


@struct.dataclass
class SearchState:
    """Mutable (functionally-updated) optimization state."""

    rb: jax.Array              # i32[P, R] replica -> broker (sentinel = empty)
    pos: jax.Array             # i32[P, R] original assignment position of the
    #                            replica in this slot (slot 0 = current leader;
    #                            pos tracks Kafka's preferred-leader order)
    offline: jax.Array         # bool[P, R] replica must move (dead broker/disk)
    util: jax.Array            # f32[B1, 4]
    replica_count: jax.Array   # i32[B1]
    leader_count: jax.Array    # i32[B1]
    potential_nw_out: jax.Array  # f32[B1]
    leader_nw_in: jax.Array    # f32[B1]
    topic_counts: jax.Array | None  # i32[T, B1] or None (only when a
    #                                 topic-scoped goal is in the chain)
    moves_applied: jax.Array   # i32 scalar — total actions applied so far


@struct.dataclass
class Candidates:
    """A batch of N candidate balancing actions (struct-of-arrays)."""

    p: jax.Array            # i32[N] partition row
    r: jax.Array            # i32[N] replica slot
    src: jax.Array          # i32[N] source broker (for leadership: slot-0 broker)
    dst: jax.Array          # i32[N] destination broker
    kind: jax.Array         # i32[N] MOVE_INTER_BROKER | MOVE_LEADERSHIP
    valid: jax.Array        # bool[N] generated-slot validity
    must: jax.Array         # bool[N] moves an offline replica (mandatory)
    d_util_src: jax.Array   # f32[N, 4]
    d_util_dst: jax.Array   # f32[N, 4]
    d_cnt: jax.Array        # i32[N] replica-count delta magnitude (0/1)
    d_lead: jax.Array       # i32[N] leader-count delta magnitude (0/1)
    d_pot: jax.Array        # f32[N] potential-NW_OUT delta magnitude
    d_lni: jax.Array        # f32[N] leader-NW_IN delta magnitude


def init_state(model: FlatClusterModel, *, with_topic_counts: int | None = None
               ) -> SearchState:
    """Build the search state from a flat model (one full reduction; all
    subsequent updates are incremental)."""
    P, R = model.replica_broker.shape
    B = model.num_brokers_padded
    B1 = B + 1
    # Fresh buffers: the engine's passes donate the state, and the caller's
    # model must survive to be diffed against the optimized placement.
    rb = jnp.array(model.replica_broker, copy=True)
    loads = replica_loads(model)                                   # [P, R, 4]
    flat_idx = rb.reshape(-1)
    util = jnp.zeros((B1, NUM_RESOURCES), jnp.float32)
    util = util.at[flat_idx].add(loads.reshape(-1, NUM_RESOURCES))
    util = util.at[B].set(0.0)

    valid = model.replica_valid
    counts = jnp.zeros((B1,), jnp.int32).at[flat_idx].add(1).at[B].set(0)
    leaders = jnp.zeros((B1,), jnp.int32).at[rb[:, 0]].add(
        jnp.where(model.partition_valid, 1, 0)).at[B].set(0)
    pot = jnp.where(valid, model.leader_load[:, None, Resource.NW_OUT], 0.0)
    potential = jnp.zeros((B1,), jnp.float32).at[flat_idx].add(
        pot.reshape(-1)).at[B].set(0.0)
    lni = jnp.where(model.partition_valid,
                    model.leader_load[:, Resource.NW_IN], 0.0)
    leader_nw_in = jnp.zeros((B1,), jnp.float32).at[rb[:, 0]].add(lni).at[B].set(0.0)

    topic_counts = None
    if with_topic_counts is not None:
        T = with_topic_counts
        idx = model.partition_topic[:, None] * B1 + rb                # [P, R]
        tc = jnp.zeros((T * B1,), jnp.int32).at[idx.reshape(-1)].add(
            jnp.where(valid, 1, 0).reshape(-1), mode="drop")
        topic_counts = tc.reshape(T, B1).at[:, B].set(0)

    pos = jnp.tile(jnp.arange(R, dtype=jnp.int32)[None, :], (P, 1))
    # A replica hosted on a dead (or padding) broker is offline whether or
    # not the model builder flagged it (ref Replica.isCurrentOffline derives
    # from broker state) — offline replicas are the must-move set that
    # drives self-healing.
    alive1 = jnp.concatenate([model.broker_alive & model.broker_valid,
                              jnp.zeros((1,), bool)])
    offline = model.replica_offline | (valid & ~alive1[rb])
    return SearchState(rb=rb, pos=pos, offline=offline,
                       util=util, replica_count=counts, leader_count=leaders,
                       potential_nw_out=potential, leader_nw_in=leader_nw_in,
                       topic_counts=topic_counts,
                       moves_applied=jnp.zeros((), jnp.int32))


def build_context(model: FlatClusterModel, *,
                  excluded_partitions: jax.Array | None = None,
                  excluded_brokers_for_replica_move: jax.Array | None = None,
                  excluded_brokers_for_leadership: jax.Array | None = None
                  ) -> SearchContext:
    """Assemble the immutable context. Exclusion masks follow
    ``OptimizationOptions`` semantics (ref analyzer/OptimizationOptions.java):
    replicas of excluded topics never move *unless offline*; excluded brokers
    never receive replicas / leadership."""
    P, R = model.replica_broker.shape
    B = model.num_brokers_padded

    def _pad1(arr, fill):
        return jnp.concatenate([arr, jnp.full((1,) + arr.shape[1:], fill,
                                              arr.dtype)], axis=0)

    alive = _pad1(model.broker_alive & model.broker_valid, False)
    bvalid = _pad1(model.broker_valid, False)
    capacity = _pad1(model.broker_capacity, 0.0)
    rack = _pad1(model.broker_rack, -1)

    dest = alive
    if excluded_brokers_for_replica_move is not None:
        dest = dest & ~_pad1(excluded_brokers_for_replica_move, True)
    lead_dest = alive & ~_pad1(model.broker_demoted, True)
    if excluded_brokers_for_leadership is not None:
        lead_dest = lead_dest & ~_pad1(excluded_brokers_for_leadership, True)

    # ``movable`` is the *static* exclusion mask: real slot, topic not
    # excluded. The offline exception ("excluded topics still heal") is
    # dynamic — an offline replica becomes immovable again once relocated —
    # so it is resolved against ``state.offline`` in base_legality/propose,
    # not frozen here.
    slot_valid = model.replica_valid
    if excluded_partitions is None:
        excluded_partitions = jnp.zeros((P,), bool)
    movable = slot_valid & ~excluded_partitions[:, None]
    leadership_movable = model.partition_valid & ~excluded_partitions

    return SearchContext(
        leader_load=model.leader_load, follower_load=model.follower_load,
        partition_topic=model.partition_topic,
        partition_valid=model.partition_valid,
        broker_capacity=capacity, broker_rack=rack, broker_alive=alive,
        broker_valid=bvalid, dest_allowed=dest,
        leader_dest_allowed=lead_dest, movable=movable,
        leadership_movable=leadership_movable)


# ---------------------------------------------------------------------------
# Metric access (the vectorized Load.expectedUtilizationFor of the goals)
# ---------------------------------------------------------------------------

def metric_values(state: SearchState, metric) -> jax.Array:
    """f32[B1] — current value of the balanced metric on every broker."""
    which, res = metric
    if which == "util":
        return state.util[:, int(res)]
    if which == "count":
        return state.replica_count.astype(jnp.float32)
    if which == "leaders":
        return state.leader_count.astype(jnp.float32)
    if which == "potential":
        return state.potential_nw_out
    if which == "leader_nw_in":
        return state.leader_nw_in
    raise ValueError(f"unknown metric {metric}")


def metric_deltas(cand: Candidates, metric):
    """(d_src, d_dst) f32[N] — metric change on source/destination rows."""
    which, res = metric
    if which == "util":
        return cand.d_util_src[..., int(res)], cand.d_util_dst[..., int(res)]
    if which == "count":
        d = cand.d_cnt.astype(jnp.float32)
        return -d, d
    if which == "leaders":
        d = cand.d_lead.astype(jnp.float32)
        return -d, d
    if which == "potential":
        return -cand.d_pot, cand.d_pot
    if which == "leader_nw_in":
        return -cand.d_lni, cand.d_lni
    raise ValueError(f"unknown metric {metric}")


# ---------------------------------------------------------------------------
# Candidate construction
# ---------------------------------------------------------------------------

def make_move_candidates(state: SearchState, ctx: SearchContext,
                         p: jax.Array, r: jax.Array, dst: jax.Array,
                         valid: jax.Array) -> Candidates:
    """Inter-broker replica relocation candidates (ref ActionType
    INTER_BROKER_REPLICA_MOVEMENT)."""
    src = state.rb[p, r]
    is_leader = (r == 0)
    load = jnp.where(is_leader[..., None], ctx.leader_load[p],
                     ctx.follower_load[p])                       # [N, 4]
    d_pot = ctx.leader_load[p, Resource.NW_OUT]
    d_lni = jnp.where(is_leader, ctx.leader_load[p, Resource.NW_IN], 0.0)
    kind = jnp.full(p.shape, MOVE_INTER_BROKER, jnp.int32)
    return Candidates(
        p=p, r=r, src=src, dst=dst, kind=kind, valid=valid,
        must=state.offline[p, r] & valid,
        d_util_src=-load, d_util_dst=load,
        d_cnt=jnp.ones(p.shape, jnp.int32),
        d_lead=is_leader.astype(jnp.int32),
        d_pot=d_pot, d_lni=d_lni)


def make_leadership_candidates(state: SearchState, ctx: SearchContext,
                               p: jax.Array, r: jax.Array,
                               valid: jax.Array) -> Candidates:
    """Leadership transfer candidates: slot ``r`` becomes the leader (ref
    ActionType LEADERSHIP_MOVEMENT; model swap per relocateLeadership)."""
    src = state.rb[p, 0]
    dst = state.rb[p, r]
    dload = ctx.leader_load[p] - ctx.follower_load[p]            # [N, 4]
    kind = jnp.full(p.shape, MOVE_LEADERSHIP, jnp.int32)
    zero = jnp.zeros(p.shape, jnp.float32)
    return Candidates(
        p=p, r=r, src=src, dst=dst, kind=kind, valid=valid,
        must=jnp.zeros(p.shape, bool),
        d_util_src=-dload, d_util_dst=dload,
        d_cnt=jnp.zeros(p.shape, jnp.int32),
        d_lead=jnp.ones(p.shape, jnp.int32),
        d_pot=zero, d_lni=ctx.leader_load[p, Resource.NW_IN])


def concat_candidates(a: Candidates, b: Candidates) -> Candidates:
    return jax.tree.map(lambda x, y: jnp.concatenate([x, y], axis=0), a, b)


def candidate_at(cand: Candidates, i: jax.Array) -> Candidates:
    """Select candidate ``i`` (scalar leaves) — used by the apply scan."""
    return jax.tree.map(lambda x: x[i], cand)


# ---------------------------------------------------------------------------
# Legality (base constraints every action must satisfy; goal acceptance is
# layered on top by the engine)
# ---------------------------------------------------------------------------

def base_legality(state: SearchState, ctx: SearchContext,
                  c: Candidates) -> jax.Array:
    """bool[N]. Re-evaluable against a *changed* state: includes staleness
    checks (slot still holds the broker captured at proposal time), so the
    apply scan can safely re-test each candidate after earlier applies."""
    row = state.rb[c.p]                                          # [N, R]
    slot_broker = state.rb[c.p, c.r]
    is_move = c.kind == MOVE_INTER_BROKER

    hosts_dst = (row == c.dst[..., None]).any(axis=-1)
    # Offline replicas are movable even when their topic is excluded from
    # rebalancing (self-healing exception, evaluated against the *current*
    # offline mask so a healed replica goes back to immovable).
    movable = ctx.movable[c.p, c.r] | state.offline[c.p, c.r]
    move_ok = (movable
               & (slot_broker == c.src)
               & ctx.dest_allowed[c.dst]
               & ~hosts_dst
               & (c.dst != c.src)
               # relocating the leader replica implies moving leadership too
               & jnp.where(c.r == 0, ctx.leader_dest_allowed[c.dst], True))

    lead_ok = ((c.r > 0)
               & ctx.leadership_movable[c.p]
               & (state.rb[c.p, 0] == c.src)
               & (slot_broker == c.dst)
               & ctx.leader_dest_allowed[c.dst]
               & ~state.offline[c.p, c.r])   # offline replica can't lead

    return c.valid & jnp.where(is_move, move_ok, lead_ok)


# ---------------------------------------------------------------------------
# Applying one candidate (the pure relocateReplica / relocateLeadership)
# ---------------------------------------------------------------------------

def apply_candidate(state: SearchState, ctx: SearchContext,
                    c: Candidates) -> SearchState:
    """Apply a single (scalar) candidate, updating assignment + aggregates."""
    p, r, src, dst = c.p, c.r, c.src, c.dst
    is_move = c.kind == MOVE_INTER_BROKER

    # Assignment update: move writes dst into the slot; leadership swaps
    # slots 0 <-> r (and their pos/offline companions).
    rb, pos, off = state.rb, state.pos, state.offline

    def do_move(args):
        rb, pos, off = args
        return (rb.at[p, r].set(dst), pos, off.at[p, r].set(False))

    def do_lead(args):
        rb, pos, off = args
        b0, br = rb[p, 0], rb[p, r]
        rb = rb.at[p, 0].set(br).at[p, r].set(b0)
        p0, pr = pos[p, 0], pos[p, r]
        pos = pos.at[p, 0].set(pr).at[p, r].set(p0)
        o0, orr = off[p, 0], off[p, r]
        off = off.at[p, 0].set(orr).at[p, r].set(o0)
        return (rb, pos, off)

    rb, pos, off = jax.lax.cond(is_move, do_move, do_lead, (rb, pos, off))

    util = state.util.at[src].add(c.d_util_src).at[dst].add(c.d_util_dst)
    dcnt = jnp.where(is_move, c.d_cnt, 0)
    counts = state.replica_count.at[src].add(-dcnt).at[dst].add(dcnt)
    leaders = state.leader_count.at[src].add(-c.d_lead).at[dst].add(c.d_lead)
    dpot = jnp.where(is_move, c.d_pot, 0.0)
    potential = state.potential_nw_out.at[src].add(-dpot).at[dst].add(dpot)
    lni = state.leader_nw_in.at[src].add(-c.d_lni).at[dst].add(c.d_lni)

    topic_counts = state.topic_counts
    if topic_counts is not None:
        t = ctx.partition_topic[p]
        tc_delta = jnp.where(is_move, 1, 0)
        topic_counts = (topic_counts.at[t, src].add(-tc_delta)
                        .at[t, dst].add(tc_delta))

    return state.replace(rb=rb, pos=pos, offline=off, util=util,
                         replica_count=counts, leader_count=leaders,
                         potential_nw_out=potential, leader_nw_in=lni,
                         topic_counts=topic_counts,
                         moves_applied=state.moves_applied + 1)


def to_model(state: SearchState, template: FlatClusterModel) -> FlatClusterModel:
    """Re-wrap the optimized assignment as a FlatClusterModel."""
    return template.replace(replica_broker=state.rb,
                            replica_offline=state.offline)
