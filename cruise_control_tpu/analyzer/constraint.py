"""Balancing constraint + search hyper-parameters.

Rebuild of ``analyzer/BalancingConstraint.java`` (ref :350): the per-resource
balance margins and capacity thresholds every goal kernel reads. Defaults
mirror ``config/constants/AnalyzerConfig.java`` (balance thresholds 1.10
``:58-103``, topic replica 3.00/min-gap 2/max-gap 40 ``:112-131``, capacity
thresholds CPU 0.7 / disk 0.8 / network 0.8 ``:141-169``, max replicas per
broker 10000 ``:225``).

Unlike the reference (an object threaded through every goal), these are plain
frozen dataclasses of Python floats: they are *trace-time constants* baked
into the compiled search kernels, so changing a threshold recompiles (rare)
while re-running with new loads does not (common).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Tuple

from ..core.resources import Resource


@dataclass(frozen=True)
class BalancingConstraint:
    # avg * threshold = balance upper limit; avg * (2 - threshold) = lower.
    resource_balance_threshold: Tuple[float, float, float, float] = (
        1.10, 1.10, 1.10, 1.10)  # CPU, NW_IN, NW_OUT, DISK
    replica_balance_threshold: float = 1.10
    leader_replica_balance_threshold: float = 1.10
    topic_replica_balance_threshold: float = 3.00
    topic_replica_balance_min_gap: int = 2
    topic_replica_balance_max_gap: int = 40
    # capacity * threshold = usable capacity ceiling.
    capacity_threshold: Tuple[float, float, float, float] = (
        0.7, 0.8, 0.8, 0.8)  # CPU, NW_IN, NW_OUT, DISK
    max_replicas_per_broker: int = 10_000
    # LeaderBytesInDistributionGoal reuses the NW_IN balance threshold.
    # Provision verdicts (ref AnalyzerConfig overprovisioned.min.brokers and
    # ResourceDistributionGoal's low.utilization.threshold — 0.0 disables
    # over-provisioning detection, the reference default).
    overprovisioned_min_brokers: int = 3
    #: ref overprovisioned.max.replicas.per.broker: a shrink verdict may
    #: not leave any broker above this replica count.
    overprovisioned_max_replicas_per_broker: int = 1500
    #: ref overprovisioned.min.extra.racks: keep enough brokers to span
    #: max-RF + this many racks (rack-aware placement headroom).
    overprovisioned_min_extra_racks: int = 2
    low_utilization_threshold: Tuple[float, float, float, float] = (
        0.0, 0.0, 0.0, 0.0)
    #: ref min.topic.leaders.per.broker (MinTopicLeadersPerBrokerGoal)
    min_topic_leaders_per_broker: int = 1
    #: ref topics.with.min.leaders.per.broker — fnmatch pattern of topics
    #: the leader minimum applies to ("" = none, the reference default)
    topics_with_min_leaders_per_broker: str = ""

    def balance_threshold(self, resource: Resource) -> float:
        return self.resource_balance_threshold[int(resource)]

    def cap_threshold(self, resource: Resource) -> float:
        return self.capacity_threshold[int(resource)]

    def with_overrides(self, **kwargs) -> "BalancingConstraint":
        return replace(self, **kwargs)

    def for_goal_violation_detection(self, multiplier: float
                                     ) -> "BalancingConstraint":
        """Distribution thresholds relaxed for violation DETECTION (ref
        goal.violation.distribution.threshold.multiplier;
        ReplicaDistributionAbstractGoal.adjustedBalancePercentage:
        ``balancePercentage * multiplier`` when the run is triggered by
        the goal-violation detector) — detection fires only beyond the
        relaxed band, so a cluster balanced to just-inside the serving
        threshold doesn't flap between violated and fixed."""
        if multiplier == 1.0:
            return self
        m = multiplier
        return replace(
            self,
            resource_balance_threshold=tuple(
                t * m for t in self.resource_balance_threshold),
            replica_balance_threshold=self.replica_balance_threshold * m,
            leader_replica_balance_threshold=(
                self.leader_replica_balance_threshold * m),
            topic_replica_balance_threshold=(
                self.topic_replica_balance_threshold * m))


@dataclass(frozen=True)
class SearchConfig:
    """Batched-search hyper-parameters (no reference equivalent — this is the
    TPU replacement for the greedy loop's implicit schedule).

    Per iteration the engine short-lists ``num_replica_candidates`` replicas
    (by goal-specific priority, ``lax.top_k`` over the flattened [P, R] grid)
    and ``num_dest_candidates`` destination brokers, scores the full cross
    product at once, and applies up to ``apply_per_iter`` non-conflicting
    improving moves via a sequential re-checked scan.
    """

    num_replica_candidates: int = 256
    num_dest_candidates: int = 16
    #: heavy-for-light swap pairs proposed per iteration by distribution
    #: goals (ref ResourceDistributionGoal's swap sub-strategies); swaps are
    #: count-neutral, escaping replica-count lexicographic dead-ends.
    num_swap_candidates: int = 128
    apply_per_iter: int = 256
    #: bulk-drain prologue (interval goals with replica-move actions): each
    #: round sheds up to this many excess replicas into receiver budgets
    #: computed by prefix-sum — conflict-free by construction, so the whole
    #: batch applies in one scatter without the [M, M] conflict machinery.
    #: The budgets bound aggregate intake analytically; per-candidate
    #: legality/acceptance still filters individually.
    drain_batch: int = 16384
    #: max bulk-drain rounds before the fine-grained loop takes over (the
    #: loop also exits early once a round applies almost nothing).
    drain_rounds: int = 12
    #: conflict-resolution rounds per iteration; candidates still blocked
    #: after this many rounds are deferred to the next iteration.
    apply_groups: int = 64
    max_iters_per_goal: int = 256
    #: consecutive zero-apply iterations (each with fresh tie-break noise)
    #: before a goal pass is declared converged.
    stall_patience: int = 5
    #: extra host-side repetitions of the whole goal chain when residual
    #: violations remain — later goals' accepted actions may drift earlier
    #: goals slightly (the acceptance escape clauses allow bounded
    #: regressions, ref ResourceDistributionGoal.actionAcceptance), and a
    #: converged goal costs one violation read (the engine's lax.cond
    #: early exit skips its candidate loop entirely).
    polish_passes: int = 2
    #: run the whole goal chain as ONE jitted program (single device
    #: dispatch + single host sync per optimize) instead of one jit per
    #: goal. Worth it when per-dispatch transport latency dominates pass
    #: compute — small models served over a tunneled device (the 3-broker
    #: demo, 1 req/s self-healing replans). Trade-offs: one big XLA
    #: compile instead of parallel per-pass compiles, and per-goal
    #: wall-clock is no longer observable (durations are attributed
    #: proportionally to iteration counts).
    fused_chain: bool = False
    epsilon: float = 1e-6
    # Tie-break noise magnitude relative to priority scale (deterministic,
    # PRNG-keyed; keeps tests reproducible while diversifying candidates).
    noise_scale: float = 1e-3

    def scaled_for(self, num_partitions: int, num_brokers: int) -> "SearchConfig":
        """Clamp candidate pool sizes for tiny models (tests, demo clusters)."""
        k = min(self.num_replica_candidates, max(8, num_partitions))
        d = min(self.num_dest_candidates, max(2, num_brokers))
        s = min(self.num_swap_candidates, k)
        m = min(self.apply_per_iter, k + s)
        db = min(self.drain_batch, max(8, num_partitions))
        return replace(self, num_replica_candidates=k, num_dest_candidates=d,
                       num_swap_candidates=s, apply_per_iter=m,
                       drain_batch=db)


@dataclass(frozen=True)
class PopulationConfig:
    """Multi-objective population search over K candidate plans
    (``search.population.*`` server config; parallel/population.py).

    A population of K plans evolves in one jitted program: every member
    runs the goal-chain walk under its own PRNG stream, between polish
    generations the population is scored JOINTLY over all goals (the
    violation stack, scale-normalized) and survivors reseed the losers,
    and the served plan is the multi-objective winner. Member 0 is the
    *anchor*: it always runs the exact sequential schedule (same key
    stream, never adopts another member's state), so K=1 degenerates to
    the sequential chain walk bit-for-bit and the winner can never score
    worse than the sequential plan under the configured objective.
    Frozen: the whole config is part of the compiled program's identity.
    """

    #: population size K; 0 = population search off. Sizes round up to
    #: the next power of two (the K-bucket — nearby sizes share one
    #: compiled program; the extra slots run as additional explorers).
    size: int = 0
    #: joint objective across goals: "weighted" = scale-normalized
    #: weighted sum (hard goals weighted by hard_weight), "pareto" =
    #: non-dominated (dominance-count) rank, weighted sum as tie-break.
    objective: str = "weighted"
    #: weight multiplier on hard goals' normalized violations in the
    #: weighted objective — large enough that any hard residual dominates
    #: every soft trade-off.
    hard_weight: float = 1000.0
    #: per-move penalty added to the weighted objective (0 = plans are
    #: judged on violations alone); biases selection toward plans that
    #: reach the same stacks with fewer executor actions.
    move_weight: float = 0.0
    #: fraction of the population that survives each generation (the
    #: truncation-selection cut). Effective count is clamped to
    #: [1, K-1]: slot 0 is force-anchored to the sequential lineage, so
    #: only K-1 slots are free for survivors
    #: (parallel/population.n_survivors).
    survivor_fraction: float = 0.5

    @property
    def enabled(self) -> bool:
        return self.size >= 1
