"""Search-hyperparameter auto-tuning: learned, scenario-aware schedules
per shape bucket.

``SearchConfig`` is a fixed schedule, and commit 867dbc1 measured why
that leaves money on the table: the best swap-candidate batch at
1K x 200K (512, -26% warm) actively hurts at 10K x 1M (leadership
candidates crowded out, iterations tripled). The right schedule is
*scenario-dependent* — a function of the cluster's shape — which is a
hyperparameter-optimization problem (PAPERS.md: "Tuning ... with
Bayesian Optimization", arxiv 1612.00383). This module provides

- :class:`SuccessiveHalvingTuner`: seeded random sampling over the
  tunable ``SearchConfig`` fields plus successive halving — evaluate the
  whole candidate pool at a small budget, keep the faster feasible half,
  re-evaluate survivors at a larger budget, repeat. The bandit-style
  successive-halving rung structure is the standard cheap stand-in for a
  full Gaussian-process Bayesian loop (same multi-fidelity idea, no
  surrogate to fit); the evaluator is injected, so the tuner itself is
  pure host code. The incumbent (the base config) is always in the pool
  and never eliminated — tuning can only improve on the shipped
  schedule, and a quality/move-count constraint relative to the
  incumbent keeps a "fast because it gave up" config infeasible;

- :class:`TunedConfigStore`: tuned field overrides persisted per *shape
  bucket* (power-of-two broker x partition buckets — geometric, so a
  long-lived process holds a logarithmic number of tuned configs),
  versioned like the ``.jax_cache/v<N>`` discipline
  (``TUNED_CONFIG_VERSION`` — a SearchConfig field change bumps it and
  retires stale files predictably). ``TpuGoalOptimizer._prepare`` (and
  the fleet's ``_prepare_member``) applies the store BEFORE the
  tiny-model clamp, so every model in a bucket resolves to ONE scaled
  config — one compiled-chain key, zero warm recompiles within the
  bucket, and in fleet mode the tuned config joins the dispatch-group
  key, splitting heterogeneously-tuned members into separate groups
  instead of silently running them under one schedule.

The tuner is driven by bench scenarios (``bench.py
run_multiobj_propose_bench`` / scenario 7), not the serving path: tuning
compiles one goal chain per candidate config, which is exactly the cost
the serving path must never pay.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field, replace

import numpy as np

from .constraint import SearchConfig

LOG = logging.getLogger(__name__)

#: Version of the persisted tuned-config format AND of the SearchConfig
#: field semantics the stored overrides assume. Bump when a tuned field
#: changes meaning — old files are then ignored (logged), mirroring the
#: .jax_cache/v<N> rule that a signature change retires stale entries
#: predictably instead of mixing them with fresh ones.
TUNED_CONFIG_VERSION = 1

#: The tunable SearchConfig fields and their sampling ranges: the
#: schedule knobs ISSUE/ROADMAP name — swap-batch size, walk length
#: (iteration cap), polish budget, candidate pool sizes, drain batch.
#: Everything else in SearchConfig is semantics (epsilon, fused mode),
#: not schedule, and stays fixed.
TUNABLE_FIELDS: dict[str, tuple[int, int]] = {
    "num_replica_candidates": (64, 4096),
    "num_dest_candidates": (4, 64),
    "num_swap_candidates": (32, 2048),
    "apply_per_iter": (64, 4096),
    "drain_batch": (1024, 65536),
    "max_iters_per_goal": (32, 1024),
    "polish_passes": (0, 3),
}


def plan_quality(result, hard_weight: float = 1000.0) -> float:
    """Scalar plan-quality score of an ``OptimizerResult`` (lower is
    better): the weighted joint objective over the final violation
    stacks — THE scoring convention shared by the tuner's feasibility
    test, the multiobj bench gates, and the population A/B tests. One
    definition so they can never silently score on different
    objectives."""
    from .engine import weighted_objective
    stacks = np.asarray([[g.violation_after for g in result.goal_results]])
    scales = np.asarray([g.scale for g in result.goal_results])
    hard = np.asarray([g.hard for g in result.goal_results])
    return float(np.asarray(weighted_objective(
        stacks, scales, hard, hard_weight=hard_weight))[0])


def shape_bucket(num_partitions: int, num_brokers: int,
                 regime: str | None = None) -> str:
    """Power-of-two shape bucket key, e.g. ``b128p32768`` — the
    granularity tuned configs persist at (shared with the population
    K-bucket rule via ``parallel.batching.pow2_bucket``). A traffic
    ``regime`` (workload/regime.py's vocabulary) qualifies the key —
    ``b128p32768@flash_crowd`` — so the continuous tuning loop persists
    one schedule per (shape, regime) pair; lookups fall back to the
    un-regimed bucket when the pair is untuned."""
    from ..parallel.batching import pow2_bucket
    base = f"b{pow2_bucket(num_brokers)}p{pow2_bucket(num_partitions)}"
    return f"{base}@{regime}" if regime else base


class TunedConfigStore:
    """Per-shape-bucket tuned ``SearchConfig`` overrides + trial history,
    persisted as one JSON file alongside the versioned XLA cache.

    Thread-safe, best-effort on IO: an unreadable/unwritable store file
    degrades to the base config (the optimizer must come up regardless —
    same contract as ``enable_compilation_cache``)."""

    def __init__(self, path: str | None = None):
        self.path = path or self.default_path()
        self._lock = threading.Lock()
        self._buckets: dict[str, dict] = {}
        self._load()

    @staticmethod
    def default_path() -> str:
        from ..utils.platform import DEFAULT_CACHE_DIR
        return os.path.join(DEFAULT_CACHE_DIR, "tuned",
                            f"v{TUNED_CONFIG_VERSION}",
                            "search_configs.json")

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        if data.get("version") != TUNED_CONFIG_VERSION:
            LOG.warning(
                "ignoring tuned search configs at %s: version %s != %d "
                "(stale format — re-tune to regenerate)",
                self.path, data.get("version"), TUNED_CONFIG_VERSION)
            return
        buckets = data.get("buckets")
        if isinstance(buckets, dict):
            self._buckets = buckets
            LOG.info("loaded tuned search configs for %d shape "
                     "bucket(s) from %s", len(buckets), self.path)

    def save(self) -> str | None:
        """Persist (best-effort). Returns the path written, or None."""
        with self._lock:
            # Snapshot INSIDE the lock: json.dump below iterates outside
            # it, and a concurrent record() replacing entries would blow
            # up mid-serialization (entry payloads are replaced
            # wholesale, never mutated in place, so a per-entry shallow
            # copy is a consistent snapshot).
            payload = {"version": TUNED_CONFIG_VERSION,
                       "buckets": {k: dict(v)
                                   for k, v in self._buckets.items()}}
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = f"{self.path}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
            return self.path
        except OSError as exc:
            LOG.warning("could not persist tuned search configs to %s: "
                        "%s", self.path, exc)
            return None

    def lookup(self, num_partitions: int, num_brokers: int, *,
               regime: str | None = None,
               fallback: bool = True) -> dict | None:
        """Tuned field overrides for this shape's bucket, or None.
        With a ``regime``, the regime-qualified entry wins; an untuned
        pair falls back to the un-regimed bucket (``fallback=False``
        disables that — the tuning loop's "has this pair been tuned"
        probe). Values are validated, not just keys: a corrupted or
        hand-edited store (string/negative/bool values) must DEGRADE to
        the base config with a warning — the class contract — not crash
        the first optimize at trace time."""
        bucket = shape_bucket(num_partitions, num_brokers, regime=regime)
        with self._lock:
            entry = self._buckets.get(bucket)
            if entry is None and regime and fallback:
                bucket = shape_bucket(num_partitions, num_brokers)
                entry = self._buckets.get(bucket)
        if not entry or not isinstance(entry.get("fields"), dict):
            return None
        fields, bad = {}, []
        for k, v in entry["fields"].items():
            if k not in TUNABLE_FIELDS:
                continue
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                bad.append(f"{k}={v!r}")
                continue
            fields[k] = v
        if bad:
            LOG.warning(
                "tuned search config %s[%s]: dropping invalid field "
                "value(s) %s (expected non-negative ints — re-tune to "
                "regenerate)", self.path, bucket, ", ".join(bad))
        return fields

    def apply(self, cfg: SearchConfig, num_partitions: int,
              num_brokers: int, *,
              regime: str | None = None) -> SearchConfig:
        """``cfg`` with this bucket's tuned overrides folded in (identity
        when the bucket is untuned; with a ``regime``, the qualified
        entry wins over the plain bucket). Callers apply this BEFORE
        ``scaled_for`` so the tiny-model clamp still bounds whatever the
        tuner picked."""
        fields = self.lookup(num_partitions, num_brokers, regime=regime)
        if not fields:
            return cfg
        return replace(cfg, **fields)

    def record(self, num_partitions: int, num_brokers: int,
               fields: dict, history: list | None = None,
               save: bool = True, regime: str | None = None) -> str:
        """Store tuned ``fields`` (a TUNABLE_FIELDS subset) for the
        shape's bucket — regime-qualified when ``regime`` is given —
        with the tuner's trial history; returns the bucket key."""
        unknown = set(fields) - set(TUNABLE_FIELDS)
        if unknown:
            raise ValueError(f"not tunable SearchConfig fields: "
                             f"{sorted(unknown)}")
        bucket = shape_bucket(num_partitions, num_brokers, regime=regime)
        with self._lock:
            self._buckets[bucket] = {
                "fields": dict(fields),
                "tunedAtMs": int(time.time() * 1000),
                "shapes": {"numPartitions": num_partitions,
                           "numBrokers": num_brokers},
                "regime": regime,
                "history": list(history or []),
            }
        if save:
            self.save()
        return bucket

    def to_json(self) -> dict:
        """The /devicestats ``tuning`` payload: per-bucket tuned fields
        and trial history."""
        with self._lock:
            return {"version": TUNED_CONFIG_VERSION, "path": self.path,
                    "buckets": {k: dict(v)
                                for k, v in self._buckets.items()}}

    def __len__(self) -> int:
        with self._lock:
            return len(self._buckets)


@dataclass
class Trial:
    """One tuner evaluation: candidate fields + measured outcome."""

    fields: dict
    rung: int
    wall_s: float
    quality: float
    moves: int
    feasible: bool
    incumbent: bool = False

    def to_json(self) -> dict:
        return {"fields": dict(self.fields), "rung": self.rung,
                "wallClockS": round(self.wall_s, 4),
                "quality": round(self.quality, 6), "moves": self.moves,
                "feasible": self.feasible, "incumbent": self.incumbent}


@dataclass
class SuccessiveHalvingTuner:
    """Random search + successive halving over ``TUNABLE_FIELDS``.

    ``evaluate(fields, rung, repeats) -> {"wall_s", "quality", "moves"}``
    is injected: it must build/run the candidate schedule and report the
    warm wall-clock (best of ``repeats``), a scalar plan-quality score
    (lower is better — the weighted joint objective over final violation
    stacks), and the move count. Rung r re-evaluates the surviving pool
    with ``r + 1`` repeats, so noise shrinks exactly where decisions
    tighten (the multi-fidelity trick of arxiv 1612.00383's
    budget-constrained loop, without a GP surrogate).

    Feasibility vs the incumbent: a candidate whose quality exceeds
    ``incumbent_quality * quality_tolerance + 1e-9`` or whose move count
    exceeds ``incumbent_moves * move_tolerance`` is ranked behind every
    feasible candidate regardless of speed — "fast because it gave up"
    never wins. The incumbent itself always survives, so ``tune``
    returns ``{}`` (keep the base schedule) when nothing beats it.
    """

    evaluate: object
    trials: int = 8
    rungs: int = 2
    seed: int = 0
    quality_tolerance: float = 1.02
    move_tolerance: float = 1.5
    history: list = field(default_factory=list)

    def sample(self, rng) -> dict:
        """One candidate: log-uniform draws over each tunable range
        (schedule knobs are scale-ish quantities), snapped to the power
        of two at or below the draw so candidate configs land on a small
        lattice — repeat tuning runs re-visit comparable points."""
        fields = {}
        for name, (lo, hi) in TUNABLE_FIELDS.items():
            if name == "polish_passes":
                fields[name] = int(rng.integers(lo, hi + 1))
                continue
            draw = float(np.exp(rng.uniform(np.log(lo), np.log(hi))))
            snapped = 1 << int(np.log2(max(draw, 1)))
            fields[name] = int(min(max(snapped, lo), hi))
        return fields

    def tune(self) -> tuple[dict, list]:
        """Run the halving loop; returns ``(best_fields, history)`` where
        ``best_fields`` is ``{}`` when the incumbent (the evaluator's
        base schedule) won."""
        rng = np.random.default_rng(self.seed)
        pool: list[dict] = [{}]            # {} = the incumbent schedule
        seen = {()}
        while len(pool) < max(self.trials, 1):
            cand = self.sample(rng)
            sig = tuple(sorted(cand.items()))
            if sig in seen:
                continue
            seen.add(sig)
            pool.append(cand)
        self.history = []
        for rung in range(max(self.rungs, 1)):
            results = []
            incumbent_metrics = None
            for cand in pool:
                out = self.evaluate(cand, rung, rung + 1)
                results.append((cand, out))
                if not cand:
                    incumbent_metrics = out
            assert incumbent_metrics is not None   # pool[0] is always {}
            q_ref = incumbent_metrics["quality"]
            m_ref = max(int(incumbent_metrics["moves"]), 1)
            ranked = []
            for i, (cand, out) in enumerate(results):
                feasible = (
                    out["quality"] <= q_ref * self.quality_tolerance + 1e-9
                    and out["moves"] <= m_ref * self.move_tolerance)
                trial = Trial(fields=cand, rung=rung,
                              wall_s=float(out["wall_s"]),
                              quality=float(out["quality"]),
                              moves=int(out["moves"]),
                              feasible=feasible, incumbent=not cand)
                self.history.append(trial)
                ranked.append((not (feasible or not cand),
                               float(out["wall_s"]), i, cand))
            ranked.sort(key=lambda t: t[:3])
            keep = max(len(pool) // 2, 1)
            pool = [cand for _, _, _, cand in ranked[:keep]]
            if not any(not c for c in pool):
                pool.append({})             # the incumbent never dies
        best = pool[0]                      # rank winner of the last rung
        return best, [t.to_json() for t in self.history]


def make_optimizer_evaluator(model, metadata, *, base: SearchConfig
                             | None = None, goals=None,
                             constraint=None, options=None,
                             collector=None):
    """The bench-scenario evaluator: builds a fresh ``TpuGoalOptimizer``
    per candidate schedule (compiled chains land in the process-wide
    shared registry + persistent cache, so re-visited lattice points are
    cheap), runs one compile+warm pass and ``repeats`` timed warm runs,
    and scores plan quality with the same weighted joint objective the
    population search selects on (:func:`plan_quality`)."""
    from .optimizer import TpuGoalOptimizer
    from .options import OptimizationOptions

    base = base or SearchConfig()
    options = options or OptimizationOptions(skip_hard_goal_check=True)

    def evaluate(fields: dict, rung: int, repeats: int) -> dict:
        cfg = replace(base, **fields) if fields else base
        opt = TpuGoalOptimizer(goals=goals, constraint=constraint,
                               config=cfg, collector=collector)
        opt.optimize(model, metadata, options)         # compile + warm
        best_s, last = float("inf"), None
        for r in range(max(repeats, 1)):
            t0 = time.monotonic()
            last = opt.optimize(model, metadata, replace(
                options, seed=options.seed + 1 + r))
            best_s = min(best_s, time.monotonic() - t0)
        return {"wall_s": best_s, "quality": plan_quality(last),
                "moves": last.num_moves}

    return evaluate


def autotune(model, metadata, *, base: SearchConfig | None = None,
             store: TunedConfigStore | None = None, trials: int = 8,
             rungs: int = 2, seed: int = 0, goals=None, constraint=None,
             options=None, save: bool = True,
             regime: str | None = None):
    """End-to-end tuning for one bench scenario: successive-halving over
    the schedule space, winner recorded into the store under the
    scenario's shape bucket (regime-qualified when the continuous loop
    passes the active ``regime``). Returns ``(fields, history,
    bucket)`` — ``fields`` empty when the base schedule won."""
    base = base or SearchConfig()
    tuner = SuccessiveHalvingTuner(
        evaluate=make_optimizer_evaluator(model, metadata, base=base,
                                          goals=goals,
                                          constraint=constraint,
                                          options=options),
        trials=trials, rungs=rungs, seed=seed)
    fields, history = tuner.tune()
    bucket = shape_bucket(metadata.num_partitions, metadata.num_brokers,
                          regime=regime)
    if store is not None:
        bucket = store.record(metadata.num_partitions,
                              metadata.num_brokers, fields,
                              history=history, save=save, regime=regime)
    return fields, history, bucket
