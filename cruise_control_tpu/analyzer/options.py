"""Optimization options (ref ``analyzer/OptimizationOptions.java``).

Per-request knobs: excluded topics (regex or explicit set — their replicas
don't move unless offline), brokers excluded from receiving leadership or
replicas, destination-broker restriction, and fast mode (smaller candidate
pools / fewer iterations).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

import numpy as np

from ..model.spec import ClusterMetadata


@dataclass(frozen=True)
class OptimizationOptions:
    excluded_topics: frozenset[str] = frozenset()
    excluded_topics_pattern: str | None = None
    #: individual partitions pinned in place (framework extension used by
    #: skip_urp_demotion: URPs must not move during a demote)
    excluded_partitions: frozenset[tuple] = frozenset()
    excluded_brokers_for_leadership: frozenset[int] = frozenset()
    excluded_brokers_for_replica_move: frozenset[int] = frozenset()
    # When non-empty, only these brokers may receive replicas
    # (ref requestedDestinationBrokerIds, used by ADD_BROKER).
    destination_broker_ids: frozenset[int] = frozenset()
    fast_mode: bool = False
    seed: int = 0
    #: When False (the default, matching the reference), an optimization
    #: that leaves a hard goal violated raises OptimizationFailureError
    #: instead of silently returning an unsafe plan (ref
    #: skip_hard_goal_check request parameter; AbstractGoal throwing
    #: OptimizationFailureException). Skipping also disables the
    #: off-chain hard-goal audit below.
    skip_hard_goal_check: bool = False
    #: Named hard goals exempted from the post-optimization audit of
    #: registered hard goals NOT in the chain (the reference enforces its
    #: configured hard goals on every run — GoalOptimizer.java:458-497 —
    #: and audits them continuously, GoalViolationDetector.java:56; a
    #: soft-goal-only chain here is still gated on the remaining hard
    #: goals). Waive a goal only when the chain deliberately cannot
    #: preserve it (e.g. a distribution-only chain vs rack-awareness) and
    #: a full-chain run covers it elsewhere.
    waived_hard_goals: frozenset[str] = frozenset()

    def excluded_partition_mask(self, metadata: ClusterMetadata,
                                padded_partitions: int) -> np.ndarray | None:
        pattern = (re.compile(self.excluded_topics_pattern)
                   if self.excluded_topics_pattern else None)
        if (not self.excluded_topics and pattern is None
                and not self.excluded_partitions):
            return None
        excluded_topic_ids = {
            metadata.topic_index[t] for t in self.excluded_topics
            if t in metadata.topic_index}
        if pattern is not None:
            for t, i in metadata.topic_index.items():
                if pattern.fullmatch(t):
                    excluded_topic_ids.add(i)
        if not excluded_topic_ids and not self.excluded_partitions:
            return None
        mask = np.zeros(padded_partitions, bool)
        for p, (topic, part) in enumerate(metadata.partition_keys):
            if (metadata.topic_index[topic] in excluded_topic_ids
                    or (topic, part) in self.excluded_partitions):
                mask[p] = True
        return mask

    def broker_mask(self, metadata: ClusterMetadata, padded_brokers: int,
                    ids: frozenset[int]) -> np.ndarray | None:
        if not ids:
            return None
        mask = np.zeros(padded_brokers, bool)
        for bid in ids:
            idx = metadata.broker_index.get(bid)
            if idx is not None:
                mask[idx] = True
        return mask

    def replica_move_exclusion_mask(self, metadata: ClusterMetadata,
                                    padded_brokers: int) -> np.ndarray | None:
        """Brokers that may NOT receive replicas: the explicit exclusion set,
        plus (when a destination restriction is given) everything outside it."""
        excl = self.broker_mask(metadata, padded_brokers,
                                self.excluded_brokers_for_replica_move)
        if self.destination_broker_ids:
            allowed = self.broker_mask(metadata, padded_brokers,
                                       self.destination_broker_ids)
            inv = ~allowed
            excl = inv if excl is None else (excl | inv)
        return excl


class OptimizationOptionsGenerator:
    """Plugin SPI deriving per-run options from cluster state (ref
    ``OptimizationOptionsGenerator.java`` /
    ``DefaultOptimizationOptionsGenerator.java``): deployments override
    this to e.g. auto-exclude system topics or newly-added brokers from
    receiving leadership during goal-violation detection runs."""

    def generate(self, base: OptimizationOptions,
                 metadata: ClusterMetadata) -> OptimizationOptions:
        raise NotImplementedError


class DefaultOptimizationOptionsGenerator(OptimizationOptionsGenerator):
    """Pass-through with an optional always-excluded topic pattern (the
    reference's default excludes topics matching
    ``topics.excluded.from.partition.movement``)."""

    def __init__(self, excluded_topics_pattern: str | None = None):
        self.excluded_topics_pattern = excluded_topics_pattern

    def generate(self, base: OptimizationOptions,
                 metadata: ClusterMetadata) -> OptimizationOptions:
        if not self.excluded_topics_pattern:
            return base
        pattern = self.excluded_topics_pattern
        if base.excluded_topics_pattern:
            # Idempotence by structure, not substring containment (a
            # request pattern that merely CONTAINS the config text, e.g.
            # 'mysystem-logs' vs 'sys', must still be combined).
            suffix = f"|(?:{pattern})"
            if base.excluded_topics_pattern.endswith(suffix):
                return base
            # Combine: the config-level exclusion is "always excluded",
            # it must survive a request that also excludes topics.
            pattern = f"(?:{base.excluded_topics_pattern}){suffix}"
        from dataclasses import replace
        return replace(base, excluded_topics_pattern=pattern)
