"""Forecast engine: observed history -> fitted trajectories -> batched
what-if sweeps -> proactive readouts.

The closing of the loop ROADMAP item 2 asked for: the aggregator already
holds the ``[E, M, W]`` window history, the what-if engine already
scores scenario batches in one vmapped dispatch, and the detector/
provisioner path already actuates recommendations. This engine is the
glue — it fits per-topic forecasts from the windows (forecast/model.py),
materializes forecast horizons as :class:`~..whatif.TrajectoryScale`
scenario batches, and runs them through the UNMODIFIED
``WhatIfEngine`` — zero new device programs for scoring; a trajectory
sweep compiles and caches exactly like an N-1 sweep of the same shapes.

Surfaced as ``GET/POST /forecast``, the ``forecast`` section of
``/devicestats``, and the ``Forecast.*`` sensor family; the scheduled
:class:`~.detector.CapacityForecastDetector` drives the same engine on
its interval. See docs/forecasting.md.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..core.aggregator import (AggregationOptions, Extrapolation,
                               NotEnoughValidWindowsError)
from ..core.metricdef import KafkaMetric
from ..whatif.spec import TrajectoryScale
from .model import ForecastSet, ForecastStore, fit_topic_forecasts

LOG = logging.getLogger(__name__)

#: default forecast horizons: +1h / +6h / +24h (forecast.horizon.ms)
DEFAULT_HORIZONS_MS = (3_600_000, 21_600_000, 86_400_000)
#: default projection quantiles: median + p90 (forecast.quantiles)
DEFAULT_QUANTILES = (0.5, 0.9)


@dataclass
class ForecastConfig:
    """The ``forecast.*`` / ``provision.partition.count.*`` config view
    (config/constants.py validates these at parse time)."""

    enabled: bool = True
    horizons_ms: tuple[int, ...] = DEFAULT_HORIZONS_MS
    quantiles: tuple[float, ...] = DEFAULT_QUANTILES
    interval_ms: int = 1_800_000
    min_history_windows: int = 3
    seasonal_period_ms: int = 86_400_000
    #: weekly rung period (forecast.weekly.period.ms); 0 disables. Arms
    #: day-of-week residual buckets when it covers >= 14 windows.
    week_period_ms: int = 0
    #: residual changepoint threshold in robust-sigma units
    #: (forecast.changepoint.min.shift); 0 disables truncation.
    changepoint_min_shift: float = 0.0
    partition_count_enabled: bool = True
    #: a topic whose per-partition load skew (max/mean) exceeds this is
    #: NOT given a partition-count recommendation: with a skewed key
    #: distribution the hot partition keeps its load no matter how many
    #: siblings exist (arxiv 2205.09415's partitioning constraint).
    partition_count_max_skew: float = 4.0
    #: growth factor below which no partition-count change is proposed
    #: (churning counts for noise-level growth costs consumer rebalances)
    partition_count_min_factor: float = 1.1

    @property
    def detection_quantile(self) -> float:
        """The quantile proactive provisioning judges breaches at: the
        most pessimistic configured quantile."""
        return max(self.quantiles) if self.quantiles else 0.9


@dataclass
class HorizonOutcome:
    """One (horizon, quantile) point of a trajectory sweep: the what-if
    scorecard plus the projection that produced it."""

    horizon_ms: int
    quantile: float
    risk: float
    capacity_pressure: float
    violated_goals: list[str]
    violated_hard_goals: list[str]
    headroom: dict
    worst_broker: object
    max_factor: float
    scenario_name: str

    def to_json(self) -> dict:
        return {"horizonMs": self.horizon_ms, "quantile": self.quantile,
                "risk": round(self.risk, 4),
                "capacityPressure": round(self.capacity_pressure, 4),
                "violatedGoals": self.violated_goals,
                "violatedHardGoals": self.violated_hard_goals,
                "headroom": self.headroom,
                "worstBroker": self.worst_broker,
                "maxFactor": round(self.max_factor, 4),
                "scenario": self.scenario_name}


@dataclass
class ForecastReport:
    """One trajectory sweep over the live model: the baseline (+0)
    outcome, every (horizon, quantile) outcome, and the derived
    time-to-breach estimate."""

    outcomes: list[HorizonOutcome]
    baseline: HorizonOutcome | None
    time_to_breach_ms: int | None
    breach_horizon_ms: int | None
    breach_quantile: float | None
    duration_s: float
    generated_at_ms: int
    stale_model: bool = False

    def to_json(self) -> dict:
        return {"generatedAtMs": self.generated_at_ms,
                "durationMs": round(self.duration_s * 1e3, 3),
                "staleModel": self.stale_model,
                "timeToBreachMs": self.time_to_breach_ms,
                "breachHorizonMs": self.breach_horizon_ms,
                "breachQuantile": self.breach_quantile,
                "baseline": (self.baseline.to_json()
                             if self.baseline is not None else None),
                "horizons": [o.to_json() for o in self.outcomes]}


def time_to_breach_ms(points: list[tuple[int, float]],
                      threshold: float = 1.0) -> int | None:
    """Linear-interpolated time until capacity pressure crosses
    ``threshold``, from (horizon_ms, pressure) points sorted by horizon
    (the +0 baseline included). None when no horizon reaches it. The
    EARLIEST breached point wins — a cluster already over the threshold
    at its first scored horizon reports that horizon (0 for the
    baseline), never a later crossing of a declining curve. The first
    crossing segment is interpolated — pressure between scored horizons
    is approximated linearly, which the chaos cross-check validates
    against realized load."""
    pts = sorted(points)
    for (h0, p0), (h1, p1) in zip(pts, pts[1:]):
        if p0 >= threshold:
            return int(h0)
        if p1 >= threshold:
            frac = (threshold - p0) / (p1 - p0)
            return int(round(h0 + frac * (h1 - h0)))
    if pts and pts[-1][1] >= threshold:
        return int(pts[-1][0])
    return None


class ForecastEngine:
    """Fits, persists, projects and scores per-topic load trajectories.

    Shares the facade's :class:`~..whatif.WhatIfEngine` (same compiled
    sweep programs as ``/simulate`` and the resilience detector) and the
    monitor's partition aggregator (the fit reads the SAME windows the
    model builder gathers). Thread-safe: the detector thread and HTTP
    requests serialize refits on one lock; sweeps ride the what-if
    engine's own program-cache locking.
    """

    def __init__(self, monitor, whatif, *,
                 config: ForecastConfig | None = None,
                 store: ForecastStore | None = None,
                 registry=None, tracer=None, collector=None,
                 now_ms=None) -> None:
        from ..core.runtime_obs import default_collector
        from ..core.sensors import MetricRegistry
        from ..core.tracing import default_tracer
        self.monitor = monitor
        self.whatif = whatif
        self.config = config or ForecastConfig()
        #: persistence slot (forecast/model.py ForecastStore) — None =
        #: in-memory only; serve.py wires the store so restarts serve
        #: projections without refitting cold.
        self.store = store
        self.registry = registry or MetricRegistry()
        self.tracer = tracer or default_tracer()
        self.collector = collector or default_collector()
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))
        self._lock = threading.RLock()
        #: last completed fit (restored from the store when wired)
        self.last_fit: ForecastSet | None = (store.load()
                                             if store is not None else None)
        #: last completed trajectory sweep
        self.last_report: ForecastReport | None = None
        #: (generation, {topic: per-partition NW_IN means}) cached off
        #: the last topic_series dense pass — partition_skew reads it
        self._partition_loads: tuple[int, dict] | None = None
        self.num_fits = 0
        self.num_sweeps = 0
        name = MetricRegistry.name
        self._fit_timer = self.registry.timer(name("Forecast", "fit-timer"))
        self._sweep_timer = self.registry.timer(
            name("Forecast", "sweep-timer"))
        self._refresh_meter = self.registry.meter(
            name("Forecast", "refresh-rate"))
        self.registry.gauge(
            name("Forecast", "topics-fitted"),
            lambda: None if self.last_fit is None else len(self.last_fit))
        self.registry.gauge(
            name("Forecast", "backtest-mape"),
            lambda: (None if self.last_fit is None
                     else self.last_fit.worst_backtest_mape()))
        self.registry.gauge(
            name("Forecast", "time-to-breach-ms"),
            lambda: (None if self.last_report is None
                     else self.last_report.time_to_breach_ms))
        self.registry.gauge(
            name("Forecast", "horizon-max-risk"),
            lambda: (None if (self.last_report is None
                              or not self.last_report.outcomes)
                     else max(o.risk for o in self.last_report.outcomes)))

    # -------------------------------------------------------------- fitting
    def topic_series(self, now_ms: int
                     ) -> tuple[dict, int, int]:
        """Per-topic window series from the monitor's partition
        aggregator: topic -> (values[4, W], valid[W]) where values sums
        the 4 resource metrics over the topic's partitions per window
        (valid cells only). Returns (series, window_ms, generation)."""
        agg = self.monitor.partition_aggregator
        result = agg.aggregate(0, now_ms,
                               AggregationOptions(min_valid_windows=1),
                               use_dense=True)
        d = result.dense
        if d is None or not d.window_times_ms:
            raise NotEnoughValidWindowsError(
                "no aggregated windows to fit forecasts from")
        E, _M, W = d.values.shape
        no_valid = Extrapolation.NO_VALID_EXTRAPOLATION.value
        cell_valid = d.extrapolations != no_valid          # [E, W]
        metrics = [KafkaMetric.CPU_USAGE, KafkaMetric.LEADER_BYTES_IN,
                   KafkaMetric.LEADER_BYTES_OUT, KafkaMetric.DISK_USAGE]
        vals = d.values[:, metrics, :]                      # [E, 4, W]
        vals = np.where(cell_valid[:, None, :], vals, 0.0)

        topics = sorted({t for t, _p in d.entities})
        tindex = {t: i for i, t in enumerate(topics)}
        rows = np.fromiter((tindex[t] for t, _p in d.entities),
                           np.int64, E)
        T = len(topics)
        sums = np.zeros((T, 4, W))
        np.add.at(sums, rows, vals)
        valid = np.zeros((T, W), bool)
        np.logical_or.at(valid, rows, cell_valid)
        series = {t: (sums[i], valid[i]) for t, i in tindex.items()}
        # Per-partition NW_IN means off the SAME dense pass, cached for
        # partition_skew() — a detector round must not pay a second
        # full [E, M, W] aggregation just to read the skew.
        nval = cell_valid.sum(axis=1)
        pmean = np.where(nval > 0,
                         vals[:, 1, :].sum(axis=1) / np.maximum(nval, 1),
                         0.0)
        ploads: dict[str, list] = {}
        for (topic, _p), m in zip(d.entities, pmean):
            ploads.setdefault(topic, []).append(float(m))
        self._partition_loads = (
            result.generation,
            {t: np.asarray(v) for t, v in ploads.items()})
        return series, agg.window_ms, result.generation

    def refresh(self, now_ms: int | None = None) -> ForecastSet:
        """Fit (and persist) forecasts from the current window history.
        Raises ``NotEnoughValidWindowsError`` while the monitor has no
        aggregated windows at all — the caller (detector / POST) decides
        whether that is skip-quietly or an HTTP error — and
        ``ValueError`` (HTTP 400) when forecasting is disabled."""
        if not self.config.enabled:
            raise ValueError(
                "forecasting is disabled (forecast.enabled=false)")
        now = now_ms if now_ms is not None else self._now_ms()
        with self._lock, self._fit_timer.time(), \
                self.tracer.span("forecast.fit") as sp:
            series, window_ms, generation = self.topic_series(now)
            fits = fit_topic_forecasts(
                series, window_ms,
                seasonal_period_ms=self.config.seasonal_period_ms,
                min_history_windows=self.config.min_history_windows,
                fitted_at_ms=now, generation=generation,
                week_period_ms=self.config.week_period_ms,
                changepoint_min_shift=self.config.changepoint_min_shift)
            self.last_fit = fits
            self.num_fits += 1
            self._refresh_meter.mark()
            if self.store is not None:
                self.store.save(fits)
            sp.set(topics=len(fits),
                   worstMape=fits.worst_backtest_mape())
        return fits

    def maybe_refresh(self, now_ms: int | None = None
                      ) -> ForecastSet | None:
        """Refit when the last fit is older than ``interval_ms``
        (``<= 0`` = no age bound) or the model generation moved; serve
        the cached fit otherwise. Returns None (instead of raising)
        when no history exists yet, and the cached fit untouched when
        forecasting is disabled (the kill switch must kill the
        compute, not just the detector schedule)."""
        if not self.config.enabled:
            return self.last_fit
        now = now_ms if now_ms is not None else self._now_ms()
        with self._lock:
            fit = self.last_fit
            fresh = (fit is not None
                     and fit.generation == self.monitor.generation
                     and (self.config.interval_ms <= 0
                          or now - fit.fitted_at_ms
                          < self.config.interval_ms))
        if fresh:
            return fit
        try:
            return self.refresh(now)
        except NotEnoughValidWindowsError:
            return self.last_fit

    # ---------------------------------------------------------- projection
    @staticmethod
    def _scenario_from_fit(fit: ForecastSet, horizon_ms: int,
                           quantile: float) -> TrajectoryScale:
        factors = tuple(sorted(fit.factors(horizon_ms, quantile).items()))
        return TrajectoryScale(horizon_ms=int(horizon_ms),
                               quantile=float(quantile), factors=factors)

    def _fitted(self, now_ms: int | None = None) -> ForecastSet:
        """The current fit, refreshed if stale. Raises ``ValueError``
        (HTTP 400) while nothing is fitted yet."""
        fit = self.maybe_refresh(now_ms)
        if fit is None or not len(fit):
            raise ValueError(
                "no fitted forecasts yet (the monitor needs at least one "
                "aggregated window; POST /forecast to force a refit)")
        return fit

    def trajectory_scenario(self, horizon_ms: int,
                            quantile: float) -> TrajectoryScale:
        """The concrete scenario spec for one (horizon, quantile) point
        of the last fit — the ``{"type": "forecast"}`` resolver
        ``parse_scenarios`` calls. Raises ``ValueError`` (HTTP 400)
        while nothing is fitted yet."""
        return self._scenario_from_fit(self._fitted(), horizon_ms,
                                       quantile)

    def trajectory_scenarios(self, now_ms: int | None = None
                             ) -> list[TrajectoryScale]:
        """The configured sweep grid: a +0 baseline scenario (factors at
        horizon 0 of the median — the pressure anchor time-to-breach
        interpolates from) plus every (horizon x quantile) point, all
        resolved against ONE fit — a refit landing mid-grid must never
        mix two fits in one report (time-to-breach interpolates across
        the whole grid)."""
        fit = self._fitted(now_ms)
        grid = [self._scenario_from_fit(fit, 0, 0.5)]
        for h in self.config.horizons_ms:
            for q in self.config.quantiles:
                grid.append(self._scenario_from_fit(fit, h, q))
        return grid

    # --------------------------------------------------------------- sweeps
    def sweep(self, now_ms: int | None = None) -> ForecastReport:
        """Score the configured trajectory grid against the live model
        through the shared WhatIfEngine (ONE batched dispatch) and
        derive the time-to-breach estimate."""
        now = now_ms if now_ms is not None else self._now_ms()
        scenarios = self.trajectory_scenarios(now)
        t0 = time.monotonic()
        with self._sweep_timer.time(), \
                self.tracer.span("forecast.sweep",
                                 scenarios=len(scenarios)) as sp:
            result = self.monitor.cluster_model(now)
            report = self.whatif.sweep(result.model, result.metadata,
                                       scenarios,
                                       stale_model=result.stale)
            out = self._build_report(scenarios, report, now,
                                     time.monotonic() - t0)
            sp.set(timeToBreachMs=out.time_to_breach_ms)
        with self._lock:
            self.last_report = out
            self.num_sweeps += 1
        return out

    def _build_report(self, scenarios, report, now: int,
                      duration_s: float) -> ForecastReport:
        outcomes: list[HorizonOutcome] = []
        baseline: HorizonOutcome | None = None
        for scn, o in zip(scenarios, report.outcomes):
            ho = HorizonOutcome(
                horizon_ms=scn.horizon_ms, quantile=scn.quantile,
                risk=o.risk, capacity_pressure=o.capacity_pressure,
                violated_goals=o.violated_goals,
                violated_hard_goals=o.violated_hard_goals,
                headroom=o.headroom, worst_broker=o.worst_broker,
                max_factor=max((f for _t, f in scn.factors),
                               default=1.0),
                scenario_name=scn.name)
            if scn.horizon_ms == 0:
                baseline = ho
            else:
                outcomes.append(ho)
        q = self.config.detection_quantile
        points = [(0, baseline.capacity_pressure)] if baseline else []
        points += [(o.horizon_ms, o.capacity_pressure) for o in outcomes
                   if o.quantile == q]
        ttb = time_to_breach_ms(points)
        breach_h = breach_q = None
        for o in sorted(outcomes, key=lambda o: o.horizon_ms):
            if o.quantile == q and (o.violated_hard_goals
                                    or o.capacity_pressure >= 1.0):
                breach_h, breach_q = o.horizon_ms, o.quantile
                break
        if ttb is None and breach_h is not None:
            # Hard-goal breach without a pressure crossing: the horizon
            # itself is the honest bound.
            ttb = breach_h
        return ForecastReport(outcomes=outcomes, baseline=baseline,
                              time_to_breach_ms=ttb,
                              breach_horizon_ms=breach_h,
                              breach_quantile=breach_q,
                              duration_s=duration_s,
                              generated_at_ms=now,
                              stale_model=report.stale_model)

    # ----------------------------------------------- partition-count logic
    def partition_skew(self) -> dict[str, float]:
        """Per-topic partition-load skew (max / mean partition NW_IN
        over the latest valid windows) — the key-distribution proxy the
        partition-count rule honors (arxiv 2205.09415: adding
        partitions only relieves load the keys actually spread).
        Served from the per-partition means the last ``topic_series``
        pass cached (same generation = same windows); only a stale or
        missing cache pays a fresh aggregation."""
        cached = self._partition_loads
        if cached is not None and cached[0] == self.monitor.generation:
            series_now = cached[1]
        else:
            try:
                series_now = self._per_partition_load()
            except NotEnoughValidWindowsError:
                return {}
        out: dict[str, float] = {}
        for topic, loads in series_now.items():
            if len(loads) == 0:
                continue
            mean = float(np.mean(loads))
            if mean <= 0:
                out[topic] = 1.0
            else:
                out[topic] = float(np.max(loads)) / mean
        return out

    def _per_partition_load(self) -> dict[str, np.ndarray]:
        """topic -> per-partition mean NW_IN over each partition's valid
        windows (the skew numerator/denominator source)."""
        agg = self.monitor.partition_aggregator
        result = agg.aggregate(0, self._now_ms(),
                               AggregationOptions(min_valid_windows=1),
                               use_dense=True)
        d = result.dense
        if d is None:
            raise NotEnoughValidWindowsError("no dense aggregate")
        no_valid = Extrapolation.NO_VALID_EXTRAPOLATION.value
        valid = d.extrapolations != no_valid
        nw_in = d.values[:, KafkaMetric.LEADER_BYTES_IN, :]
        nval = valid.sum(axis=1)
        mean = np.where(nval > 0,
                        (nw_in * valid).sum(axis=1) / np.maximum(nval, 1),
                        0.0)
        out: dict[str, list] = {}
        for (topic, _p), m in zip(d.entities, mean):
            out.setdefault(topic, []).append(float(m))
        return {t: np.asarray(v) for t, v in out.items()}

    def partition_count_targets(self, horizon_ms: int, quantile: float,
                                partition_counts: dict[str, int]
                                ) -> list[dict]:
        """Forecast-informed partition-count targets for hot topics:
        keep projected per-partition load at the horizon no worse than
        today's by growing the count with the projected factor —
        ``target = ceil(count * factor)`` — skipping topics whose
        key-distribution skew caps the benefit and growth below the
        configured noise floor. Counts only ever grow (Kafka cannot
        shrink a topic's partition count)."""
        fit = self.last_fit
        if fit is None or not self.config.partition_count_enabled:
            return []
        skews = self.partition_skew()
        cfg = self.config
        out = []
        for topic, factor in sorted(
                fit.factors(horizon_ms, quantile).items()):
            count = partition_counts.get(topic)
            if not count or factor < cfg.partition_count_min_factor:
                continue
            skew = skews.get(topic, 1.0)
            if skew > cfg.partition_count_max_skew:
                LOG.info(
                    "forecast: topic %s projects %.2fx at +%dms but its "
                    "partition-load skew %.1f exceeds %.1f — partitions "
                    "would not relieve the hot key; skipping",
                    topic, factor, horizon_ms, skew,
                    cfg.partition_count_max_skew)
                continue
            target = int(np.ceil(count * factor))
            if target > count:
                out.append({"topic": topic, "current": count,
                            "target": target,
                            "factor": round(float(factor), 4),
                            "skew": round(float(skew), 4)})
        return out

    # --------------------------------------------------------------- state
    def stats_json(self) -> dict:
        """The ``forecast`` section of ``/devicestats``."""
        with self._lock:
            fit, report = self.last_fit, self.last_report
        return {
            "enabled": self.config.enabled,
            "horizonsMs": list(self.config.horizons_ms),
            "quantiles": list(self.config.quantiles),
            "fits": self.num_fits, "sweeps": self.num_sweeps,
            "storePath": self.store.path if self.store is not None else None,
            "fittedTopics": None if fit is None else len(fit),
            "fittedAtMs": None if fit is None else fit.fitted_at_ms,
            "worstBacktestMape": (None if fit is None
                                  else fit.worst_backtest_mape()),
            "timeToBreachMs": (None if report is None
                               else report.time_to_breach_ms),
            "lastSweepMs": (None if report is None
                            else report.generated_at_ms),
        }

    def report_json(self) -> dict:
        """The ``GET /forecast`` payload: fit summary + the cached (or
        first-computed) trajectory report. With ``forecast.enabled``
        off the endpoint still answers — enabled=false state, whatever
        report was cached, and NO fit/sweep compute (the kill-switch
        contract in configuration.md)."""
        with self._lock:
            report = self.last_report
        if report is None and self.config.enabled:
            report = self.sweep()
        with self._lock:
            fit = self.last_fit
        return {
            **self.stats_json(),
            "topics": ({} if fit is None
                       else {t: {"degraded": f.degraded,
                                 "backtestMape": f.backtest_mape,
                                 "trendPerWindow": [
                                     round(float(v), 6) for v in f.trend]}
                             for t, f in sorted(fit.forecasts.items())}),
            "report": None if report is None else report.to_json(),
        }
