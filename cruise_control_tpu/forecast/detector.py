"""Capacity-forecast detector: proactive provisioning from predicted
load trajectories.

Sibling of :class:`~..detector.resilience.ResilienceDetector` — same
scheduled shape, same "arrive before the outage" contract, but the time
axis replaces the failure axis: instead of asking "which broker loss
breaks us NOW", it asks "when does the PROJECTED load break us", and
raises a :class:`~..detector.anomalies.CapacityForecast` anomaly with a
time-to-breach estimate and concrete ProvisionRecommendations (broker
adds, and forecast-informed partition-count growth for hot topics —
arxiv 2205.09415) riding the existing notifier -> provisioner path.
"""

from __future__ import annotations

import logging
import math

from ..detector.anomalies import CapacityForecast
from ..detector.provisioner import ProvisionRecommendation, ProvisionStatus
from ..whatif.spec import RESOURCE_KEYS

LOG = logging.getLogger(__name__)


class CapacityForecastDetector:
    """Scheduled trajectory sweep over the live cluster model.

    Skips rounds while the cluster has realized failures (those are
    BrokerFailure/DiskFailure territory — a projection on a degraded
    cluster would double-report the live anomaly) and while the monitor
    (or forecast engine) lacks history. Exposes the last time-to-breach
    for ``/state`` consumers (the manager's ``state_json`` picks
    ``last_time_to_breach_ms`` up like the resilience score).
    """

    def __init__(self, monitor, forecast, *, registry=None) -> None:
        self.monitor = monitor
        #: the shared ForecastEngine (facade.forecast) — the detector
        #: never builds its own, so /forecast and the detector agree on
        #: one fit and one compiled sweep program set.
        self.forecast = forecast
        #: last sweep's ForecastReport (None until the first run)
        self.last_report = None
        #: last estimated ms-to-breach. None = no sweep ran or no breach
        #: projected — the gauge and /state surface None, never a
        #: fabricated all-clear.
        self.last_time_to_breach_ms: int | None = None
        if registry is not None:
            from ..core.sensors import MetricRegistry
            registry.gauge(
                MetricRegistry.name("AnomalyDetector",
                                    "forecast-time-to-breach-ms"),
                lambda: self.last_time_to_breach_ms)

    def detect(self, now_ms: int) -> list[CapacityForecast]:
        from ..monitor import NotEnoughValidWindowsException
        alive = self.monitor.admin.describe_cluster()
        if not all(alive.values()):
            # A realized failure outranks any projection; the live
            # anomaly owns this round.
            self.last_time_to_breach_ms = None
            return []
        if self.forecast.maybe_refresh(now_ms) is None:
            return []        # no window history yet: nothing to project
        try:
            report = self.forecast.sweep(now_ms)
        except NotEnoughValidWindowsException:
            return []
        self.last_report = report
        self.last_time_to_breach_ms = report.time_to_breach_ms
        if report.breach_horizon_ms is None:
            return []
        q = report.breach_quantile
        breach = next(o for o in report.outcomes
                      if o.horizon_ms == report.breach_horizon_ms
                      and o.quantile == q)
        recs = self._recommendations(report, breach, alive)
        LOG.warning(
            "capacity forecast: projected breach at +%dms p%d (time to "
            "breach ~%s ms, pressure %.2f, hard violations %s); %d "
            "provision recommendation(s)",
            breach.horizon_ms, int(round(q * 100)),
            report.time_to_breach_ms, breach.capacity_pressure,
            breach.violated_hard_goals, len(recs))
        return [CapacityForecast(
            detected_ms=now_ms,
            time_to_breach_ms=report.time_to_breach_ms,
            horizon_ms=breach.horizon_ms, quantile=q,
            recommendations=recs, max_risk=breach.risk)]

    def _recommendations(self, report, breach, alive
                         ) -> list[ProvisionRecommendation]:
        """The provisioning evidence for one projected breach: a broker
        add sized from the projected pressure overshoot, plus
        partition-count targets for the hot topics driving it."""
        fit = self.forecast.last_fit
        provenance = {
            **(fit.provenance() if fit is not None else {}),
            "horizonMs": breach.horizon_ms, "quantile": breach.quantile,
            "scenario": breach.scenario_name,
        }
        tightest = min(
            (k for k in RESOURCE_KEYS
             if breach.headroom.get(k, {}).get("minBrokerFrac")
             is not None),
            key=lambda k: breach.headroom[k]["minBrokerFrac"],
            default=None)
        n_alive = max(sum(alive.values()), 1)
        # Brokers needed so the projected aggregate demand fits back
        # under the usable bound: pressure scales ~1/N at fixed demand.
        overshoot = max(breach.capacity_pressure - 1.0, 0.0)
        extra = max(int(math.ceil(n_alive * overshoot)), 1)
        when = ("unknown" if report.time_to_breach_ms is None
                else f"~{report.time_to_breach_ms / 60000.0:.0f} min")
        recs = [ProvisionRecommendation(
            ProvisionStatus.UNDER_PROVISIONED,
            num_brokers=extra,
            resource=tightest,
            reason=(f"forecast: projected load at +{breach.horizon_ms}ms "
                    f"p{int(round(breach.quantile * 100))} reaches "
                    f"pressure {breach.capacity_pressure:.2f} "
                    f"(violates {breach.violated_hard_goals}); breach in "
                    f"{when}"),
            headroom={"scenario": breach.scenario_name,
                      "capacityPressure": round(breach.capacity_pressure,
                                                4),
                      "perResource": breach.headroom},
            time_to_breach_ms=report.time_to_breach_ms,
            forecast=provenance)]
        counts: dict[str, int] = {}
        for t, _p in self.monitor.admin.describe_partitions():
            counts[t] = counts.get(t, 0) + 1
        for target in self.forecast.partition_count_targets(
                breach.horizon_ms, breach.quantile, counts):
            recs.append(ProvisionRecommendation(
                ProvisionStatus.UNDER_PROVISIONED,
                num_partitions=target["target"],
                topic=target["topic"],
                reason=(f"forecast: topic {target['topic']} projects "
                        f"{target['factor']}x at +{breach.horizon_ms}ms "
                        f"(skew {target['skew']}); grow partitions "
                        f"{target['current']} -> {target['target']}; "
                        f"breach in {when}"),
                time_to_breach_ms=report.time_to_breach_ms,
                forecast=provenance))
        return recs
