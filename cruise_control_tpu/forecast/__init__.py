"""Forecast subsystem (L-forecast): predictive load trajectories,
proactive provisioning and partition-count proposals — the time axis on
top of the what-if scenario machinery (docs/forecasting.md).

- :mod:`.model` — deterministic per-topic level+trend+seasonal fits
  over the aggregator's window history, with confidence intervals, a
  backtest error metric, and persistence next to the tuned-config store;
- :mod:`.engine` — :class:`ForecastEngine`: fits -> ``TrajectoryScale``
  scenario batches -> batched ``WhatIfEngine`` sweeps (zero new device
  programs) -> time-to-breach estimates;
- :mod:`.detector` — :class:`CapacityForecastDetector`: the scheduled
  loop converting predicted-horizon violations into
  ``ProvisionRecommendation``s BEFORE pressure materializes.
"""

from .model import (FORECAST_STORE_VERSION, ForecastSet, ForecastStore,
                    TopicForecast, fit_series, fit_topic_forecasts,
                    quantile_z)
from .engine import (DEFAULT_HORIZONS_MS, DEFAULT_QUANTILES,
                     ForecastConfig, ForecastEngine, ForecastReport,
                     HorizonOutcome, time_to_breach_ms)
from .detector import CapacityForecastDetector

__all__ = [
    "FORECAST_STORE_VERSION", "TopicForecast", "ForecastSet",
    "ForecastStore", "fit_series", "fit_topic_forecasts", "quantile_z",
    "ForecastConfig", "ForecastEngine", "ForecastReport",
    "HorizonOutcome", "time_to_breach_ms", "DEFAULT_HORIZONS_MS",
    "DEFAULT_QUANTILES", "CapacityForecastDetector",
]
