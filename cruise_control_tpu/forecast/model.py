"""Per-topic load forecast fitting: level + trend + diurnal seasonality.

No reference analog — the reference control plane is purely reactive.
This module turns the aggregator's windowed history (the same
``[E, M, W]`` cube the monitor builds models from) into per-topic,
per-resource forecasts the what-if machinery can project forward
(PAPERS.md: "Integrative Dynamic Reconfiguration", arxiv 1602.03770 —
one reconfiguration plane acting ahead of workload shifts).

Model form (documented in docs/forecasting.md): for each topic and each
of the four resource metrics, the window series ``y_w`` decomposes as

    y_w = level + trend * w + seasonal[w mod K] + eps,   eps ~ N(0, sigma)

fitted deterministically — ordinary least squares for level/trend,
phase-bucket residual means for the seasonal component (K = seasonal
period / window width), sample std for sigma. Two opt-in rungs extend
the ladder (ROADMAP item 5's richer forecast forms):

- **weekly seasonality** (``week_windows`` = windows per week): seven
  day-of-week residual buckets fitted on top of the daily component,
  only when the history covers >= one full week (shorter histories
  degrade to ``no-weekly``);
- **changepoint detection** (``changepoint_min_shift`` > 0): a robust
  CUSUM split on the fit residual — when the pre/post split means
  differ by >= ``min_shift`` x the median residual diff, the fit
  TRUNCATES to the post-changepoint suffix (original window
  coordinates kept) and refits, so a step migration or a passed flash
  crowd stops polluting the level. Up to three truncation rounds, so a
  burst (two shifts: up then down) resolves to the clean tail.

Seasonality is only fitted when the history covers at least one full
period; shorter histories degrade to level+trend (and histories under
``min_history_windows`` degrade to a flat persistence forecast) — the
degrade ladder (none -> no-weekly -> no-seasonal -> persistence) is
explicit state on the fit, never a silent zero.

Everything here is host-side numpy and seeded by nothing: the same
window history always fits the same model (the backtest property tests
rely on that).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from dataclasses import dataclass, field
from statistics import NormalDist

import numpy as np

from ..whatif.spec import RESOURCE_KEYS

LOG = logging.getLogger(__name__)

#: Version of the persisted forecast format. A change to the model form
#: bumps it and retires stale files predictably (the TunedConfigStore /
#: ``.jax_cache/v<N>`` discipline — forecasts persist NEXT to the tuned
#: configs, see :meth:`ForecastStore.default_path`).
FORECAST_STORE_VERSION = 2

#: floor for relative errors / scale factors so an idle topic (level 0)
#: never divides by zero or explodes a factor.
_EPS = 1e-9


def quantile_z(quantile: float) -> float:
    """Normal z-score of ``quantile`` (0.5 -> 0, 0.9 -> 1.2816): the
    confidence-interval multiplier on the fitted residual sigma."""
    if not 0.0 < quantile < 1.0:
        raise ValueError(f"quantile must be in (0, 1), got {quantile}")
    return NormalDist().inv_cdf(quantile)


@dataclass
class TopicForecast:
    """One topic's fitted forecast: 4 per-resource component fits.

    ``level``/``trend`` are in window units (``x = 0`` at the OLDEST
    fitted window; predictions extrapolate from ``num_windows - 1``),
    ``seasonal`` is ``[4, K]`` (K = 0 when degraded to level+trend),
    ``sigma`` the per-resource residual std. ``degraded`` names the
    ladder rung: ``none`` (full model), ``no-seasonal`` (history < one
    period), ``persistence`` (history < min_history_windows: flat
    last-level forecast, trend zero)."""

    topic: str
    window_ms: int
    num_windows: int
    level: np.ndarray            # f64[4] — intercept at x = 0
    trend: np.ndarray            # f64[4] — per-window slope
    seasonal: np.ndarray         # f64[4, K]; K == 0 when not fitted
    sigma: np.ndarray            # f64[4]
    last_phase: int              # (last fitted window index) mod K
    backtest_mape: float | None  # 1-window-holdout relative error
    #: the MODEL's expected-utilization basis per resource — mean over
    #: valid windows for CPU/NW, latest valid window for DISK, exactly
    #: the monitor's per-metric ValueComputingStrategy. The scale
    #: factor projects the predicted load CHANGE onto this basis (see
    #: :meth:`factor`), so ``factor x model load`` tracks what the
    #: monitor's own estimator will report at the horizon — the same
    #: quantity the breach-replay chaos test measures.
    basis: np.ndarray = field(default=None)
    #: current (x = num_windows - 1) fitted value per resource, seasonal
    #: included — the display-side "load right now"
    current: np.ndarray = field(default=None)
    degraded: str = "none"
    #: day-of-week residual buckets ``[4, 7]`` (empty when the weekly
    #: rung was not requested or not fittable)
    week_seasonal: np.ndarray = field(default=None)
    #: windows per week the weekly buckets were fitted at (0 = no
    #: weekly component; bucket of window x = ``(x % Kw) * 7 // Kw``)
    week_windows: int = 0
    #: original window index the fit was truncated at by changepoint
    #: detection (None = no changepoint found / detection off)
    changepoint_window: int | None = None

    def __post_init__(self):
        if self.week_seasonal is None:
            self.week_seasonal = np.zeros((4, 0))
        if self.current is None:
            self.current = self.predict(0.0, 0.5)
        if self.basis is None:
            self.basis = np.asarray(self.current, float).copy()

    @property
    def season_windows(self) -> int:
        return int(self.seasonal.shape[1]) if self.seasonal.size else 0

    def predict(self, horizon_windows: float, quantile: float
                ) -> np.ndarray:
        """Predicted per-resource load ``horizon_windows`` past the last
        fitted window, at ``quantile`` (floored at 0 — load is never
        negative)."""
        x = (self.num_windows - 1) + horizon_windows
        y = self.level + self.trend * x
        K = self.season_windows
        if K:
            phase = int(round(x)) % K
            y = y + self.seasonal[:, phase]
        Kw = self.week_windows
        if Kw >= 2 and self.week_seasonal.size:
            wphase = (int(round(x)) % Kw) * 7 // Kw
            y = y + self.week_seasonal[:, wphase]
        z = quantile_z(quantile)
        return np.maximum(y + z * self.sigma, 0.0)

    def factor(self, horizon_ms: float, quantile: float) -> float:
        """Projected load-scale factor at ``horizon_ms``:
        ``1 + (y_hat(t + h, q) - y_hat(t, 0.5)) / basis``, maximized
        over live resources (the tightest resource drives capacity
        risk). Projecting the predicted load *change* onto the model's
        expected-utilization basis means ``factor x model load`` is the
        load the monitor's own estimator reports once the projection
        realizes — for a trending series the trailing mean shifts by
        exactly ``trend x h`` — so sweep pressure, time-to-breach, and
        the breach-replay measurement all share one scale. Idle
        resources (basis ~ 0) are excluded; an entirely idle topic
        projects 1.0."""
        h = horizon_ms / self.window_ms
        pred = self.predict(h, quantile)
        now = self.predict(0.0, 0.5)
        basis = np.asarray(self.basis, float)
        live = basis > _EPS
        if not live.any():
            return 1.0
        delta = np.max((pred[live] - now[live]) / basis[live])
        return max(1.0 + float(delta), 0.0)

    def to_json(self) -> dict:
        return {
            "topic": self.topic, "windowMs": self.window_ms,
            "numWindows": self.num_windows,
            "level": [round(float(v), 6) for v in self.level],
            "trend": [round(float(v), 8) for v in self.trend],
            "seasonal": [[round(float(v), 6) for v in row]
                         for row in self.seasonal],
            "sigma": [round(float(v), 6) for v in self.sigma],
            "lastPhase": self.last_phase,
            "basis": [round(float(v), 6) for v in self.basis],
            "current": [round(float(v), 6) for v in self.current],
            "backtestMape": (None if self.backtest_mape is None
                             else round(float(self.backtest_mape), 6)),
            "degraded": self.degraded,
            "weekSeasonal": [[round(float(v), 6) for v in row]
                             for row in self.week_seasonal],
            "weekWindows": self.week_windows,
            "changepointWindow": self.changepoint_window,
        }

    @classmethod
    def from_json(cls, obj: dict) -> "TopicForecast":
        seasonal = np.asarray(obj.get("seasonal", []), float)
        if seasonal.ndim != 2:
            seasonal = np.zeros((4, 0))
        week = np.asarray(obj.get("weekSeasonal", []), float)
        if week.ndim != 2:
            week = np.zeros((4, 0))
        cp = obj.get("changepointWindow")
        return cls(
            week_seasonal=week,
            week_windows=int(obj.get("weekWindows", 0)),
            changepoint_window=None if cp is None else int(cp),
            topic=str(obj["topic"]), window_ms=int(obj["windowMs"]),
            num_windows=int(obj["numWindows"]),
            level=np.asarray(obj["level"], float),
            trend=np.asarray(obj["trend"], float),
            seasonal=seasonal,
            sigma=np.asarray(obj["sigma"], float),
            last_phase=int(obj.get("lastPhase", 0)),
            backtest_mape=obj.get("backtestMape"),
            basis=(np.asarray(obj["basis"], float)
                   if "basis" in obj else None),
            current=np.asarray(obj["current"], float),
            degraded=str(obj.get("degraded", "none")))


@dataclass
class ForecastSet:
    """The whole fitted pool: topic -> :class:`TopicForecast` plus the
    fit provenance every downstream consumer (scenario factors,
    recommendations, /forecast) carries along."""

    forecasts: dict[str, TopicForecast]
    fitted_at_ms: int
    window_ms: int
    generation: int = 0

    def __len__(self) -> int:
        return len(self.forecasts)

    def worst_backtest_mape(self) -> float | None:
        errs = [f.backtest_mape for f in self.forecasts.values()
                if f.backtest_mape is not None]
        return max(errs) if errs else None

    def factors(self, horizon_ms: float, quantile: float
                ) -> dict[str, float]:
        return {t: f.factor(horizon_ms, quantile)
                for t, f in self.forecasts.items()}

    def provenance(self) -> dict:
        """The fields a ProvisionRecommendation carries as forecast
        provenance (docs/forecasting.md §Provenance)."""
        return {"fittedAtMs": self.fitted_at_ms,
                "windowMs": self.window_ms,
                "generation": self.generation,
                "numTopics": len(self.forecasts),
                "worstBacktestMape": self.worst_backtest_mape()}

    def to_json(self) -> dict:
        return {"fittedAtMs": self.fitted_at_ms,
                "windowMs": self.window_ms,
                "generation": self.generation,
                "topics": {t: f.to_json()
                           for t, f in sorted(self.forecasts.items())}}

    @classmethod
    def from_json(cls, obj: dict) -> "ForecastSet":
        return cls(forecasts={t: TopicForecast.from_json(f)
                              for t, f in obj.get("topics", {}).items()},
                   fitted_at_ms=int(obj.get("fittedAtMs", 0)),
                   window_ms=int(obj.get("windowMs", 1)),
                   generation=int(obj.get("generation", 0)))


def _ols(x: np.ndarray, y: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized least-squares line fit per row of ``y`` ([R, N]) over
    shared abscissa ``x`` ([N]); returns (intercept[R], slope[R])."""
    n = len(x)
    if n < 2:
        lvl = y[:, -1] if n else np.zeros(y.shape[0])
        return lvl, np.zeros(y.shape[0])
    xm = x.mean()
    ym = y.mean(axis=1)
    denom = float(((x - xm) ** 2).sum())
    if denom <= 0.0:
        return ym, np.zeros(y.shape[0])
    slope = ((x - xm)[None, :] * (y - ym[:, None])).sum(axis=1) / denom
    return ym - slope * xm, slope


def _decompose(x: np.ndarray, y: np.ndarray, K: int
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Level/trend + K-phase seasonal decomposition with one backfitting
    refinement: a history covering a non-integer number of periods makes
    plain OLS absorb part of the seasonal swing as a spurious slope, so
    after the first seasonal estimate the trend is REFIT on the
    seasonally-adjusted series and the seasonal recomputed. Returns
    (level[R], trend[R], seasonal[R, K], residual[R, N])."""
    R = y.shape[0]
    seasonal = np.zeros((R, max(K, 0)))
    phases = x.astype(int) % K if K >= 2 else None
    level = trend = None
    for _ in range(2 if K >= 2 else 1):
        adjusted = y - seasonal[:, phases] if K >= 2 else y
        level, trend = _ols(x, adjusted)
        resid = y - (level[:, None] + trend[:, None] * x[None, :])
        if K < 2:
            return level, trend, np.zeros((R, 0)), resid
        for p in range(K):
            sel = phases == p
            if sel.any():
                seasonal[:, p] = resid[:, sel].mean(axis=1)
        # Re-center so the seasonal component carries no net level (the
        # OLS already owns the mean).
        seasonal -= seasonal.mean(axis=1, keepdims=True)
    resid = (y - (level[:, None] + trend[:, None] * x[None, :])
             - seasonal[:, phases])
    return level, trend, seasonal, resid


def _fit_components(x: np.ndarray, y: np.ndarray, K: int, Kw: int
                    ) -> tuple[np.ndarray, np.ndarray, np.ndarray,
                               np.ndarray, np.ndarray]:
    """:func:`_decompose` plus the weekly rung: with ``Kw`` (windows per
    week) >= 2, seven day-of-week residual buckets are backfitted on top
    of the daily component, alternating until the buckets settle (up to
    8 rounds — with unbalanced bucket occupancy, e.g. history ending
    mid-week, fewer rounds leave a biased trend whose residual ramp
    false-trips the changepoint rung). ``Kw = 0`` is EXACTLY the
    pre-weekly fit (the back-compat anchor the ladder tests pin).
    Returns (level[R], trend[R], seasonal[R, K], week[R, 7],
    residual[R, N])."""
    R = y.shape[0]
    week = np.zeros((R, 7))
    if Kw < 2:
        level, trend, seasonal, resid = _decompose(x, y, K)
        return level, trend, seasonal, week, resid
    wph = (x.astype(int) % Kw) * 7 // Kw
    phases = x.astype(int) % K if K >= 2 else None
    r_full = None
    for _ in range(8):
        prev = week.copy()
        adjusted = y - week[:, wph]
        level, trend, seasonal, _resid = _decompose(x, adjusted, K)
        base = level[:, None] + trend[:, None] * x[None, :]
        if K >= 2:
            base = base + seasonal[:, phases]
        r_full = y - base
        for d in range(7):
            sel = wph == d
            if sel.any():
                week[:, d] = r_full[:, sel].mean(axis=1)
        week -= week.mean(axis=1, keepdims=True)
        if np.abs(week - prev).max() <= 1e-9 * (1.0 + np.abs(level).max()):
            break
    resid = r_full - week[:, wph]
    return level, trend, seasonal, week, resid


def _changepoint_split(resid: np.ndarray, y: np.ndarray,
                       min_shift: float, min_tail: int) -> int | None:
    """Best CUSUM split of the fit residual: the index ``j`` (at least
    ``min_tail`` from either edge) maximizing the pre/post mean
    difference, normalized per resource by the median absolute
    window-to-window diff (a robust noise scale a genuine level shift
    barely moves). A candidate shift must ALSO move at least 5% of the
    resource's median level — a near-perfect fit of a smooth series has
    a tiny diff scale, and without the relative floor residual wiggles
    from an imperfect seasonal backfit read as many-sigma shifts.
    Returns ``j`` when the best eligible shift reaches ``min_shift``,
    else None. Periodic structure the ladder already fitted never trips
    this — it tests the RESIDUAL."""
    _R, n = resid.shape
    if n < 2 * min_tail or min_tail < 1:
        return None
    scale = (np.median(np.abs(np.diff(resid, axis=1)), axis=1)
             + _EPS)                                        # [R]
    floor = 0.05 * np.median(np.abs(y), axis=1) + _EPS      # [R]
    csum = np.cumsum(resid, axis=1)
    total = csum[:, -1:]
    js = np.arange(min_tail, n - min_tail + 1)
    pre_mean = csum[:, js - 1] / js
    post_mean = (total - csum[:, js - 1]) / (n - js)
    shift = np.abs(post_mean - pre_mean)                    # [R, |js|]
    ratio = np.where(shift >= floor[:, None],
                     shift / scale[:, None], 0.0)
    best = ratio.max(axis=0)
    k = int(np.argmax(best))
    if best[k] >= min_shift:
        return int(js[k])
    return None


def fit_series(topic: str, values: np.ndarray, valid: np.ndarray,
               window_ms: int, *, season_windows: int = 0,
               week_windows: int = 0, min_history_windows: int = 3,
               changepoint_min_shift: float = 0.0) -> TopicForecast:
    """Fit one topic from its ``[4, W]`` window series.

    ``valid[W]`` marks windows with real samples — invalid columns are
    excluded from every regression (they are zero-filled in the cube and
    would silently drag the level down). ``week_windows`` (windows per
    week, >= 14 to arm) and ``changepoint_min_shift`` (> 0 to arm) are
    the opt-in ladder rungs — both default OFF, reproducing the
    pre-extension fit bit for bit. Deterministic; see the module
    docstring for the model form and degrade ladder."""
    values = np.asarray(values, float)
    valid = np.asarray(valid, bool)
    W = values.shape[1]
    x_all = np.arange(W, dtype=float)
    x = x_all[valid]
    y = values[:, valid]
    n = len(x)

    # The model's expected-utilization basis (mean over valid windows
    # for CPU/NW, LATEST valid window for DISK — the monitor's
    # per-metric ValueComputingStrategy), so a factor applied to a live
    # model's loads reproduces the predicted absolute load.
    if n:
        basis = y.mean(axis=1)
        basis[3] = y[3, -1]
    else:
        basis = np.zeros(4)

    if n < max(min_history_windows, 2):
        # Persistence: too little history for a slope anyone should act
        # on — forecast the last seen level, flat.
        lvl = y[:, -1] if n else np.zeros(4)
        return TopicForecast(
            topic=topic, window_ms=window_ms, num_windows=W,
            level=lvl, trend=np.zeros(4), seasonal=np.zeros((4, 0)),
            sigma=np.zeros(4), last_phase=0, backtest_mape=None,
            basis=basis, degraded="persistence")

    K_req, Kw_req = int(season_windows), int(week_windows)

    def _feasible(m: int) -> tuple[int, int]:
        K = K_req if (K_req >= 2 and m >= K_req) else 0
        Kw = Kw_req if (Kw_req >= 14 and m >= Kw_req) else 0
        return K, Kw

    # Changepoint rung: fit, test the residual for a persistent level
    # shift, truncate to the post-shift suffix, repeat (<= 3 rounds — a
    # completed burst needs two cuts: its onset, then its decay edge).
    cp_window = None
    if changepoint_min_shift > 0.0:
        min_tail = max(min_history_windows, 4)
        for _ in range(3):
            if len(x) < 2 * min_tail:
                break
            K, Kw = _feasible(len(x))
            _l, _t, _s, _w, resid = _fit_components(x, y, K, Kw)
            j = _changepoint_split(resid, y, changepoint_min_shift,
                                   min_tail)
            if j is None:
                break
            cp_window = int(x[j])
            x, y = x[j:], y[:, j:]

    n = len(x)
    K, Kw = _feasible(n)
    fit_seasonal = K >= 2
    fit_weekly = Kw >= 14
    level, trend, seasonal, week, resid = _fit_components(x, y, K, Kw)
    if not fit_seasonal:
        degraded = "no-seasonal"
    elif Kw_req >= 14 and not fit_weekly:
        degraded = "no-weekly"
    else:
        degraded = "none"
    sigma = resid.std(axis=1) if n > 1 else np.zeros(4)

    backtest = _backtest_mape(x, y,
                              season_windows=K if fit_seasonal else 0,
                              week_windows=Kw if fit_weekly else 0)
    return TopicForecast(
        topic=topic, window_ms=window_ms, num_windows=W,
        level=level, trend=trend, seasonal=seasonal, sigma=sigma,
        last_phase=(int(x[-1]) % K) if fit_seasonal else 0,
        backtest_mape=backtest, basis=basis, degraded=degraded,
        week_seasonal=week if fit_weekly else np.zeros((4, 0)),
        week_windows=Kw if fit_weekly else 0,
        changepoint_window=cp_window)


def _backtest_mape(x: np.ndarray, y: np.ndarray, *,
                   season_windows: int,
                   week_windows: int = 0) -> float | None:
    """One-window-holdout backtest: fit on all but the last valid
    window, predict it, report the mean relative error over resources
    with meaningful load. The accuracy number every fit carries (and
    the bench's ``forecast_backtest_mape`` row aggregates)."""
    if len(x) < 3:
        return None
    xf, yf = x[:-1], y[:, :-1]
    K = season_windows if (season_windows >= 2
                           and len(xf) >= season_windows) else 0
    Kw = week_windows if (week_windows >= 14
                          and len(xf) >= week_windows) else 0
    level, trend, seasonal, week, _resid = _fit_components(xf, yf, K, Kw)
    pred = level + trend * x[-1]
    if K >= 2:
        pred = pred + seasonal[:, int(x[-1]) % K]
    if Kw >= 14:
        pred = pred + week[:, (int(x[-1]) % Kw) * 7 // Kw]
    actual = y[:, -1]
    live = np.abs(actual) > _EPS
    if not live.any():
        return None
    return float(np.mean(np.abs(pred[live] - actual[live])
                         / np.abs(actual[live])))


def fit_topic_forecasts(series: dict[str, tuple[np.ndarray, np.ndarray]],
                        window_ms: int, *, seasonal_period_ms: int,
                        min_history_windows: int, fitted_at_ms: int,
                        generation: int = 0, week_period_ms: int = 0,
                        changepoint_min_shift: float = 0.0) -> ForecastSet:
    """Fit every topic in ``series`` (topic -> (values[4, W],
    valid[W])). The seasonal bucket count K = period / window width; a
    period that does not cleanly cover >= 2 windows disables the
    seasonal component for the whole fit. ``week_period_ms`` arms the
    weekly rung the same way (7 day-of-week buckets, needs >= 14
    covered windows); ``changepoint_min_shift`` > 0 arms residual
    changepoint truncation (see :func:`fit_series`)."""
    K = int(seasonal_period_ms // window_ms) if window_ms > 0 else 0
    if K < 2:
        K = 0
    Kw = int(week_period_ms // window_ms) if window_ms > 0 else 0
    if Kw < 14:
        Kw = 0
    forecasts = {
        topic: fit_series(topic, values, valid, window_ms,
                          season_windows=K, week_windows=Kw,
                          min_history_windows=min_history_windows,
                          changepoint_min_shift=changepoint_min_shift)
        for topic, (values, valid) in sorted(series.items())}
    return ForecastSet(forecasts=forecasts, fitted_at_ms=fitted_at_ms,
                       window_ms=window_ms, generation=generation)


class ForecastStore:
    """Fitted forecasts persisted as one JSON file next to the tuned
    search configs, so restarts serve projections without refitting cold
    (same contract as TunedConfigStore: best-effort IO, versioned,
    thread-safe)."""

    def __init__(self, path: str | None = None):
        self.path = path or self.default_path()
        self._lock = threading.Lock()

    @staticmethod
    def default_path() -> str:
        from ..utils.platform import DEFAULT_CACHE_DIR
        return os.path.join(DEFAULT_CACHE_DIR, "forecast",
                            f"v{FORECAST_STORE_VERSION}", "forecasts.json")

    def load(self) -> ForecastSet | None:
        """The persisted fit, or None (missing / unreadable /
        version-skewed files degrade to a cold refit, logged)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None
        if data.get("version") != FORECAST_STORE_VERSION:
            LOG.warning(
                "ignoring persisted forecasts at %s: version %s != %d "
                "(stale format — refit regenerates)",
                self.path, data.get("version"), FORECAST_STORE_VERSION)
            return None
        try:
            fits = ForecastSet.from_json(data.get("forecasts", {}))
        except (KeyError, TypeError, ValueError) as exc:
            LOG.warning("corrupt persisted forecasts at %s (%s); "
                        "refitting cold", self.path, exc)
            return None
        LOG.info("loaded %d persisted topic forecasts from %s",
                 len(fits), self.path)
        return fits

    def save(self, fits: ForecastSet) -> str | None:
        """Persist (best-effort, atomic tmp+rename). Returns the path
        written, or None on IO failure (logged — the engine must keep
        serving either way)."""
        payload = {"version": FORECAST_STORE_VERSION,
                   "forecasts": fits.to_json()}
        with self._lock:
            try:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                tmp = f"{self.path}.tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(payload, f, indent=1, sort_keys=True)
                os.replace(tmp, self.path)
                return self.path
            except OSError as exc:
                LOG.warning("could not persist forecasts to %s: %s",
                            self.path, exc)
                return None


#: resource axis labels shared with the what-if layer (cpu, nwIn,
#: nwOut, disk) — re-exported so consumers need not import whatif.
RESOURCES = RESOURCE_KEYS
