"""The load monitor: metric ingestion -> cluster model factory.

Rebuild of ``monitor/LoadMonitor.java:78``. Owns the partition/broker
windowed aggregators, the capacity resolver, and the metadata source (the
cluster admin client — same SPI the executor uses); ``cluster_model()``
(ref ``:439``) aggregates the retained windows, checks the caller's
completeness requirements, attributes per-replica loads, and flattens
everything into a ``FlatClusterModel`` ready for the TPU analyzer.

Window semantics: each partition's expected utilization is the mean over
its valid aggregated windows (the vectorized equivalent of
``Load.expectedUtilizationFor`` averaging ``AggregatedMetricValues`` rows);
the per-window arrays are preserved on the result for the /load endpoint
and for anomaly detection percentiles.
"""

from __future__ import annotations

import copy
import logging
import threading
from dataclasses import dataclass

import numpy as np

from ..core.aggregator import (AggregationGranularity, AggregationOptions,
                               Extrapolation, MetricSampleAggregator,
                               MetricSampleCompleteness,
                               NotEnoughValidWindowsError)
from ..core.metricdef import (KafkaMetric, broker_metric_def,
                              partition_metric_def)
from ..config.capacity import (BrokerCapacityConfigResolver,
                               FixedCapacityResolver)
from ..model.spec import BrokerSpec, ClusterSpec, PartitionSpec, flatten_spec
from .requirements import ModelCompletenessRequirements
from .sampler import Samples

LOG = logging.getLogger(__name__)


class NotEnoughValidWindowsException(NotEnoughValidWindowsError):
    """Alias with the reference's exception name."""


class StaleClusterModelError(NotEnoughValidWindowsException):
    """An executing (non-dryrun) operation would act on a stale-served
    model: the topology it describes predates the sample dropout, so
    reassignments computed from it may target dead brokers or undo
    post-cache changes. Dryrun/read paths serve stale models flagged;
    execution refuses them (``KafkaCruiseControl.allow_stale_execution``
    overrides)."""


@dataclass
class MonitorConfig:
    """Subset of MonitorConfig constants (ref config/constants/MonitorConfig:
    num.partition.metrics.windows=5, partition.metrics.window.ms=3600000,
    min.samples.per.partition.metrics.window=1, broker variants)."""

    num_windows: int = 5
    window_ms: int = 3_600_000
    min_samples_per_window: int = 1
    num_broker_windows: int = 20
    broker_window_ms: int = 300_000
    min_samples_per_broker_window: int = 1
    max_allowed_extrapolations_per_partition: int = 5
    max_allowed_extrapolations_per_broker: int = 5
    #: follower CPU as a fraction of the leader's attributed CPU (ref
    #: ModelUtils leader/follower CPU estimation).
    follower_cpu_ratio: float = 0.5
    #: default completeness floor for cluster_model() calls that pass no
    #: explicit requirements (ref min.valid.partition.ratio; the served
    #: path wires the config value — 0.95 — while direct library
    #: construction keeps 0.0 so toy models stay buildable).
    min_valid_partition_ratio: float = 0.0
    #: monitor.dense.pipeline: build the cluster model through the dense
    #: whole-pool path (one [E, M, W] aggregation + whole-array flat-model
    #: gathers). False selects the retained per-entity reference path —
    #: kept for parity testing, not for production scale.
    dense_pipeline: bool = True
    #: graceful degradation under sample dropouts (ref
    #: monitor.serve.stale.on.incomplete): when the live window history
    #: no longer meets completeness, serve the last good model — flagged
    #: ``stale`` and metered — instead of failing every proposal path.
    #: Library default False (toy models should fail loudly); the served
    #: stack wires the config key (default True).
    serve_stale_on_incomplete: bool = False
    #: how old a cached model may get before stale-serving gives up and
    #: the completeness error propagates after all (ref
    #: monitor.max.stale.model.age.ms)
    max_stale_model_age_ms: int = 3_600_000
    #: monitor.resident.state: keep the canonical cluster model resident
    #: on device and apply metric-only cycles as compact delta scatters
    #: (model/resident.py); structural changes bump the resident epoch
    #: and fall back to one full rebuild + upload. Dense-pipeline only —
    #: the per-entity reference path always uploads in full.
    resident_state: bool = True
    #: partition-axis pad multiple (model.partition.pad.multiple): the
    #: padded partition count is the next multiple of this, trading
    #: recompiles on partition churn against padded-row HBM waste. At 1M
    #: partitions a coarse (e.g. power-of-two) bucket can waste near 2x
    #: device memory, so the multiple is an explicit knob with a
    #: padding-waste budget watching it (docs/scaling.md).
    partition_pad_multiple: int = 128
    #: broker-axis pad multiple (model.broker.pad.multiple).
    broker_pad_multiple: int = 8


@dataclass
class LoadMonitorState:
    """Serialized into /state (ref LoadMonitorState.java)."""

    state: str
    num_valid_windows: int
    num_total_windows: int
    valid_partition_ratio: float
    num_monitored_partitions: int
    generation: int

    def to_json(self) -> dict:
        return {"state": self.state,
                "numValidWindows": self.num_valid_windows,
                "numTotalWindows": self.num_total_windows,
                "validPartitionsRatio": self.valid_partition_ratio,
                "numMonitoredPartitions": self.num_monitored_partitions,
                "generation": self.generation}


class ClusterModelResult:
    """A flattened model + everything the API layers want alongside it.

    On the dense pipeline, ``spec`` (the per-partition object graph) and
    ``partition_windows`` are built lazily on first access: the serving
    path (optimizer) consumes only the flat arrays, while the object
    consumers (/partition_load, spec mutators, tests) pay the O(P) Python
    cost only when they actually ask.
    """

    def __init__(self, model, metadata, completeness, window_times_ms,
                 generation, *, spec: ClusterSpec | None = None,
                 spec_factory=None,
                 partition_windows: dict | None = None,
                 partition_windows_factory=None):
        #: True when this result was served from the monitor's last-good
        #: cache because the live history missed completeness (sample
        #: dropouts) — consumers may act on it but should surface the flag
        self.stale = False
        #: non-None marks a HYPOTHETICAL result (a what-if scenario
        #: transform of the live model, labeled with the scenario name).
        #: Scenario results must never reach live-cluster consumers: the
        #: proposal cache rejects them outright (ProposalCache.store /
        #: _compute). The monitor itself always emits None here.
        self.scenario_label: str | None = None
        self.model = model                  # FlatClusterModel
        self.metadata = metadata            # ClusterMetadata
        self.completeness = completeness
        self.window_times_ms = window_times_ms
        self.generation = generation
        self._spec = spec
        self._spec_factory = spec_factory
        self._partition_windows = partition_windows
        self._partition_windows_factory = partition_windows_factory

    @property
    def spec(self) -> ClusterSpec:
        if self._spec is None:
            self._spec = self._spec_factory()
        return self._spec

    @property
    def partition_windows(self) -> dict[tuple[str, int], np.ndarray]:
        """(topic, partition) -> [num_metrics, num_windows] window values."""
        if self._partition_windows is None:
            self._partition_windows = self._partition_windows_factory()
        return self._partition_windows


class LoadMonitor:
    """ref LoadMonitor.java:78."""

    def __init__(self, admin, config: MonitorConfig | None = None,
                 capacity_resolver: BrokerCapacityConfigResolver | None = None,
                 rack_by_broker: dict[int, str] | None = None,
                 broker_set_resolver=None,
                 max_concurrent_model_builds: int = 2,
                 registry=None, tracer=None, collector=None,
                 admin_retry=None, sleep_ms=None, now_ms=None,
                 mesh=None) -> None:
        from ..core.runtime_obs import default_collector
        from ..core.sensors import (LOAD_MONITOR_SENSOR, MetricRegistry)
        from ..core.tracing import default_tracer
        self.admin = admin
        self.config = config or MonitorConfig()
        self.capacity_resolver = capacity_resolver or FixedCapacityResolver()
        self.rack_by_broker = rack_by_broker or {}
        #: optional BrokerSetResolver feeding BrokerSetAwareGoal
        self.broker_set_resolver = broker_set_resolver
        #: span tracer (None = process default): cluster_model() emits
        #: nested monitor.cluster-model → monitor.aggregate →
        #: monitor.model-build spans
        self.tracer = tracer or default_tracer()
        #: device-runtime ledger (None = process default): every dense
        #: model build feeds padding-waste ratios host-side (zero device
        #: syncs — the counts are known before the upload), and the model
        #: upload itself is metered in FlatClusterModel.from_numpy.
        self.collector = collector or default_collector()
        c = self.config
        self.partition_aggregator = MetricSampleAggregator(
            c.num_windows, c.window_ms, c.min_samples_per_window,
            partition_metric_def(), entity_group_fn=lambda tp: tp[0],
            tracer=self.tracer)
        self.broker_aggregator = MetricSampleAggregator(
            c.num_broker_windows, c.broker_window_ms,
            c.min_samples_per_broker_window, broker_metric_def(),
            tracer=self.tracer)
        #: bounds concurrent model builds (ref the model-generation
        #: semaphore LoadMonitor.java:94,396); thread-safety of ingest lives
        #: inside MetricSampleAggregator's own lock.
        self._model_semaphore = threading.Semaphore(max_concurrent_model_builds)
        #: optional shared RetryPolicy for the admin reads inside model
        #: builds (serve.py wires the admin.retry.* policy; the chaos
        #: harness passes its engine clock) — None = single attempt, the
        #: library default, so toy stacks keep exact-call semantics.
        self._admin_retry = admin_retry
        self._admin_sleep_ms = sleep_ms
        #: clock the retry policy's overall deadline budget is measured
        #: on (admin.retry.deadline.ms) — the chaos harness passes its
        #: engine clock alongside the engine sleep so deadline cuts
        #: replay byte-identically.
        self._admin_now_ms = now_ms
        self.registry = registry or MetricRegistry()
        #: optional jax.sharding.Mesh (search.mesh.devices, wired by
        #: serve.py): dense model builds upload straight into the
        #: partition-axis sharded layout, so the optimizer/what-if
        #: programs consume the resident buffers without a re-shard.
        self.mesh = mesh
        from ..model.resident import ResidentClusterState
        #: device-resident model state (None when disabled or on the
        #: reference pipeline): the dense assembler routes every build
        #: through it so metric-only cycles become delta scatters instead
        #: of full uploads. Sensors land on this monitor's registry
        #: (``ResidentState.*``).
        self.resident = (
            ResidentClusterState(registry=self.registry,
                                 collector=self.collector,
                                 tracer=self.tracer, mesh=mesh)
            if (c.resident_state and c.dense_pipeline) else None)
        #: replication opt-in (facade.attach_replication_channel): when
        #: the local sample history cannot satisfy a model build, serve
        #: the stream-fed resident model instead of failing the read —
        #: the follower serving path (:meth:`_serve_resident`).
        self.serve_from_resident = False
        # ref LoadMonitor.java:101 cluster-model-creation-timer; the
        # valid-windows / monitored-partitions gauges mirror
        # LoadMonitor.java:104-110 sensor registrations.
        self._model_timer = self.registry.timer(MetricRegistry.name(
            LOAD_MONITOR_SENSOR, "cluster-model-creation-timer"))
        self.registry.gauge(
            MetricRegistry.name(LOAD_MONITOR_SENSOR,
                                "total-monitored-windows"),
            self.partition_aggregator.num_available_windows)
        self.registry.gauge(
            MetricRegistry.name(LOAD_MONITOR_SENSOR,
                                "num-monitored-partitions"),
            lambda: len(self.partition_aggregator.all_entities()))
        # Stale-model degradation bookkeeping: the last successfully-built
        # result (timestamped) + visibility for served-stale events.
        self._last_good: tuple[int, ClusterModelResult] | None = None
        self._last_model_stale = False
        self._stale_served = self.registry.meter(MetricRegistry.name(
            LOAD_MONITOR_SENSOR, "stale-models-served"))
        self._admin_retries = self.registry.meter(MetricRegistry.name(
            LOAD_MONITOR_SENSOR, "admin-retry-rate"))
        #: structural model-validation issues observed at build time
        #: (model.flat.validation_issue_counts over the pre-upload numpy
        #: arrays) — marked per issue so a corrupted admin snapshot shows
        #: on /metrics instead of living in a dict only tests read.
        self._validation_issues = self.registry.meter(MetricRegistry.name(
            LOAD_MONITOR_SENSOR, "flat-model-validation-issues"))
        self.registry.gauge(
            MetricRegistry.name(LOAD_MONITOR_SENSOR, "last-model-stale"),
            lambda: int(self._last_model_stale))
        # Remaining rows of the documented LoadMonitor sensor catalog
        # (Sensors.md): topology health derived from ONE short-TTL admin
        # snapshot per scrape — describe_partitions is O(P x replicas)
        # against a real cluster, and a /metrics read hits all four
        # gauges back-to-back.
        self._topology_cache: tuple[float, dict] | None = None
        for sensor in ("num-topics", "brokers-with-replicas",
                       "dead-brokers-with-replicas",
                       "has-partitions-with-isr-greater-than-replicas"):
            self.registry.gauge(
                MetricRegistry.name(LOAD_MONITOR_SENSOR, sensor),
                (lambda key=sensor: self._topology_snapshot()[key]))

    def _admin_read(self, fn):
        """Admin reads inside model builds ride the shared retry policy
        when one is wired (serve.py / chaos harness): a transient timeout
        on describe_partitions must not fail a whole proposal path.
        Retries are metered (`admin-retry-rate`) and logged; without a
        policy the call is a plain single attempt."""
        if self._admin_retry is None:
            return fn()
        from ..executor.kafka_admin import RETRYABLE_ADMIN_ERRORS

        def on_retry(attempt, delay_ms, exc):
            self._admin_retries.mark()
            LOG.warning(
                "monitor admin read %s failed transiently (%s: %s); "
                "retry %d in %d ms", fn.__name__, type(exc).__name__, exc,
                attempt + 1, delay_ms)
        return self._admin_retry.call(fn, retry_on=RETRYABLE_ADMIN_ERRORS,
                                      sleep_ms=self._admin_sleep_ms,
                                      now_ms=self._admin_now_ms,
                                      on_retry=on_retry)

    def _topology_snapshot(self, ttl_s: float = 5.0) -> dict:
        import time as _time
        now = _time.monotonic()
        if self._topology_cache is not None:
            stamp, snap = self._topology_cache
            if now - stamp < ttl_s:
                return snap
        parts = self.admin.describe_partitions()
        alive = self.admin.describe_cluster()
        hosting = {b for info in parts.values() for b in info.replicas}
        snap = {
            "num-topics": len({t for t, _p in parts}),
            "brokers-with-replicas": len(hosting),
            "dead-brokers-with-replicas": sum(
                1 for b in hosting if not alive.get(b, False)),
            # The documented semantics: MORE ISR entries than replicas
            # (a metadata anomaly), not "ISR outside the replica list".
            "has-partitions-with-isr-greater-than-replicas": int(any(
                len(info.isr) > len(info.replicas)
                for info in parts.values())),
        }
        self._topology_cache = (now, snap)
        return snap

    # -------------------------------------------------------------- ingest
    @staticmethod
    def _ingest_batch(aggregator: MetricSampleAggregator, samples) -> None:
        """One vectorized ingest per batch: one lock acquisition and one
        scatter instead of a per-sample add loop (the dense path of
        ``add_samples_dense``, bit-identical to scalar ingest)."""
        if not samples:
            return
        if len(samples) == 1:
            aggregator.add_sample(samples[0].to_aggregator_sample())
            return
        num_metrics = aggregator.num_metrics
        values = np.full((len(samples), num_metrics), np.nan)
        times = np.empty(len(samples), np.int64)
        entities = []
        for i, s in enumerate(samples):
            entities.append(s.entity)
            times[i] = s.time_ms
            for metric_id, value in s.values.items():
                values[i, metric_id] = value
        aggregator.add_samples_dense(entities, times, values)

    def add_samples(self, samples: Samples) -> None:
        self._ingest_batch(self.partition_aggregator,
                           samples.partition_samples)
        self._ingest_batch(self.broker_aggregator, samples.broker_samples)

    @property
    def generation(self) -> int:
        """Model generation: bumps when aggregation windows roll (the
        proposal cache's staleness key, ref ModelGeneration)."""
        return self.partition_aggregator.generation

    def seed_generation(self, generation: int) -> None:
        """Snapshot restore: resume the pre-crash generation numbering
        (monotonic raise — see MetricSampleAggregator.seed_generation)
        so the restored proposal cache is generation-valid until real
        sample ingest rolls a window."""
        self.partition_aggregator.seed_generation(generation)

    def retain_current_topology(self) -> None:
        """Drop aggregator state for partitions no longer in the cluster
        (ref LoadMonitor's aggregator cleaner :813)."""
        tps = set(self.admin.describe_partitions())
        self.partition_aggregator.retain_entities(tps)
        self.broker_aggregator.retain_entities(
            set(self.admin.describe_cluster()))

    # --------------------------------------------------------------- reads
    def meets_completeness_requirements(
            self, requirements: ModelCompletenessRequirements,
            now_ms: int) -> bool:
        """ref LoadMonitor.meetCompletenessRequirements (:655)."""
        try:
            completeness = self._aggregate(now_ms, requirements).completeness
        except NotEnoughValidWindowsError:
            return False
        return requirements.met_by(completeness)

    def state(self, now_ms: int) -> LoadMonitorState:
        try:
            result = self._aggregate(
                now_ms, ModelCompletenessRequirements(min_required_num_windows=0))
            valid_ratio = result.completeness.valid_entity_ratio
            valid_windows = len(result.completeness.valid_windows)
        except NotEnoughValidWindowsError:
            valid_ratio, valid_windows = 0.0, 0
        return LoadMonitorState(
            state="RUNNING",
            num_valid_windows=valid_windows,
            num_total_windows=self.partition_aggregator.num_available_windows(),
            valid_partition_ratio=valid_ratio,
            num_monitored_partitions=len(
                self.partition_aggregator.all_entities()),
            generation=self.generation)

    def _aggregate(self, now_ms: int,
                   requirements: ModelCompletenessRequirements,
                   partitions=None):
        interested = set(partitions if partitions is not None
                         else self.admin.describe_partitions())
        options = AggregationOptions(
            min_valid_entity_ratio=requirements.min_monitored_partitions_percentage,
            min_valid_windows=requirements.min_required_num_windows,
            max_allowed_extrapolations_per_entity=
                self.config.max_allowed_extrapolations_per_partition,
            granularity=(AggregationGranularity.ENTITY_GROUP
                         if requirements.include_all_topics
                         else AggregationGranularity.ENTITY),
            interested_entities=interested)
        return self.partition_aggregator.aggregate(
            0, now_ms, options, use_dense=self.config.dense_pipeline)

    def cluster_model(self, now_ms: int,
                      requirements: ModelCompletenessRequirements | None = None,
                      *, populate_replica_placement_only: bool = False
                      ) -> ClusterModelResult:
        """Build the flattened cluster model (ref LoadMonitor.clusterModel
        :439). Raises NotEnoughValidWindowsError when the sample history
        cannot satisfy ``requirements``."""
        requirements = requirements or ModelCompletenessRequirements(
            min_monitored_partitions_percentage=(
                self.config.min_valid_partition_ratio))
        with self._model_semaphore, self._model_timer.time(), \
                self.tracer.span("monitor.cluster-model") as sp:
            try:
                result = self._build_model(now_ms, requirements,
                                           populate_replica_placement_only)
            except NotEnoughValidWindowsException:
                stale = self._serve_stale(now_ms, requirements)
                if stale is None:
                    stale = self._serve_resident(now_ms, requirements)
                if stale is None:
                    raise
                sp.set(stale=True,
                       generation=stale.generation)
                return stale
            # Window contents stay "valid" no matter how old they are (the
            # aggregator only rolls on ingest), so completeness alone
            # cannot see a total sample dropout — age the history against
            # the clock as well.
            result.stale = self._history_is_stale(now_ms)
            self._last_model_stale = result.stale
            if result.stale:
                self._stale_served.mark()
                LOG.warning(
                    "sample history has fallen behind the clock at t=%d "
                    "(newest window end %s); serving stale-flagged model",
                    now_ms, self._newest_window_end_ms())
            elif not populate_replica_placement_only:
                # Placement-only models skip load data; caching one would
                # degrade a later stale serve to zero loads silently.
                self._last_good = (now_ms, result)
            sp.set(partitions=len(result.metadata.partition_keys),
                   generation=result.generation)
            return result

    @property
    def last_model_stale(self) -> bool:
        """Whether the most recently served model was stale-flagged (the
        ``last-model-stale`` gauge)."""
        return self._last_model_stale

    def history_stale(self, now_ms: int) -> bool:
        """Whether live sample flow is broken right now (newest completed
        window ended more than two windows ago). The facade's execution
        gate asks this at execution time: a total dropout freezes the
        model generation, so cached proposals can stay generation-valid
        without any model build ever flagging staleness."""
        return self._history_is_stale(now_ms)

    def _newest_window_end_ms(self) -> int | None:
        times = self.partition_aggregator.available_window_times()
        return max(times) + self.config.window_ms if times else None

    def _history_is_stale(self, now_ms: int) -> bool:
        """True when the newest completed partition window ended more than
        TWO full windows before ``now_ms`` — i.e. at least two whole
        windows of samples never arrived. One missed window is scheduling
        jitter (a slow sampling round, a compile pause); two is a real
        dropout/fetcher outage."""
        newest_end = self._newest_window_end_ms()
        return (newest_end is not None
                and now_ms - newest_end > 2 * self.config.window_ms)

    def _serve_stale(self, now_ms: int,
                     requirements: ModelCompletenessRequirements,
                     ) -> ClusterModelResult | None:
        """Graceful degradation on sample dropouts: hand back the last
        good model — flagged ``stale``, metered, logged — instead of
        failing the caller, for as long as the cache stays inside
        ``max_stale_model_age_ms`` AND the cached model satisfies the
        caller's completeness requirements (a strict-requirements request
        must not be answered by a cache built under weaker ones). Returns
        None otherwise (the completeness error then propagates as
        before)."""
        if not self.config.serve_stale_on_incomplete \
                or self._last_good is None:
            return None
        built_ms, result = self._last_good
        if now_ms - built_ms > self.config.max_stale_model_age_ms:
            return None
        if not requirements.met_by(result.completeness):
            return None
        LOG.warning(
            "sample history below completeness at t=%d; serving stale "
            "model built at t=%d (age %d ms, generation %d)", now_ms,
            built_ms, now_ms - built_ms, result.generation)
        self._stale_served.mark()
        self._last_model_stale = True
        # Flag a shallow copy: the cached object may still be held by a
        # caller who received it fresh — never flip .stale under them.
        result = copy.copy(result)
        result.stale = True
        return result

    def _serve_resident(self, now_ms: int,
                        requirements) -> "ClusterModelResult | None":
        """Follower serving path (core/replication.py): a stream-fed
        replica has NO local sample history — the replicated
        device-resident model is its serving state. Build the structural
        planes from the local admin view (placement-only: zero-load,
        resident mirrors untouched) and substitute the resident model's
        arrays, so /load, /partition_load and friends serve the
        leader's streamed numbers. The result is stale-flagged: reads
        are bounded by the replication staleness contract instead of
        local completeness, and the stale-execution gate keeps refusing
        to ACT on it. Assumes leader and replica watch the SAME cluster
        (identical sorted partition keys — true by construction for
        replicas of one serving plane); a topology drift shows up as a
        shape mismatch and falls through to the completeness error."""
        res = self.resident
        if not self.serve_from_resident or res is None \
                or res.model is None:
            return None
        try:
            result = self._build_model(now_ms, requirements, True)
        except Exception:
            return None
        model = res.model
        if (tuple(np.asarray(model.replica_broker).shape)
                != tuple(np.asarray(result.model.replica_broker).shape)):
            LOG.warning(
                "resident-serve refused: replicated model shape %s != "
                "local admin-derived shape %s (topology drift?)",
                tuple(np.asarray(model.replica_broker).shape),
                tuple(np.asarray(result.model.replica_broker).shape))
            return None
        result.model = model
        # Patch the replicated loads into the lazy spec view: without
        # this, /partition_load and other spec consumers would read the
        # placement-only build's zero loads.
        base_factory = result._spec_factory

        def patched_spec():
            spec = base_factory()
            lead = np.asarray(model.leader_load)
            foll = np.asarray(model.follower_load)
            for i, p in enumerate(spec.partitions):
                p.leader_load = tuple(float(x) for x in lead[i])
                p.follower_load = tuple(float(x) for x in foll[i])
            return spec

        result._spec_factory = patched_spec
        result._spec = None
        result.stale = True
        self._stale_served.mark()
        self._last_model_stale = True
        LOG.debug("serving resident-backed model (replication follower "
                  "path): generation %d, epoch %d", result.generation,
                  res.epoch)
        return result

    def _build_model(self, now_ms, requirements, placement_only):
        partitions = self._admin_read(self.admin.describe_partitions)
        alive = self._admin_read(self.admin.describe_cluster)
        result = None
        if not placement_only:
            with self.tracer.span("monitor.aggregate"):
                try:
                    result = self._aggregate(now_ms, requirements,
                                             partitions)
                except NotEnoughValidWindowsError as e:
                    raise NotEnoughValidWindowsException(str(e)) from None
            if not requirements.met_by(result.completeness):
                raise NotEnoughValidWindowsException(
                    f"completeness {result.completeness.valid_entity_ratio:.2f} "
                    f"/ {len(result.completeness.valid_windows)} windows does "
                    f"not meet {requirements}")

        offline_dirs_fn = getattr(self.admin, "offline_logdirs", None)
        offline_dirs = offline_dirs_fn() if offline_dirs_fn is not None else {}
        brokers: list[BrokerSpec] = []
        for broker_id, is_alive in sorted(alive.items()):
            rack = self.rack_by_broker.get(broker_id, f"rack-{broker_id}")
            cap = self.capacity_resolver.capacity_for_broker(
                rack, f"host-{broker_id}", broker_id)
            broker_set = (self.broker_set_resolver.broker_set_for(broker_id)
                          if self.broker_set_resolver is not None else None)
            brokers.append(BrokerSpec(
                broker_id=broker_id, rack=rack, capacity=cap.as_vector(),
                alive=is_alive, broker_set=broker_set,
                broken_disk=bool(offline_dirs.get(broker_id))))

        # Per-replica offline marks beyond dead brokers (failed logdirs) —
        # ref Replica.isCurrentOffline covering bad-disk replicas.
        offline_fn = getattr(self.admin, "offline_replicas", None)
        extra_offline = offline_fn() if offline_fn is not None else set()
        dense = self.config.dense_pipeline and (result is None
                                                or result.dense is not None)
        with self.tracer.span("monitor.model-build", dense=dense):
            if dense:
                return self._assemble_dense(partitions, alive, brokers,
                                            result, extra_offline)
            return self._assemble_reference(partitions, alive, brokers,
                                            result, extra_offline)

    def _assemble_reference(self, partitions, alive, brokers, result,
                            extra_offline) -> ClusterModelResult:
        """The retained per-partition reference assembler (spec objects +
        flatten_spec), used when ``dense_pipeline`` is off and by the
        dense result's lazy ``spec`` property."""
        pspecs, windows, window_times = self._partition_specs(
            partitions, alive, result, extra_offline)
        spec = ClusterSpec(brokers=brokers, partitions=pspecs)
        model, metadata = flatten_spec(
            spec,
            partition_pad_multiple=self.config.partition_pad_multiple,
            broker_pad_multiple=self.config.broker_pad_multiple)
        # Padding accounting from shape metadata + the spec (no device
        # read); the structural-issue meter lives on the dense path only —
        # checking here would cost a device fetch of the just-uploaded
        # arrays, and this assembler exists for parity testing.
        self.collector.observe_padding(
            partitions=len(metadata.partition_keys),
            partitions_padded=model.num_partitions_padded,
            brokers=len(metadata.broker_ids),
            brokers_padded=model.num_brokers_padded,
            replica_slots_used=sum(len(p.replicas) for p in pspecs),
            replica_slots_total=(model.num_partitions_padded
                                 * model.max_replication_factor))
        return ClusterModelResult(
            model=model, metadata=metadata,
            completeness=(result.completeness if result is not None
                          else MetricSampleCompleteness(
                              generation=self.generation)),
            window_times_ms=window_times, generation=self.generation,
            spec=spec, partition_windows=windows)

    def _partition_specs(self, partitions, alive, result, extra_offline):
        """Per-partition object-graph population (ref LoadMonitor
        clusterModel's createReplica/setReplicaLoad walk)."""
        c = self.config
        pspecs: list[PartitionSpec] = []
        windows: dict[tuple[str, int], np.ndarray] = {}
        window_times: list[int] = []
        for tp, info in sorted(partitions.items()):
            leader_load = (0.0, 0.0, 0.0, float(info.size_mb))
            follower_load = None
            if result is not None:
                vae = result.entity_values.get(tp)
                valid_cols = [j for j, e in enumerate(vae.extrapolations)
                              if e is not Extrapolation.NO_VALID_EXTRAPOLATION
                              ] if vae is not None else []
                if vae is not None and valid_cols:
                    windows[tp] = vae.values
                    window_times = vae.window_times_ms
                    # Per-metric ValueComputingStrategy (ref
                    # KafkaMetricDef.java:43-46 + ModelUtils.java:162
                    # expectedUtilizationFor): CPU/NW_IN/NW_OUT are the AVG
                    # over valid windows; DISK is the LATEST valid window —
                    # disk usage is a level, not a rate, so averaging old
                    # windows would understate a growing partition and hide
                    # a burst from the capacity goals. Valid windows only —
                    # invalid windows are zero-filled columns that would
                    # silently dilute the load.
                    mean = vae.values[:, valid_cols].mean(axis=1)
                    latest = vae.values[:, valid_cols[-1]]
                    cpu = float(mean[KafkaMetric.CPU_USAGE])
                    nw_in = float(mean[KafkaMetric.LEADER_BYTES_IN])
                    nw_out = float(mean[KafkaMetric.LEADER_BYTES_OUT])
                    disk = float(latest[KafkaMetric.DISK_USAGE])
                    leader_load = (cpu, nw_in, nw_out, disk)
                    follower_load = (cpu * c.follower_cpu_ratio, nw_in, 0.0,
                                     disk)
            offline = [b for b in info.replicas
                       if not alive.get(b, False)
                       or (tp[0], tp[1], b) in extra_offline]
            # Slot 0 of the flat model is the leader positionally; the admin
            # tracks leadership separately and it diverges from replicas[0]
            # after failover/elections — reorder leader-first.
            replicas = list(info.replicas)
            if info.leader in replicas and replicas[0] != info.leader:
                replicas = [info.leader,
                            *[b for b in replicas if b != info.leader]]
            pspecs.append(PartitionSpec(
                topic=tp[0], partition=tp[1], replicas=replicas,
                leader_load=leader_load, follower_load=follower_load,
                offline_replicas=offline,
                # The admin's stored order IS Kafka's preferred order; when
                # the current leader drifted from it, PLE can now see that.
                preferred_replicas=list(info.replicas)))
        return pspecs, windows, window_times

    def _assemble_dense(self, partitions, alive, brokers, result,
                        extra_offline) -> ClusterModelResult:
        """Whole-array flat-model construction (the dense pipeline).

        One fused pass extracts partition attributes from the admin's
        object graph into flat arrays; replica placement, leader-first
        rotation, offline marks, and expected-utilization loads are then
        whole-array operations — the loads gathered straight from the
        ``DenseAggregate`` cube instead of E ``entity_values`` lookups.
        ``spec`` / ``partition_windows`` stay available as lazy views.
        """
        from ..model.flat import FlatClusterModel
        from ..model.spec import _round_up, flatten_brokers

        c = self.config
        ba = flatten_brokers(brokers,
                             broker_pad_multiple=c.broker_pad_multiple)
        bindex = ba.broker_index
        Bpad = ba.padded
        keys = sorted(partitions)
        P = len(keys)
        infos = [partitions[k] for k in keys]

        rep_counts = np.fromiter((len(i.replicas) for i in infos),
                                 np.int64, P)
        total = int(rep_counts.sum())
        try:
            rep_idx = np.fromiter((bindex[b] for i in infos
                                   for b in i.replicas), np.int64, total)
        except KeyError as e:
            raise ValueError(
                f"partition references unknown broker {e.args[0]}"
            ) from None
        leader_idx = np.fromiter((bindex.get(i.leader, -1) for i in infos),
                                 np.int64, P)
        sizes = np.fromiter((i.size_mb for i in infos), np.float64, P)
        topic_index: dict[str, int] = {}
        ptopic_real = np.fromiter(
            (topic_index.setdefault(t, len(topic_index)) for t, _ in keys),
            np.int64, P)
        partition_index = {k: i for i, k in enumerate(keys)}

        R = max(int(rep_counts.max()) if P else 1, 1)
        Ppad = _round_up(P, c.partition_pad_multiple)
        sentinel = Bpad
        rb = np.full((Ppad, R), sentinel, np.int32)
        if total:
            rep_rows = np.repeat(np.arange(P), rep_counts)
            starts = np.concatenate(([0], np.cumsum(rep_counts)[:-1]))
            rep_cols = np.arange(total) - np.repeat(starts, rep_counts)
            rb[rep_rows, rep_cols] = rep_idx
            srt = np.sort(rb[:P], axis=1)
            dup = (srt[:, 1:] == srt[:, :-1]) & (srt[:, 1:] < sentinel)
            bad = np.nonzero(dup.any(axis=1))[0]
            if bad.size:
                raise ValueError(
                    f"partition {keys[int(bad[0])]}: duplicate replica "
                    "brokers")

        # Slot 0 is the leader positionally; leadership diverges from
        # replicas[0] after failover — rotate leader-first, preserving
        # the followers' relative (= preferred) order.
        pref_pos = np.tile(np.arange(R, dtype=np.int32), (Ppad, 1))
        if P:
            is_lead = rb[:P] == leader_idx[:, None]
            pos = is_lead.argmax(axis=1)
            rot = is_lead.any(axis=1) & (pos != 0)
            rrows = np.nonzero(rot)[0]
            if rrows.size:
                idx = np.arange(R)[None, :]
                src = np.where((idx >= 1) & (idx <= pos[rrows, None]),
                               idx - 1, idx)
                rb[rrows] = np.take_along_axis(rb[rrows], src, axis=1)
                rb[rrows, 0] = leader_idx[rrows]
                psrc = src.astype(np.int32)
                psrc[:, 0] = pos[rrows]
                pref_pos[rrows] = psrc

        alive_ext = np.append(ba.alive, True)    # sentinel slot never offline
        offline = np.zeros((Ppad, R), bool)
        if P:
            offline[:P] = (rb[:P] < sentinel) & ~alive_ext[rb[:P]]
        for (t, pi, b) in extra_offline:
            row = partition_index.get((t, pi))
            bi = bindex.get(b)
            if row is None or bi is None:
                continue
            offline[row, rb[row] == bi] = True

        # Loads: expected utilization per partition by whole-array gathers
        # from the dense aggregate (AVG over valid windows for CPU/NW,
        # LATEST valid window for DISK — see _partition_specs for the
        # per-metric ValueComputingStrategy rationale).
        lead_np = np.zeros((P, 4))
        lead_np[:, 3] = sizes
        foll_np = None
        window_times: list[int] = []
        d = result.dense if result is not None else None
        if d is not None and d.window_times_ms and P:
            no_valid = Extrapolation.NO_VALID_EXTRAPOLATION.value
            hv = (d.extrapolations != no_valid).any(axis=1)
            erow = np.fromiter((d.row_index.get(k, -1) for k in keys),
                               np.int64, P)
            er = np.where(erow >= 0, erow, 0)
            validw = (d.extrapolations[er] != no_valid) & (erow >= 0)[:, None]
            nval = validw.sum(axis=1)
            has = nval > 0
            vals = d.values[er]                               # [P, M, W]
            mean = ((vals * validw[:, None, :]).sum(axis=2)
                    / np.maximum(nval, 1)[:, None])
            Wn = d.extrapolations.shape[1]
            last = Wn - 1 - np.argmax(validw[:, ::-1], axis=1)
            latest = np.take_along_axis(
                vals, last[:, None, None], axis=2)[:, :, 0]
            cpu = np.where(has, mean[:, KafkaMetric.CPU_USAGE], 0.0)
            nw_in = np.where(has, mean[:, KafkaMetric.LEADER_BYTES_IN], 0.0)
            nw_out = np.where(has, mean[:, KafkaMetric.LEADER_BYTES_OUT],
                              0.0)
            disk = np.where(has, latest[:, KafkaMetric.DISK_USAGE], sizes)
            lead_np = np.column_stack([cpu, nw_in, nw_out, disk])
            foll_np = np.column_stack([cpu * c.follower_cpu_ratio, nw_in,
                                       np.zeros(P), disk])
            if hv.any():
                window_times = d.window_times_ms
        if foll_np is None:
            foll_np = lead_np.copy()
            foll_np[:, 0] *= c.follower_cpu_ratio
            foll_np[:, 2] = 0.0

        lead_load = np.zeros((Ppad, 4), np.float32)
        foll_load = np.zeros((Ppad, 4), np.float32)
        lead_load[:P] = lead_np
        foll_load[:P] = foll_np
        ptopic = np.full(Ppad, -1, np.int32)
        ptopic[:P] = ptopic_real
        pvalid = np.zeros(Ppad, bool)
        pvalid[:P] = True

        # Structural validation + padding accounting on the PRE-UPLOAD
        # numpy arrays: metering every build costs vectorized host math
        # only — no device sync, no per-partition Python loop.
        from ..model.flat import validation_issue_counts
        issues = validation_issue_counts(rb, pvalid, ba.valid)
        num_issues = sum(issues.values())
        if num_issues:
            self._validation_issues.mark(num_issues)
            LOG.warning("flat-model validation issues at build: %s",
                        {k: v for k, v in issues.items() if v})
        self.collector.observe_padding(
            partitions=P, partitions_padded=Ppad,
            brokers=len(ba.broker_ids), brokers_padded=Bpad,
            replica_slots_used=total, replica_slots_total=Ppad * R)

        arrays = dict(
            replica_broker=rb, leader_load=lead_load,
            follower_load=foll_load, partition_topic=ptopic,
            partition_valid=pvalid, replica_offline=offline,
            replica_pref_pos=pref_pos, broker_capacity=ba.capacity,
            broker_rack=ba.rack, broker_host=ba.host,
            broker_set=ba.broker_set, broker_alive=ba.alive,
            broker_new=ba.new, broker_demoted=ba.demoted,
            broker_broken_disk=ba.broken, broker_valid=ba.valid)
        if self.resident is not None and result is not None:
            # Resident path: metric-only cycles upload a compact load
            # delta and reuse the device-resident structural buffers;
            # anything else bumps the epoch and full-rebuilds. The arrays
            # above are freshly built every cycle, so handing ownership
            # to the resident state is safe. Placement-only builds
            # (result is None — /load?capacity_only) bypass the resident
            # state entirely: their zero load planes would clobber the
            # mirrors and turn the next real cycle into a full-size
            # "delta" (the same reason _last_good never caches them).
            model = self.resident.update(arrays)
        else:
            model = FlatClusterModel.from_numpy(mesh=self.mesh, **arrays)
        from ..model.spec import ClusterMetadata
        metadata = ClusterMetadata(
            broker_ids=ba.broker_ids, broker_index=bindex,
            topics=list(topic_index), topic_index=topic_index,
            partition_keys=keys, partition_index=partition_index,
            racks=ba.racks, hosts=ba.hosts, broker_sets=ba.broker_sets)

        def spec_factory():
            pspecs, _w, _t = self._partition_specs(partitions, alive,
                                                   result, extra_offline)
            return ClusterSpec(brokers=brokers, partitions=pspecs)

        def pw_factory():
            if d is None or not d.window_times_ms or not P:
                return {}
            return {k: d.values[r] for k, r in zip(keys, erow)
                    if r >= 0 and hv[r]}

        return ClusterModelResult(
            model=model, metadata=metadata,
            completeness=(result.completeness if result is not None
                          else MetricSampleCompleteness(
                              generation=self.generation)),
            window_times_ms=window_times, generation=self.generation,
            spec_factory=spec_factory,
            partition_windows_factory=pw_factory)

    def broker_window_stats(self, now_ms: int) -> dict[int, np.ndarray]:
        """Per-broker [num_metrics, num_valid_windows] aggregates (feeds
        slow-broker and metric-anomaly detection). Invalid windows are
        zero-filled columns in the raw aggregate — dropping them here keeps
        a merely-missed sampling round from reading as a metric collapse."""
        try:
            result = self.broker_aggregator.aggregate(
                0, now_ms, AggregationOptions(min_valid_windows=0),
                use_dense=self.config.dense_pipeline)
        except NotEnoughValidWindowsError:
            return {}
        out: dict[int, np.ndarray] = {}
        if result.dense is not None:
            valid = (result.dense.extrapolations
                     != Extrapolation.NO_VALID_EXTRAPOLATION.value)
            for i, entity in enumerate(result.dense.entities):
                if valid[i].any():
                    out[entity] = result.dense.values[i][:, valid[i]]
            return out
        for entity, vae in result.entity_values.items():
            cols = [j for j, e in enumerate(vae.extrapolations)
                    if e is not Extrapolation.NO_VALID_EXTRAPOLATION]
            if cols:
                out[entity] = vae.values[:, cols]
        return out
