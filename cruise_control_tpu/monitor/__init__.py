"""Monitor layer (L2): metric ingestion -> cluster model factory.

Rebuild of ``cruise-control/.../monitor/``: samplers (:mod:`.sampler`),
the raw-metrics processor with CPU attribution (:mod:`.processor`), sample
persistence/replay (:mod:`.store`), fetch fan-out (:mod:`.fetcher`),
completeness gating (:mod:`.requirements`), the load monitor itself
(:mod:`.monitor`) and the sampling state machine (:mod:`.task_runner`).
"""

from .fetcher import MetricFetcherManager
from .monitor import (ClusterModelResult, LoadMonitor, LoadMonitorState,
                      MonitorConfig, NotEnoughValidWindowsException,
                      StaleClusterModelError)
from .processor import CruiseControlMetricsProcessor
from .prometheus import (PrometheusAdapter, PrometheusMetricSampler,
                         PrometheusResult)
from .requirements import ModelCompletenessRequirements
from .sampler import (AgentTopicSampler, MetricSampler, SamplerAssignment,
                      Samples, SyntheticWorkloadSampler)
from .samples import BrokerMetricSample, PartitionMetricSample
from .store import FileSampleStore, NoopSampleStore, SampleStore
from .task_runner import LoadMonitorTaskRunner, RunnerState

__all__ = [
    "MetricFetcherManager", "ClusterModelResult", "LoadMonitor",
    "LoadMonitorState", "MonitorConfig", "NotEnoughValidWindowsException",
    "StaleClusterModelError",
    "CruiseControlMetricsProcessor", "ModelCompletenessRequirements",
    "PrometheusAdapter", "PrometheusMetricSampler", "PrometheusResult",
    "AgentTopicSampler", "MetricSampler", "SamplerAssignment", "Samples",
    "SyntheticWorkloadSampler", "BrokerMetricSample", "PartitionMetricSample",
    "FileSampleStore", "NoopSampleStore", "SampleStore",
    "LoadMonitorTaskRunner", "RunnerState",
]
