"""Prometheus metric sampler — scrape a Prometheus server instead of the
agent metrics topic.

Ref ``monitor/sampling/prometheus/PrometheusMetricSampler.java`` (sampler),
``PrometheusAdapter.java`` (the ``/api/v1/query_range`` HTTP client) and
``DefaultPrometheusQuerySupplier.java`` (the PromQL catalog mapping raw
Kafka broker/topic/partition metrics to queries). The host-to-broker-id
mapping follows the reference: the ``instance`` label's host part must
resolve to a broker id via the caller-supplied ``broker_id_by_host`` map
(ref ``PrometheusMetricSampler.java`` HOST_PORT pattern handling).

The HTTP transport is injectable (``http_get``) so tests run against a
fake server, like the reference's ``PrometheusMetricSamplerTest`` fake
HTTP harness.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Callable

from ..core.metricdef import BrokerMetric, KafkaMetric
from .sampler import SamplerAssignment, Samples
from .samples import BrokerMetricSample, PartitionMetricSample

#: PromQL per broker-scope metric (ref DefaultPrometheusQuerySupplier
#: TYPE_TO_QUERY broker entries).
DEFAULT_BROKER_QUERIES: dict[BrokerMetric, str] = {
    BrokerMetric.CPU_USAGE:
        "1 - avg by (instance) (irate(node_cpu_seconds_total{mode=\"idle\"}[1m]))",
    BrokerMetric.LEADER_BYTES_IN:
        "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_BytesInPerSec[1m]))",
    BrokerMetric.LEADER_BYTES_OUT:
        "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_BytesOutPerSec[1m]))",
    BrokerMetric.DISK_USAGE:
        "sum by (instance) (kafka_log_Log_Size)",
    BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_MEAN:
        "avg by (instance) (kafka_log_LogFlushStats_LogFlushRateAndTimeMs{quantile=\"0.5\"})",
}

#: PromQL per partition-scope metric; results must carry topic+partition
#: labels (ref DefaultPrometheusQuerySupplier topic/partition entries).
DEFAULT_PARTITION_QUERIES: dict[KafkaMetric, str] = {
    KafkaMetric.LEADER_BYTES_IN:
        "sum by (instance, topic, partition) "
        "(irate(kafka_server_BrokerTopicMetrics_BytesInPerSec[1m]))",
    KafkaMetric.LEADER_BYTES_OUT:
        "sum by (instance, topic, partition) "
        "(irate(kafka_server_BrokerTopicMetrics_BytesOutPerSec[1m]))",
    KafkaMetric.DISK_USAGE:
        "sum by (instance, topic, partition) (kafka_log_Log_Size)",
}


@dataclass
class PrometheusResult:
    """One series of a range-query response (ref PrometheusQueryResult)."""

    labels: dict[str, str]
    values: list[tuple[float, float]]   # (epoch seconds, value)


class PrometheusAdapter:
    """Thin ``/api/v1/query_range`` client (ref PrometheusAdapter.java).

    ``http_get(url) -> str`` is injectable for tests; the default uses
    urllib with a bounded timeout.
    """

    def __init__(self, endpoint: str, *,
                 http_get: Callable[[str], str] | None = None,
                 timeout_s: float = 10.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s
        self._http_get = http_get or self._default_get

    def _default_get(self, url: str) -> str:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return resp.read().decode()

    def query_range(self, query: str, start_ms: int, end_ms: int,
                    step_ms: int) -> list[PrometheusResult]:
        params = urllib.parse.urlencode({
            "query": query,
            "start": start_ms / 1000.0,
            "end": end_ms / 1000.0,
            "step": max(step_ms // 1000, 1),
        })
        raw = self._http_get(f"{self.endpoint}/api/v1/query_range?{params}")
        doc = json.loads(raw)
        if doc.get("status") != "success":
            raise IOError(f"prometheus query failed: {doc.get('error', raw[:200])}")
        out = []
        for series in doc.get("data", {}).get("result", []):
            out.append(PrometheusResult(
                labels=dict(series.get("metric", {})),
                values=[(float(t), float(v))
                        for t, v in series.get("values", [])]))
        return out


def _host_of(instance: str) -> str:
    """``host:port`` (or bare host) -> host, ref HOST_AND_PORT_PATTERN."""
    return instance.rsplit(":", 1)[0] if ":" in instance else instance


class PrometheusMetricSampler:
    """MetricSampler scraping Prometheus (ref PrometheusMetricSampler.java).

    Stateless per call — safe for fetcher fan-out over partition shards.
    """

    parallel_safe = True

    def __init__(self, adapter: PrometheusAdapter,
                 broker_id_by_host: dict[str, int], *,
                 broker_queries: dict[BrokerMetric, str] | None = None,
                 partition_queries: dict[KafkaMetric, str] | None = None,
                 step_ms: int = 30_000):
        self.adapter = adapter
        self.broker_id_by_host = broker_id_by_host
        self.broker_queries = (DEFAULT_BROKER_QUERIES if broker_queries is None
                               else broker_queries)
        self.partition_queries = (DEFAULT_PARTITION_QUERIES
                                  if partition_queries is None
                                  else partition_queries)
        self.step_ms = step_ms

    def _broker_for(self, labels: dict[str, str]) -> int | None:
        host = _host_of(labels.get("instance", ""))
        return self.broker_id_by_host.get(host)

    def get_samples(self, assignment: SamplerAssignment) -> Samples:
        # One sample per (entity, resolution step), like the reference: the
        # PrometheusMetricSampler iterates every (timestamp, value) pair of
        # each range-query series and emits a sample per step, so a window
        # accumulates windows/step samples rather than one per round.
        # The assignment window is treated as half-open (start, end]:
        # Prometheus query_range includes both endpoints, and consecutive
        # sampling rounds share a boundary (round N's end is round N+1's
        # start), so keeping an inclusive start would double-ingest every
        # boundary point into the aggregator (sums/counts skew).
        start_ms = assignment.start_ms
        boundary_skipped = 0
        bsamples: dict[tuple[int, int], BrokerMetricSample] = {}
        wanted_brokers = set(assignment.brokers)
        series_seen = 0
        unresolved_hosts: set[str] = set()
        for metric, query in self.broker_queries.items():
            for series in self.adapter.query_range(
                    query, assignment.start_ms, assignment.end_ms,
                    self.step_ms):
                series_seen += 1
                broker = self._broker_for(series.labels)
                if broker is None:
                    unresolved_hosts.add(
                        _host_of(series.labels.get("instance", "")))
                    continue
                if broker not in wanted_brokers:
                    continue
                for ts_s, value in series.values:
                    ts_ms = int(ts_s * 1000)
                    if ts_ms <= start_ms:
                        boundary_skipped += 1
                        continue
                    s = bsamples.setdefault(
                        (broker, ts_ms), BrokerMetricSample(broker, ts_ms))
                    s.record(metric, value)

        wanted = set(assignment.partitions)
        psamples: dict[tuple[str, int, int], PartitionMetricSample] = {}
        for metric, query in self.partition_queries.items():
            for series in self.adapter.query_range(
                    query, assignment.start_ms, assignment.end_ms,
                    self.step_ms):
                topic = series.labels.get("topic")
                part = series.labels.get("partition")
                if topic is None or part is None or not series.values:
                    continue
                tp = (topic, int(part))
                if tp not in wanted:
                    continue
                for ts_s, value in series.values:
                    ts_ms = int(ts_s * 1000)
                    if ts_ms <= start_ms:
                        boundary_skipped += 1
                        continue
                    s = psamples.setdefault(
                        (tp[0], tp[1], ts_ms),
                        PartitionMetricSample(tp[0], tp[1], ts_ms))
                    s.record(metric, value)
        # A scrape that returns series but records no sample at all is a
        # host-map misconfiguration (unresolved hosts, or hosts resolving
        # to broker ids outside the cluster), not an empty cluster — fail
        # loudly here instead of starving the monitor into
        # NotEnoughValidWindowsException with no cause attached. Points
        # dropped only by the half-open start boundary are legitimate.
        if (series_seen and not bsamples and not psamples
                and not boundary_skipped):
            raise IOError(
                f"prometheus returned {series_seen} series but none "
                f"resolved to a wanted broker id; unresolved hosts "
                f"{sorted(unresolved_hosts)[:5]}, configured host map "
                f"{sorted(self.broker_id_by_host)[:5]}, wanted brokers "
                f"{sorted(wanted_brokers)[:5]} — check "
                "prometheus.broker.host.map.file")
        # CPU attribution: the reference estimates partition CPU from broker
        # CPU x the partition's share of broker bytes
        # (CruiseControlMetricsProcessor); here partition CPU_USAGE is left
        # to the processor-side estimator when absent from Prometheus.
        return Samples(list(psamples.values()), list(bsamples.values()))
