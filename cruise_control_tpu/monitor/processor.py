"""Raw metrics -> metric samples, with CPU attribution.

Rebuild of ``monitor/sampling/CruiseControlMetricsProcessor.java`` (+
``SamplingUtils.java`` / ``ModelUtils.estimateLeaderCpuUtil``): buffers the
raw :class:`CruiseControlMetric` records a sampler polled from the agent
transport, then per window emits

- one :class:`BrokerMetricSample` per broker with reported metrics, and
- one :class:`PartitionMetricSample` per *leader* partition, whose CPU is
  attributed from its broker's CPU by the partition's share of the broker's
  leader bytes in+out (the reference's core estimation trick — per-partition
  CPU is not directly measurable).

Topic-level byte rates are apportioned to the topic's partitions on that
broker by partition size share when sizes are known, else uniformly.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

from ..core.metricdef import BrokerMetric, KafkaMetric
from ..reporter.metrics import CruiseControlMetric, MetricScope, RawMetricType
from .samples import BrokerMetricSample, PartitionMetricSample
from .sampler import SamplerAssignment, Samples

#: follower CPU as a fraction of leader CPU for the same bytes (ref
#: ModelUtils.FOLLOWER_FETCH_... estimation constants).
DEFAULT_CPU_UTIL_FOR_MISSING = 0.0


@dataclass
class _BrokerLoad:
    """Per-broker view of one processing window (ref holder/BrokerLoad.java)."""

    broker_metrics: dict[RawMetricType, float] = field(default_factory=dict)
    #: topic -> RawMetricType -> value
    topic_metrics: dict[str, dict[RawMetricType, float]] = field(
        default_factory=lambda: defaultdict(dict))
    #: (topic, partition) -> size MB
    partition_sizes: dict[tuple[str, int], float] = field(default_factory=dict)


@dataclass
class _TopicGroup:
    """One (broker, topic) attribution group: everything emit() needs to
    score one partition in O(1) (sizes/topic totals/CPU denominators,
    computed once per round in prepare())."""

    time_ms: int
    sizes: dict[tuple[str, int], float]
    total_size: float
    num_tps: int
    t_in: float
    t_out: float
    t_msg: float
    broker_cpu: float
    denom: float


@dataclass
class PreparedRound:
    """One sampling round's folded per-broker state (output of
    :meth:`CruiseControlMetricsProcessor.prepare`): immutable by contract —
    fetcher shards read it concurrently. ``tp_group`` indexes every
    attributable partition to its (broker, topic) group so per-shard
    emission is O(shard size), not O(cluster size)."""

    loads: dict[int, _BrokerLoad]
    times: dict[int, int]
    leader_of: dict[tuple[str, int], int] | None
    groups: dict[tuple[int, str], _TopicGroup]
    #: tp -> every (broker, topic) group that attributes it. With
    #: leadership metadata this is exactly the leader's group; without it,
    #: one group per hosting broker that reported topic metrics (the
    #: pre-fan-out behavior: each hosting broker's view lands in the
    #: aggregator and is averaged within the window).
    tp_groups: dict[tuple[str, int], list[tuple[int, str]]]


class CruiseControlMetricsProcessor:
    def __init__(self, metadata_source=None, cpu_model=None) -> None:
        """``metadata_source``: optional admin client
        (``describe_partitions``) used to attribute topic byte rates only to
        partitions the broker *leads* — the reference processor holds Kafka
        ``Cluster`` metadata for exactly this (SamplingUtils leadership
        checks). Without it, followers of a topic the broker also leads
        would siphon off a share of the leader bytes.

        ``cpu_model``: optional fitted
        :class:`~cruise_control_tpu.model.cpu_regression.LinearRegressionModelParameters`
        (the TRAIN endpoint's output). When a broker's CPU metric is
        missing from a round, CPU is estimated from its byte rates instead
        of defaulting to 0 (ref ``ModelUtils.estimateLeaderCpuUtil`` with
        ``use.linear.regression.model``)."""
        self._records: list[CruiseControlMetric] = []
        self._metadata_source = metadata_source
        self._cpu_model = cpu_model

    def add_metrics(self, records: list[CruiseControlMetric]) -> None:
        self._records.extend(records)

    def prepare(self, start_ms: int, end_ms: int) -> "PreparedRound":
        """Fold buffered records into per-broker loads for one window —
        the cross-partition/cross-broker half of processing, done ONCE per
        sampling round so :meth:`emit` can fan out over partition shards
        (ref ``MetricFetcherManager.java:37``: the reference parallelizes
        the sampler fetch; here the shared state is isolated first so the
        per-shard attribution is a pure read). Clears the buffer."""
        loads: dict[int, _BrokerLoad] = defaultdict(_BrokerLoad)
        times: dict[int, int] = {}
        for r in self._records:
            if not (start_ms <= r.time_ms < end_ms):
                continue
            bl = loads[r.broker_id]
            times[r.broker_id] = max(times.get(r.broker_id, 0), r.time_ms)
            if r.metric_type.scope is MetricScope.BROKER:
                bl.broker_metrics[r.metric_type] = r.value
            elif r.metric_type.scope is MetricScope.TOPIC:
                bl.topic_metrics[r.topic][r.metric_type] = r.value
            else:
                bl.partition_sizes[(r.topic, r.partition)] = r.value
        self._records.clear()

        leader_of: dict[tuple[str, int], int] | None = None
        if self._metadata_source is not None:
            leader_of = {tp: info.leader for tp, info in
                         self._metadata_source.describe_partitions().items()}
        for bl in loads.values():
            # Missing broker CPU: estimate from byte rates via the trained
            # regression (TRAIN endpoint) rather than defaulting to 0 —
            # both the broker sample and the per-partition CPU attribution
            # then read the estimate (ref ModelUtils.estimateLeaderCpuUtil).
            if (RawMetricType.BROKER_CPU_UTIL not in bl.broker_metrics
                    and self._cpu_model is not None):
                est = self._cpu_model.estimate(
                    bl.broker_metrics.get(RawMetricType.ALL_TOPIC_BYTES_IN,
                                          0.0),
                    bl.broker_metrics.get(RawMetricType.ALL_TOPIC_BYTES_OUT,
                                          0.0))
                if est is not None:
                    bl.broker_metrics[RawMetricType.BROKER_CPU_UTIL] = est

        # Per-(broker, topic) attribution groups — the cross-partition
        # half of partition-sample attribution, done once per round so emit() costs
        # O(shard) regardless of fan-out width.
        groups: dict[tuple[int, str], _TopicGroup] = {}
        tp_groups: dict[tuple[str, int], list[tuple[int, str]]] = {}
        for broker_id, bl in loads.items():
            t = times[broker_id]
            broker_cpu = bl.broker_metrics.get(
                RawMetricType.BROKER_CPU_UTIL, DEFAULT_CPU_UTIL_FOR_MISSING)
            tot_in = bl.broker_metrics.get(
                RawMetricType.ALL_TOPIC_BYTES_IN, 0.0)
            tot_out = bl.broker_metrics.get(
                RawMetricType.ALL_TOPIC_BYTES_OUT, 0.0)
            by_topic: dict[str, list[tuple[str, int]]] = defaultdict(list)
            for tp in bl.partition_sizes:
                if leader_of is not None and leader_of.get(tp) != broker_id:
                    continue
                by_topic[tp[0]].append(tp)
            for topic, tms in bl.topic_metrics.items():
                tps = by_topic.get(topic, [])
                if not tps:
                    continue
                sizes = {tp: max(bl.partition_sizes.get(tp, 0.0), 0.0)
                         for tp in tps}
                g = _TopicGroup(
                    time_ms=t, sizes=sizes,
                    total_size=sum(sizes.values()), num_tps=len(tps),
                    t_in=tms.get(RawMetricType.TOPIC_BYTES_IN, 0.0),
                    t_out=tms.get(RawMetricType.TOPIC_BYTES_OUT, 0.0),
                    t_msg=tms.get(RawMetricType.TOPIC_MESSAGES_IN_PER_SEC,
                                  0.0),
                    broker_cpu=broker_cpu, denom=tot_in + tot_out)
                groups[(broker_id, topic)] = g
                for tp in tps:
                    tp_groups.setdefault(tp, []).append((broker_id, topic))
        return PreparedRound(loads=loads, times=times, leader_of=leader_of,
                             groups=groups, tp_groups=tp_groups)

    def emit(self, prepared: "PreparedRound",
             assignment: SamplerAssignment, *,
             include_brokers: bool | None = None,
             empty_assignment_means_all: bool = False) -> Samples:
        """Samples for one shard of a prepared round. Pure read of
        ``prepared`` — safe to call concurrently from fetcher threads on
        disjoint partition shards, and O(shard size): each wanted
        partition is an index lookup into the prepared attribution groups.
        Broker samples are emitted only for the shard that carries the
        broker assignment (exactly one per round), unless
        ``include_brokers`` forces it. An EMPTY shard emits nothing
        (``empty_assignment_means_all`` restores the single-shot
        "no filter = everything" contract for :meth:`process`)."""
        if include_brokers is None:
            include_brokers = bool(assignment.brokers)
        if assignment.partitions:
            wanted = assignment.partitions
        elif empty_assignment_means_all:
            wanted = list(prepared.tp_groups)
        else:
            wanted = []
        psamples: list[PartitionMetricSample] = []
        bsamples: list[BrokerMetricSample] = []
        if include_brokers:
            for broker_id, bl in prepared.loads.items():
                bsamples.append(self._broker_sample(
                    broker_id, prepared.times[broker_id], bl))
        for tp in wanted:
            for gkey in prepared.tp_groups.get(tp, ()):
                g = prepared.groups[gkey]
                share = (g.sizes[tp] / g.total_size if g.total_size > 0
                         else 1.0 / g.num_tps)
                p_in = g.t_in * share
                p_out = g.t_out * share
                s = PartitionMetricSample(tp[0], tp[1], g.time_ms)
                s.record(KafkaMetric.LEADER_BYTES_IN, p_in)
                s.record(KafkaMetric.LEADER_BYTES_OUT, p_out)
                s.record(KafkaMetric.DISK_USAGE, g.sizes.get(tp, 0.0))
                s.record(KafkaMetric.MESSAGE_IN_RATE, g.t_msg * share)
                # CPU attribution: broker CPU x partition share of broker
                # leader bytes (ref ModelUtils.estimateLeaderCpuUtil).
                cpu_share = (p_in + p_out) / g.denom if g.denom > 0 else 0.0
                s.record(KafkaMetric.CPU_USAGE, g.broker_cpu * cpu_share)
                psamples.append(s)
        return Samples(psamples, bsamples)

    def emit_dense(self, prepared: "PreparedRound",
                   assignment: SamplerAssignment, *,
                   empty_assignment_means_all: bool = False):
        """Array-native variant of :meth:`emit` for the dense ingest path.

        Returns ``(entities, times_ms, values)`` parallel arrays ready for
        ``MetricSampleAggregator.add_samples_dense`` (``values`` is
        ``[N, num_metrics]`` with NaN marking unset metrics) — the same
        attribution math as :meth:`emit`, computed as whole-array
        operations over the prepared groups with no per-sample holder
        objects. Broker samples stay on the object path (:meth:`emit`);
        the broker axis is orders of magnitude smaller than the partition
        axis.

        The default serving path still routes through :meth:`emit`
        because the sample-store persistence contract consumes
        ``PartitionMetricSample`` objects; this is the seam for a
        store-side dense writer to plug into. Attribution parity with
        :meth:`emit` is pinned by
        tests/test_monitor.py::test_processor_emit_dense_matches_emit,
        so the two cannot silently drift."""
        import numpy as np

        from ..core.metricdef import partition_metric_def
        if assignment.partitions:
            wanted = assignment.partitions
        elif empty_assignment_means_all:
            wanted = list(prepared.tp_groups)
        else:
            wanted = []
        pairs = [(tp, gkey) for tp in wanted
                 for gkey in prepared.tp_groups.get(tp, ())]
        N = len(pairs)
        M = partition_metric_def().size()
        values = np.full((N, M), np.nan)
        if not N:
            return [], np.empty(0, np.int64), values
        groups = prepared.groups
        gid = {gkey: i for i, gkey in enumerate(groups)}
        garr = np.array([[g.t_in, g.t_out, g.t_msg, g.broker_cpu, g.denom,
                          g.total_size, g.num_tps, g.time_ms]
                         for g in groups.values()])
        pg = np.fromiter((gid[gkey] for _tp, gkey in pairs), np.int64, N)
        sizes = np.fromiter((groups[gkey].sizes[tp] for tp, gkey in pairs),
                            np.float64, N)
        entities = [tp for tp, _gkey in pairs]
        g = garr[pg]
        total, num_tps, denom = g[:, 5], g[:, 6], g[:, 4]
        share = np.where(total > 0,
                         sizes / np.where(total > 0, total, 1.0),
                         1.0 / num_tps)
        p_in = g[:, 0] * share
        p_out = g[:, 1] * share
        cpu_share = np.where(denom > 0,
                             (p_in + p_out) / np.where(denom > 0, denom, 1.0),
                             0.0)
        values[:, KafkaMetric.LEADER_BYTES_IN] = p_in
        values[:, KafkaMetric.LEADER_BYTES_OUT] = p_out
        values[:, KafkaMetric.DISK_USAGE] = sizes
        values[:, KafkaMetric.MESSAGE_IN_RATE] = g[:, 2] * share
        # CPU attribution: broker CPU x partition share of broker leader
        # bytes (ref ModelUtils.estimateLeaderCpuUtil), as in emit().
        values[:, KafkaMetric.CPU_USAGE] = g[:, 3] * cpu_share
        return entities, g[:, 7].astype(np.int64), values

    def process(self, assignment: SamplerAssignment) -> Samples:
        """Convert buffered records into samples for the assignment window
        (ref CruiseControlMetricsProcessor.process). Clears the buffer.
        Single-shot equivalent of :meth:`prepare` + :meth:`emit`."""
        prepared = self.prepare(assignment.start_ms, assignment.end_ms)
        return self.emit(prepared, assignment, include_brokers=True,
                         empty_assignment_means_all=True)

    def _broker_sample(self, broker_id: int, t: int,
                       bl: _BrokerLoad) -> BrokerMetricSample:
        s = BrokerMetricSample(broker_id, t)
        m = bl.broker_metrics

        def put(dst: BrokerMetric, src: RawMetricType):
            if src in m:
                s.record(dst, m[src])

        put(BrokerMetric.CPU_USAGE, RawMetricType.BROKER_CPU_UTIL)
        put(BrokerMetric.LEADER_BYTES_IN, RawMetricType.ALL_TOPIC_BYTES_IN)
        put(BrokerMetric.LEADER_BYTES_OUT, RawMetricType.ALL_TOPIC_BYTES_OUT)
        put(BrokerMetric.REPLICATION_BYTES_IN_RATE,
            RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN)
        put(BrokerMetric.REPLICATION_BYTES_OUT_RATE,
            RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT)
        put(BrokerMetric.BROKER_PRODUCE_REQUEST_RATE,
            RawMetricType.ALL_TOPIC_PRODUCE_REQUEST_RATE)
        put(BrokerMetric.BROKER_CONSUMER_FETCH_REQUEST_RATE,
            RawMetricType.ALL_TOPIC_FETCH_REQUEST_RATE)
        put(BrokerMetric.BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT,
            RawMetricType.BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT)
        put(BrokerMetric.BROKER_LOG_FLUSH_RATE, RawMetricType.BROKER_LOG_FLUSH_RATE)
        put(BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_MEAN,
            RawMetricType.BROKER_LOG_FLUSH_TIME_MS_MEAN)
        put(BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_999TH,
            RawMetricType.BROKER_LOG_FLUSH_TIME_MS_999TH)
        s.record(BrokerMetric.DISK_USAGE, sum(bl.partition_sizes.values()))
        return s

