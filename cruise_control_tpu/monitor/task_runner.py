"""Sampling task runner: the monitor's state machine + scheduled loop.

Rebuild of ``monitor/task/LoadMonitorTaskRunner.java:33`` with states
``NOT_STARTED / LOADING / RUNNING / SAMPLING / PAUSED / BOOTSTRAPPING``
(``:57-58``) and the bootstrap task (``BootstrapTask.java`` — replay a
historic range through the sampler to warm the aggregators).

Clock-driven rather than thread-scheduled: :meth:`maybe_run_sampling` is
called by the serving loop (or a timer thread) and fires when the sampling
interval elapsed — the same pattern the executor uses, keeping tests
wall-clock free.
"""

from __future__ import annotations

import enum
import threading

from .fetcher import MetricFetcherManager
from .monitor import LoadMonitor


class RunnerState(enum.Enum):
    NOT_STARTED = "NOT_STARTED"
    LOADING = "LOADING"
    RUNNING = "RUNNING"
    SAMPLING = "SAMPLING"
    PAUSED = "PAUSED"
    BOOTSTRAPPING = "BOOTSTRAPPING"
    TRAINING = "TRAINING"


class LoadMonitorTaskRunner:
    def __init__(self, monitor: LoadMonitor, fetcher: MetricFetcherManager,
                 sampling_interval_ms: int = 120_000) -> None:
        self.monitor = monitor
        self.fetcher = fetcher
        self.sampling_interval_ms = sampling_interval_ms
        self._state = RunnerState.NOT_STARTED
        self._lock = threading.RLock()
        self._last_sample_ms: int | None = None
        self._reason_for_pause: str | None = None

    @property
    def state(self) -> RunnerState:
        return self._state

    # ------------------------------------------------------------ lifecycle
    def start(self, now_ms: int, *, skip_loading: bool = False) -> int:
        """Replay persisted samples (LOADING) then enter RUNNING (ref
        LoadMonitor.startUp -> sample store loading). Returns #samples
        replayed."""
        with self._lock:
            if self._state is not RunnerState.NOT_STARTED:
                raise RuntimeError(f"already started ({self._state})")
            replayed = 0
            newest = 0
            if not skip_loading:
                self._state = RunnerState.LOADING
                dense_fn = getattr(self.fetcher.store,
                                   "load_samples_dense", None)
                dense = dense_fn() if dense_fn is not None else None
                if dense is not None:
                    # Native columnar replay (store.py load_samples_dense):
                    # the partition history ingests in one vectorized call;
                    # newest comes from the store (computed once there for
                    # retention).
                    (entities, times, values), bsamples, newest = dense
                    self.monitor.partition_aggregator.add_samples_dense(
                        entities, times, values)
                    for s in bsamples:
                        self.monitor.broker_aggregator.add_sample(
                            s.to_aggregator_sample())
                    replayed = len(entities) + len(bsamples)
                else:
                    samples = self.fetcher.store.load_samples()
                    self.monitor.add_samples(samples)
                    replayed = (len(samples.partition_samples)
                                + len(samples.broker_samples))
                    if replayed:
                        newest = max(
                            s.time_ms
                            for s in (samples.partition_samples
                                      + samples.broker_samples))
            self._state = RunnerState.RUNNING
            if replayed:
                # Seed from the newest replayed sample so the first live
                # round starts where the store left off — otherwise it
                # re-covers [now-interval, now) and double-ingests samples
                # just replayed from that window (sample_counts inflate).
                # Clamped into [now - aggregator retention, now]: after a
                # long downtime the catch-up fetch is bounded by what the
                # windows can retain anyway (an uncapped range would be one
                # giant query — Prometheus rejects >11K points/series — and
                # a future timestamp from clock skew would stall sampling).
                c = self.monitor.config
                retention_ms = max(
                    c.num_windows * c.window_ms,
                    c.num_broker_windows * c.broker_window_ms)
                self._last_sample_ms = min(
                    max(newest, now_ms - retention_ms), now_ms)
            else:
                # Leave unset: the first maybe_run_sampling is immediately
                # due (the reference's sampling loop fetches right at
                # startup) and covers one interval back.
                self._last_sample_ms = None
            return replayed

    def pause(self, reason: str = "") -> None:
        """ref pauseSampling (PAUSE_SAMPLING endpoint)."""
        with self._lock:
            if self._state in (RunnerState.RUNNING, RunnerState.SAMPLING):
                self._state = RunnerState.PAUSED
                self._reason_for_pause = reason

    def resume(self, reason: str = "") -> None:
        with self._lock:
            if self._state is RunnerState.PAUSED:
                self._state = RunnerState.RUNNING
                self._reason_for_pause = None

    # ------------------------------------------------------------- sampling
    def maybe_run_sampling(self, now_ms: int) -> bool:
        """Run one sampling round if due; returns True when sampled."""
        with self._lock:
            if self._state is not RunnerState.RUNNING:
                return False
            if (self._last_sample_ms is not None
                    and now_ms - self._last_sample_ms < self.sampling_interval_ms):
                return False
            self._state = RunnerState.SAMPLING
        try:
            # First round covers one interval back: a [now, now) window
            # would be empty, so window-filtered samplers (the agent
            # pipeline, Prometheus range queries) could never deliver
            # their first records.
            start = (max(now_ms - self.sampling_interval_ms, 0)
                     if self._last_sample_ms is None
                     else self._last_sample_ms)
            partitions = sorted(self.monitor.admin.describe_partitions())
            brokers = sorted(self.monitor.admin.describe_cluster())
            samples = self.fetcher.fetch(partitions, brokers, start, now_ms)
            self.monitor.add_samples(samples)
            self._last_sample_ms = now_ms
            return True
        finally:
            with self._lock:
                if self._state is RunnerState.SAMPLING:
                    self._state = RunnerState.RUNNING

    def bootstrap(self, start_ms: int, end_ms: int,
                  step_ms: int | None = None) -> int:
        """Replay a historic range through the sampler to warm the window
        history (ref BootstrapTask.java; BOOTSTRAP endpoint). Returns the
        number of sampling rounds executed."""
        with self._lock:
            prev = self._state
            if prev is RunnerState.NOT_STARTED:
                raise RuntimeError("start() the runner before bootstrapping")
            if prev not in (RunnerState.RUNNING, RunnerState.PAUSED):
                raise RuntimeError(
                    f"cannot bootstrap while {prev.value} (a sampling or "
                    "bootstrap round is in flight)")
            self._state = RunnerState.BOOTSTRAPPING
        rounds = 0
        try:
            step = step_ms or self.sampling_interval_ms
            partitions = sorted(self.monitor.admin.describe_partitions())
            brokers = sorted(self.monitor.admin.describe_cluster())
            t = start_ms
            while t < end_ms:
                t_end = min(t + step, end_ms)
                samples = self.fetcher.fetch(partitions, brokers, t, t_end)
                self.monitor.add_samples(samples)
                t = t_end
                rounds += 1
            self._last_sample_ms = end_ms
            return rounds
        finally:
            with self._lock:
                if self._state is RunnerState.BOOTSTRAPPING:
                    self._state = prev

    def training(self):
        """Context manager marking a TRAIN run in the state machine (ref
        LoadMonitorTaskRunner.java:57-58 TRAINING state — sampling pauses
        while the regression trains, and resumes after)."""
        runner = self

        class _Training:
            def __enter__(self):
                with runner._lock:
                    self._prev = runner._state
                    if self._prev not in (RunnerState.RUNNING,
                                          RunnerState.PAUSED):
                        raise RuntimeError(
                            f"cannot train while {self._prev.value}")
                    runner._state = RunnerState.TRAINING
                return self

            def __exit__(self, *exc):
                with runner._lock:
                    if runner._state is RunnerState.TRAINING:
                        runner._state = self._prev
                return False

        return _Training()

    def state_json(self) -> dict:
        return {"state": self._state.value,
                "reasonOfLatestPauseOrResume": self._reason_for_pause,
                "lastSampleTimeMs": self._last_sample_ms}
