"""Sample persistence: the checkpoint/resume mechanism.

Rebuild of ``monitor/sampling/KafkaSampleStore.java:68`` (the reference
stores every sample in two compacted Kafka topics and replays them on
startup, so a restarted server regains its N-hour metrics window without
re-sampling). Here the durable medium is an append-only JSONL file pair;
the SPI is the same store/replay contract (``SampleStore.java``).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Protocol

from .sampler import Samples
from .samples import BrokerMetricSample, PartitionMetricSample

LOG = logging.getLogger(__name__)


class SampleStore(Protocol):
    """ref SampleStore.java:96."""

    def store_samples(self, samples: Samples) -> None: ...

    def load_samples(self) -> Samples: ...

    def close(self) -> None: ...


class NoopSampleStore:
    """ref NoopSampleStore: persistence disabled."""

    def store_samples(self, samples: Samples) -> None:
        pass

    def load_samples(self) -> Samples:
        return Samples([], [])

    def close(self) -> None:
        pass


class FileSampleStore:
    """Append-only JSONL files, one line per sample (the file-backed
    equivalent of the two sample-store topics,
    ``partition.metric.sample.store.topic`` / ``broker.metric.sample.store.
    topic`` ``KafkaSampleStore.java:93-94``)."""

    def __init__(self, directory: str, *,
                 retention_ms: int | None = None) -> None:
        os.makedirs(directory, exist_ok=True)
        self._dir = directory
        self._retention_ms = retention_ms
        #: records skipped on replay because the line would not parse —
        #: a crash mid-append leaves a torn trailing line; it used to
        #: poison the whole replay (one json.loads error killed the
        #: LOADING state). Metered here, surfaced via the warning log.
        self.skipped_records = 0
        self._lock = threading.Lock()
        self._pfile = open(os.path.join(directory, "partition_samples.jsonl"),
                           "a", encoding="utf-8")
        self._bfile = open(os.path.join(directory, "broker_samples.jsonl"),
                           "a", encoding="utf-8")

    def store_samples(self, samples: Samples) -> None:
        with self._lock:
            for s in samples.partition_samples:
                self._pfile.write(json.dumps(s.to_json()) + "\n")
            for s in samples.broker_samples:
                self._bfile.write(json.dumps(s.to_json()) + "\n")
            self._pfile.flush()
            self._bfile.flush()

    def load_samples_dense(self):
        """Columnar replay: the partition side parsed by the native
        scanner (sidecar/libsample_loader.so) straight into
        ``add_samples_dense``-shaped arrays; broker samples (small) stay
        object-parsed. Returns ``((entities, times, values),
        broker_samples)`` or ``None`` when the native loader is
        unavailable or refuses the file — callers then use
        :meth:`load_samples`."""
        from ..core.metricdef import partition_metric_def
        from . import native_loader
        with self._lock:
            self._pfile.flush()
            self._bfile.flush()
            block = native_loader.load_partition_samples_dense(
                os.path.join(self._dir, "partition_samples.jsonl"),
                partition_metric_def().size())
            if block is None:
                return None
            bsamples = self._read(
                os.path.join(self._dir, "broker_samples.jsonl"),
                BrokerMetricSample.from_json)
        entities, times, values = block
        latest = max(int(times.max()) if len(times) else 0,
                     max((s.time_ms for s in bsamples), default=0))
        if self._retention_ms is not None:
            horizon = latest - self._retention_ms
            keep = times >= horizon
            entities = [e for e, k in zip(entities, keep) if k]
            times, values = times[keep], values[keep]
            bsamples = [s for s in bsamples if s.time_ms >= horizon]
        return (entities, times, values), bsamples, latest

    def load_samples(self) -> Samples:
        """Replay everything retained (ref KafkaSampleStore loadSamples -> the
        LOADING monitor state)."""
        with self._lock:
            self._pfile.flush()
            self._bfile.flush()
            psamples = self._read(os.path.join(self._dir,
                                               "partition_samples.jsonl"),
                                  PartitionMetricSample.from_json)
            bsamples = self._read(os.path.join(self._dir,
                                               "broker_samples.jsonl"),
                                  BrokerMetricSample.from_json)
        latest = max([s.time_ms for s in psamples + bsamples], default=0)
        if self._retention_ms is not None:
            horizon = latest - self._retention_ms
            psamples = [s for s in psamples if s.time_ms >= horizon]
            bsamples = [s for s in bsamples if s.time_ms >= horizon]
        return Samples(psamples, bsamples)

    def _read(self, path: str, parse):
        out = []
        if not os.path.exists(path):
            return out
        skipped = 0
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                # Crash-tolerance: a process dying mid-append leaves a
                # torn trailing line (and, on weirder filesystems, a
                # NUL-padded hole). Skip + meter the unparseable record
                # instead of failing the whole replay — losing one
                # sample is noise; losing N hours of history repays the
                # entire warm-in.
                try:
                    out.append(parse(json.loads(line)))
                except (ValueError, KeyError, TypeError):
                    skipped += 1
        if skipped:
            self.skipped_records += skipped
            LOG.warning(
                "sample replay from %s skipped %d unparseable record(s) "
                "(torn append from a crash mid-write; replay continues "
                "with the remaining history)", path, skipped)
        return out

    def close(self) -> None:
        with self._lock:
            self._pfile.close()
            self._bfile.close()


class OnExecutionSampleStore:
    """Secondary store capturing partition samples taken WHILE an
    execution is in flight (ref
    ``KafkaPartitionMetricSampleOnExecutionStore.java:106`` — the
    reference writes them to a dedicated topic so the load impact of an
    execution can be audited separately from steady-state history).

    Wraps any :class:`SampleStore`; ``has_ongoing_execution`` is the
    executor probe — samples arriving outside an execution are dropped.
    """

    def __init__(self, inner: SampleStore, has_ongoing_execution) -> None:
        self.inner = inner
        self.has_ongoing_execution = has_ongoing_execution

    def store_samples(self, samples: Samples) -> None:
        if self.has_ongoing_execution():
            self.inner.store_samples(
                Samples(samples.partition_samples, []))

    def load_samples(self) -> Samples:
        return self.inner.load_samples()

    def close(self) -> None:
        self.inner.close()
