"""Metric sampler SPI + bundled implementations.

Ref ``monitor/sampling/MetricSampler.java`` (the pluggable interface),
``CruiseControlMetricsReporterSampler.java`` (consumes the agent's metrics
topic) and ``prometheus/PrometheusMetricSampler.java``. Here:

- :class:`MetricSampler` — the SPI (``get_samples(assignment, window)``);
- :class:`AgentTopicSampler` — consumes :class:`CruiseControlMetric` records
  produced by the L0 reporter agent into a :class:`MetricsTransport`
  (the stand-in for the ``__CruiseControlMetrics`` Kafka topic) and runs
  them through the processor — the default pipeline, matching the
  reference's reporter -> topic -> sampler -> processor flow;
- :class:`SyntheticWorkloadSampler` — samples a
  :class:`~cruise_control_tpu.executor.simulated.SimulatedKafkaCluster`
  with a deterministic synthetic workload model (tests, demos, benches).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Protocol

import numpy as np

from ..core.metricdef import BrokerMetric, KafkaMetric
from .samples import BrokerMetricSample, PartitionMetricSample


@dataclass
class SamplerAssignment:
    """Which partitions/brokers this sampler call covers (ref
    MetricFetcherManager splits the partition universe across fetchers)."""

    partitions: list[tuple[str, int]]
    brokers: list[int]
    start_ms: int
    end_ms: int


@dataclass
class Samples:
    """ref MetricSampler.Samples."""

    partition_samples: list[PartitionMetricSample]
    broker_samples: list[BrokerMetricSample]


class MetricSampler(Protocol):
    """SPI (ref MetricSampler.java:121).

    Implementations that can be called concurrently on disjoint partition
    shards (stateless scrapers, e.g. a Prometheus-style sampler) should set
    ``parallel_safe = True`` to let the fetcher manager fan out; samplers
    with cross-partition state must leave it False (the default) and
    receive the whole assignment in one call.
    """

    parallel_safe: bool = False

    def get_samples(self, assignment: SamplerAssignment) -> Samples: ...


class SyntheticWorkloadSampler:
    """Deterministic per-partition workload against a simulated cluster.

    Each partition gets a stable base rate drawn from its identity hash plus
    optional per-call jitter; broker metrics are derived by summing the
    leader/follower shares, so processor CPU attribution round-trips
    exactly in tests.
    """

    def __init__(self, cluster, *, base_bytes_in: float = 50.0,
                 fanout: float = 1.5, jitter: float = 0.0, seed: int = 0,
                 cpu_per_byte: float = 0.001,
                 broker_cpu_overrides: dict[int, float] | None = None):
        self.cluster = cluster
        self.base_bytes_in = base_bytes_in
        self.fanout = fanout
        self.jitter = jitter
        self.seed = seed
        self.cpu_per_byte = cpu_per_byte
        self.broker_cpu_overrides = broker_cpu_overrides or {}

    def _partition_rates(self, tp: tuple[str, int], end_ms: int):
        # crc32, not hash(): Python's str hash is salted per process, which
        # would make "deterministic" rates differ across restarts and break
        # sample-store replay consistency.
        import zlib
        digest = zlib.crc32(f"{self.seed}:{tp[0]}:{tp[1]}".encode())
        h = digest % 1000 / 1000.0
        rng = np.random.default_rng((digest + end_ms) % 2**31)
        wobble = 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        bytes_in = self.base_bytes_in * (0.5 + h) * wobble
        bytes_out = bytes_in * self.fanout
        return bytes_in, bytes_out

    def get_samples(self, assignment: SamplerAssignment) -> Samples:
        infos = self.cluster.describe_partitions()
        t = assignment.end_ms
        psamples: list[PartitionMetricSample] = []
        by_broker_in: dict[int, float] = {}
        by_broker_out: dict[int, float] = {}
        by_broker_disk: dict[int, float] = {}
        for tp in assignment.partitions:
            info = infos.get(tp)
            if info is None:
                continue
            bytes_in, bytes_out = self._partition_rates(tp, t)
            s = PartitionMetricSample(tp[0], tp[1], t)
            s.record(KafkaMetric.LEADER_BYTES_IN, bytes_in)
            s.record(KafkaMetric.LEADER_BYTES_OUT, bytes_out)
            s.record(KafkaMetric.DISK_USAGE, info.size_mb)
            s.record(KafkaMetric.PRODUCE_RATE, bytes_in / 10.0)
            s.record(KafkaMetric.FETCH_RATE, bytes_out / 10.0)
            s.record(KafkaMetric.MESSAGE_IN_RATE, bytes_in / 100.0)
            s.record(KafkaMetric.REPLICATION_BYTES_IN_RATE,
                     bytes_in * max(len(info.replicas) - 1, 0))
            s.record(KafkaMetric.CPU_USAGE,
                     self.cpu_per_byte * (bytes_in + bytes_out))
            psamples.append(s)
            by_broker_in[info.leader] = by_broker_in.get(info.leader, 0.0) + bytes_in
            by_broker_out[info.leader] = (by_broker_out.get(info.leader, 0.0)
                                          + bytes_out)
            for b in info.replicas:
                by_broker_disk[b] = by_broker_disk.get(b, 0.0) + info.size_mb
                if b != info.leader:
                    by_broker_in[b] = by_broker_in.get(b, 0.0) + bytes_in
        bsamples: list[BrokerMetricSample] = []
        alive = self.cluster.describe_cluster()
        for b in assignment.brokers:
            if not alive.get(b, False):
                continue
            s = BrokerMetricSample(b, t)
            tot_in = by_broker_in.get(b, 0.0)
            tot_out = by_broker_out.get(b, 0.0)
            cpu = self.broker_cpu_overrides.get(
                b, self.cpu_per_byte * (tot_in + tot_out))
            s.record(BrokerMetric.CPU_USAGE, cpu)
            s.record(BrokerMetric.LEADER_BYTES_IN, tot_in)
            s.record(BrokerMetric.LEADER_BYTES_OUT, tot_out)
            s.record(BrokerMetric.DISK_USAGE, by_broker_disk.get(b, 0.0))
            metrics = self.cluster.broker_metrics(b)
            s.record(BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_MEAN,
                     metrics.get("log_flush_time_ms", 0.0))
            bsamples.append(s)
        return Samples(psamples, bsamples)


class AgentTopicSampler:
    """Consume the L0 reporter agent's raw metric records and convert them to
    samples via the processor (ref CruiseControlMetricsReporterSampler.java:35
    polling the ``__CruiseControlMetrics`` topic at ``:93``).

    Parallel-safe via the two-phase protocol (the flagship ingestion path
    must fan out like the reference's fetcher threads,
    ``MetricFetcherManager.java:37``): the fetcher manager calls
    :meth:`prepare_round` once per round — one transport poll, one
    cross-broker fold in the processor — then ``get_samples`` per shard is
    a pure read over the prepared state, so N fetchers attribute N
    disjoint partition shards concurrently without double-counting broker
    or topic aggregates."""

    parallel_safe = True

    def __init__(self, transport, processor):
        self.transport = transport
        self.processor = processor
        self._round = None
        self._round_window: tuple[int, int] | None = None

    def prepare_round(self, start_ms: int, end_ms: int) -> None:
        records = self.transport.poll(start_ms, end_ms)
        self.processor.add_metrics(records)
        self._round = self.processor.prepare(start_ms, end_ms)
        self._round_window = (start_ms, end_ms)

    def get_samples(self, assignment: SamplerAssignment) -> Samples:
        window = (assignment.start_ms, assignment.end_ms)
        if self._round is None or self._round_window != window:
            # Direct (manager-less) use, or a window the manager never
            # prepared: ingest the window now (never serve a stale
            # round's samples) and emit with the single-shot contract
            # (all brokers; empty partition filter = everything).
            self.prepare_round(assignment.start_ms, assignment.end_ms)
            return self.processor.emit(self._round, assignment,
                                       include_brokers=True,
                                       empty_assignment_means_all=True)
        return self.processor.emit(self._round, assignment)
