"""Metric sample holders (ref ``monitor/sampling/holder/PartitionMetricSample.java``
and ``BrokerMetricSample.java``).

A sample is a point-in-time metric vector for one entity. Partition entities
are ``(topic, partition)`` tuples (entity group = topic, matching the
reference's ENTITY_GROUP granularity); broker entities are broker ids.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core.aggregator import MetricSample
from ..core.metricdef import BrokerMetric, KafkaMetric


@dataclass
class PartitionMetricSample:
    """Per-partition sample in model metric space (ref
    PartitionMetricSample.java)."""

    topic: str
    partition: int
    time_ms: int
    #: KafkaMetric id -> value
    values: dict[int, float] = field(default_factory=dict)

    def record(self, metric: KafkaMetric, value: float) -> None:
        self.values[int(metric)] = value

    @property
    def entity(self) -> tuple[str, int]:
        return (self.topic, self.partition)

    def to_aggregator_sample(self) -> MetricSample:
        return MetricSample(entity=self.entity, sample_time_ms=self.time_ms,
                            values=dict(self.values), entity_group=self.topic)

    def to_json(self) -> dict:
        return {"topic": self.topic, "partition": self.partition,
                "timeMs": self.time_ms,
                "values": {str(k): v for k, v in self.values.items()}}

    @classmethod
    def from_json(cls, d: dict) -> "PartitionMetricSample":
        return cls(topic=d["topic"], partition=int(d["partition"]),
                   time_ms=int(d["timeMs"]),
                   values={int(k): float(v)
                           for k, v in d["values"].items()})


@dataclass
class BrokerMetricSample:
    """Per-broker sample (ref BrokerMetricSample.java)."""

    broker_id: int
    time_ms: int
    values: dict[int, float] = field(default_factory=dict)

    def record(self, metric: BrokerMetric, value: float) -> None:
        self.values[int(metric)] = value

    @property
    def entity(self) -> int:
        return self.broker_id

    def to_aggregator_sample(self) -> MetricSample:
        return MetricSample(entity=self.broker_id, sample_time_ms=self.time_ms,
                            values=dict(self.values))

    def to_json(self) -> dict:
        return {"brokerId": self.broker_id, "timeMs": self.time_ms,
                "values": {str(k): v for k, v in self.values.items()}}

    @classmethod
    def from_json(cls, d: dict) -> "BrokerMetricSample":
        return cls(broker_id=int(d["brokerId"]), time_ms=int(d["timeMs"]),
                   values={int(k): float(v)
                           for k, v in d["values"].items()})
