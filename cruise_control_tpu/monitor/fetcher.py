"""Metric fetch fan-out (ref ``monitor/sampling/MetricFetcherManager.java:37``
and ``SamplingFetcher.java:31``).

Splits the partition universe into N shards and runs the sampler once per
shard — in a thread pool, like the reference's fetcher threads — then
funnels every shard's samples through the sample store and into the load
monitor's aggregators.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

from .sampler import MetricSampler, SamplerAssignment, Samples
from .store import NoopSampleStore, SampleStore


class DefaultPartitionAssignor:
    """Splits the partition universe across fetcher shards (ref
    DefaultMetricSamplerPartitionAssignor — round-robin so every shard
    carries a representative topic mix). Pluggable via
    metric.sampler.partition.assignor.class."""

    def assign(self, partitions: list[tuple[str, int]],
               num_shards: int) -> list[list[tuple[str, int]]]:
        return [partitions[i::num_shards] for i in range(num_shards)]


class MetricFetcherManager:
    def __init__(self, sampler: MetricSampler, num_fetchers: int = 1,
                 store: SampleStore | None = None,
                 assignor: DefaultPartitionAssignor | None = None,
                 on_execution_store: SampleStore | None = None,
                 registry=None, max_retries: int = 0, tracer=None) -> None:
        from ..core.sensors import MetricRegistry
        from ..core.tracing import default_tracer
        self.tracer = tracer or default_tracer()
        self.sampler = sampler
        self.num_fetchers = max(1, num_fetchers)
        #: ref fetch.metric.samples.max.retry.count: transient sampler
        #: failures are retried this many times within one round before
        #: the round fails (each attempt still marks the failure meter).
        self.max_retries = max(0, max_retries)
        self.store = store or NoopSampleStore()
        self.assignor = assignor or DefaultPartitionAssignor()
        #: optional secondary store for samples taken during an ongoing
        #: execution (ref KafkaPartitionMetricSampleOnExecutionStore)
        self.on_execution_store = on_execution_store
        # ref the MetricFetcherManager sensor table (Sensors.md):
        # per-round fetch timer + failure rate.
        self.registry = registry or MetricRegistry()
        self._fetch_timer = self.registry.timer(
            "MetricFetcherManager.partition-samples-fetcher-timer")
        self._fetch_failures = self.registry.meter(
            "MetricFetcherManager.partition-samples-fetcher-failure-rate")

    def fetch(self, partitions: list[tuple[str, int]], brokers: list[int],
              start_ms: int, end_ms: int) -> Samples:
        """One sampling round across all shards (ref
        fetchMetricsFor... methods).

        Sharding only applies to samplers that declare ``parallel_safe``:
        samplers with cross-partition state (the agent-topic sampler's
        processor buffer, the synthetic sampler's per-broker sums) must see
        the whole assignment in one call or they would race / double-count.
        """
        with self._fetch_timer.time(), \
                self.tracer.span("monitor.fetch-samples",
                                 partitions=len(partitions),
                                 brokers=len(brokers)):
            for attempt in range(self.max_retries + 1):
                try:
                    merged = self._fetch(partitions, brokers, start_ms,
                                         end_ms)
                    break
                except Exception:
                    self._fetch_failures.mark()
                    if attempt == self.max_retries:
                        raise
            # Persistence sits OUTSIDE the retried section: a store
            # failure after a successful write must not re-store the
            # round (replay would double-count the window's load) — but
            # it still marks the failure meter (round failed either way).
            try:
                self.store.store_samples(merged)
                if self.on_execution_store is not None:
                    self.on_execution_store.store_samples(merged)
            except Exception:
                self._fetch_failures.mark()
                raise
            return merged

    def _fetch(self, partitions: list[tuple[str, int]], brokers: list[int],
               start_ms: int, end_ms: int) -> Samples:
        parallel_safe = getattr(self.sampler, "parallel_safe", False)
        n = self.num_fetchers if parallel_safe else 1
        # Two-phase samplers (the agent-topic path) isolate their
        # cross-partition state once per round so the per-shard calls
        # below are pure reads.
        prepare = getattr(self.sampler, "prepare_round", None)
        if prepare is not None:
            prepare(start_ms, end_ms)
        shard_parts = self.assignor.assign(partitions, n)
        shards = [SamplerAssignment(partitions=shard_parts[i],
                                    brokers=(brokers if i == 0 else []),
                                    start_ms=start_ms, end_ms=end_ms)
                  for i in range(n)]
        if n == 1:
            results = [self.sampler.get_samples(shards[0])]
        else:
            with ThreadPoolExecutor(max_workers=n) as pool:
                results = list(pool.map(self.sampler.get_samples, shards))
        merged = Samples([], [])
        for r in results:
            merged.partition_samples.extend(r.partition_samples)
            merged.broker_samples.extend(r.broker_samples)
        return merged
