"""ctypes binding for the native sample-store loader
(``sidecar/sample_loader.cc`` → ``libsample_loader.so``).

Checkpoint replay (the monitor's LOADING state, ref
``KafkaSampleStore.java:93`` loadSamples) parses the whole retained
sample history before serving; at scale that is tens of millions of JSONL
lines, where Python ``json`` is the cold-start bottleneck. The native
scanner reads the exact format ``FileSampleStore`` writes into columnar
arrays ready for ``MetricSampleAggregator.add_samples_dense``.

Entirely optional: :func:`load_partition_samples_dense` returns ``None``
when the library isn't built or reports parse errors (foreign or
hand-edited files), and callers fall back to the Python path — behavior
never changes, only speed.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB_PATHS = (
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "sidecar", "libsample_loader.so"),
    "libsample_loader.so",
)

_lib = None


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    for path in _LIB_PATHS:
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            continue
        lib.csl_load.restype = ctypes.c_void_p
        lib.csl_load.argtypes = [ctypes.c_char_p, ctypes.c_int]
        for fn in (lib.csl_count, lib.csl_errors, lib.csl_topic_bytes):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p]
        lib.csl_fill.restype = ctypes.c_int
        lib.csl_fill.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 5
        lib.csl_free.restype = None
        lib.csl_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib
    return None


def available() -> bool:
    return _load_lib() is not None


def load_partition_samples_dense(path: str, num_metrics: int):
    """Parse a partition_samples.jsonl natively.

    Returns ``(entities, times_ms, values)`` matching
    ``add_samples_dense``'s signature — ``entities`` a list of
    ``(topic, partition)`` tuples, ``times_ms`` int64 [N], ``values``
    float64 [N, num_metrics] with NaN for absent metrics — or ``None``
    when the native library is unavailable, the file can't be read, or
    any line failed the strict scanner (callers then use the Python
    json fallback, which accepts anything).
    """
    lib = _load_lib()
    if lib is None or not os.path.exists(path):
        return None
    handle = lib.csl_load(path.encode(), num_metrics)
    if not handle:
        return None
    try:
        if lib.csl_errors(handle):
            return None
        n = lib.csl_count(handle)
        times = np.empty(n, np.int64)
        values = np.empty((n, num_metrics), np.float64)
        partitions = np.empty(n, np.int32)
        offsets = np.empty(n + 1, np.int64)
        topic_data = ctypes.create_string_buffer(
            max(int(lib.csl_topic_bytes(handle)), 1))
        rc = lib.csl_fill(
            handle,
            times.ctypes.data_as(ctypes.c_void_p),
            values.ctypes.data_as(ctypes.c_void_p),
            partitions.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p),
            ctypes.cast(topic_data, ctypes.c_void_p))
        if rc != 0:
            return None
        raw = topic_data.raw
        entities = [(raw[offsets[i]:offsets[i + 1]].decode(),
                     int(partitions[i])) for i in range(n)]
        return entities, times, values
    finally:
        lib.csl_free(handle)
