"""Model completeness requirements (ref
``monitor/ModelCompletenessRequirements.java``): the gate between "we have
some samples" and "the model is trustworthy enough to act on". Every goal
declares one; the optimizer request uses the strongest combination of its
goals' requirements (ref ``combine``)."""

from __future__ import annotations

from dataclasses import dataclass

from ..core.aggregator import MetricSampleCompleteness


@dataclass(frozen=True)
class ModelCompletenessRequirements:
    min_required_num_windows: int = 1
    min_monitored_partitions_percentage: float = 0.0
    include_all_topics: bool = False

    def combine(self, other: "ModelCompletenessRequirements | None"
                ) -> "ModelCompletenessRequirements":
        """Strongest of the two (ref stronger())."""
        if other is None:
            return self
        return ModelCompletenessRequirements(
            min_required_num_windows=max(self.min_required_num_windows,
                                         other.min_required_num_windows),
            min_monitored_partitions_percentage=max(
                self.min_monitored_partitions_percentage,
                other.min_monitored_partitions_percentage),
            include_all_topics=self.include_all_topics or other.include_all_topics)

    def met_by(self, completeness: MetricSampleCompleteness) -> bool:
        """ref LoadMonitor.meetCompletenessRequirements (LoadMonitor.java:655)."""
        if len(completeness.valid_windows) < self.min_required_num_windows:
            return False
        if (completeness.valid_entity_ratio
                < self.min_monitored_partitions_percentage):
            return False
        return True

    def to_json(self) -> dict:
        return {"requiredNumWindows": self.min_required_num_windows,
                "minMonitoredPartitionsPercentage":
                    self.min_monitored_partitions_percentage,
                "includeAllTopics": self.include_all_topics}
