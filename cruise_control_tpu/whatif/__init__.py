"""What-if scenario layer (L-whatif): batched device-side simulation of
hypothetical topologies — failures, growth, capacity changes — scored by
the same goal kernels that drive optimization.

The whole point of flattening ``ClusterModel`` into arrays is that a
hypothetical topology is just an array transform: a 100-broker N-1 sweep
is ONE vmapped device program over a ``[S, ...]`` scenario axis, not 100
sequential model rebuilds.
"""

from .spec import (BrokerAdd, BrokerLoss, CapacityResize, LoadScale,
                   Scenario, TopicAdd, TrajectoryScale, alive_broker_ids,
                   n1_sweep, n2_sweep, parse_scenarios)
from .engine import (ScenarioOutcome, WhatIfEngine, WhatIfReport,
                     trajectory_pscale_row)

__all__ = [
    "Scenario", "BrokerLoss", "BrokerAdd", "CapacityResize", "LoadScale",
    "TopicAdd", "TrajectoryScale", "n1_sweep", "n2_sweep",
    "alive_broker_ids", "parse_scenarios", "WhatIfEngine", "WhatIfReport",
    "ScenarioOutcome", "trajectory_pscale_row",
]
