"""Declarative what-if scenario specs.

Each scenario describes ONE hypothetical edit of the live cluster; the
engine materializes a *batch* of them as per-scenario parameter arrays
stacked along a leading ``S`` axis and applies them as pure array
transforms on device (see ``engine.py``). Specs are plain frozen
dataclasses with a JSON round-trip (``parse_scenarios`` /
``Scenario.to_json``) so the ``/simulate`` endpoint and the resilience
detector share one vocabulary.

Scenario types:

- :class:`BrokerLoss` — brokers die; leadership fails over to the best
  alive replica (preferred order), surviving followers on the dead
  brokers go offline. :func:`n1_sweep` / :func:`n2_sweep` expand into
  every single / pairwise loss.
- :class:`BrokerAdd` — new empty brokers join (each on a fresh rack),
  capacity defaulting to the alive-broker mean.
- :class:`CapacityResize` — scale broker capacity (all brokers or a
  subset, all resources or one) — models hardware changes or revised
  capacity estimates.
- :class:`LoadScale` — multiply partition load (uniform or per-topic,
  all four resources) — models traffic growth.
- :class:`TopicAdd` — a new topic with projected per-partition load,
  placed round-robin over alive brokers.
- :class:`TrajectoryScale` — per-topic load factors at one forecast
  (horizon, quantile) point: the materialized form of a fitted load
  trajectory (forecast/engine.py). A ``{"type": "forecast", ...}``
  request resolves through the server's forecast engine into exactly
  this spec, so the JSON echo of a forecast sweep round-trips.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

import numpy as np

RESOURCE_KEYS = ("cpu", "nwIn", "nwOut", "disk")


@dataclass(frozen=True)
class Scenario:
    """Base scenario. ``name`` is the stable label used in reports."""

    @property
    def name(self) -> str:
        raise NotImplementedError

    def to_json(self) -> dict:
        raise NotImplementedError


@dataclass(frozen=True)
class BrokerLoss(Scenario):
    """Brokers ``brokers`` (ids) die simultaneously."""

    brokers: tuple[int, ...]

    @property
    def name(self) -> str:
        return "loss:" + ",".join(str(b) for b in self.brokers)

    def to_json(self) -> dict:
        return {"type": "broker_loss", "brokers": list(self.brokers)}


@dataclass(frozen=True)
class BrokerAdd(Scenario):
    """``count`` new empty brokers join. ``capacity`` (CPU, NW_IN, NW_OUT,
    DISK) defaults to the mean capacity of alive brokers; each added
    broker lands on its own fresh rack (growth normally adds failure
    domains — a pessimistic same-rack add can be modeled by combining
    with CapacityResize instead)."""

    count: int
    capacity: tuple[float, float, float, float] | None = None

    @property
    def name(self) -> str:
        return f"add:{self.count}"

    def to_json(self) -> dict:
        out: dict = {"type": "broker_add", "count": self.count}
        if self.capacity is not None:
            out["capacity"] = list(self.capacity)
        return out


@dataclass(frozen=True)
class CapacityResize(Scenario):
    """Scale broker capacity by ``factor``: every broker when ``brokers``
    is None, one resource when ``resource`` (cpu|nwIn|nwOut|disk) is
    given, all four otherwise."""

    factor: float
    brokers: tuple[int, ...] | None = None
    resource: str | None = None

    @property
    def name(self) -> str:
        scope = ("all" if self.brokers is None
                 else ",".join(str(b) for b in self.brokers))
        res = self.resource or "all"
        return f"resize:{scope}:{res}:{self.factor:g}"

    def to_json(self) -> dict:
        out: dict = {"type": "capacity_resize", "factor": self.factor}
        if self.brokers is not None:
            out["brokers"] = list(self.brokers)
        if self.resource is not None:
            out["resource"] = self.resource
        return out


@dataclass(frozen=True)
class LoadScale(Scenario):
    """Multiply partition load (all four resources) by ``factor`` —
    uniformly, or only for the named ``topics``."""

    factor: float
    topics: tuple[str, ...] | None = None

    @property
    def name(self) -> str:
        scope = "all" if self.topics is None else ",".join(self.topics)
        return f"load:{scope}:{self.factor:g}"

    def to_json(self) -> dict:
        out: dict = {"type": "load_scale", "factor": self.factor}
        if self.topics is not None:
            out["topics"] = list(self.topics)
        return out


@dataclass(frozen=True)
class TopicAdd(Scenario):
    """A new topic with ``partitions`` partitions at replication factor
    ``rf``, each with projected ``leader_load`` (CPU, NW_IN, NW_OUT,
    DISK). Follower load defaults to the standard derivation (half the
    leader CPU, full NW_IN replication, no NW_OUT, same DISK). Replicas
    are placed round-robin over alive brokers — the question answered is
    "does the cluster have room", not "what is the optimal placement"."""

    topic: str
    partitions: int
    rf: int
    leader_load: tuple[float, float, float, float]
    follower_load: tuple[float, float, float, float] | None = None

    @property
    def name(self) -> str:
        return f"topic:{self.topic}:{self.partitions}x{self.rf}"

    def derived_follower_load(self) -> tuple[float, ...]:
        if self.follower_load is not None:
            return tuple(self.follower_load)
        cpu, nw_in, _nw_out, disk = self.leader_load
        return (0.5 * cpu, nw_in, 0.0, disk)

    def to_json(self) -> dict:
        out: dict = {"type": "topic_add", "topic": self.topic,
                     "partitions": self.partitions, "rf": self.rf,
                     "leaderLoad": list(self.leader_load)}
        if self.follower_load is not None:
            out["followerLoad"] = list(self.follower_load)
        return out


@dataclass(frozen=True)
class TrajectoryScale(Scenario):
    """Per-topic load factors at one projected (horizon, quantile)
    point. ``factors`` carries (topic, factor) pairs from a fitted
    forecast; topics without an entry scale by ``default_factor``
    (1.0 = unchanged). Topics that disappeared since the fit are
    skipped at materialization — a stale forecast entry must degrade,
    not 400 a sweep of the live cluster."""

    horizon_ms: int
    quantile: float
    factors: tuple[tuple[str, float], ...] = ()
    default_factor: float = 1.0
    label: str = "forecast"

    @property
    def name(self) -> str:
        return (f"{self.label}:+{_fmt_horizon(self.horizon_ms)}"
                f":p{int(round(self.quantile * 100))}")

    def to_json(self) -> dict:
        out: dict = {"type": "trajectory_scale",
                     "horizonMs": self.horizon_ms,
                     "quantile": self.quantile,
                     "factors": {t: f for t, f in self.factors}}
        if self.default_factor != 1.0:
            out["defaultFactor"] = self.default_factor
        if self.label != "forecast":
            out["label"] = self.label
        return out


def _fmt_horizon(horizon_ms: int) -> str:
    """Compact horizon label: 3600000 -> "1h", 90000 -> "90s"."""
    s = horizon_ms / 1000.0
    for width, unit in ((86400, "d"), (3600, "h"), (60, "m")):
        if s >= width and s % width == 0:
            return f"{int(s // width)}{unit}"
    return f"{s:g}s"


# ---------------------------------------------------------------- sweeps

def n1_sweep(broker_ids: list[int]) -> list[BrokerLoss]:
    """Every single-broker loss — the resilience detector's bread and
    butter: S = len(broker_ids) scenarios, scored in one device program."""
    return [BrokerLoss(brokers=(b,)) for b in broker_ids]


def n2_sweep(broker_ids: list[int]) -> list[BrokerLoss]:
    """Every pairwise loss (S = n*(n-1)/2) — correlated-failure coverage;
    quadratic, so callers gate it behind the slow tier."""
    return [BrokerLoss(brokers=(a, b))
            for a, b in itertools.combinations(broker_ids, 2)]


def alive_broker_ids(model, metadata) -> list[int]:
    """Broker ids currently alive+valid in a flat model — the sweep
    population (dead brokers are already-realized scenarios)."""
    alive = np.asarray(model.broker_alive) & np.asarray(model.broker_valid)
    return [metadata.broker_ids[i]
            for i in range(len(metadata.broker_ids)) if alive[i]]


# ----------------------------------------------------------- JSON parsing

_PARSERS = {}


def _parser(type_name):
    def deco(fn):
        _PARSERS[type_name] = fn
        return fn
    return deco


def _ids(raw, what: str) -> tuple[int, ...]:
    if not isinstance(raw, (list, tuple)) or not raw:
        raise ValueError(f"{what}: 'brokers' must be a non-empty list")
    try:
        return tuple(int(b) for b in raw)
    except (TypeError, ValueError):
        raise ValueError(f"{what}: broker ids must be integers, got {raw!r}")


def _load4(raw, what: str) -> tuple[float, float, float, float]:
    if not isinstance(raw, (list, tuple)) or len(raw) != 4:
        raise ValueError(f"{what}: want 4 numbers (CPU, NW_IN, NW_OUT, "
                         f"DISK), got {raw!r}")
    return tuple(float(x) for x in raw)


@_parser("broker_loss")
def _parse_loss(obj: dict) -> BrokerLoss:
    return BrokerLoss(brokers=_ids(obj.get("brokers"), "broker_loss"))


@_parser("broker_add")
def _parse_add(obj: dict) -> BrokerAdd:
    count = int(obj.get("count", 1))
    if count < 1:
        raise ValueError("broker_add: count must be >= 1")
    cap = obj.get("capacity")
    return BrokerAdd(count=count,
                     capacity=None if cap is None
                     else _load4(cap, "broker_add"))


@_parser("capacity_resize")
def _parse_resize(obj: dict) -> CapacityResize:
    factor = float(obj["factor"])
    if factor <= 0:
        raise ValueError("capacity_resize: factor must be > 0")
    res = obj.get("resource")
    if res is not None and res not in RESOURCE_KEYS:
        raise ValueError(f"capacity_resize: resource {res!r} not in "
                         f"{RESOURCE_KEYS}")
    brokers = obj.get("brokers")
    return CapacityResize(factor=factor,
                          brokers=None if brokers is None
                          else _ids(brokers, "capacity_resize"),
                          resource=res)


@_parser("load_scale")
def _parse_scale(obj: dict) -> LoadScale:
    factor = float(obj["factor"])
    if factor < 0:
        raise ValueError("load_scale: factor must be >= 0")
    topics = obj.get("topics")
    if topics is not None and (not isinstance(topics, (list, tuple))
                               or not topics):
        raise ValueError("load_scale: 'topics' must be a non-empty list")
    return LoadScale(factor=factor,
                     topics=None if topics is None else tuple(topics))


@_parser("topic_add")
def _parse_topic(obj: dict) -> TopicAdd:
    partitions = int(obj.get("partitions", 1))
    rf = int(obj.get("rf", 1))
    if partitions < 1 or rf < 1:
        raise ValueError("topic_add: partitions and rf must be >= 1")
    fl = obj.get("followerLoad")
    return TopicAdd(topic=str(obj.get("topic", "whatif-topic")),
                    partitions=partitions, rf=rf,
                    leader_load=_load4(obj.get("leaderLoad"), "topic_add"),
                    follower_load=None if fl is None
                    else _load4(fl, "topic_add"))


@_parser("trajectory_scale")
def _parse_trajectory(obj: dict) -> TrajectoryScale:
    horizon_ms = int(obj.get("horizonMs", 0))
    if horizon_ms < 0:
        raise ValueError("trajectory_scale: horizonMs must be >= 0")
    quantile = float(obj.get("quantile", 0.5))
    if not 0.0 < quantile < 1.0:
        raise ValueError("trajectory_scale: quantile must be in (0, 1)")
    raw = obj.get("factors", {})
    if not isinstance(raw, dict):
        raise ValueError("trajectory_scale: 'factors' must be an object "
                         "{topic: factor}")
    factors = []
    for t, f in sorted(raw.items()):
        f = float(f)
        if f < 0:
            raise ValueError(
                f"trajectory_scale: factor for topic {t!r} must be >= 0")
        factors.append((str(t), f))
    default = float(obj.get("defaultFactor", 1.0))
    if default < 0:
        raise ValueError("trajectory_scale: defaultFactor must be >= 0")
    return TrajectoryScale(horizon_ms=horizon_ms, quantile=quantile,
                           factors=tuple(factors), default_factor=default,
                           label=str(obj.get("label", "forecast")))


def parse_scenarios(payload: dict, broker_ids: list[int],
                    forecaster=None) -> list[Scenario]:
    """Parse a ``/simulate`` request payload into scenario specs.

    Accepts either ``{"sweep": "N1"|"N2"}`` (expanded over
    ``broker_ids``) or ``{"scenarios": [{"type": ...}, ...]}``.
    Raises ``ValueError`` (HTTP 400) on anything malformed — validation
    happens before any device work is scheduled.

    ``forecaster`` resolves ``{"type": "forecast", "horizonMs": ...,
    "quantile": ...}`` scenario sources into concrete
    :class:`TrajectoryScale` specs from the server's fitted forecasts
    (``KafkaCruiseControl.simulate`` wires the forecast engine's
    ``trajectory_scenario``); without one, forecast sources are a
    validation error.
    """
    sweep = payload.get("sweep")
    raw = payload.get("scenarios")
    if (sweep is None) == (raw is None):
        raise ValueError(
            "simulate requires exactly one of 'sweep' (N1|N2) or "
            "'scenarios' (a list of scenario objects)")
    if sweep is not None:
        sweep = str(sweep).upper()
        if sweep == "N1":
            return n1_sweep(broker_ids)
        if sweep == "N2":
            return n2_sweep(broker_ids)
        raise ValueError(f"unknown sweep {sweep!r} (want N1 or N2)")
    if not isinstance(raw, (list, tuple)) or not raw:
        raise ValueError("'scenarios' must be a non-empty list")
    out = []
    for i, obj in enumerate(raw):
        if not isinstance(obj, dict):
            raise ValueError(f"scenario #{i} is not an object: {obj!r}")
        if obj.get("type") == "forecast":
            # Forecast scenario source: resolved against the server's
            # fitted per-topic forecasts into a TrajectoryScale, so the
            # response echoes the concrete factors it scored (and that
            # echo round-trips through the trajectory_scale parser).
            if forecaster is None:
                raise ValueError(
                    f"scenario #{i}: 'forecast' scenarios need a fitted "
                    "forecast source (forecast.enabled on the server)")
            if "horizonMs" not in obj:
                raise ValueError(
                    f"scenario #{i}: forecast requires horizonMs")
            horizon_ms = int(obj["horizonMs"])
            if horizon_ms < 0:
                raise ValueError(
                    f"scenario #{i}: forecast horizonMs must be >= 0")
            quantile = float(obj.get("quantile", 0.9))
            if not 0.0 < quantile < 1.0:
                raise ValueError(
                    f"scenario #{i}: forecast quantile must be in (0, 1)")
            out.append(forecaster(horizon_ms, quantile))
            continue
        parser = _PARSERS.get(obj.get("type"))
        if parser is None:
            raise ValueError(
                f"scenario #{i}: unknown type {obj.get('type')!r}; "
                f"supported: {sorted(_PARSERS) + ['forecast']}")
        out.append(parser(obj))
    return out
