"""What-if engine: materialize scenario batches and score them on device.

A scenario batch becomes five per-scenario parameter arrays stacked on a
leading ``S`` axis (dead-broker mask, added-broker mask, capacity scale,
partition load scale, partition enable mask). ONE jitted program then
vmaps the whole pipeline per scenario:

    transform (kill/add/resize/scale/enable + leadership failover)
      -> init_state / build_context        (analyzer/state.py, unchanged)
      -> violation_stack over the goal chain (analyzer/goals.py, unchanged)
      -> headroom / pressure / availability reductions

so a 100-broker N-1 sweep scores every goal for every scenario in a
single device dispatch — no per-scenario Python loop, no model rebuilds.
The scenario axis is padded to a bucket multiple so sweeps of nearby
sizes reuse one compiled program.

Leadership failover inside the transform mirrors Kafka's election: the
alive, non-offline replica with the lowest *preferred-order* position
(``replica_pref_pos``) becomes the leader; partitions with no electable
replica are counted unavailable. Dead brokers keep their (now invisible
to the alive-masked goal reductions) residual load — the scored state is
the cluster *immediately after failover*, before any self-healing moves.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..analyzer.constraint import BalancingConstraint
from ..analyzer.engine import violation_stack
from ..analyzer.state import build_context, init_state
from ..core.resources import NUM_RESOURCES
from ..model.flat import FlatClusterModel
from ..parallel.batching import ProgramCache, pad_model_to, round_up
from .spec import (BrokerAdd, BrokerLoss, CapacityResize, LoadScale,
                   RESOURCE_KEYS, Scenario, TopicAdd, TrajectoryScale)

#: risk-score shape constants (documented in docs/whatif.md): the four
#: component terms combine as 1 - prod(1 - term), each term in [0, 1].
_RISK_HARD_W = 0.9     # any violated hard goal dominates
_RISK_SOFT_W = 0.3     # soft violations alone cap at moderate risk
_RISK_PRESSURE_W = 0.7  # capacity pressure ramps 70% -> 130% of usable
_RISK_PRESSURE_LO = 0.7
_RISK_PRESSURE_SPAN = 0.6

_round_up = round_up   # shared bucket math (parallel/batching.py)


def violated_matrix(viol: np.ndarray, vscale: np.ndarray) -> np.ndarray:
    """Boolean violated-goal matrix with the same ulp-aware cutoff as
    ``GoalResult.satisfied``: a broker landing exactly on a float32-summed
    capacity limit is not a violation. Shared by the what-if report and
    the fleet risk sweep."""
    return viol > (1e-6 + 1e-6 * vscale)


def risk_scores(hard_frac: np.ndarray, soft_frac: np.ndarray,
                pressure: np.ndarray, unavailable: np.ndarray,
                valid_parts: np.ndarray) -> np.ndarray:
    """Composite [0, 1] risk (documented in docs/whatif.md): hard/soft
    violation fractions, capacity-pressure ramp, and availability combine
    as ``1 - prod(1 - term)``. One definition shared by the what-if
    report builder and the fleet N-1 sweep so a fleet-reported risk means
    exactly what ``/simulate`` reports."""
    pressure_term = np.clip(
        (pressure - _RISK_PRESSURE_LO) / _RISK_PRESSURE_SPAN, 0.0, 1.0)
    avail_term = np.where(
        unavailable > 0,
        np.minimum(0.9 + 0.1 * unavailable / valid_parts, 1.0), 0.0)
    return 1.0 - ((1.0 - _RISK_HARD_W * hard_frac)
                  * (1.0 - _RISK_SOFT_W * soft_frac)
                  * (1.0 - _RISK_PRESSURE_W * pressure_term)
                  * (1.0 - avail_term))


@dataclass
class ScenarioOutcome:
    """One scenario's scorecard (everything host-side numbers)."""

    scenario: Scenario
    risk: float
    violated_goals: list[str]
    violated_hard_goals: list[str]
    capacity_pressure: float          # max alive util / usable capacity
    unavailable_partitions: int       # no electable replica post-failover
    offline_replicas: int
    #: per-resource post-scenario headroom: remaining usable capacity
    #: (absolute, summed over alive brokers, floored at 0) and the worst
    #: single broker's headroom fraction
    headroom: dict = field(default_factory=dict)
    #: broker id (or "new-<row>") with the least headroom fraction
    worst_broker: object = None

    def to_json(self) -> dict:
        return {"scenario": self.scenario.to_json(),
                "name": self.scenario.name,
                "risk": round(self.risk, 4),
                "violatedGoals": self.violated_goals,
                "violatedHardGoals": self.violated_hard_goals,
                "capacityPressure": round(self.capacity_pressure, 4),
                "unavailablePartitions": self.unavailable_partitions,
                "offlineReplicas": self.offline_replicas,
                "headroom": self.headroom,
                "worstBroker": self.worst_broker}


@dataclass
class WhatIfReport:
    outcomes: list[ScenarioOutcome]
    goals: list[str]
    duration_s: float
    stale_model: bool = False

    @property
    def num_scenarios(self) -> int:
        return len(self.outcomes)

    def riskiest(self) -> ScenarioOutcome | None:
        return max(self.outcomes, key=lambda o: o.risk, default=None)

    def to_json(self) -> dict:
        worst = self.riskiest()
        return {"numScenarios": self.num_scenarios,
                "goals": self.goals,
                "durationMs": round(self.duration_s * 1e3, 3),
                "staleModel": self.stale_model,
                "riskiest": None if worst is None else worst.scenario.name,
                "maxRisk": 0.0 if worst is None else round(worst.risk, 4),
                "scenarios": [o.to_json() for o in self.outcomes]}


@dataclass
class _Batch:
    """Materialized scenario batch: a staged template model (added-broker
    rows and projected-topic rows pre-written into padding) plus the
    per-scenario parameter arrays, padded to ``S_pad``."""

    template: FlatClusterModel
    dead: np.ndarray        # bool[S_pad, B]
    add: np.ndarray         # bool[S_pad, B]
    cap_scale: np.ndarray   # f32[S_pad, B, 4]
    pscale: np.ndarray      # f32[S_pad, P]
    pvalid: np.ndarray      # bool[S_pad, P]
    num_real: int
    new_broker_rows: dict[int, int]   # padding row -> scenario index
    #: distinct staged (TopicAdd) topics, ids metadata.num_topics + k
    num_staged_topics: int = 0


class WhatIfEngine:
    """Batched hypothetical-topology scorer.

    ``goals`` default to the analyzer's default chain; the engine binds
    them per metadata exactly like the optimizer, and caches one jitted
    program per (shape, scenario-bucket, goal-binding) signature so
    repeated sweeps — the resilience detector's steady state — pay XLA
    once.
    """

    def __init__(self, goals=None, constraint: BalancingConstraint | None = None,
                 *, registry=None, tracer=None, collector=None, mesh=None,
                 scenario_pad_multiple: int = 8,
                 # Model re-pad buckets for scenarios that outgrow the
                 # live model's padding slack (BrokerAdd/TopicAdd) — wire
                 # the SAME multiples the monitor builds with
                 # (model.*.pad.multiple; the facade does) or the re-pad
                 # lands on off-bucket shapes and compiles extra sweep
                 # variants per growth step.
                 partition_pad_multiple: int = 128,
                 broker_pad_multiple: int = 8,
                 # Covers a full N-2 pairwise sweep up to 128 brokers
                 # (128*127/2 = 8128); per-scenario [S, P] parameter
                 # arrays scale the footprint, so operators with huge
                 # partition counts can lower it (whatif.max.scenarios).
                 max_scenarios: int = 8192,
                 program_cache_size: int = 8) -> None:
        from ..analyzer.goals import default_goals
        from ..core.runtime_obs import default_collector
        from ..core.sensors import MetricRegistry
        from ..core.tracing import default_tracer
        self.constraint = constraint or BalancingConstraint()
        #: device-runtime ledger: the vmapped sweep/transform programs
        #: register as TrackedPrograms (compile events + dispatch counts
        #: on /devicestats), and sweep() meters its batch upload + result
        #: fetch bytes.
        self.collector = collector or default_collector()
        self.goals = (goals if goals is not None
                      else default_goals(self.constraint))
        #: optional jax.sharding.Mesh (search.mesh.devices — the same
        #: mesh the optimizer runs on): the template model and the
        #: ``[S, P]`` per-scenario parameter planes shard the partition
        #: axis, so the vmapped sweep partitions exactly like the goal
        #: passes (broker-indexed parameters and the scenario axis
        #: replicate; the per-scenario broker aggregates ride the same
        #: ICI all-reduce — parallel/sharding.py layout note).
        self.mesh = mesh
        from ..parallel.sharding import mesh_fingerprint
        self._mesh_key = mesh_fingerprint(mesh)
        self.scenario_pad_multiple = scenario_pad_multiple
        self.partition_pad_multiple = partition_pad_multiple
        self.broker_pad_multiple = broker_pad_multiple
        self.max_scenarios = max_scenarios
        self.program_cache_size = program_cache_size
        # The engine is shared between HTTP request threads (/simulate)
        # and the detector background thread — the shared ProgramCache
        # (parallel/batching.py) holds its lock across the build, so two
        # racing first sweeps converge on ONE program object.
        self._programs = ProgramCache(program_cache_size)
        self.registry = registry or MetricRegistry()
        self.tracer = tracer or default_tracer()
        name = MetricRegistry.name
        self._sweep_timer = self.registry.timer(
            name("WhatIfEngine", "sweep-timer"))
        self._sweep_meter = self.registry.meter(
            name("WhatIfEngine", "sweep-rate"))
        self._scenario_counter = self.registry.counter(
            name("WhatIfEngine", "scenarios-evaluated"))

    # ------------------------------------------------------------- public
    def sweep(self, model: FlatClusterModel, metadata, scenarios,
              *, stale_model: bool = False) -> WhatIfReport:
        """Score ``scenarios`` against the live model; returns the report.

        The input model is never mutated (everything is functional); the
        hypothetical models never leave the device, so they cannot leak
        into any live-cluster consumer (see ProposalCache's scenario
        guard for the belt-and-braces host side).
        """
        if not scenarios:
            raise ValueError("sweep requires at least one scenario")
        if len(scenarios) > self.max_scenarios:
            raise ValueError(
                f"{len(scenarios)} scenarios exceed the engine cap of "
                f"{self.max_scenarios} (raise max_scenarios or split the "
                "sweep)")
        t0 = time.monotonic()
        with self.tracer.span("whatif.sweep",
                              scenarios=len(scenarios)) as sp:
            batch = self._materialize(model, metadata, scenarios)
            goals = [g.bind(metadata) for g in self.goals]
            program = self._program_for(batch, goals, metadata)
            out = program(*self._place_batch(batch))
            fetched = jax.device_get(out)
            self.collector.record_d2h(self.collector.tree_bytes(fetched))
            (viol, vscale, headroom, hfrac, pressure, unavailable,
             n_offline) = (np.asarray(a) for a in fetched)
            report = self._build_report(
                scenarios, goals, metadata, batch,
                viol, vscale, headroom, hfrac, pressure, unavailable,
                n_offline, t0, stale_model)
            worst = report.riskiest()
            sp.set(maxRisk=round(worst.risk, 4),
                   riskiest=worst.scenario.name)
        self._sweep_timer.update(report.duration_s)
        self._sweep_meter.mark()
        self._scenario_counter.inc(len(scenarios))
        return report

    def warmup(self, model: FlatClusterModel, metadata,
               num_scenarios: int = 1) -> None:
        """Pre-compile the sweep program for this model's shapes and a
        scenario bucket covering ``num_scenarios`` (no-op scenarios)."""
        self.sweep(model, metadata,
                   [LoadScale(factor=1.0)] * max(num_scenarios, 1))

    def transformed(self, model: FlatClusterModel, metadata, scenarios
                    ) -> list[FlatClusterModel]:
        """The post-transform hypothetical models, unstacked to host —
        debug/test surface (the sweep itself never materializes these
        outside the device program)."""
        batch = self._materialize(model, metadata, scenarios)
        key = ("transform",) + self._shape_key(batch) + (self._mesh_key,)
        program = self._programs.get_or_build(
            key, lambda: self.collector.track(
                "whatif.transform",
                jax.jit(jax.vmap(scenario_transform,
                                 in_axes=(None, 0, 0, 0, 0, 0)))))
        stacked, _has_alive = program(*self._place_batch(batch))
        return [jax.tree.map(lambda a, i=i: a[i], stacked)
                for i in range(batch.num_real)]

    def _place_batch(self, batch: _Batch):
        """Device placement + h2d metering for one materialized batch:
        the sweep program's argument tuple. Under a mesh the template and
        the [S, P] parameter planes upload as partition-axis shards
        (broker/scenario parameters replicate — metered at their real
        per-device cost); unsharded, everything rides plain asarray."""
        params = {"dead": batch.dead, "add": batch.add,
                  "cap_scale": batch.cap_scale, "pscale": batch.pscale,
                  "pvalid": batch.pvalid}
        if self.mesh is None:
            # Per-scenario parameter upload: the sweep's host->device
            # cost (the template model is already resident).
            self.collector.record_h2d(
                sum(a.nbytes for a in params.values()))
            return (batch.template,) + tuple(
                jnp.asarray(params[k]) for k in
                ("dead", "add", "cap_scale", "pscale", "pvalid"))
        from ..core.runtime_obs import device_bytes
        from ..parallel.sharding import (scenario_batch_shardings,
                                         shard_model)
        template = shard_model(batch.template, self.mesh)
        shardings = scenario_batch_shardings(
            self.mesh, batch.template.num_partitions_padded, params)
        placed = {k: jax.device_put(a, shardings[k])
                  for k, a in params.items()}
        self.collector.record_h2d(
            sum(device_bytes(a) for a in placed.values()))
        return (template,) + tuple(
            placed[k] for k in ("dead", "add", "cap_scale", "pscale",
                                "pvalid"))

    # -------------------------------------------------------- device side
    @staticmethod
    def _transform_fn():
        """(model, dead, add, cap_scale, pscale, pvalid) -> (model',
        has_alive[P]) — the pure per-scenario topology edit."""
        return scenario_transform

    def _program_for(self, batch: _Batch, goals, metadata):
        needs_tlc = any(g.uses_topic_leader_counts for g in goals)
        needs_topics = needs_tlc or any(g.uses_topic_counts for g in goals)
        # Staged (TopicAdd) topics get ids beyond metadata.num_topics —
        # the topic-count arrays must cover them or topic-scoped goals
        # would silently drop the simulated topic's replicas.
        num_topics = metadata.num_topics + batch.num_staged_topics
        key = (("sweep",) + self._shape_key(batch)
               + (tuple((g.name, g.bind_signature()) for g in goals),
                  num_topics if needs_topics else None, needs_tlc,
                  self._mesh_key))
        one = make_scenario_scorer(
            goals, self.constraint.capacity_threshold,
            num_topics=num_topics, needs_topics=needs_topics,
            needs_tlc=needs_tlc)
        return self._programs.get_or_build(
            key, lambda: self.collector.track(
                "whatif.sweep",
                jax.jit(jax.vmap(one, in_axes=(None, 0, 0, 0, 0, 0)))))

    @staticmethod
    def _shape_key(batch: _Batch):
        t = batch.template
        return (batch.dead.shape[0], t.replica_broker.shape,
                t.broker_capacity.shape)

    # ---------------------------------------------------------- host side
    def _materialize(self, model: FlatClusterModel, metadata,
                     scenarios) -> _Batch:
        """Expand scenario specs into the staged template + per-scenario
        parameter arrays (all host-side numpy; one device upload each)."""
        S = len(scenarios)
        S_pad = _round_up(S, self.scenario_pad_multiple)

        bvalid = np.asarray(model.broker_valid)
        balive = np.asarray(model.broker_alive)
        pvalid0 = np.asarray(model.partition_valid)
        adds = [s for s in scenarios if isinstance(s, BrokerAdd)]
        topic_adds = [s for s in scenarios if isinstance(s, TopicAdd)]
        need_b = sum(s.count for s in adds)
        need_p = sum(s.partitions for s in topic_adds)
        need_r = max([s.rf for s in topic_adds], default=0)
        model = _ensure_padding(model, int((~bvalid).sum()), need_b,
                                int((~pvalid0).sum()), need_p, need_r,
                                partition_pad_multiple=
                                self.partition_pad_multiple,
                                broker_pad_multiple=self.broker_pad_multiple)
        bvalid = np.asarray(model.broker_valid)
        balive = np.asarray(model.broker_alive)
        pvalid0 = np.asarray(model.partition_valid)
        B = model.num_brokers_padded
        P, R = model.replica_broker.shape
        free_b = list(np.nonzero(~bvalid)[0])
        free_p = list(np.nonzero(~pvalid0)[0])
        alive_rows = np.nonzero(bvalid & balive)[0]

        dead = np.zeros((S_pad, B), bool)
        add = np.zeros((S_pad, B), bool)
        cap_scale = np.ones((S_pad, B, NUM_RESOURCES), np.float32)
        pscale = np.ones((S_pad, P), np.float32)
        pvalid = np.tile(pvalid0, (S_pad, 1))

        # Staged template arrays (copies only when something needs
        # staging).
        capacity = rack = host = rb = ll = fl = ptopic = None
        new_broker_rows: dict[int, int] = {}
        if adds:
            capacity = np.array(model.broker_capacity)
            rack = np.array(model.broker_rack)
            host = np.array(model.broker_host)
            mean_cap = capacity[alive_rows].mean(axis=0) if len(alive_rows) \
                else np.zeros(NUM_RESOURCES, np.float32)
            next_rack = int(rack[bvalid].max(initial=-1)) + 1
            next_host = int(host[bvalid].max(initial=-1)) + 1
        if topic_adds:
            rb = np.array(model.replica_broker)
            ll = np.array(model.leader_load)
            fl = np.array(model.follower_load)
            ptopic = np.array(model.partition_topic)

        topic_add_idx = 0
        for s_i, scn in enumerate(scenarios):
            if isinstance(scn, BrokerLoss):
                for bid in scn.brokers:
                    row = metadata.broker_index.get(bid)
                    if row is None:
                        raise ValueError(
                            f"broker_loss: unknown broker id {bid}")
                    dead[s_i, row] = True
            elif isinstance(scn, BrokerAdd):
                for _ in range(scn.count):
                    row = free_b.pop(0)
                    add[s_i, row] = True
                    new_broker_rows[row] = s_i
                    capacity[row] = np.asarray(
                        scn.capacity if scn.capacity is not None
                        else mean_cap, np.float32)
                    rack[row] = next_rack
                    host[row] = next_host
                    next_rack += 1
                    next_host += 1
            elif isinstance(scn, CapacityResize):
                rows = (slice(None) if scn.brokers is None else
                        [self._broker_row(metadata, b, "capacity_resize")
                         for b in scn.brokers])
                if scn.resource is None:
                    cap_scale[s_i, rows, :] *= scn.factor
                else:
                    cap_scale[s_i, rows,
                              RESOURCE_KEYS.index(scn.resource)] *= \
                        scn.factor
            elif isinstance(scn, LoadScale):
                if scn.topics is None:
                    pscale[s_i, :] *= scn.factor
                else:
                    tids = []
                    for t in scn.topics:
                        tid = metadata.topic_index.get(t)
                        if tid is None:
                            raise ValueError(
                                f"load_scale: unknown topic {t!r}")
                        tids.append(tid)
                    sel = np.isin(np.asarray(model.partition_topic), tids)
                    pscale[s_i, sel] *= scn.factor
            elif isinstance(scn, TrajectoryScale):
                pscale[s_i, :] *= trajectory_pscale_row(
                    scn, metadata.topic_index,
                    np.asarray(model.partition_topic))
            elif isinstance(scn, TopicAdd):
                if scn.rf > len(alive_rows):
                    raise ValueError(
                        f"topic_add: rf {scn.rf} exceeds the "
                        f"{len(alive_rows)} alive brokers")
                lead = np.asarray(scn.leader_load, np.float32)
                foll = np.asarray(scn.derived_follower_load(), np.float32)
                tid = metadata.num_topics + topic_add_idx
                topic_add_idx += 1
                for k in range(scn.partitions):
                    row = free_p.pop(0)
                    pvalid[s_i, row] = True
                    rb[row, :] = B
                    for r in range(scn.rf):
                        rb[row, r] = alive_rows[(k + r) % len(alive_rows)]
                    ll[row] = lead
                    fl[row] = foll
                    ptopic[row] = tid
            else:
                raise ValueError(f"unknown scenario type {type(scn)}")

        replaced = {}
        if adds:
            replaced.update(broker_capacity=jnp.asarray(capacity),
                            broker_rack=jnp.asarray(rack),
                            broker_host=jnp.asarray(host))
        if topic_adds:
            replaced.update(replica_broker=jnp.asarray(rb),
                            leader_load=jnp.asarray(ll),
                            follower_load=jnp.asarray(fl),
                            partition_topic=jnp.asarray(ptopic))
        template = model.replace(**replaced) if replaced else model
        return _Batch(template=template, dead=dead, add=add,
                      cap_scale=cap_scale, pscale=pscale, pvalid=pvalid,
                      num_real=S, new_broker_rows=new_broker_rows,
                      num_staged_topics=len(topic_adds))

    @staticmethod
    def _broker_row(metadata, bid: int, what: str) -> int:
        row = metadata.broker_index.get(bid)
        if row is None:
            raise ValueError(f"{what}: unknown broker id {bid}")
        return row

    def _build_report(self, scenarios, goals, metadata, batch,
                      viol, vscale, headroom, hfrac, pressure, unavailable,
                      n_offline, t0, stale_model) -> WhatIfReport:
        S = len(scenarios)
        hard = np.array([g.hard for g in goals], bool)
        # Ulp-aware violation cutoff + composite risk: the shared
        # definitions (violated_matrix / risk_scores) the fleet N-1 sweep
        # reports through as well.
        violated = violated_matrix(viol[:S], vscale[:S])
        n_hard = max(int(hard.sum()), 1)
        n_soft = max(int((~hard).sum()), 1)
        hard_frac = violated[:, hard].sum(axis=1) / n_hard
        soft_frac = violated[:, ~hard].sum(axis=1) / n_soft
        pressure = pressure[:S]
        unavailable = unavailable[:S].astype(int)
        valid_parts = batch.pvalid[:S].sum(axis=1).clip(min=1)
        risk = risk_scores(hard_frac, soft_frac, pressure, unavailable,
                           valid_parts)

        def broker_label(row: int):
            if row in batch.new_broker_rows:
                return f"new-{row}"
            if row < len(metadata.broker_ids):
                return metadata.broker_ids[row]
            return int(row)

        outcomes = []
        for i, scn in enumerate(scenarios):
            names = [g.name for g, v in zip(goals, violated[i]) if v]
            hard_names = [g.name for g, v, h in zip(goals, violated[i],
                                                    hard) if v and h]
            hf = hfrac[i]                       # [B, 4], inf on non-alive
            per_res = {}
            for r, key in enumerate(RESOURCE_KEYS):
                col = hf[:, r]
                finite = np.isfinite(col)
                per_res[key] = {
                    "remaining": round(
                        float(np.clip(headroom[i, :, r], 0.0, None).sum()),
                        3),
                    "minBrokerFrac": round(float(col[finite].min()), 4)
                    if finite.any() else None}
            min_per_broker = hf.min(axis=1)
            worst_row = int(np.argmin(
                np.where(np.isfinite(min_per_broker), min_per_broker,
                         np.inf)))
            outcomes.append(ScenarioOutcome(
                scenario=scn,
                risk=float(risk[i]),
                violated_goals=names,
                violated_hard_goals=hard_names,
                capacity_pressure=float(pressure[i]),
                unavailable_partitions=int(unavailable[i]),
                offline_replicas=int(n_offline[i]),
                headroom=per_res,
                worst_broker=broker_label(worst_row)))
        return WhatIfReport(outcomes=outcomes,
                            goals=[g.name for g in goals],
                            duration_s=time.monotonic() - t0,
                            stale_model=stale_model)


def _ensure_padding(model: FlatClusterModel, spare_b: int, need_b: int,
                    spare_p: int, need_p: int, need_r: int, *,
                    partition_pad_multiple: int = 128,
                    broker_pad_multiple: int = 8) -> FlatClusterModel:
    """Re-pad the model (host-side) when the scenario batch needs more
    padding broker rows / partition rows / replica slots than the live
    model carries. Rare (BrokerAdd / TopicAdd beyond the pad slack) —
    costs one numpy round-trip and a fresh program compile for the new
    shapes. The multiples mirror the model builder's configured pad
    buckets so the re-pad stays on-bucket; the padding math itself is the
    shared :func:`..parallel.batching.pad_model_to`."""
    B = model.num_brokers_padded
    P, R = model.replica_broker.shape
    new_B = (B if need_b <= spare_b
             else _round_up(B + need_b - spare_b, broker_pad_multiple))
    new_P = (P if need_p <= spare_p
             else _round_up(P + need_p - spare_p, partition_pad_multiple))
    new_R = max(R, need_r)
    return pad_model_to(model, new_B, new_P, new_R)


def trajectory_pscale_row(scn: TrajectoryScale, topic_index: dict,
                          partition_topic: np.ndarray) -> np.ndarray:
    """One scenario's ``[P]`` partition load-scale plane from a
    :class:`TrajectoryScale` spec: ``default_factor`` everywhere, each
    forecast topic's factor on its partitions. Topics no longer in the
    live metadata are skipped (a stale forecast entry degrades, never
    errors). Shared by the what-if materializer and the fleet layer's
    ``[C, S]`` trajectory sweep, so a fleet-projected factor means
    exactly what a ``/simulate`` one does."""
    row = np.full(partition_topic.shape, scn.default_factor, np.float32)
    for topic, factor in scn.factors:
        tid = topic_index.get(topic)
        if tid is None:
            continue
        row[partition_topic == tid] = factor
    return row


def scenario_transform(model: FlatClusterModel, dead, add, cap_scale,
                       pscale, pvalid):
    """``(model, dead, add, cap_scale, pscale, pvalid) -> (model',
    has_alive[P])`` — the pure per-scenario topology edit
    (kill/add/resize/scale/enable plus leadership failover), shared by
    the what-if engine's vmapped sweep and the fleet layer's
    cluster-sharded N-1 sweep."""
    B = model.num_brokers_padded
    valid_b = model.broker_valid | add
    alive_b = (model.broker_alive | add) & ~dead
    capacity = model.broker_capacity * cap_scale
    leader_load = model.leader_load * pscale[:, None]
    follower_load = model.follower_load * pscale[:, None]
    # Disabled partition rows (template padding this scenario does
    # not enable) must stay empty: route their replicas to the
    # sentinel so no scatter ever sees them.
    rb = jnp.where(pvalid[:, None], model.replica_broker, B)
    off = model.replica_offline & pvalid[:, None]
    pref = model.replica_pref_pos

    # Leadership failover: the alive, non-offline replica with the
    # lowest preferred-order position takes over (Kafka elects from
    # the ISR in assignment order; pref_pos IS that order).
    P, R = rb.shape
    alive1 = jnp.concatenate([alive_b & valid_b,
                              jnp.zeros((1,), bool)])
    slot_valid = rb < B
    electable = slot_valid & alive1[rb] & ~off
    score = jnp.where(electable, pref, R + 1)
    j = jnp.argmin(score, axis=1).astype(jnp.int32)
    has_alive = electable.any(axis=1)
    need = has_alive & ~electable[:, 0] & pvalid
    rows = jnp.arange(P)
    # Swap slot j <-> slot 0 (broker, preferred position, offline
    # flag travel together); non-failover rows route the column
    # write out of bounds (dropped). j > 0 whenever need holds:
    # slot 0 scores R+1 then, strictly above any electable slot.
    jw = jnp.where(need, j, R)
    lead_j, lead_0 = rb[rows, j], rb[:, 0]
    rb = rb.at[rows, jw].set(lead_0, mode="drop")
    rb = rb.at[:, 0].set(jnp.where(need, lead_j, lead_0))
    pref_j, pref_0 = pref[rows, j], pref[:, 0]
    pref = pref.at[rows, jw].set(pref_0, mode="drop")
    pref = pref.at[:, 0].set(jnp.where(need, pref_j, pref_0))
    off_j, off_0 = off[rows, j], off[:, 0]
    off = off.at[rows, jw].set(off_0, mode="drop")
    off = off.at[:, 0].set(jnp.where(need, off_j, off_0))
    # Every replica stranded on a dead/invalid broker is offline.
    off = off | ((rb < B) & ~alive1[rb])

    m = model.replace(
        replica_broker=rb, replica_offline=off,
        replica_pref_pos=pref,
        leader_load=leader_load, follower_load=follower_load,
        partition_valid=pvalid,
        broker_capacity=capacity,
        broker_alive=alive_b, broker_valid=valid_b,
        broker_new=model.broker_new | add)
    return m, has_alive


def make_scenario_scorer(goals, capacity_threshold, *, num_topics: int,
                         needs_topics: bool, needs_tlc: bool):
    """Build the per-scenario scoring function ``one(model, dead, add,
    cap_scale, pscale, pvalid) -> (viol[G], vscale[G], headroom[B, 4],
    hfrac[B, 4], pressure, unavailable, n_offline)`` — transform +
    init_state/build_context + violation stack + headroom reductions.
    The what-if engine vmaps it over the ``[S]`` scenario axis; the fleet
    N-1 sweep nests it under a cluster axis. One definition, so a fleet
    risk and a ``/simulate`` risk can never drift apart."""
    cap_thr = jnp.asarray(capacity_threshold, jnp.float32)
    goals = tuple(goals)

    def one(model, dead, add, cap_scale, pscale, pvalid):
        m, has_alive = scenario_transform(model, dead, add, cap_scale,
                                          pscale, pvalid)
        state = init_state(
            m,
            with_topic_counts=num_topics if needs_topics else None,
            with_topic_leader_counts=needs_tlc)
        ctx = build_context(m)
        viol = violation_stack(goals, state, ctx)
        vscale = jnp.stack([g.violation_scale(state, ctx)
                            for g in goals])
        B = m.num_brokers_padded
        util = state.util[:B]
        usable = m.broker_capacity * cap_thr[None, :]
        alive = m.broker_alive & m.broker_valid
        headroom = jnp.where(alive[:, None], usable - util, 0.0)
        hfrac = jnp.where(
            alive[:, None],
            1.0 - util / jnp.maximum(usable, 1e-9), jnp.inf)
        pressure = jnp.where(alive[:, None],
                             util / jnp.maximum(usable, 1e-9),
                             0.0).max()
        unavailable = (m.partition_valid & ~has_alive).sum()
        n_offline = (m.replica_offline & (m.replica_broker < B)).sum()
        return viol, vscale, headroom, hfrac, pressure, unavailable, \
            n_offline

    return one
