"""REST API server — the 23 endpoints (ref
``servlet/CruiseControlEndPoint.java:16-39``, dispatch per
``KafkaCruiseControlRequestHandler``), async User-Task-ID semantics
(``UserTaskManager.java:69``), two-step review purgatory, and pluggable
security, over ``http.server`` (the stdlib stand-in for Jetty/Vert.x —
``KafkaCruiseControlServletApp``/``KafkaCruiseControlVertxApp``).

GET  : state, load, partition_load, proposals, kafka_cluster_state,
       user_tasks, review_board, permissions, bootstrap, train
POST : rebalance, add_broker, remove_broker, fix_offline_replicas,
       demote_broker, topic_configuration, rightsize, remove_disks,
       stop_proposal_execution, pause_sampling, resume_sampling, admin,
       review
"""

from __future__ import annotations

import contextlib
import json
import logging
import threading
import time
# Distinct from builtin TimeoutError before Python 3.11.
from concurrent.futures import TimeoutError as _FuturesTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..analyzer import OptimizationOptions
from ..core.leader import NotLeaderError
from .admission import AdmissionLimitError
from .facade import KafkaCruiseControl
from .parameters import ParsedParams, parse_endpoint_params
from .purgatory import Purgatory
from .security import (AllowAllSecurityProvider, AuthorizationError,
                       SecurityProvider, check_access, ENDPOINT_MIN_ROLE)
from .tasks import TooManyUserTasksError, UserTaskManager

#: private handle()->router marker: "render this 200 as plaintext"
#: (json=false resolved by the typed parameter layer). Popped by
#: route_request before the response leaves the process.
_PLAINTEXT_MARKER = "x-cc-render-plaintext"

GET_ENDPOINTS = {"state", "load", "partition_load", "proposals",
                 "kafka_cluster_state", "user_tasks", "review_board",
                 "permissions", "bootstrap", "train", "openapi", "fleet",
                 "forecast", "history"}
POST_ENDPOINTS = {"rebalance", "add_broker", "remove_broker",
                  "fix_offline_replicas", "demote_broker",
                  "topic_configuration", "rightsize", "remove_disks",
                  "stop_proposal_execution", "pause_sampling",
                  "resume_sampling", "admin", "review", "simulate",
                  "fleet_rebalance", "forecast_refresh"}
#: POSTs that execute immediately even with two-step verification on
#: (ref Purgatory: REVIEW itself and flow-control endpoints skip review;
#: simulate is a pure read — a what-if sweep mutates nothing, so parking
#: it for review would only delay the answer; fleet_rebalance only
#: refreshes the members' proposal caches — execution stays behind the
#: per-cluster endpoints, which keep their review parking;
#: forecast_refresh only refits host-side forecasts and re-scores a
#: dry-run sweep — provisioning actions stay behind rightsize/detector).
NO_REVIEW_REQUIRED = {"review", "stop_proposal_execution", "simulate",
                      "fleet_rebalance", "forecast_refresh"}
#: bare GET handlers outside the servlet endpoint table (observability
#: surfaces + the API explorer) — instrumented through the same shared
#: request-timing wrapper as every dispatched endpoint.
AUX_GET_ENDPOINTS = {"metrics", "trace", "devicestats", "explorer",
                     "replication_stream"}
#: GET endpoints a read replica refuses while its stream lag exceeds
#: replication.max.staleness.ms (503 + leaderId + Retry-After): the
#: cluster-state surfaces where stale answers mislead. Observability
#: endpoints (/metrics, /devicestats, /trace, the explorer) and the
#: admin bookkeeping GETs stay up on a lagging replica — that is
#: exactly when an operator needs to scrape it.
STALENESS_GATED_ENDPOINTS = {"state", "load", "partition_load",
                             "proposals", "kafka_cluster_state", "fleet",
                             "forecast"}

#: per-request access log (ref webserver.accesslog.enabled; the reference
#: writes an NCSA access log through Jetty)
_ACCESS_LOG = logging.getLogger("cruise_control_tpu.access")
#: endpoints whose work runs async behind a User-Task-ID
ASYNC_ENDPOINTS = {"rebalance", "add_broker", "remove_broker",
                   "fix_offline_replicas", "demote_broker",
                   "topic_configuration", "rightsize", "proposals", "load",
                   "partition_load", "bootstrap", "train", "remove_disks"}


def _auth_headers(e: AuthorizationError, provider) -> dict:
    """RFC 7235: every 401 carries a WWW-Authenticate challenge —
    the error's own, or the provider's default (wrong-password retries
    need the challenge just as much as missing-credential ones)."""
    challenge = e.challenge
    if challenge is None and e.status == 401:
        challenge = getattr(provider, "default_challenge", None)
    return {"WWW-Authenticate": challenge} if challenge else {}


class CruiseControlApp:
    """Wires facade + task manager + purgatory + security into a server
    (ref KafkaCruiseControlApp.java)."""

    def __init__(self, facade: KafkaCruiseControl, host: str = "127.0.0.1",
                 port: int = 9090,
                 security: SecurityProvider | None = None,
                 two_step_verification: bool = False,
                 max_active_tasks: int | None = None,
                 completed_task_retention_ms: int | None = None,
                 max_cached_completed_tasks: int | None = None,
                 purgatory_retention_ms: int | None = None,
                 purgatory_max_requests: int | None = None,
                 reason_required: bool = False,
                 cors: dict | None = None,
                 accesslog: bool = False,
                 ssl_context=None,
                 parameter_overrides: dict | None = None,
                 engine: str = "threading",
                 max_block_time_ms: int | None = None,
                 admission_rate_per_s: float | None = None,
                 admission_burst: int | None = None) -> None:
        # None = use the component's own default (single source of truth
        # in tasks.py / purgatory.py); values are forwarded only when set.
        self.facade = facade
        from ..core.sensors import MetricRegistry as _MR
        self.registry = _MR()
        task_kwargs = {k: v for k, v in (
            ("max_active_tasks", max_active_tasks),
            ("completed_task_retention_ms", completed_task_retention_ms),
            ("max_cached_completed", max_cached_completed_tasks),
        ) if v is not None}
        self.tasks = UserTaskManager(registry=self.registry, **task_kwargs)
        #: write-path admission control (api/admission.py): None =
        #: disabled (tier-1 stacks and single-user CLIs are unthrottled;
        #: serving deployments set admission.rate.per.sec).
        self.admission = None
        if admission_rate_per_s is not None:
            from .admission import AdmissionController
            self.admission = AdmissionController(
                rate_per_s=admission_rate_per_s,
                burst=(admission_burst if admission_burst is not None
                       else 10),
                registry=self.registry)
        purgatory_kwargs = {k: v for k, v in (
            ("retention_ms", purgatory_retention_ms),
            ("max_requests", purgatory_max_requests)) if v is not None}
        self.purgatory = (Purgatory(**purgatory_kwargs)
                          if two_step_verification else None)
        self.security = security or AllowAllSecurityProvider()
        #: POSTs must carry reason= (ref request.reason.required)
        self.reason_required = reason_required
        #: CORS header map sent on every response when configured (ref
        #: webserver.http.cors.*)
        self.cors = cors or {}
        self.accesslog = accesslog
        #: endpoint -> EndpointParameters subclass overriding the built-in
        #: (ref CruiseControlParametersConfig pluggable parameter classes)
        self.parameter_overrides = parameter_overrides or {}
        #: cap on how long one request may block awaiting an async result
        #: (ref webserver.request.maxBlockTimeMs): a larger
        #: get_response_timeout_s is clamped here and the client re-polls
        #: by User-Task-ID. None = unclamped (direct construction).
        self.max_block_time_ms = max_block_time_ms
        #: "threading" (stdlib ThreadingHTTPServer, the Jetty analog) or
        #: "asyncio" (event-loop engine, the Vert.x analog) — ref the
        #: reference's dual web-server engines (webserver.* configs apply
        #: to both).
        self.engine = engine
        # Per-endpoint request sensors (ref the KafkaCruiseControlServlet
        # sensor table: <endpoint>-request-rate and
        # <endpoint>-successful-request-execution-timer), merged into the
        # facade's scrape view. One registry per app — the task-queue and
        # admission sensors above share it, so backpressure is scraped
        # alongside the request rates.
        if hasattr(facade, "extra_registries"):
            facade.extra_registries.append(self.registry)
        # Pre-built enum-keyed sensor maps (the reference keys its servlet
        # sensors by the CruiseControlEndPoint enum): no per-request
        # registry lookups or name formatting on the dispatch path.
        # Striped variants: a mark/update is a per-thread append (no
        # shared Lock), so N request threads never serialize on their own
        # instrumentation — the scrape drains the stripes.
        _sensor_eps = (("GET", GET_ENDPOINTS | AUX_GET_ENDPOINTS),
                       ("POST", POST_ENDPOINTS))
        self._request_meters = {
            (m, e): self.registry.striped_meter(
                f"KafkaCruiseControlServlet.{e}-request-rate")
            for m, eps in _sensor_eps for e in eps}
        self._success_timers = {
            (m, e): self.registry.striped_timer(
                f"KafkaCruiseControlServlet.{e}-successful-"
                f"request-execution-timer")
            for m, eps in _sensor_eps for e in eps}
        # Conditional-request accounting: a 304 is a SUCCESS (the client
        # has the current bytes) with its own count per endpoint.
        self._not_modified = {
            e: self.registry.striped_counter(f"api.{e}.not-modified")
            for e in GET_ENDPOINTS | AUX_GET_ENDPOINTS}
        self._aio = None
        self.server = None
        if engine == "asyncio":
            from .aioserver import AsyncHttpEngine
            self._aio = AsyncHttpEngine(self, host=host, port=port,
                                        ssl_context=ssl_context)
        else:
            handler = _make_handler(self)
            self.server = ThreadingHTTPServer((host, port), handler)
            if ssl_context is not None:
                # ref webserver.ssl.*: TLS termination on the same listener.
                self.server.socket = ssl_context.wrap_socket(
                    self.server.socket, server_side=True)
        self._thread: threading.Thread | None = None

    def _parse(self, endpoint: str, query: dict) -> "ParsedParams":
        cls = self.parameter_overrides.get(endpoint)
        if cls is not None:
            return cls.parse(endpoint, query)
        return parse_endpoint_params(endpoint, query)

    @property
    def port(self) -> int:
        if self._aio is not None:
            return self._aio.port
        return self.server.server_address[1]

    def start(self) -> None:
        if self._aio is not None:
            self._aio.start()
            return
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="cc-http")
        self._thread.start()

    def stop(self) -> None:
        if self._aio is not None:
            self._aio.stop()
        else:
            self.server.shutdown()
        self.tasks.shutdown()
        # Detach our sensors: a new app over the same facade must not
        # leave duplicate KafkaCruiseControlServlet.* series behind.
        extra = getattr(self.facade, "extra_registries", None)
        if extra is not None and self.registry in extra:
            extra.remove(self.registry)
        self.facade.shutdown()

    # ------------------------------------------------------------ dispatch
    @contextlib.contextmanager
    def request_timing(self, method: str, endpoint: str):
        """The ONE per-request instrumentation wrapper shared by every
        handler — servlet endpoints (sync and aio engines both dispatch
        through :meth:`handle`) AND the bare handlers (/metrics, /trace,
        the API explorer) that used to bypass the sensors entirely.

        Method-resolved sensors only (the reference meters requests the
        servlet actually dispatches): a GET probe of a POST endpoint, an
        unknown path, or an auth rejection never marks a rate; a
        dispatched request that fails (parse error, operation failure)
        still counts as a request, but only successes feed the timer.
        Every request also gets an ``api.<endpoint>`` root span.

        Yields a dict; the caller sets ``["status"]`` before the block
        exits (unset = treated as a 500)."""
        meter = self._request_meters.get((method, endpoint))
        timer = self._success_timers.get((method, endpoint))
        t0 = time.monotonic()
        outcome = {"status": 500}
        # Span names must stay low-cardinality: the endpoint is
        # client-controlled path input, so unknown ones share one name
        # (the real endpoint table is finite and keyed by the sensor map).
        span_name = (f"api.{endpoint}" if meter is not None
                     else "api.unknown")
        with self.facade.tracer.span(span_name, method=method,
                                     endpoint=endpoint) as sp:
            try:
                yield outcome
            except AuthorizationError:
                raise
            except Exception:
                if meter is not None:
                    meter.mark()
                raise
            status = outcome["status"]
            sp.set(status=status)
            if meter is not None and status not in (401, 403, 405):
                meter.mark()
            if status == 304:
                nm = self._not_modified.get(endpoint)
                if nm is not None:
                    nm.inc()
            if timer is not None and status < 400:
                timer.update(time.monotonic() - t0)

    def handle(self, method: str, endpoint: str, params: dict,
               headers: dict) -> tuple[int, dict, dict]:
        """Returns (status, response_json, extra_headers)."""
        with self.request_timing(method, endpoint) as outcome:
            out = self._handle(method, endpoint, params, headers)
            outcome["status"] = out[0]
        return out

    def _handle(self, method: str, endpoint: str, params: dict,
                headers: dict) -> tuple[int, dict, dict]:
        principal = check_access(self.security, endpoint, headers)
        # Parameter names are case-insensitive (the typed layer lowercases
        # on parse); normalize once so the raw reads below (reason,
        # review_id) agree with the parser.
        params = {k.lower(): v for k, v in params.items()}
        if method == "GET" and endpoint not in GET_ENDPOINTS:
            return 405, {"errorMessage": f"{endpoint} is not a GET endpoint"}, {}
        if method == "POST" and endpoint not in POST_ENDPOINTS:
            return 405, {"errorMessage": f"{endpoint} is not a POST endpoint"}, {}

        # Write-path admission: every POST draws from its principal's
        # token bucket BEFORE any work is parked, parsed or queued. An
        # empty bucket raises AdmissionLimitError -> 429 + Retry-After
        # (mapped by route_request); GETs are never admission-gated.
        if method == "POST" and self.admission is not None:
            self.admission.admit(principal.name)

        # ref request.reason.required: mutating requests must say why
        # (recorded in the access/audit logs).
        if (method == "POST" and self.reason_required
                and endpoint not in NO_REVIEW_REQUIRED
                and not params.get("reason", [None])[0]):
            return 400, {"errorMessage":
                         "a reason parameter is required "
                         "(request.reason.required=true)"}, {}

        # Two-step verification: un-reviewed POSTs park in the purgatory.
        consumed_review: int | None = None
        if (method == "POST" and self.purgatory is not None
                and endpoint not in NO_REVIEW_REQUIRED):
            review_id = params.get("review_id", [None])[0]
            if review_id is None:
                # Validate eagerly: malformed requests must not park in the
                # purgatory and fail only at approval time.
                self._parse(endpoint, params)
                info = self.purgatory.add(endpoint, {k: v[0] for k, v
                                                     in params.items()},
                                          principal.name)
                return 202, {"reviewResult": info.to_json()}, {}
            # Validate the merged request BEFORE submit(): submit
            # irreversibly burns the approval, so a typo in the replay
            # must not consume the reviewed request. Same for task
            # capacity — a 429 is "back off and retry", which is a lie if
            # the approval was already consumed (the retry would 400 on
            # a Submitted review).
            pending = self.purgatory.get(int(review_id), endpoint)
            merged = {k.lower(): [v] for k, v in pending.params.items()}
            merged.update(params)
            self._parse(endpoint, merged)
            if endpoint in ASYNC_ENDPOINTS:
                # Pre-check narrows the 429-after-burn window; the
                # restore below closes it.
                self.tasks.ensure_capacity()
            self.purgatory.submit(int(review_id), endpoint)
            consumed_review = int(review_id)
            params = merged

        # Typed parse + validation (ref servlet/parameters/*): unknown
        # parameters, bad types, missing required params and forbidden
        # combinations are a 400 before any work is scheduled.
        parsed = self._parse(endpoint, params)

        if endpoint in ASYNC_ENDPOINTS:
            try:
                result = self._handle_async(endpoint, parsed, headers)
            except TooManyUserTasksError:
                # A concurrent submission can still steal the last slot
                # between ensure_capacity() and tasks.submit(): a 429
                # promises "retry works", so the consumed approval must
                # be restored before it propagates.
                if consumed_review is not None:
                    self.purgatory.restore_approval(consumed_review)
                raise
        else:
            result = self._handle_sync(endpoint, parsed, principal)
        if parsed.get("json") is False:
            # The plaintext decision belongs HERE, where the TYPED value
            # is known (case-insensitive parse; purgatory-merged replay
            # params included) — the transport layer only sees the raw
            # query. Signalled via a private marker header the router
            # pops before the response leaves the process.
            status, payload, extra = result
            result = status, payload, {**extra, _PLAINTEXT_MARKER: "1"}
        return result

    def _handle_async(self, endpoint: str, params: ParsedParams,
                      headers: dict) -> tuple[int, dict, dict]:
        uuid = headers.get("user-task-id") or params.get("user_task_id")
        existing = self.tasks.get(uuid) if uuid else None
        if existing is None:
            fn = self._operation(endpoint, params)
            # Root span for the async work: it runs on a user-task worker
            # thread, so the request's api.<endpoint> span (this thread)
            # cannot parent it — the task span is the thread-local root
            # the facade/monitor/optimizer/executor spans nest under.
            tracer = self.facade.tracer

            def traced_fn(progress, _fn=fn, _ep=endpoint):
                with tracer.span(f"task.{_ep}"):
                    return _fn(progress)

            existing = self.tasks.submit(endpoint, endpoint, traced_fn,
                                         user_task_id=uuid)
        hdrs = {"User-Task-ID": existing.user_task_id}
        timeout = float(params.get("get_response_timeout_s", 10.0))
        if self.max_block_time_ms is not None:
            timeout = min(timeout, self.max_block_time_ms / 1000.0)
        try:
            result = existing.future.result(timeout=timeout)
            return 200, result, hdrs
        except (TimeoutError, _FuturesTimeout):
            return 202, {"progress": existing.progress.to_json(),
                         "userTaskId": existing.user_task_id}, hdrs
        except NotLeaderError as e:
            # Standby replica: execution endpoints answer 503 with the
            # leader's identity so clients (and LBs) can redirect — reads
            # keep being served here (docs/operations.md §HA). Retry-After
            # covers clients that retry the same node instead of
            # redirecting: back off one lease beat, don't hot-loop.
            return 503, {"errorMessage": str(e),
                         "leaderId": e.leader_id,
                         "userTaskId": existing.user_task_id}, {
                             **hdrs, "Retry-After": "1"}
        except Exception as e:  # operation failed
            return 500, {"errorMessage": str(e),
                         "userTaskId": existing.user_task_id}, hdrs

    def _operation(self, endpoint: str, params: ParsedParams):
        """Build the callable a user task runs (ref the Runnable classes in
        servlet/handler/async/runnable/)."""
        facade = self.facade
        dryrun = params.get("dryrun", True)
        goals = params.goal_list() if endpoint not in (
            "load", "partition_load", "bootstrap", "train",
            "rightsize") else None
        exec_kwargs = params.execution_kwargs()

        def maybe_stop_ongoing():
            """ref STOP_ONGOING_EXECUTION_PARAM: preempt the in-flight
            execution so this request's (non-dryrun) plan replaces it."""
            if dryrun or not params.get("stop_ongoing_execution"):
                return
            facade.stop_ongoing_and_wait()

        def options_from(params: ParsedParams) -> OptimizationOptions:
            pattern = params.get("excluded_topics") or ""
            no_leadership = set(
                params.get("exclude_brokers_for_leadership") or ())
            no_replicas = set(
                params.get("exclude_brokers_for_replica_move") or ())
            # ref EXCLUDE_RECENTLY_(DEMOTED|REMOVED)_BROKERS_PARAM: fold the
            # executor's expiring history into the request's exclusions.
            if params.get("exclude_recently_demoted_brokers"):
                no_leadership |= set(
                    facade.executor.recently_demoted_brokers)
            if params.get("exclude_recently_removed_brokers"):
                no_replicas |= set(facade.executor.recently_removed_brokers)
            # Kafka-assigner mode replaces the whole chain with the
            # assigner goals and the reference waives its hard-goal
            # presence check there (ParameterUtils sanity check skips when
            # isKafkaAssignerMode) — waive the off-chain audit to match;
            # the assigner's own hard rack goal still gates in-chain.
            # Framework extension: per-request audit waivers (named goals
            # only — in-chain hard goals still gate). Names were
            # registry-validated at parse time (400 on a typo).
            from ..analyzer.goals import short_goal_name
            waived = frozenset(short_goal_name(n) for n in
                               (params.get("waived_hard_goals") or ()))
            if params.get("kafka_assigner"):
                # Waive the server's REGISTERED hard-goal set (hard.goals
                # config when set, default catalog otherwise) — waiving
                # only default names would leave a custom registered goal
                # gating assigner mode.
                names = facade.optimizer.hard_goal_names
                if names is None:
                    from ..analyzer.goals import default_goals
                    names = [g.name for g in default_goals() if g.hard]
                waived = waived | frozenset(short_goal_name(n)
                                            for n in names)
            return OptimizationOptions(
                excluded_topics=frozenset(
                    t for t in pattern.split(",") if t),
                fast_mode=params.get("fast_mode", False),
                skip_hard_goal_check=params.get("skip_hard_goal_check",
                                                False),
                waived_hard_goals=waived,
                excluded_brokers_for_leadership=frozenset(no_leadership),
                excluded_brokers_for_replica_move=frozenset(no_replicas),
                destination_broker_ids=frozenset(
                    params.get("destination_broker_ids") or ()))

        if endpoint == "rebalance":
            if params.get("rebalance_disk"):
                # Disk-only mode: intra-broker moves, never cross-broker
                # (ref REBALANCE_DISK_MODE_PARAM -> intra-broker goals).
                def run(progress):
                    return facade.rebalance_disks(dryrun=dryrun,
                                                  progress=progress,
                                                  **exec_kwargs)
            else:
                def run(progress):
                    maybe_stop_ongoing()
                    res, exec_res = facade.rebalance(
                        goals=goals, dryrun=dryrun,
                        options=options_from(params),
                        progress=progress,
                        ignore_proposal_cache=params.get(
                            "ignore_proposal_cache", False),
                        **exec_kwargs)
                    return _optimization_response(
                        res, exec_res, verbose=params.get("verbose", False))
        elif endpoint == "add_broker":
            def run(progress):
                maybe_stop_ongoing()
                kwargs = dict(exec_kwargs)
                if not params.get("throttle_added_broker", True):
                    kwargs["throttle_excluded_brokers"] = set(
                        params["brokerid"])
                res, exec_res = facade.add_brokers(
                    params["brokerid"], dryrun=dryrun, goals=goals,
                    progress=progress, options=options_from(params),
                    **kwargs)
                return _optimization_response(res, exec_res)
        elif endpoint == "remove_broker":
            def run(progress):
                maybe_stop_ongoing()
                kwargs = dict(exec_kwargs)
                if not params.get("throttle_removed_broker", True):
                    kwargs["throttle_excluded_brokers"] = set(
                        params["brokerid"])
                res, exec_res = facade.remove_brokers(
                    params["brokerid"], dryrun=dryrun, goals=goals,
                    progress=progress,
                    destination_broker_ids=frozenset(
                        params.get("destination_broker_ids") or ()),
                    options=options_from(params), **kwargs)
                return _optimization_response(res, exec_res)
        elif endpoint == "demote_broker":
            def run(progress):
                maybe_stop_ongoing()
                res, exec_res = facade.demote_brokers(
                    params["brokerid"], dryrun=dryrun,
                    progress=progress, options=options_from(params),
                    skip_urp_demotion=params.get("skip_urp_demotion", True),
                    exclude_follower_demotion=params.get(
                        "exclude_follower_demotion", True),
                    **exec_kwargs)
                return _optimization_response(res, exec_res)
        elif endpoint == "fix_offline_replicas":
            def run(progress):
                maybe_stop_ongoing()
                res, exec_res = facade.fix_offline_replicas(
                    dryrun=dryrun, goals=goals, progress=progress,
                    options=options_from(params), **exec_kwargs)
                return _optimization_response(res, exec_res)
        elif endpoint == "topic_configuration":
            def run(progress):
                maybe_stop_ongoing()
                res, exec_res = facade.update_topic_configuration(
                    params["topic"], params["replication_factor"],
                    dryrun=dryrun, progress=progress, goals=goals,
                    options=options_from(params), **exec_kwargs)
                return _optimization_response(res, exec_res)
        elif endpoint == "proposals":
            def run(progress):
                res = facade.proposals(
                    ignore_cache=params.get("ignore_proposal_cache", False),
                    goals=goals, progress=progress)
                return _optimization_response(
                    res, None, verbose=params.get("verbose", False))
        elif endpoint == "load":
            def run(progress):
                return facade.load(
                    populate_disk_info=params.get("populate_disk_info",
                                                  False),
                    capacity_only=params.get("capacity_only", False))
        elif endpoint == "partition_load":
            def run(progress):
                return {"records": facade.partition_load(
                    resource=params.get("resource", "DISK"),
                    start=params.get("start", 0),
                    max_entries=params.get("entries", 2**31),
                    topic_pattern=params.get("topic"),
                    broker_ids=params.get("brokerid"),
                    max_load=params.get("max_load", False))}
        elif endpoint == "bootstrap":
            def run(progress):
                rounds = facade.bootstrap(params.get("start", 0),
                                          params.get("end", 0))
                return {"message": f"bootstrapped {rounds} rounds"}
        elif endpoint == "train":
            def run(progress):
                return facade.train()
        elif endpoint == "rightsize":
            def run(progress):
                return facade.rightsize()
        elif endpoint == "remove_disks":
            # brokerid_and_logdirs=0-logdirA,0-logdirB,1-logdirA (the
            # reference's parameter format). Parsed + validated EAGERLY so
            # bad input is a 400 at dispatch, not an opaque 500 from the
            # async task.
            raw = params["brokerid_and_logdirs"]
            drained: dict[int, list[str]] = {}
            for entry in raw.split(","):
                if not entry.strip():
                    continue
                broker, _, logdir = entry.partition("-")
                if not broker.strip().isdigit() or not logdir:
                    raise ValueError(
                        f"bad brokerid_and_logdirs entry {entry!r} "
                        "(want <brokerId>-<logdir>)")
                drained.setdefault(int(broker), []).append(logdir)
            if not drained:
                raise ValueError("remove_disks requires brokerid_and_logdirs")
            known = set(self.facade.admin.describe_cluster())
            unknown = set(drained) - known
            if unknown:
                raise ValueError(f"unknown broker ids {sorted(unknown)}")

            def run(progress):
                return facade.remove_disks(drained, dryrun=dryrun,
                                           progress=progress, **exec_kwargs)
        else:  # pragma: no cover
            raise ValueError(endpoint)
        return run

    def _handle_sync(self, endpoint: str, params: ParsedParams,
                     principal) -> tuple[int, dict, dict]:
        facade = self.facade
        if endpoint == "state":
            return 200, facade.state(params.get("substates")), {}
        if endpoint == "kafka_cluster_state":
            return 200, facade.kafka_cluster_state(
                verbose=params.get("verbose", False),
                topic_pattern=params.get("topic")), {}
        if endpoint == "openapi":
            from .openapi import openapi_spec
            return 200, openapi_spec(), {}
        if endpoint == "user_tasks":
            tasks = self.tasks.all_tasks()
            # ref UserTasksParameters filters: by task id / endpoint / type.
            ids = params.get("user_task_ids")
            if ids:
                wanted = set(ids)
                tasks = [t for t in tasks if t.user_task_id in wanted]
            endpoints = params.get("endpoints")
            if endpoints:
                wanted = {e.lower() for e in endpoints}
                tasks = [t for t in tasks if t.endpoint.lower() in wanted]
            types = params.get("types")
            if types:
                wanted = {s.upper() for s in types}
                tasks = [t for t in tasks
                         if t.state.value.upper() in wanted]
            entries = params.get("entries")
            if entries:
                tasks = tasks[:entries]
            return 200, {"userTasks": [t.to_json() for t in tasks]}, {}
        if endpoint == "permissions":
            return 200, {"principal": principal.name,
                         "role": principal.role.name,
                         "endpoints": sorted(
                             e for e, r in ENDPOINT_MIN_ROLE.items()
                             if principal.role.value >= r.value)}, {}
        if endpoint == "review_board":
            if self.purgatory is None:
                return 400, {"errorMessage":
                             "two-step verification is disabled"}, {}
            rows = self.purgatory.review_board()
            ids = params.get("review_ids")
            if ids:
                wanted = set(ids)
                rows = [r for r in rows if r.review_id in wanted]
            return 200, {"requestInfo": [r.to_json() for r in rows]}, {}
        if endpoint == "review":
            if self.purgatory is None:
                return 400, {"errorMessage":
                             "two-step verification is disabled"}, {}
            touched = self.purgatory.apply_review(
                set(params.get("approve") or ()),
                set(params.get("discard") or ()),
                params.get("reason") or "")
            return 200, {"requestInfo": [r.to_json()
                                         for r in touched.values()]}, {}
        if endpoint == "stop_proposal_execution":
            facade.stop_proposal_execution(
                force=params.get("force_stop", False),
                stop_external_agent=params.get("stop_external_agent",
                                               False))
            return 200, {"message": "Execution stop requested."}, {}
        if endpoint == "pause_sampling":
            facade.pause_sampling(params.get("reason") or "")
            return 200, {"message": "Sampling paused."}, {}
        if endpoint == "resume_sampling":
            facade.resume_sampling(params.get("reason") or "")
            return 200, {"message": "Sampling resumed."}, {}
        if endpoint == "admin":
            return 200, self._admin(params), {}
        if endpoint == "simulate":
            payload: dict = {}
            if params.get("sweep"):
                payload["sweep"] = params["sweep"]
            raw = params.get("scenarios")
            if raw:
                try:
                    payload["scenarios"] = json.loads(raw)
                except json.JSONDecodeError as e:
                    raise ValueError(
                        f"parameter scenarios is not valid JSON: {e}")
            return 200, facade.simulate(payload), {}
        if endpoint == "fleet":
            return 200, facade.fleet_summary(), {}
        if endpoint == "fleet_rebalance":
            return 200, facade.fleet_rebalance(), {}
        if endpoint == "forecast":
            return 200, facade.forecast_json(), {}
        if endpoint == "forecast_refresh":
            return 200, facade.forecast_refresh(), {}
        if endpoint == "history":
            # The flight recorder is an observability surface: never
            # render-cached and never staleness-gated — a lagging replica's
            # own journal is exactly what post-failover forensics needs.
            severity = params.get("severity")
            return 200, facade.history_json(
                categories=params.get("category"),
                severity=severity.lower() if severity else None,
                since_seq=params.get("since_seq", 0),
                limit=params.get("limit", 256)), {}
        return 404, {"errorMessage": f"unknown endpoint {endpoint}"}, {}

    def _admin(self, params: ParsedParams) -> dict:
        """ref AdminParameters: runtime toggles."""
        out: dict = {}
        if "concurrent_partition_movements_per_broker" in params:
            cap = params["concurrent_partition_movements_per_broker"]
            self.facade.executor.config.concurrency.\
                num_concurrent_partition_movements_per_broker = cap
            out["concurrencyPerBroker"] = cap
        if "concurrent_intra_broker_partition_movements" in params:
            cap = params["concurrent_intra_broker_partition_movements"]
            self.facade.executor.config.concurrency.\
                num_concurrent_intra_broker_partition_movements = cap
            out["concurrencyIntraBroker"] = cap
        if "concurrent_leader_movements" in params:
            cap = params["concurrent_leader_movements"]
            self.facade.executor.config.concurrency.\
                num_concurrent_leader_movements = cap
            out["concurrencyLeader"] = cap
        if params.get("drop_recently_removed_brokers"):
            self.facade.executor.recently_removed_brokers.clear()
            out["droppedRecentlyRemovedBrokers"] = True
        if params.get("drop_recently_demoted_brokers"):
            self.facade.executor.recently_demoted_brokers.clear()
            out["droppedRecentlyDemotedBrokers"] = True
        if "min_isr_based_concurrency_adjustment" in params:
            self.facade.executor.config.concurrency_adjuster_enabled = \
                params["min_isr_based_concurrency_adjustment"]
            out["minIsrBasedConcurrencyAdjustment"] = params[
                "min_isr_based_concurrency_adjustment"]
        from ..executor.concurrency import VALID_ADJUSTER_TYPES

        def _adjuster_types(raw: list) -> list[str]:
            types = [t.strip().lower() for t in raw]
            bad = [t for t in types if t not in VALID_ADJUSTER_TYPES]
            if bad:
                raise ValueError(
                    f"unknown concurrency type(s) {bad}; valid: "
                    f"{sorted(VALID_ADJUSTER_TYPES)}")
            return types

        if "disable_concurrency_adjuster_for" in params:
            for t in _adjuster_types(
                    params["disable_concurrency_adjuster_for"]):
                self.facade.executor.adjuster_disabled_types.add(t)
            out["disabledConcurrencyAdjuster"] = params[
                "disable_concurrency_adjuster_for"]
        if "enable_concurrency_adjuster_for" in params:
            for t in _adjuster_types(
                    params["enable_concurrency_adjuster_for"]):
                self.facade.executor.adjuster_disabled_types.discard(t)
            out["enabledConcurrencyAdjuster"] = params[
                "enable_concurrency_adjuster_for"]
        detector = self.facade.detector
        if detector is not None:
            if "disable_self_healing_for" in params:
                for name in params["disable_self_healing_for"]:
                    detector.set_self_healing_enabled(name, False)
                out["disabledSelfHealing"] = params[
                    "disable_self_healing_for"]
            if "enable_self_healing_for" in params:
                for name in params["enable_self_healing_for"]:
                    detector.set_self_healing_enabled(name, True)
                out["enabledSelfHealing"] = params["enable_self_healing_for"]
        return out or {"message": "no-op"}


def _optimization_response(res, exec_res, verbose: bool = False) -> dict:
    out = res.to_json()
    if verbose:
        # ref verbose proposals responses carrying the optimized load
        # (ProposalsRunnable verbose -> broker stats after optimization).
        from ..model.stats import stats_summary
        out["loadAfterOptimization"] = stats_summary(res.final_model)
    if exec_res is not None:
        out["executionResult"] = {
            "succeeded": exec_res.succeeded, "stopped": exec_res.stopped,
            "numDeadTasks": exec_res.num_dead_tasks,
            "taskSummary": exec_res.state_counts}
    return out


#: GET endpoints the render cache may serve (bare requests, plus the
#: ``json=`` flag): the servlet read tier + the bare observability
#: handlers. Anything with other parameters (verbose, substates, ...)
#: takes the full typed path.
CACHED_GET_ENDPOINTS = {"proposals", "state", "kafka_cluster_state",
                        "load", "devicestats", "fleet", "forecast",
                        "metrics", "trace", "explorer"}
#: access-control names for the bare handlers (identical gates to their
#: uncached handlers above; servlet endpoints check their own name).
_CACHED_ACCESS = {"metrics": "state", "trace": "state",
                  "devicestats": "state", "explorer": "openapi"}


def _cached_get(app: "CruiseControlApp", parts: list, parsed,
                headers: dict) -> tuple[int, str, bytes, dict] | None:
    """The read tier's lock-free fast path: serve a GET straight from
    the facade's render cache — one dict read, an ETag compare, striped
    counter bumps. No facade ``RLock``, no ``ProposalCache`` condition,
    no tracer-span or Meter lock is touched. Returns the full response
    tuple, or None to fall through to the ordinary dispatch path (which
    re-runs access control and produces identical bytes, minus the
    ETag)."""
    rc = getattr(app.facade, "rendercache", None)
    if rc is None or not rc.enabled:
        return None
    if parts in ([], ["kafkacruisecontrol"]):
        endpoint = "explorer"
    else:
        rest = parts[1:] if parts[:1] == ["kafkacruisecontrol"] else parts
        if len(rest) != 1:
            return None
        endpoint = rest[0].lower()
        if endpoint not in CACHED_GET_ENDPOINTS:
            return None
    params = {k.lower(): v for k, v in parse_qs(parsed.query).items()}
    if set(params) - {"json"}:
        return None
    try:
        check_access(app.security, _CACHED_ACCESS.get(endpoint, endpoint),
                     headers)
    except AuthorizationError:
        # Full path re-checks and emits the 401/403 with its challenge.
        return None
    t0 = time.monotonic()
    entry = rc.lookup_or_render(endpoint)
    if entry is None:
        return None
    wants_text = (params.get("json", ["true"])[0].strip().lower()
                  in ("false", "0", "no"))
    if wants_text:
        if entry.text is None:
            return None
        body, ctype = entry.text, "text/plain; charset=utf-8"
        # Representation-specific strong ETag: the text bytes differ
        # from the JSON bytes, so their validators must too.
        etag = entry.etag[:-1] + '-txt"'
    else:
        body, ctype = entry.body, entry.content_type
        etag = entry.etag
    meter = app._request_meters.get(("GET", endpoint))
    timer = app._success_timers.get(("GET", endpoint))
    inm = headers.get("if-none-match")
    if (inm is not None
            and etag in {t.strip() for t in inm.split(",")}):
        if meter is not None:
            meter.mark()
        nm = app._not_modified.get(endpoint)
        if nm is not None:
            nm.inc()
        if timer is not None:
            timer.update(time.monotonic() - t0)
        return 304, ctype, b"", {**app.cors, "ETag": etag}
    if meter is not None:
        meter.mark()
    if timer is not None:
        timer.update(time.monotonic() - t0)
    return 200, ctype, body, {**app.cors, "ETag": etag}


def route_request(app: "CruiseControlApp", method: str, raw_path: str,
                  headers: dict, body: bytes, peer: str
                  ) -> tuple[int, str, bytes, dict]:
    """Transport-neutral request router shared by BOTH web engines (the
    stdlib threading server and the asyncio engine — ref the reference's
    Jetty/Vert.x duality sharing one servlet layer). Returns
    ``(status, content_type, body_bytes, headers)``."""

    def json_resp(status: int, payload: dict, extra: dict | None = None):
        data = json.dumps({"version": 1, **payload}).encode()
        return status, "application/json", data, {**app.cors, **(extra or {})}

    parsed = urlparse(raw_path)
    parts = [p for p in parsed.path.split("/") if p]
    headers = {k.lower(): v for k, v in headers.items()}
    # Socket-derived peer address for source-gated providers (never
    # trusted from the wire — overwritten here).
    headers["x-cc-peer-address"] = peer

    if method == "OPTIONS":
        # CORS preflight (ref webserver.http.cors.*).
        return ((200 if app.cors else 405), "application/json", b"",
                dict(app.cors))
    # Bounded-staleness gate: a read replica whose stream lag exceeds
    # replication.max.staleness.ms refuses the cluster-state GETs with
    # 503 + the leader's identity, BEFORE the render-cache fast path —
    # a stale cached body must never short-circuit past the refusal.
    # Leaders and unreplicated deployments answer None and skip this.
    if method == "GET":
        rest0 = parts[1:] if parts[:1] == ["kafkacruisecontrol"] else parts
        if (len(rest0) == 1
                and rest0[0].lower() in STALENESS_GATED_ENDPOINTS):
            refusal_fn = getattr(app.facade, "read_refusal", None)
            refusal = refusal_fn() if refusal_fn is not None else None
            if refusal is not None:
                return json_resp(
                    503, {"errorMessage":
                          "replica is beyond the bounded-staleness "
                          "contract; redirect to the leader",
                          **refusal},
                    {"Retry-After": "1"})
    # Render-cache fast path: both engines' hot GETs (cached or
    # disabled per endpoint — see facade._register_render_endpoints)
    # short-circuit here; a None falls through to the handlers below,
    # which stay the source of truth for the response bytes.
    if method == "GET":
        fast = _cached_get(app, parts, parsed, headers)
        if fast is not None:
            return fast
    # Root: a self-contained API explorer (the stand-in for the
    # reference's swagger-ui webroot). Gated by the same security
    # provider as the endpoints it documents (VIEWER, like openapi).
    if method == "GET" and parts in ([], ["kafkacruisecontrol"]):
        try:
            check_access(app.security, "openapi", headers)
        except AuthorizationError as e:
            return json_resp(e.status, {"errorMessage": str(e)},
                             _auth_headers(e, app.security))
        from .openapi import api_explorer_html
        with app.request_timing("GET", "explorer") as outcome:
            body = api_explorer_html().encode()
            outcome["status"] = 200
        return 200, "text/html; charset=utf-8", body, {}
    # /metrics: Prometheus text exposition of the self-metric sensors
    # (the HTTP stand-in for the reference's JMX-exposed Dropwizard
    # registry). Viewer-gated like /state.
    if method == "GET" and parts in (["metrics"],
                                     ["kafkacruisecontrol", "metrics"]):
        try:
            check_access(app.security, "state", headers)
        except AuthorizationError as e:
            return json_resp(e.status, {"errorMessage": str(e)},
                             _auth_headers(e, app.security))
        with app.request_timing("GET", "metrics") as outcome:
            body = app.facade.registry.expose_text().encode()
            outcome["status"] = 200
        return (200, "text/plain; version=0.0.4; charset=utf-8", body, {})
    # /trace: Chrome trace-event JSON export of the span ring buffer
    # (loadable in Perfetto / chrome://tracing). Viewer-gated like /state.
    if method == "GET" and parts in (["trace"],
                                     ["kafkacruisecontrol", "trace"]):
        try:
            check_access(app.security, "state", headers)
        except AuthorizationError as e:
            return json_resp(e.status, {"errorMessage": str(e)},
                             _auth_headers(e, app.security))
        with app.request_timing("GET", "trace") as outcome:
            body = json.dumps(app.facade.trace_json()).encode()
            outcome["status"] = 200
        return 200, "application/json", body, {}
    # /devicestats: the device-runtime ledger (compile lifecycle,
    # host<->device transfers, memory, padding waste). Viewer-gated like
    # /state; json=false renders the fixed-width table (this is a bare
    # handler, so the flag is read from the raw query — no typed layer to
    # resolve it).
    if method == "GET" and parts in (["devicestats"],
                                     ["kafkacruisecontrol", "devicestats"]):
        try:
            check_access(app.security, "state", headers)
        except AuthorizationError as e:
            return json_resp(e.status, {"errorMessage": str(e)},
                             _auth_headers(e, app.security))
        with app.request_timing("GET", "devicestats") as outcome:
            payload = app.facade.device_stats_json()
            outcome["status"] = 200
        raw_json = parse_qs(parsed.query).get("json", ["true"])[0]
        if raw_json.strip().lower() in ("false", "0", "no"):
            from .plaintext import render
            return (200, "text/plain; charset=utf-8",
                    (render("devicestats", payload) + "\n").encode(),
                    dict(app.cors))
        return json_resp(200, payload)
    # /replication_stream: the leader's delta push channel
    # (core/replication.py) — long-poll GET with ?cursor=<next-seq> and
    # ?wait_ms=<hold-open budget>. The payload is the restricted-pickle
    # frame batch (decode_stream_payload), a replica-to-leader transport
    # surface rather than a public JSON API; followers treat any non-200
    # as a stream cut and re-poll. Viewer-gated like /state.
    if method == "GET" and parts in (["replication_stream"],
                                     ["kafkacruisecontrol",
                                      "replication_stream"]):
        try:
            check_access(app.security, "state", headers)
        except AuthorizationError as e:
            return json_resp(e.status, {"errorMessage": str(e)},
                             _auth_headers(e, app.security))
        session = getattr(app.facade, "replication", None)
        channel = getattr(session, "channel", None)
        # A DualChannel node serves its LOCAL ring (never proxies its
        # peer); a plain ReplicationChannel serves itself.
        channel = getattr(channel, "ring", channel)
        if channel is None or not hasattr(channel, "publish"):
            # Not wired, or this node is itself a follower over HTTP
            # (its "channel" is a client, not the ring buffer).
            return json_resp(404, {"errorMessage":
                                   "replication streaming is not "
                                   "enabled on this node"})
        q = parse_qs(parsed.query)
        try:
            cursor = int(q.get("cursor", ["0"])[0])
            wait_ms = min(int(q.get("wait_ms", ["0"])[0]), 30_000)
        except ValueError:
            return json_resp(400, {"errorMessage":
                                   "cursor and wait_ms must be integers"})
        from ..core.replication import encode_stream_payload
        with app.request_timing("GET", "replication_stream") as outcome:
            res = channel.poll(cursor, session._now_ms(), wait_ms=wait_ms)
            if res is None:
                # A chaos cut (or a not-yet-serving channel): tell the
                # follower to back off and re-poll.
                outcome["status"] = 503
                return json_resp(503, {"errorMessage":
                                       "replication stream unavailable"},
                                 {"Retry-After": "1"})
            # Delta compression is negotiated: only a poller that
            # advertised compress=1 may receive a compressed payload
            # (replication.compress.min.bytes sets the ring's
            # threshold; 0 disables server-side).
            wants_compressed = q.get("compress", ["0"])[0] == "1"
            data = encode_stream_payload(
                res,
                compress_min_bytes=(
                    getattr(channel, "compress_min_bytes", 0)
                    if wants_compressed else 0),
                stats=channel)
            outcome["status"] = 200
        return 200, "application/octet-stream", data, dict(app.cors)
    # /fleet and /fleet/rebalance: REST-shaped aliases for the fleet
    # endpoints (also reachable at their flat servlet names). Rewritten
    # before the flat-path check so they dispatch through the ordinary
    # typed/secured handler path.
    rest = parts[1:] if parts[:1] == ["kafkacruisecontrol"] else parts
    if rest == ["fleet", "rebalance"]:
        parts = ["kafkacruisecontrol", "fleet_rebalance"]
    elif rest == ["fleet"]:
        parts = ["kafkacruisecontrol", "fleet"]
    elif rest == ["forecast"]:
        # GET /forecast reads the cached trajectory report (viewer);
        # POST /forecast forces a refit + fresh sweep (user) — one REST
        # path, two servlet endpoints, split by method here.
        parts = ["kafkacruisecontrol",
                 "forecast_refresh" if method == "POST" else "forecast"]
    if len(parts) != 2 or parts[0] != "kafkacruisecontrol":
        return json_resp(404, {"errorMessage": f"bad path {parsed.path}"})
    endpoint = parts[1].lower()
    params = parse_qs(parsed.query)
    if method == "POST" and body:
        try:
            decoded = body.decode()
        except UnicodeDecodeError:
            return json_resp(400, {"errorMessage":
                                   "request body is not valid UTF-8"})
        if "application/json" in headers.get("content-type", ""):
            # JSON request bodies: top-level keys become parameters
            # (scalars verbatim, nested values re-serialized — exactly
            # what the typed layer's JSON-string parameters, e.g.
            # simulate's ``scenarios``, expect).
            try:
                obj = json.loads(decoded)
            except json.JSONDecodeError as e:
                return json_resp(400, {"errorMessage":
                                       f"request body is not valid "
                                       f"JSON: {e}"})
            if not isinstance(obj, dict):
                return json_resp(400, {"errorMessage":
                                       "JSON request body must be an "
                                       "object"})
            for k, v in obj.items():
                params.setdefault(
                    str(k), [v if isinstance(v, str) else json.dumps(v)])
        else:
            for k, v in parse_qs(decoded).items():
                params.setdefault(k, v)
    try:
        status, payload, extra = app.handle(method, endpoint, params,
                                            headers)
    except AuthorizationError as e:
        status, payload = e.status, {"errorMessage": str(e)}
        extra = _auth_headers(e, app.security)
    except (KeyError, ValueError) as e:
        status, payload, extra = 400, {"errorMessage": str(e)}, {}
    except TooManyUserTasksError as e:
        # Capacity pushback is the client's signal to back off, not a
        # server fault (deviation from the reference, which 500s here —
        # see TooManyUserTasksError). Retry-After makes the shed an
        # instruction: the queue drains, the retry succeeds.
        status, payload = 429, {"errorMessage": str(e)}
        extra = {"Retry-After": str(e.retry_after_s)}
    except AdmissionLimitError as e:
        # Per-principal write throttle (api/admission.py): the bucket's
        # own refill time rides the Retry-After header.
        status, payload = 429, {"errorMessage": str(e),
                                "principal": e.principal}
        extra = {"Retry-After": str(e.retry_after_s)}
        journal = getattr(app.facade, "journal", None)
        if journal is not None:
            journal.record("admission", "shed-429", severity="warn",
                           detail={"principal": e.principal,
                                   "endpoint": endpoint,
                                   "retryAfterS": e.retry_after_s})
    except NotLeaderError as e:
        # Sync execution path on a standby replica (async paths map this
        # inside _handle_async, keeping their User-Task-ID header).
        status, payload = 503, {"errorMessage": str(e),
                                "leaderId": e.leader_id}
        extra = {"Retry-After": "1"}
    except Exception as e:
        status, payload, extra = 500, {"errorMessage": str(e)}, {}
    # json=false: fixed-width text tables (ref the response classes'
    # writeOutputStream plaintext path). The flag is resolved by the
    # TYPED parameter layer inside handle() (case-insensitive, purgatory
    # merge included) and signalled via a private marker header. Only
    # successful bodies — errors and async-progress replies stay JSON so
    # clients parse them uniformly.
    wants_text = bool(extra.pop(_PLAINTEXT_MARKER, None)) if extra else False
    if wants_text and status == 200:
        from .plaintext import render
        return (200, "text/plain; charset=utf-8",
                (render(endpoint, payload) + "\n").encode(),
                {**app.cors, **(extra or {})})
    return json_resp(status, payload, extra)


def _make_handler(app: CruiseControlApp):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _serve(self, method: str):
            body = b""
            if method == "POST":
                length = int(self.headers.get("Content-Length", 0))
                if length:
                    body = self.rfile.read(length)
            status, ctype, data, hdrs = route_request(
                app, method, self.path, dict(self.headers), body,
                self.client_address[0])
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in hdrs.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)
            if app.accesslog:
                _ACCESS_LOG.info("%s %s %s -> %d",
                                 self.client_address[0], method,
                                 self.path, status)

        def do_GET(self):
            self._serve("GET")

        def do_POST(self):
            self._serve("POST")

        def do_OPTIONS(self):
            self._serve("OPTIONS")

    return Handler
