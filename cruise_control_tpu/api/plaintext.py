"""Plaintext response rendering (``json=false``).

The reference's servlet renders fixed-width text tables when ``json``
is absent/false (each response class's ``writeOutputStream`` — e.g.
``servlet/response/BrokerStats.java``, the original curl-friendly UX);
this module is that renderer for the rebuild. JSON stays the default
here (``json`` parameter defaults true — a documented deviation; every
modern client asks for JSON), so plaintext is opt-in via ``json=false``.

One entry point: :func:`render` maps the endpoint's JSON payload to a
text document; endpoints without a bespoke table fall back to pretty-
printed JSON, so ``json=false`` never errors.
"""

from __future__ import annotations

import json
from typing import Any


def _table(headers: list[str], rows: list[list[Any]]) -> str:
    """Fixed-width columns, left-aligned text / right-aligned numbers."""
    cells = [[str(c) for c in row] for row in rows]
    widths = [max(len(h), *(len(r[i]) for r in cells)) if cells else len(h)
              for i, h in enumerate(headers)]

    def fmt(row, src=None):
        out = []
        for i, c in enumerate(row):
            num = src is not None and isinstance(src[i], (int, float)) \
                and not isinstance(src[i], bool)
            out.append(c.rjust(widths[i]) if num else c.ljust(widths[i]))
        return "  ".join(out).rstrip()

    lines = [fmt(headers)]
    for raw, row in zip(rows, cells):
        lines.append(fmt(row, raw))
    return "\n".join(lines)


def _num(v, nd=3):
    return round(v, nd) if isinstance(v, float) else v


def _render_load(payload: dict) -> str:
    rows = [[b.get("Broker"), b.get("BrokerState", ""),
             _num(b.get("CpuPct", b.get("CPU", 0.0))),
             _num(b.get("NwInRate", 0.0)), _num(b.get("NwOutRate", 0.0)),
             _num(b.get("DiskMB", 0.0)), b.get("Replicas", 0),
             b.get("Leaders", 0)]
            for b in payload.get("brokers", [])]
    text = _table(["BROKER", "STATE", "CPU", "NW_IN", "NW_OUT", "DISK",
                   "REPLICAS", "LEADERS"], rows)
    summary = payload.get("summary")
    if summary:
        text += "\n\n" + "\n".join(f"{k}: {_num(v)}"
                                   for k, v in sorted(summary.items()))
    return text


def _render_partition_load(payload: dict) -> str:
    recs = payload.get("records", [])
    if not recs:
        return "(no records)"
    keys = list(recs[0].keys())
    return _table([k.upper() for k in keys],
                  [[_num(r.get(k, "")) for k in keys] for r in recs])


def _render_proposals(payload: dict) -> str:
    parts = []
    summary = payload.get("summary")
    if summary:
        parts.append("\n".join(f"{k}: {_num(v)}"
                               for k, v in sorted(summary.items())))
    goals = payload.get("goalSummary", [])
    if goals:
        parts.append(_table(
            ["GOAL", "STATUS", "BEFORE", "AFTER"],
            [[g.get("goal"), g.get("status", ""),
              _num(g.get("violationBefore", g.get("before", ""))),
              _num(g.get("violationAfter", g.get("after", "")))]
             for g in goals]))
    audit = payload.get("hardGoalAudit", [])
    if audit:
        parts.append("Hard-goal audit (registered hard goals not in the "
                     "chain):\n" + _table(
                         ["GOAL", "STATUS", "BEFORE", "AFTER"],
                         [[g.get("goal"), g.get("status", ""),
                           _num(g.get("violationBefore", "")),
                           _num(g.get("violationAfter", ""))]
                          for g in audit]))
    return "\n\n".join(parts) or _pretty(payload)


def _render_state(payload: dict) -> str:
    parts = []
    for section, body in payload.items():
        if section == "version" or not isinstance(body, dict):
            continue
        lines = [f"[{section}]"]
        for k, v in body.items():
            if isinstance(v, (dict, list)):
                v = json.dumps(v, sort_keys=True)
            lines.append(f"  {k}: {v}")
        parts.append("\n".join(lines))
    return "\n\n".join(parts) or _pretty(payload)


def _render_kafka_cluster_state(payload: dict) -> str:
    return _render_state(payload)


def _render_user_tasks(payload: dict) -> str:
    rows = [[t.get("UserTaskId"), t.get("RequestURL", t.get("endpoint", "")),
             t.get("Status"), t.get("StartMs", "")]
            for t in payload.get("userTasks", [])]
    return _table(["USER TASK ID", "REQUEST", "STATUS", "START"], rows)


def _pretty(payload: dict) -> str:
    return json.dumps(payload, indent=2, sort_keys=True)


def _render_simulate(payload: dict) -> str:
    rows = [[s.get("name"), _num(float(s.get("risk", 0.0))),
             _num(float(s.get("capacityPressure", 0.0))),
             s.get("unavailablePartitions", 0),
             s.get("offlineReplicas", 0),
             ",".join(s.get("violatedHardGoals", [])) or "-",
             ",".join(g for g in s.get("violatedGoals", [])
                      if g not in s.get("violatedHardGoals", [])) or "-"]
            for s in payload.get("scenarios", [])]
    text = _table(["SCENARIO", "RISK", "PRESSURE", "UNAVAIL", "OFFLINE",
                   "HARD_VIOLATIONS", "SOFT_VIOLATIONS"], rows)
    worst = payload.get("riskiest")
    if worst is not None:
        text += (f"\n\nriskiest: {worst} (maxRisk "
                 f"{_num(float(payload.get('maxRisk', 0.0)))})")
    return text


def _render_devicestats(payload: dict) -> str:
    compile_ = payload.get("compile", {})
    rows = [[name, st.get("compiles", 0), st.get("aotCompiles", 0),
             st.get("dispatches", 0), st.get("shapeBuckets", 0)]
            for name, st in sorted(compile_.get("byProgram", {}).items())]
    text = _table(["PROGRAM", "COMPILES", "AOT", "DISPATCHES", "BUCKETS"],
                  rows)
    text += (f"\n\ncompile events: {compile_.get('totalEvents', 0)} "
             f"(+{compile_.get('aotEvents', 0)} aot), recompiles: "
             f"{compile_.get('recompileEvents', 0)}")
    recent = [e for e in compile_.get("recentEvents", [])
              if e.get("trigger") == "signature-change"]
    if recent:
        text += "\nrecent recompiles:\n" + _table(
            ["PROGRAM", "BUCKET", "CACHE", "MS"],
            [[e.get("program"), e.get("shapeBucket"), e.get("cache"),
              _num(float(e.get("durationMs", 0.0)))] for e in recent])
    transfers = payload.get("transfers", {})
    text += (f"\nh2d bytes: {transfers.get('h2dBytesTotal', 0)}  "
             f"d2h bytes: {transfers.get('d2hBytesTotal', 0)}")
    cycle = transfers.get("lastCycle")
    if cycle:
        text += (f"\nlast cycle [{cycle.get('label')}]: "
                 f"h2d {cycle.get('h2dBytes', 0)}  "
                 f"d2h {cycle.get('d2hBytes', 0)}  "
                 f"compiles {cycle.get('compileEvents', 0)}  "
                 f"{_num(float(cycle.get('durationMs', 0.0)))} ms")
    memory = payload.get("memory", {})
    text += (f"\nmemory [{memory.get('source')}]: live "
             f"{memory.get('liveBytes')} (peak "
             f"{memory.get('peakLiveBytes')}), allocator "
             f"{memory.get('allocatorBytesInUse')}")
    padding = payload.get("padding")
    if padding:
        text += (f"\npadding waste: partitions "
                 f"{padding.get('partitionWastePct')}% "
                 f"({padding.get('partitions')}/"
                 f"{padding.get('partitionsPadded')}), brokers "
                 f"{padding.get('brokerWastePct')}%, replica slots "
                 f"{padding.get('replicaSlotWastePct', '-')}%")
    budget = payload.get("budget")
    if budget and (budget.get("paddingWasteBudgetPct") is not None
                   or budget.get("hbmBudgetBytes") is not None):
        flags = [name for name, key in
                 (("PADDING-OVER-BUDGET", "paddingOverBudget"),
                  ("HBM-OVER-BUDGET", "hbmOverBudget"))
                 if budget.get(key)]
        def _or_dash(key):
            v = budget.get(key)
            return "-" if v is None else v
        text += (f"\nbudget: padding {_or_dash('paddingWastePct')}% / "
                 f"{_or_dash('paddingWasteBudgetPct')}%, peak "
                 f"{_or_dash('peakBytes')} / "
                 f"{_or_dash('hbmBudgetBytes')} bytes"
                 + (f"  ** {' '.join(flags)} **" if flags else "  ok"))
    resident = payload.get("resident")
    if resident:
        text += (f"\nresident state: epoch {resident.get('epoch')} "
                 f"[last {resident.get('lastUpdate')}], "
                 f"{resident.get('deltaCycles')} delta / "
                 f"{resident.get('noopCycles')} noop / "
                 f"{resident.get('fullRebuilds')} full cycles, last delta "
                 f"{resident.get('lastDeltaRows')} rows "
                 f"({resident.get('lastDeltaBytes')} bytes)")
    fresh = payload.get("proposalFreshness")
    if fresh:
        text += (f"\nproposal freshness: age {fresh.get('ageMs')} ms, "
                 f"lag {fresh.get('lagMs')} ms (target "
                 f"{fresh.get('targetMs')} ms), "
                 f"{fresh.get('computations')} computations, "
                 f"{fresh.get('breaches')} SLO breaches")
    fleet = payload.get("fleet")
    if fleet:
        bucket = fleet.get("bucket") or {}
        text += (f"\nfleet: {fleet.get('clusterCount')} clusters, "
                 f"{fleet.get('ticks')} ticks, bucket "
                 f"{bucket.get('clustersPadded', '-')}x"
                 f"{bucket.get('brokersPadded', '-')}x"
                 f"{bucket.get('partitionsPadded', '-')}, last dispatch "
                 f"{fleet.get('lastDispatchMs')} ms")
    forecast = payload.get("forecast")
    if forecast and forecast.get("fittedTopics") is not None:
        ttb = forecast.get("timeToBreachMs")
        text += (f"\nforecast: {forecast.get('fittedTopics')} topics "
                 f"fitted ({forecast.get('fits')} fits / "
                 f"{forecast.get('sweeps')} sweeps), worst backtest MAPE "
                 f"{forecast.get('worstBacktestMape')}, time to breach "
                 + (f"{ttb} ms" if ttb is not None else "none projected"))
    pop = payload.get("population")
    if pop:
        text += (f"\npopulation: K={pop.get('size')} "
                 f"[{pop.get('objective')}], winner "
                 f"{pop.get('winner')}"
                 f"{' (anchor)' if pop.get('winnerIsAnchor') else ''}, "
                 f"pareto front {pop.get('paretoFrontSize')}, moves "
                 f"{pop.get('movesPerMember')}")
    tuning = payload.get("tuning")
    if tuning and tuning.get("buckets"):
        rows = [[bkt, json.dumps(entry.get("fields", {}),
                                 sort_keys=True),
                 len(entry.get("history", []))]
                for bkt, entry in sorted(tuning["buckets"].items())]
        text += "\ntuned search configs:\n" + _table(
            ["BUCKET", "FIELDS", "TRIALS"], rows)
    snap = payload.get("snapshot")
    if snap:
        fallbacks = snap.get("restoreFallbacks") or {}
        refused = ", ".join(f"{k}={v}" for k, v in sorted(fallbacks.items())
                            if v) or "none"
        text += (f"\nsnapshot: {snap.get('writes')} writes "
                 f"({snap.get('writeFailures')} failed), "
                 f"{snap.get('restores')} restores, refused: {refused}, "
                 f"last write {snap.get('lastWriteMs')} ms "
                 f"({snap.get('bytes')} bytes)")
    ha = payload.get("ha")
    if ha and ha.get("enabled"):
        text += (f"\nha: {ha.get('role')} [{ha.get('identity')}], leader "
                 f"{ha.get('leaderId')}, fencing epoch "
                 f"{ha.get('fencingEpoch')}, {ha.get('takeovers')} "
                 f"takeovers")
    return text


def _render_fleet(payload: dict) -> str:
    if not payload.get("enabled"):
        return "fleet control plane disabled (fleet.enabled=false)"
    rows = []
    for c in payload.get("clusters", []):
        fresh = c.get("freshness") or {}
        risk = c.get("risk") or {}
        rows.append([
            c.get("clusterId"),
            "ready" if c.get("ready") else "NOT-READY",
            c.get("generation"),
            c.get("balanceScore", "-"),
            c.get("numProposals", "-"),
            "yes" if fresh.get("valid") else "no",
            fresh.get("ageMs", "-"),
            risk.get("maxRisk", "-"),
            risk.get("riskiestBroker", "-")])
    text = _table(["CLUSTER", "STATE", "GEN", "BALANCE", "PROPOSALS",
                   "FRESH", "AGE-MS", "N1-RISK", "RISKIEST"], rows)
    bucket = payload.get("bucket") or {}
    text += (f"\n\n{payload.get('numClusters')} clusters, "
             f"{payload.get('ticks')} ticks, bucket "
             f"{bucket.get('clustersPadded', '-')}x"
             f"{bucket.get('brokersPadded', '-')}x"
             f"{bucket.get('partitionsPadded', '-')}, last dispatch "
             f"{payload.get('lastDispatchMs')} ms")
    return text


def _render_forecast(payload: dict) -> str:
    report = payload.get("report") or {}
    rows = []
    baseline = report.get("baseline")
    if baseline:
        rows.append(["now", "-",
                     _num(float(baseline.get("risk", 0.0))),
                     _num(float(baseline.get("capacityPressure", 0.0))),
                     _num(float(baseline.get("maxFactor", 1.0))),
                     ",".join(baseline.get("violatedHardGoals", []))
                     or "-"])
    for o in report.get("horizons", []):
        rows.append([f"+{o.get('horizonMs')}ms",
                     f"p{int(round(float(o.get('quantile', 0.5)) * 100))}",
                     _num(float(o.get("risk", 0.0))),
                     _num(float(o.get("capacityPressure", 0.0))),
                     _num(float(o.get("maxFactor", 1.0))),
                     ",".join(o.get("violatedHardGoals", [])) or "-"])
    text = _table(["HORIZON", "QUANTILE", "RISK", "PRESSURE", "MAXFACTOR",
                   "HARD_VIOLATIONS"], rows)
    ttb = payload.get("timeToBreachMs")
    text += (f"\n\ntopics fitted: {payload.get('fittedTopics')}, worst "
             f"backtest MAPE: {payload.get('worstBacktestMape')}, time to "
             f"breach: " + (f"{ttb} ms" if ttb is not None else "none "
                            "projected"))
    return text


def _render_history(payload: dict) -> str:
    rows = []
    for e in payload.get("events", []):
        detail = e.get("detail")
        rows.append([e.get("seq"), e.get("tsMs"),
                     e.get("category", ""), e.get("action", ""),
                     e.get("severity", ""),
                     e.get("epoch") if e.get("epoch") is not None else "-",
                     e.get("cause") if e.get("cause") is not None else "-",
                     e.get("node") or "-",
                     json.dumps(detail, sort_keys=True) if detail else "-"])
    text = _table(["SEQ", "TS_MS", "CATEGORY", "ACTION", "SEV", "EPOCH",
                   "CAUSE", "NODE", "DETAIL"], rows)
    text += (f"\n\nrole: {payload.get('role')}, node: "
             f"{payload.get('node') or '-'}, lastSeq: "
             f"{payload.get('lastSeq')}, shown: {payload.get('numEvents')},"
             f" dropped: {payload.get('dropped')}")
    return text


_RENDERERS = {
    "history": _render_history,
    "load": _render_load,
    "forecast": _render_forecast,
    "forecast_refresh": _render_forecast,
    "simulate": _render_simulate,
    "devicestats": _render_devicestats,
    "fleet": _render_fleet,
    "fleet_rebalance": _render_fleet,
    "partition_load": _render_partition_load,
    "proposals": _render_proposals,
    "rebalance": _render_proposals,
    "add_broker": _render_proposals,
    "remove_broker": _render_proposals,
    "state": _render_state,
    "kafka_cluster_state": _render_kafka_cluster_state,
    "user_tasks": _render_user_tasks,
}


def render(endpoint: str, payload: dict) -> str:
    """Plaintext document for a 200 payload; pretty JSON when the
    endpoint has no bespoke table (so ``json=false`` always works)."""
    renderer = _RENDERERS.get(endpoint, _pretty)
    try:
        return renderer(payload)
    except Exception:
        # A malformed/partial payload must not turn a good response into
        # a 500 — fall back to the lossless form.
        return _pretty(payload)
