"""Proposal precompute + generation-keyed cache + freshness SLO loop.

Rebuild of the reference's background "train loop"
(``GoalOptimizer.run()`` ``GoalOptimizer.java:152-203``): a cached
optimization result serves ``GET /proposals`` and goal-violation-free
rebalances instantly; the cache is valid while the monitor's model
generation is unchanged (``:232-239``); readers either take the cache, or
block until the in-flight computation lands (``:304-352``), or force a
fresh computation (``ignore_proposal_cache``).

On top of generation keying, the cache tracks a **proposal-freshness
SLO** (``proposals.freshness.target.ms``): *lag* is how long the current
monitor generation has gone unanswered by the cache (0 while the cache is
generation-valid), *age* is how old the cached result itself is. The
background refresher ticks fast enough to keep lag under the target
(``min(interval, target/4)``) and recomputes the moment the generation
moves, so ``GET /proposals`` under concurrent traffic stays a
generation-checked cache read with bounded staleness; a recompute that
lands later than the target after the generation moved marks the
``ProposalCache.freshness-slo-breaches`` meter (and logs) — the signal
operators alert on. ``freshness-age-ms`` / ``freshness-lag-ms`` gauges
join the facade's scrape view.
"""

from __future__ import annotations

import logging
import threading
import time as _time

from ..analyzer import OptimizationOptions

LOG = logging.getLogger(__name__)


class CacheEntry:
    """Immutable published cache entry — the lock-free read surface.

    Writers build a fresh instance under the Condition and publish it
    with ONE attribute store (atomic under the GIL); readers grab the
    reference with one attribute load and get a consistent
    (result, generation, stamp, seq) tuple without ever touching the
    Condition. ``seq`` increments per publish, so render caches keyed on
    it notice a same-generation refill (a fleet tick re-store)."""

    __slots__ = ("result", "generation", "cached_at_ms", "seq")

    def __init__(self, result, generation, cached_at_ms, seq) -> None:
        object.__setattr__(self, "result", result)
        object.__setattr__(self, "generation", generation)
        object.__setattr__(self, "cached_at_ms", cached_at_ms)
        object.__setattr__(self, "seq", seq)

    def __setattr__(self, name, value):
        raise AttributeError("CacheEntry is immutable")


class ProposalCache:
    def __init__(self, monitor, optimizer, *,
                 options: OptimizationOptions | None = None,
                 registry=None, now_ms=None,
                 cache_id: str | None = None) -> None:
        from ..core.sensors import MetricRegistry
        self.monitor = monitor
        self.optimizer = optimizer
        #: cluster-scoped cache identity (fleet members): carried into
        #: the freshness gauge/meter names so two members' series never
        #: collide on one scrape, and enforced by :meth:`store` so a
        #: result computed for one cluster can never serve another —
        #: generation numbers are per-monitor counters, so two clusters
        #: can easily sit at the SAME generation int and generation
        #: keying alone cannot tell them apart. None = the single-cluster
        #: default (names unchanged).
        self.cache_id = cache_id
        # The cache is a dry-run measurement: a hard goal that cannot be
        # satisfied is a *cacheable finding* (served with its provision
        # verdict), not an error to re-burn compute on every refresh tick.
        # Readers that execute re-apply strict semantics (facade.rebalance).
        self.options = options or OptimizationOptions(
            skip_hard_goal_check=True)
        # Writer-side Condition: _compute/store/restore/invalidate and
        # BLOCKING readers (get() waiting on an in-flight compute) take
        # it; the hot read path never does — it reads ``_entry``.
        self._lock = threading.Condition()
        self._cached = None            # OptimizerResult
        self._cached_generation: int | None = None
        #: published immutable CacheEntry | None — ONE attribute read
        #: serves the lock-free fast path (peek/valid/get-when-warm).
        self._entry: CacheEntry | None = None
        self._entry_seq = 0
        self._computing = False
        self._refresher: threading.Thread | None = None
        self._stop = threading.Event()
        #: callbacks invoked (exception-safe) at the end of every
        #: refresh tick — the facade's render cache re-publishes its
        #: response snapshots here, off the serving hot path.
        self.on_tick: list = []
        self.num_computations = 0
        # ---- freshness SLO bookkeeping -------------------------------
        self._now_ms_fn = now_ms or (lambda: int(_time.time() * 1000))
        #: 0 disables the SLO (plain interval refresher, no breach
        #: accounting); serve.py wires proposals.freshness.target.ms.
        self.freshness_target_ms = 0
        self._cached_at_ms: int | None = None
        self._gen_seen: int | None = None
        self._gen_changed_at_ms: int | None = None
        #: high-water generation a breach was already marked for — one
        #: breach per unanswered generation, whether detected by a
        #: late-landing recompute or by the tick watching lag grow past
        #: the target (monotonic so a slow compute for an OLD generation
        #: landing after a newer one was marked cannot double-count)
        self._breach_marked_gen: int | None = None
        self.registry = registry or MetricRegistry()
        # Cluster-scoped sensor group: fleet members' freshness series
        # render as ProposalCache.<cache_id>.freshness-* so one merged
        # scrape over many members stays unambiguous.
        group = (f"ProposalCache.{cache_id}" if cache_id
                 else "ProposalCache")
        name = MetricRegistry.name
        self._breaches = self.registry.meter(
            name(group, "freshness-slo-breaches"))
        self.registry.gauge(name(group, "freshness-age-ms"),
                            self.freshness_age_ms)
        self.registry.gauge(name(group, "freshness-lag-ms"),
                            self.freshness_lag_ms)
        self.registry.gauge(name(group, "freshness-target-ms"),
                            lambda: self.freshness_target_ms or None)

    # ------------------------------------------------------------- reads
    def peek(self):
        """The cached OptimizerResult without blocking, recompute, or any
        lock (may be stale or None) — for gauges that must never trigger
        work and for the serving tier's hot path."""
        e = self._entry
        return e.result if e is not None else None

    def fast_entry(self) -> CacheEntry | None:
        """Lock-free generation-valid read: the published immutable entry
        when it answers the monitor's CURRENT generation, else None. The
        render cache serves ``GET /proposals`` off this — one attribute
        load plus one int compare, no Condition, no facade lock."""
        e = self._entry
        if e is not None and e.generation == self.monitor.generation:
            return e
        return None

    def valid(self) -> bool:
        """ref validCachedProposal GoalOptimizer.java:232-239 (lock-free:
        reads the published entry)."""
        return self.fast_entry() is not None

    def latest_entry(self) -> CacheEntry | None:
        """The newest published entry regardless of generation validity
        (lock-free; None when empty). The replication follower-serving
        path reads this: a stream-fed replica's generation advances with
        the leader's frames while its proposal entry only moves when the
        leader re-exports one, so generation-strict ``fast_entry`` would
        refuse an entry that is merely one export behind — the replica
        serves the newest replicated entry and lets the bounded-staleness
        contract (core/replication.py read_refusal) police its age."""
        return self._entry

    def _publish_locked(self) -> None:
        """Mirror the Condition-side fields into a fresh immutable entry
        (caller holds the Condition). One attribute store publishes."""
        if self._cached is None:
            self._entry = None
            return
        self._entry_seq += 1
        self._entry = CacheEntry(self._cached, self._cached_generation,
                                 self._cached_at_ms, self._entry_seq)

    def observe_generation(self, now_ms: int | None = None) -> None:
        """Stamp when the monitor's generation last moved — the anchor
        freshness lag is measured from. Called on every refresher tick
        and on freshness reads, so observation granularity is the tick."""
        gen = self.monitor.generation
        now = now_ms if now_ms is not None else self._now_ms_fn()
        with self._lock:
            if gen != self._gen_seen:
                self._gen_seen = gen
                self._gen_changed_at_ms = now

    def freshness_age_ms(self, now_ms: int | None = None) -> int | None:
        """Age of the cached result (None when empty) — how old the
        proposals a cache read would serve actually are."""
        now = now_ms if now_ms is not None else self._now_ms_fn()
        with self._lock:
            if self._cached is None or self._cached_at_ms is None:
                return None
            return max(int(now - self._cached_at_ms), 0)

    def freshness_lag_ms(self, now_ms: int | None = None) -> int | None:
        """How long the CURRENT generation has gone unanswered: 0 while
        the cache is generation-valid, else ms since the generation was
        observed to move (None before anything was ever observed). This
        is the number the SLO bounds."""
        now = now_ms if now_ms is not None else self._now_ms_fn()
        self.observe_generation(now)
        with self._lock:
            if (self._cached is not None
                    and self._cached_generation == self.monitor.generation):
                return 0
            if self._gen_changed_at_ms is None:
                return None
            return max(int(now - self._gen_changed_at_ms), 0)

    def freshness_json(self, now_ms: int | None = None) -> dict:
        """The ``proposalFreshness`` section of ``/devicestats``."""
        now = now_ms if now_ms is not None else self._now_ms_fn()
        return {"valid": self.valid(),
                "cacheId": self.cache_id,
                "ageMs": self.freshness_age_ms(now),
                "lagMs": self.freshness_lag_ms(now),
                "targetMs": self.freshness_target_ms or None,
                "computations": self.num_computations,
                "breaches": self._breaches.count}

    def get(self, now_ms: int, timeout_s: float = 60.0):
        """Serve the cached result, computing (or waiting on the in-flight
        computation) when stale (ref blocking read :304-352). A waiter whose
        in-flight computation fails takes over the computation itself (so
        the original error surfaces rather than a bogus timeout)."""
        # Warm fast path: one published-entry read. No Condition — N
        # concurrent readers of a generation-valid cache never serialize.
        e = self.fast_entry()
        if e is not None:
            return e.result
        deadline = _time.monotonic() + timeout_s
        while True:
            with self._lock:
                if self.valid():
                    return self._cached
                if self._computing:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0 or not self._lock.wait_for(
                            lambda: self.valid() or not self._computing,
                            timeout=remaining):
                        raise TimeoutError(
                            "proposal computation did not finish")
                    continue   # re-check: either valid now, or take over
                self._computing = True
            try:
                return self._compute(now_ms)
            finally:
                with self._lock:
                    self._computing = False
                    self._lock.notify_all()

    def _compute(self, now_ms: int):
        self.observe_generation(now_ms)
        gen = self.monitor.generation
        # Anchor breach lag to the generation THIS compute answers: the
        # generation (and its change stamp) may move again mid-compute.
        with self._lock:
            gen_changed0 = self._gen_changed_at_ms
        model_result = self.monitor.cluster_model(now_ms)
        # Belt-and-braces: the monitor only emits live results, but a
        # plugged monitor (or future refactor) handing a what-if scenario
        # transform here would poison every default-chain read until the
        # next generation bump — refuse outright.
        label = getattr(model_result, "scenario_label", None)
        if label:
            raise ValueError(
                f"proposal cache refuses scenario-modified model "
                f"{label!r}: only live monitor models may seed the cache")
        result = self.optimizer.optimize(model_result.model,
                                         model_result.metadata, self.options)
        if model_result.stale:
            # Carried to the facade's execution gate: cached proposals
            # computed from a stale-served model must not execute.
            from dataclasses import replace
            result = replace(result, stale_model=True)
        done_ms = self._now_ms_fn()
        with self._lock:
            had_cache = self._cached is not None
            self._cached = result
            self._cached_generation = gen
            self._cached_at_ms = done_ms
            self.num_computations += 1
            self._publish_locked()
            self._lock.notify_all()
            catch_up = (done_ms - gen_changed0
                        if gen_changed0 is not None else None)
        # Breach accounting: a previously-warm cache that took longer
        # than the target to catch the moved generation back up. The
        # first-ever fill (startup warm-in) is exempt — that cost is what
        # the startup pre-warm exists to hide. One breach per generation
        # (the tick path below may have marked this one already).
        if (self.freshness_target_ms and had_cache
                and catch_up is not None
                and catch_up > self.freshness_target_ms):
            self._mark_breach(gen, catch_up)
        return result

    def _mark_breach(self, gen: int, lag_ms: int) -> None:
        with self._lock:
            if (self._breach_marked_gen is not None
                    and gen <= self._breach_marked_gen):
                return
            self._breach_marked_gen = gen
        self._breaches.mark()
        LOG.warning(
            "proposal freshness SLO breach: generation %s unanswered "
            "%d ms after it appeared (target %d ms)", gen, lag_ms,
            self.freshness_target_ms)

    def store(self, result, *, generation: int,
              scenario_label: str | None = None,
              cache_id: str | None = None) -> bool:
        """Offer an externally computed OptimizerResult to the cache.

        The ONLY write path besides :meth:`_compute`, with three guards:

        - **scenario rejection** (hard error): results computed from a
          what-if scenario transform carry the scenario label and are
          refused outright — ``/simulate`` and the resilience detector's
          proactive sweeps can never poison the live-cluster cache.
        - **cluster scoping** (hard error): when this cache carries a
          ``cache_id`` (a fleet member), a result offered under a
          DIFFERENT id is a wiring bug — generation ints are
          per-monitor counters, so two clusters at the same generation
          would otherwise cross-serve each other's proposals silently.
          A result offered with no id at all is likewise refused on an
          id-scoped cache (the fleet tick always stamps its writes).
        - **generation keying** (soft reject): a result computed against
          any generation other than the monitor's CURRENT one is dropped
          (returns False) — by the time it arrives it describes a
          cluster that no longer exists.
        """
        if scenario_label:
            raise ValueError(
                f"proposal cache refuses scenario-modified result "
                f"{scenario_label!r}: only live-cluster optimizations "
                "may be cached")
        if self.cache_id is not None and cache_id != self.cache_id:
            raise ValueError(
                f"proposal cache {self.cache_id!r} refuses result "
                f"offered for cluster {cache_id!r}: fleet members must "
                "never cross-serve proposals")
        with self._lock:
            if generation != self.monitor.generation:
                return False
            self._cached = result
            self._cached_generation = generation
            self._cached_at_ms = self._now_ms_fn()
            self._publish_locked()
            self._lock.notify_all()
            return True

    def invalidate(self) -> None:
        with self._lock:
            self._cached = None
            self._cached_generation = None
            self._cached_at_ms = None
            self._entry = None

    def mark_stale(self) -> bool:
        """Republish the current entry force-flagged ``stale_model`` (the
        same degradation :meth:`restore_state` applies to a restored
        snapshot). The fleet registry calls this when a member degrades
        or quarantines: its last-good proposals keep SERVING (reads are
        bounded-staleness by design) but the stale-execution gate
        (facade._refuse_stale_execution) refuses to ACT on them until a
        live fetch rebuilds the model. Returns False when the cache is
        empty or already stale-flagged (idempotent)."""
        from dataclasses import replace
        with self._lock:
            if self._cached is None or self._cached.stale_model:
                return False
            self._cached = replace(self._cached, stale_model=True)
            self._publish_locked()
            return True

    # -------------------------------------------------- snapshot/restore
    def export_state(self) -> dict | None:
        """The cache entry + generation keying + freshness stamps for the
        crash-safe snapshot (core/snapshot.py); None when empty. The
        result object is immutable by convention (readers never mutate
        it), so it is exported by reference."""
        with self._lock:
            if self._cached is None:
                return None
            return {"result": self._cached,
                    "generation": self._cached_generation,
                    "cachedAtMs": self._cached_at_ms,
                    "numComputations": self.num_computations}

    def restore_state(self, state: dict) -> None:
        """Install a snapshot's cache entry. The restored result is
        force-flagged ``stale_model``: a restarted process may *serve* it
        immediately (reads are bounded-staleness by design) but must not
        *execute* it until a live model build confirms the topology — the
        stale-execution gate (facade._refuse_stale_execution) enforces
        exactly that, which is how a stale-snapshot restore trips the
        refusal instead of acting on a dead cluster's plan. Bypasses the
        ``store()`` guards deliberately: the caller (facade restore)
        already verified the snapshot's cluster identity and seeded the
        monitor generation to the snapshot's."""
        from dataclasses import replace
        result = replace(state["result"], stale_model=True)
        with self._lock:
            self._cached = result
            self._cached_generation = state["generation"]
            self._cached_at_ms = state["cachedAtMs"]
            self.num_computations = state.get("numComputations", 0)
            self._publish_locked()
            self._lock.notify_all()

    # ------------------------------------------- background refresh loop
    def refresh_once(self, now_ms_fn=None, *, compute: bool = True) -> bool:
        """One freshness tick: observe the generation, recompute when the
        cache no longer answers it. Returns True when a recompute ran
        (False on cache-valid ticks and on compute failures — monitor
        not ready / transient errors retry next tick, ref :160-167 skip
        states). ``compute=False`` is the watch-only form: full breach
        accounting, no recompute — for caches whose refills arrive from
        elsewhere (the fleet tick's batched store)."""
        fn = now_ms_fn or self._now_ms_fn
        now = fn()
        self.observe_generation(now)
        if self.valid():
            self._notify_tick()
            return False
        # A persistent compute failure is the WORST freshness outage:
        # mark the breach from the tick itself (once per generation) the
        # moment a previously-warm cache's lag exceeds the target — a
        # recompute that never lands must not keep the alerting meter
        # flat. Startup warm-in (nothing cached yet) stays exempt.
        if self.freshness_target_ms:
            lag = self.freshness_lag_ms(now)
            with self._lock:
                gen = self._gen_seen
                had_cache = self._cached is not None
            if (had_cache and gen is not None and lag is not None
                    and lag > self.freshness_target_ms):
                self._mark_breach(gen, lag)
        if not compute:
            self._notify_tick()
            return False
        try:
            self.get(fn())
            return True
        except Exception:
            return False
        finally:
            self._notify_tick()

    def _notify_tick(self) -> None:
        for cb in list(self.on_tick):
            try:
                cb()
            except Exception:          # pragma: no cover - defensive
                LOG.debug("proposal-cache on_tick hook failed",
                          exc_info=True)

    def start_refresher(self, interval_s: float, now_ms_fn, *,
                        freshness_target_ms: int = 0,
                        watch_only: bool = False) -> None:
        """ref the precompute thread started by KafkaCruiseControl.startUp
        (KafkaCruiseControl.java:225). With a freshness target the tick
        tightens to ``min(interval, target/4)`` so a generation bump is
        noticed (and recomputed) well inside the SLO window.

        ``watch_only``: keep the full freshness/breach accounting but
        never recompute — for fleet members, whose caches are refilled
        by the registry's batched tick (a second per-cluster compute
        racing it would just duplicate device work)."""
        if self._refresher is not None:
            return
        # Fresh stop event per start (stop() leaves the old one set):
        # a cache restarted after stop() must actually refresh again,
        # and an orphan loop from a timed-out join exits on its own
        # event at its next wait.
        stop = threading.Event()
        self._stop = stop
        self._now_ms_fn = now_ms_fn
        self.freshness_target_ms = int(freshness_target_ms or 0)
        tick = interval_s
        if self.freshness_target_ms > 0:
            tick = min(interval_s,
                       max(self.freshness_target_ms / 4000.0, 0.05))

        def loop():
            # Failure backoff: a compute that cannot land (monitor warming
            # in after restart — hours on 1h windows) must not be retried
            # at the tightened freshness tick; every attempt pays admin
            # describe sweeps before it can raise. Doubling up to the
            # plain interval restores the pre-SLO cadence under
            # persistent failure; any success (or a valid cache) snaps
            # back to the fast tick. (Watch-only loops never compute, so
            # they always tick fast — breach observation is cheap.)
            delay = tick
            while not stop.wait(delay):
                if self.refresh_once(now_ms_fn,
                                     compute=not watch_only) \
                        or watch_only or self.valid():
                    delay = tick
                else:
                    delay = min(max(delay * 2, tick), interval_s)

        self._refresher = threading.Thread(target=loop, daemon=True,
                                           name="proposal-precompute")
        self._refresher.start()

    def stop(self) -> None:
        self._stop.set()
        if self._refresher is not None:
            self._refresher.join(timeout=5)
            self._refresher = None
