"""Proposal precompute + generation-keyed cache.

Rebuild of the reference's background "train loop"
(``GoalOptimizer.run()`` ``GoalOptimizer.java:152-203``): a cached
optimization result serves ``GET /proposals`` and goal-violation-free
rebalances instantly; the cache is valid while the monitor's model
generation is unchanged (``:232-239``); readers either take the cache, or
block until the in-flight computation lands (``:304-352``), or force a
fresh computation (``ignore_proposal_cache``).
"""

from __future__ import annotations

import threading

from ..analyzer import OptimizationOptions


class ProposalCache:
    def __init__(self, monitor, optimizer, *,
                 options: OptimizationOptions | None = None) -> None:
        self.monitor = monitor
        self.optimizer = optimizer
        # The cache is a dry-run measurement: a hard goal that cannot be
        # satisfied is a *cacheable finding* (served with its provision
        # verdict), not an error to re-burn compute on every refresh tick.
        # Readers that execute re-apply strict semantics (facade.rebalance).
        self.options = options or OptimizationOptions(
            skip_hard_goal_check=True)
        self._lock = threading.Condition()
        self._cached = None            # OptimizerResult
        self._cached_generation: int | None = None
        self._computing = False
        self._refresher: threading.Thread | None = None
        self._stop = threading.Event()
        self.num_computations = 0

    # ------------------------------------------------------------- reads
    def peek(self):
        """The cached OptimizerResult without blocking or recompute (may
        be stale or None) — for gauges that must never trigger work."""
        with self._lock:
            return self._cached

    def valid(self) -> bool:
        """ref validCachedProposal GoalOptimizer.java:232-239."""
        with self._lock:
            return (self._cached is not None
                    and self._cached_generation == self.monitor.generation)

    def get(self, now_ms: int, timeout_s: float = 60.0):
        """Serve the cached result, computing (or waiting on the in-flight
        computation) when stale (ref blocking read :304-352). A waiter whose
        in-flight computation fails takes over the computation itself (so
        the original error surfaces rather than a bogus timeout)."""
        import time as _t
        deadline = _t.monotonic() + timeout_s
        while True:
            with self._lock:
                if self.valid():
                    return self._cached
                if self._computing:
                    remaining = deadline - _t.monotonic()
                    if remaining <= 0 or not self._lock.wait_for(
                            lambda: self.valid() or not self._computing,
                            timeout=remaining):
                        raise TimeoutError(
                            "proposal computation did not finish")
                    continue   # re-check: either valid now, or take over
                self._computing = True
            try:
                return self._compute(now_ms)
            finally:
                with self._lock:
                    self._computing = False
                    self._lock.notify_all()

    def _compute(self, now_ms: int):
        gen = self.monitor.generation
        model_result = self.monitor.cluster_model(now_ms)
        # Belt-and-braces: the monitor only emits live results, but a
        # plugged monitor (or future refactor) handing a what-if scenario
        # transform here would poison every default-chain read until the
        # next generation bump — refuse outright.
        label = getattr(model_result, "scenario_label", None)
        if label:
            raise ValueError(
                f"proposal cache refuses scenario-modified model "
                f"{label!r}: only live monitor models may seed the cache")
        result = self.optimizer.optimize(model_result.model,
                                         model_result.metadata, self.options)
        if model_result.stale:
            # Carried to the facade's execution gate: cached proposals
            # computed from a stale-served model must not execute.
            from dataclasses import replace
            result = replace(result, stale_model=True)
        with self._lock:
            self._cached = result
            self._cached_generation = gen
            self.num_computations += 1
            self._lock.notify_all()
        return result

    def store(self, result, *, generation: int,
              scenario_label: str | None = None) -> bool:
        """Offer an externally computed OptimizerResult to the cache.

        The ONLY write path besides :meth:`_compute`, with two guards:

        - **scenario rejection** (hard error): results computed from a
          what-if scenario transform carry the scenario label and are
          refused outright — ``/simulate`` and the resilience detector's
          proactive sweeps can never poison the live-cluster cache.
        - **generation keying** (soft reject): a result computed against
          any generation other than the monitor's CURRENT one is dropped
          (returns False) — by the time it arrives it describes a
          cluster that no longer exists.
        """
        if scenario_label:
            raise ValueError(
                f"proposal cache refuses scenario-modified result "
                f"{scenario_label!r}: only live-cluster optimizations "
                "may be cached")
        with self._lock:
            if generation != self.monitor.generation:
                return False
            self._cached = result
            self._cached_generation = generation
            self._lock.notify_all()
            return True

    def invalidate(self) -> None:
        with self._lock:
            self._cached = None
            self._cached_generation = None

    # ------------------------------------------- background refresh loop
    def start_refresher(self, interval_s: float, now_ms_fn) -> None:
        """ref the precompute thread started by KafkaCruiseControl.startUp
        (KafkaCruiseControl.java:225)."""
        if self._refresher is not None:
            return

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    if not self.valid():
                        self.get(now_ms_fn())
                except Exception:
                    # Monitor not ready (NotEnoughValidWindows) or transient
                    # failure: retry next tick (ref :160-167 skip states).
                    pass

        self._refresher = threading.Thread(target=loop, daemon=True,
                                           name="proposal-precompute")
        self._refresher.start()

    def stop(self) -> None:
        self._stop.set()
        if self._refresher is not None:
            self._refresher.join(timeout=5)
            self._refresher = None
