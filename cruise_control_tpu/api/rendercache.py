"""Serving-tier render cache: immutable pre-rendered response snapshots.

The heavy-traffic read problem (ROADMAP 4a, PAPERS.md arxiv 2207.02026's
read-plane/optimization-plane split): the reference serves its hot read
endpoints from cached state (``GET /proposals`` is a cache read,
``GoalOptimizer.java:232-352``), but a naive rebuild still pays — per
request — the facade ``RLock``, the ``ProposalCache`` condition, a JSON
re-serialization of a payload that has not changed, and a ``Lock`` per
request-rate meter. Under N request threads those serialize the whole
read tier on a handful of locks while the bytes they produce are
byte-identical.

This module publishes, per endpoint, ONE immutable
:class:`RenderedEntry` — pre-serialized JSON bytes (the final
``{"version": 1, ...}`` envelope), the optional ``json=false``
plaintext rendering, and a strong ``ETag`` — keyed on the stack's
cheap, lock-free change detectors:

- the monitor's **model generation** (bumps when an aggregation window
  rolls — the proposal cache's own staleness key),
- the resident store's **epoch** (bumps on structural device rebuilds),
- the facade registry's **mutation count** (bumps on sensor
  registration — the scrape-surface shape),

plus per-endpoint extras (the published proposal entry's ``seq``, the
device-stats collector's ``cycle_seq``). Writers — the precompute
refresher tick, the fleet tick's re-store, a devicestats cycle landing,
or the first request after a key moved — render under the normal locks
and publish with one dict store. Readers (``api/server.py``'s
``route_request``) do one dict read plus one key compare; on an
``If-None-Match`` hit they answer ``304`` without building a byte of
body. The facade ``RLock`` and the ``ProposalCache`` condition are
never touched on the cached path.

Freshness model (documented in docs/operations.md §Serving-tier
tuning): ``ttl_ms=None`` means the key alone bounds staleness (exact
for ``/proposals`` — the body is a pure function of the published cache
entry — and for the static explorer page). Endpoints whose payloads
embed live values the key cannot see (``/state``'s executor phase,
``/metrics`` values, ``/devicestats`` memory numbers) use a ttl
micro-cache: within the window every request shares one render; past
it the next request re-renders. ``ttl_ms=0`` disables caching for the
endpoint entirely (the tier-1 default for live-value endpoints — tests
and single-user stacks always see fresh bytes; ``enable()`` flips the
serving profile on for production/bench stacks).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable

LOG = logging.getLogger(__name__)

#: endpoints enable() flips from "always fresh" to ttl micro-caching.
LIVE_VALUE_ENDPOINTS = ("state", "kafka_cluster_state", "devicestats",
                        "fleet", "forecast", "trace", "metrics")


class Uncacheable(Exception):
    """Raised by a key/payload function when the endpoint cannot be
    served from cache right now (e.g. the proposal cache is cold or
    generation-invalid) — the caller falls through to the full path."""


class RenderedEntry:
    """One immutable published response snapshot. Replaced wholesale,
    never mutated, so a reader that grabbed the reference always has a
    consistent (etag, body) pair — torn reads are structurally
    impossible."""

    __slots__ = ("endpoint", "key", "etag", "body", "text",
                 "content_type", "seq", "expires_mono")

    def __init__(self, endpoint, key, etag, body, text, content_type,
                 seq, expires_mono) -> None:
        object.__setattr__(self, "endpoint", endpoint)
        object.__setattr__(self, "key", key)
        object.__setattr__(self, "etag", etag)
        object.__setattr__(self, "body", body)
        object.__setattr__(self, "text", text)
        object.__setattr__(self, "content_type", content_type)
        object.__setattr__(self, "seq", seq)
        object.__setattr__(self, "expires_mono", expires_mono)

    def __setattr__(self, name, value):
        raise AttributeError("RenderedEntry is immutable")


class _Renderer:
    __slots__ = ("endpoint", "key_fn", "payload_fn", "content_type",
                 "ttl_ms", "raw", "plaintext", "auto_refresh")

    def __init__(self, endpoint, key_fn, payload_fn, content_type,
                 ttl_ms, raw, plaintext, auto_refresh) -> None:
        self.endpoint = endpoint
        self.key_fn = key_fn
        self.payload_fn = payload_fn
        self.content_type = content_type
        self.ttl_ms = ttl_ms
        self.raw = raw
        self.plaintext = plaintext
        self.auto_refresh = auto_refresh


class RenderCache:
    """Generation-keyed immutable response snapshots for the read tier.

    Thread model: ``get()`` is lock-free (one dict read, one key
    compare, striped hit counters). ``_render_and_publish`` serializes
    writers on a small publish lock — writers are rare (key moves, ttl
    expiries, refresher ticks) and the lock is never held while a
    cached read is served.
    """

    def __init__(self, *, registry=None) -> None:
        from ..core.sensors import MetricRegistry
        self._renderers: dict[str, _Renderer] = {}
        self._entries: dict[str, RenderedEntry] = {}
        self._publish_lock = threading.Lock()
        #: endpoints that have been served through the cache at least
        #: once — the only ones refresh() keeps warm (set.add is
        #: GIL-atomic; a lost race just delays warm-keeping one request).
        self._hot: set[str] = set()
        self._seq = 0
        #: master switch — the bench's A/B baseline flips it off.
        self.enabled = True
        self.registry = registry or MetricRegistry()
        name = MetricRegistry.name
        g = "RenderCache"
        self._hits = self.registry.striped_counter(name(g, "hits"))
        self._misses = self.registry.striped_counter(name(g, "misses"))
        self._renders = self.registry.counter(name(g, "renders"))
        self.registry.gauge(name(g, "endpoints"),
                            lambda: len(self._renderers))
        self.registry.gauge(name(g, "published"),
                            lambda: len(self._entries))

    # -------------------------------------------------------- registration
    def register(self, endpoint: str, key_fn: Callable[[], tuple],
                 payload_fn: Callable[[], object], *,
                 content_type: str = "application/json",
                 ttl_ms: int | None = None, raw: bool = False,
                 plaintext: bool = False,
                 auto_refresh: bool = False) -> None:
        """Wire an endpoint into the cache.

        ``raw`` payload functions return ``str``/``bytes`` served as-is
        under ``content_type`` (``/metrics``, ``/trace``, the explorer);
        JSON payload functions return the response dict, serialized here
        into the final ``{"version": 1, ...}`` envelope bytes (and, with
        ``plaintext``, the ``json=false`` text rendering). ``ttl_ms``:
        None = key-only, 0 = disabled, >0 = micro-cache window.
        ``auto_refresh`` marks the endpoint for :meth:`refresh` (the
        refresher-tick publish set)."""
        self._renderers[endpoint] = _Renderer(
            endpoint, key_fn, payload_fn, content_type, ttl_ms, raw,
            plaintext, auto_refresh)

    def set_ttl(self, endpoint: str, ttl_ms: int | None) -> None:
        r = self._renderers.get(endpoint)
        if r is None:
            raise KeyError(f"no renderer registered for {endpoint!r}")
        r.ttl_ms = ttl_ms
        self._entries.pop(endpoint, None)

    def enable(self, ttl_ms: int = 500, *,
               metrics_ttl_ms: int | None = None) -> None:
        """Flip the serving profile on: live-value endpoints get a
        ``ttl_ms`` micro-cache (``/metrics`` optionally tighter — scrape
        staleness tolerances differ from dashboard ones). Key-only
        endpoints (``/proposals``, explorer) are always on."""
        for ep in LIVE_VALUE_ENDPOINTS:
            if ep in self._renderers:
                ttl = ttl_ms
                if ep == "metrics" and metrics_ttl_ms is not None:
                    ttl = metrics_ttl_ms
                self.set_ttl(ep, ttl)

    # --------------------------------------------------------------- reads
    def get(self, endpoint: str) -> RenderedEntry | None:
        """The lock-free fast read: published entry if its key still
        matches (and its ttl window is open), else None. Never renders,
        never blocks, never takes a lock."""
        if not self.enabled:
            return None
        entry = self._entries.get(endpoint)
        if entry is None:
            return None
        if (entry.expires_mono is not None
                and time.monotonic() >= entry.expires_mono):
            self._misses.inc()
            return None
        r = self._renderers.get(endpoint)
        if r is None:
            return None
        try:
            key = r.key_fn()
        except Uncacheable:
            return None
        if entry.key != key:
            self._misses.inc()
            return None
        self._hits.inc()
        return entry

    def lookup_or_render(self, endpoint: str) -> RenderedEntry | None:
        """Serve the published entry, or render+publish inline (the
        first request after a key moved pays the render; everyone behind
        it reads the new entry lock-free). None when the endpoint is not
        registered, disabled (ttl 0), or currently uncacheable — the
        caller falls through to the full request path."""
        if not self.enabled:
            return None
        r = self._renderers.get(endpoint)
        if r is None or r.ttl_ms == 0:
            return None
        # Mark the endpoint hot: refresh() keeps only actually-served
        # endpoints warm, so control planes nobody is polling (and unit
        # tests churning generations) never pay background renders.
        self._hot.add(endpoint)
        entry = self.get(endpoint)
        if entry is not None:
            return entry
        try:
            return self._render_and_publish(r)
        except Uncacheable:
            return None

    # -------------------------------------------------------------- writes
    def _render_and_publish(self, r: _Renderer) -> RenderedEntry:
        with self._publish_lock:
            # A racing writer may have published while we waited.
            entry = self.get(r.endpoint)
            if entry is not None:
                return entry
            key = r.key_fn()
            payload = r.payload_fn()
            if r.raw:
                body = (payload.encode() if isinstance(payload, str)
                        else bytes(payload))
                text = None
            else:
                body = json.dumps({"version": 1, **payload}).encode()
                text = None
                if r.plaintext:
                    from .plaintext import render as render_text
                    # Trailing newline matches the uncached json=false
                    # path byte-for-byte (server.py appends it).
                    text = (render_text(r.endpoint, payload)
                            + "\n").encode()
            self._seq += 1
            etag = '"cc-{}-{}-{}"'.format(
                r.endpoint, self._seq,
                "-".join(str(k) for k in key))
            expires = None
            if r.ttl_ms is not None:
                expires = time.monotonic() + r.ttl_ms / 1000.0
            entry = RenderedEntry(r.endpoint, key, etag, body, text,
                                  r.content_type, self._seq, expires)
            self._entries[r.endpoint] = entry
            self._renders.inc()
            return entry

    def refresh(self) -> int:
        """Re-publish every stale auto-refresh endpoint — the precompute
        refresher tick / fleet tick hook, keeping the hot entries warm
        so requests almost never pay a render. Exception-safe (a cold
        proposal cache is normal); returns the number published."""
        published = 0
        if not self.enabled:
            return published
        for ep, r in list(self._renderers.items()):
            if not r.auto_refresh or r.ttl_ms == 0:
                continue
            # Warm-keeping applies only to endpoints traffic has
            # actually hit: rendering the full proposals payload on
            # every generation bump is pure overhead when nobody polls.
            if ep not in self._hot:
                continue
            if self.get(ep) is not None:
                continue
            try:
                self._render_and_publish(r)
                published += 1
            except Uncacheable:
                continue
            except Exception:
                LOG.debug("render-cache refresh failed for %s", ep,
                          exc_info=True)
        return published

    def invalidate(self, endpoint: str | None = None) -> None:
        if endpoint is None:
            self._entries.clear()
        else:
            self._entries.pop(endpoint, None)

    def to_json(self) -> dict:
        return {"enabled": self.enabled,
                "endpoints": {
                    ep: {"ttlMs": r.ttl_ms,
                         "published": ep in self._entries,
                         "autoRefresh": r.auto_refresh}
                    for ep, r in sorted(self._renderers.items())},
                "hits": self._hits.count,
                "misses": self._misses.count,
                "renders": self._renders.count}
