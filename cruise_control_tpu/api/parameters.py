"""Typed per-endpoint request parameters.

Rebuild of ``servlet/parameters/`` (``AbstractParameters.java``,
``ParameterUtils.java`` and the ~30 per-endpoint classes, ~4,400 LoC):
every endpoint declares the exact parameter set it accepts, each parameter
is parsed to its type with validation, unknown parameters are rejected
with a 400 (ref ``UserTaskManager``'s unrecognized-parameter handling),
required parameters and forbidden combinations are enforced before any
work runs.

The registry at the bottom (:data:`ENDPOINT_PARAMETERS`) maps endpoint
name -> parameter class; the HTTP layer parses once and hands the typed
:class:`ParsedParams` to the facade dispatch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable


class ParameterError(ValueError):
    """Maps to HTTP 400 (ref UserRequestException)."""


_TRUE = ("true", "1", "yes")
_FALSE = ("false", "0", "no")


@dataclass(frozen=True)
class Param:
    """One declared parameter (ref ParameterUtils *_PARAM constants)."""

    name: str
    kind: str                    # bool | int | double | string | csv_str |
                                 # csv_int | enum
    choices: tuple = ()          # for enum (case-insensitive)
    required: bool = False
    default: object = None
    min_value: float | None = None

    def parse(self, raw: str):
        if self.kind == "bool":
            low = raw.strip().lower()
            if low in _TRUE:
                return True
            if low in _FALSE:
                return False
            raise ParameterError(
                f"parameter {self.name}: {raw!r} is not a boolean")
        if self.kind == "int":
            try:
                value = int(raw)
            except ValueError:
                raise ParameterError(
                    f"parameter {self.name}: {raw!r} is not an integer")
            self._check_min(value)
            return value
        if self.kind == "double":
            try:
                value = float(raw)
            except ValueError:
                raise ParameterError(
                    f"parameter {self.name}: {raw!r} is not a number")
            self._check_min(value)
            return value
        if self.kind == "csv_str":
            return [x.strip() for x in raw.split(",") if x.strip()]
        if self.kind == "csv_int":
            try:
                return [int(x) for x in raw.split(",") if x.strip()]
            except ValueError:
                raise ParameterError(
                    f"parameter {self.name}: {raw!r} is not a "
                    "comma-separated integer list")
        if self.kind == "enum":
            value = raw.strip().upper()
            if value not in self.choices:
                raise ParameterError(
                    f"parameter {self.name}: {raw!r} not in "
                    f"{sorted(self.choices)}")
            return value
        return raw              # string

    def _check_min(self, value):
        if self.min_value is not None and value < self.min_value:
            raise ParameterError(
                f"parameter {self.name}: {value} < minimum "
                f"{self.min_value}")


#: parameters every endpoint accepts (ref AbstractParameters: json,
#: get_response_schema, doAs; reason is recorded for audit on POSTs;
#: user_task_id/get_response_timeout_s drive the async task protocol).
COMMON_PARAMS = (
    Param("json", "bool", default=True),
    Param("get_response_schema", "bool", default=False),
    Param("doas", "string"),
    Param("reason", "string"),
    Param("user_task_id", "string"),
    Param("get_response_timeout_s", "double", default=10.0, min_value=0),
    Param("review_id", "int", min_value=0),
)

#: shared goal-based optimization surface (ref
#: GoalBasedOptimizationParameters.java)
_GOAL_PARAMS = (
    Param("goals", "csv_str"),
    Param("kafka_assigner", "bool", default=False),
    Param("allow_capacity_estimation", "bool", default=True),
    Param("excluded_topics", "string"),
    Param("use_ready_default_goals", "bool", default=False),
    Param("exclude_recently_demoted_brokers", "bool", default=False),
    Param("exclude_recently_removed_brokers", "bool", default=False),
    Param("skip_hard_goal_check", "bool", default=False),
    # Framework extension: exempt NAMED goals from the off-chain
    # registered-hard-goal audit instead of the all-or-nothing
    # skip_hard_goal_check (in-chain hard goals still gate).
    Param("waived_hard_goals", "csv_str"),
    Param("fast_mode", "bool", default=False),
    Param("verbose", "bool", default=False),
    # Framework extension: explicit per-request broker exclusion masks
    # (the reference only excludes recently removed/demoted brokers).
    Param("exclude_brokers_for_leadership", "csv_int"),
    Param("exclude_brokers_for_replica_move", "csv_int"),
)

#: shared execution knobs (ref the runnables reading per-request
#: concurrency/strategy/throttle overrides)
_EXECUTION_PARAMS = (
    Param("dryrun", "bool", default=True),
    Param("concurrent_partition_movements_per_broker", "int", min_value=1),
    Param("max_partition_movements_in_cluster", "int", min_value=1),
    Param("concurrent_intra_broker_partition_movements", "int", min_value=1),
    Param("concurrent_leader_movements", "int", min_value=1),
    Param("broker_concurrent_leader_movements", "int", min_value=1),
    Param("execution_progress_check_interval_ms", "int", min_value=5),
    Param("replica_movement_strategies", "csv_str"),
    Param("replication_throttle", "int", min_value=0),
    Param("stop_ongoing_execution", "bool", default=False),
)


class ParsedParams:
    """Typed view of one request's parameters."""

    def __init__(self, endpoint: str, values: dict):
        self.endpoint = endpoint
        self._values = values

    def get(self, name: str, default=None):
        v = self._values.get(name)
        return default if v is None else v

    def __getitem__(self, name: str):
        return self._values[name]

    def __contains__(self, name: str) -> bool:
        return self._values.get(name) is not None

    def to_dict(self) -> dict:
        return {k: v for k, v in self._values.items() if v is not None}

    # -------------------------------------------------- derived conveniences
    def goal_list(self) -> list[str] | None:
        """Explicit goals, or the kafka-assigner chain, or None (defaults).
        ref ParameterUtils.getGoals + kafka_assigner mode resolution."""
        goals = self.get("goals")
        if goals:
            return list(goals)
        if self.get("kafka_assigner"):
            from ..analyzer.goals import KAFKA_ASSIGNER_GOALS
            return list(KAFKA_ASSIGNER_GOALS)
        return None

    def execution_kwargs(self) -> dict:
        """Executor overrides for facade execute calls."""
        out: dict = {}
        if "replica_movement_strategies" in self:
            out["strategy_names"] = list(self["replica_movement_strategies"])
        if "replication_throttle" in self:
            out["throttle_bytes"] = self["replication_throttle"]
        overrides = {}
        for pname, field in (
                ("concurrent_partition_movements_per_broker",
                 "num_concurrent_partition_movements_per_broker"),
                ("max_partition_movements_in_cluster",
                 "max_num_cluster_partition_movements"),
                ("concurrent_intra_broker_partition_movements",
                 "num_concurrent_intra_broker_partition_movements"),
                ("concurrent_leader_movements",
                 "num_concurrent_leader_movements"),
                ("broker_concurrent_leader_movements",
                 "num_concurrent_leader_movements_per_broker")):
            if pname in self:
                overrides[field] = self[pname]
        if overrides:
            out["concurrency_overrides"] = overrides
        if "execution_progress_check_interval_ms" in self:
            out["progress_check_interval_ms"] = self[
                "execution_progress_check_interval_ms"]
        return out


class EndpointParameters:
    """Base per-endpoint declaration (ref AbstractParameters.java)."""

    #: endpoint-specific parameters, on top of COMMON_PARAMS
    PARAMS: tuple[Param, ...] = ()
    #: extra validation hook: receives the parsed value dict
    validators: tuple[Callable[[dict], None], ...] = ()

    @classmethod
    def specs(cls) -> dict[str, Param]:
        out = {}
        for p in (*COMMON_PARAMS, *cls.PARAMS):
            out[p.name] = p
        return out

    @classmethod
    def parse(cls, endpoint: str, query: dict[str, list[str]]
              ) -> ParsedParams:
        specs = cls.specs()
        # Parameter names are case-insensitive (ref ParameterUtils — the
        # servlet lowercases). Normalize here so non-HTTP callers (plugins,
        # tests, programmatic use) get the same contract instead of a
        # silently applied default on a mixed-case key.
        lowered: dict[str, list[str]] = {}
        for k, v in query.items():
            # Merge case-variant spellings of one name so the duplicate
            # check below still fires (?DryRun=x&dryrun=y is the same
            # parameter given twice, not a silent overwrite).
            lowered.setdefault(k.lower(), []).extend(v)
        query = lowered
        unknown = [k for k in query if k not in specs]
        if unknown:
            raise ParameterError(
                f"unrecognized parameter(s) {sorted(unknown)} for endpoint "
                f"{endpoint}; supported: {sorted(specs)}")
        values: dict = {}
        for name, spec in specs.items():
            raw_list = query.get(name)
            if raw_list is None:
                values[name] = spec.default
                if spec.required:
                    raise ParameterError(
                        f"missing required parameter {name!r} for "
                        f"endpoint {endpoint}")
                continue
            if len(raw_list) > 1:
                raise ParameterError(
                    f"parameter {name} given {len(raw_list)} times")
            values[name] = spec.parse(raw_list[0])
        for validate in cls.validators:
            validate(values)
        # Goal NAMES are validated eagerly (ref ParameterUtils: unknown
        # goals are a 400 at dispatch, not an opaque failure from the
        # async operation): both the chain list and the audit waivers
        # must name registered goals, FQN or short form.
        for pname in ("goals", "waived_hard_goals"):
            names = values.get(pname)
            if names:
                from ..analyzer.goals import GOAL_REGISTRY, short_goal_name
                bad = sorted(n for n in names
                             if short_goal_name(n) not in GOAL_REGISTRY)
                if bad:
                    raise ParameterError(
                        f"unknown goal(s) {bad} in parameter {pname!r}")
        return ParsedParams(endpoint, values)


def _forbid(a: str, b: str) -> Callable[[dict], None]:
    def check(values: dict) -> None:
        if values.get(a) and values.get(b):
            raise ParameterError(
                f"parameters {a!r} and {b!r} are mutually exclusive")
    return check


# ----------------------------------------------------------- GET endpoints

class StateParameters(EndpointParameters):
    """ref CruiseControlStateParameters.java."""

    PARAMS = (Param("substates", "csv_str"),
              Param("verbose", "bool", default=False),
              Param("super_verbose", "bool", default=False))


class LoadParameters(EndpointParameters):
    """ref ClusterLoadParameters.java."""

    PARAMS = (Param("time", "int", min_value=0),
              Param("start", "int", min_value=0),
              Param("end", "int", min_value=0),
              Param("allow_capacity_estimation", "bool", default=True),
              Param("populate_disk_info", "bool", default=False),
              Param("capacity_only", "bool", default=False))


class PartitionLoadParameters(EndpointParameters):
    """ref PartitionLoadParameters.java."""

    PARAMS = (Param("resource", "enum",
                    choices=("CPU", "NW_IN", "NW_OUT", "DISK"),
                    default="DISK"),
              Param("start", "int", default=0, min_value=0),
              Param("end", "int", min_value=0),
              Param("entries", "int", default=2**31, min_value=1),
              Param("topic", "string"),
              Param("partition", "string"),
              Param("min_valid_partition_ratio", "double", min_value=0),
              Param("allow_capacity_estimation", "bool", default=True),
              Param("max_load", "bool", default=False),
              Param("avg_load", "bool", default=False),
              Param("brokerid", "csv_int"))
    validators = (_forbid("max_load", "avg_load"),)


class ProposalsParameters(EndpointParameters):
    """ref ProposalsParameters.java."""

    PARAMS = (*_GOAL_PARAMS,
              Param("ignore_proposal_cache", "bool", default=False),
              Param("data_from", "enum",
                    choices=("VALID_WINDOWS", "VALID_PARTITIONS"),
                    default="VALID_WINDOWS"))


class KafkaClusterStateParameters(EndpointParameters):
    """ref KafkaClusterStateParameters.java."""

    PARAMS = (Param("topic", "string"),
              Param("verbose", "bool", default=False))


class UserTasksParameters(EndpointParameters):
    """ref UserTasksParameters.java."""

    PARAMS = (Param("user_task_ids", "csv_str"),
              Param("client_ids", "csv_str"),
              Param("endpoints", "csv_str"),
              Param("types", "csv_str"),
              Param("entries", "int", min_value=1),
              Param("fetch_completed_task", "bool", default=False))


class BootstrapParameters(EndpointParameters):
    """ref BootstrapParameters.java."""

    PARAMS = (Param("start", "int", default=0, min_value=0),
              Param("end", "int", default=0, min_value=0),
              Param("clear_metrics", "bool", default=False))

    @staticmethod
    def _range(values: dict) -> None:
        if values.get("end") and values.get("start", 0) > values["end"]:
            raise ParameterError("bootstrap start must be <= end")
    validators = (_range,)


class TrainParameters(EndpointParameters):
    """ref TrainParameters.java."""

    PARAMS = (Param("start", "int", default=0, min_value=0),
              Param("end", "int", default=0, min_value=0))


class ReviewBoardParameters(EndpointParameters):
    """ref ReviewBoardParameters.java."""

    PARAMS = (Param("review_ids", "csv_int"),)


class PermissionsParameters(EndpointParameters):
    """ref UserPermissionsParameters.java."""


class OpenApiParameters(EndpointParameters):
    pass


# ---------------------------------------------------------- POST endpoints

class RebalanceParameters(EndpointParameters):
    """ref RebalanceParameters.java."""

    PARAMS = (*_GOAL_PARAMS, *_EXECUTION_PARAMS,
              Param("ignore_proposal_cache", "bool", default=False),
              Param("destination_broker_ids", "csv_int"),
              Param("rebalance_disk", "bool", default=False))
    validators = (_forbid("rebalance_disk", "destination_broker_ids"),)


class AddBrokerParameters(EndpointParameters):
    """ref AddBrokerParameters.java (AddedOrRemovedBrokerParameters)."""

    PARAMS = (*_GOAL_PARAMS, *_EXECUTION_PARAMS,
              Param("brokerid", "csv_int", required=True),
              Param("throttle_added_broker", "bool", default=True))


class RemoveBrokerParameters(EndpointParameters):
    """ref RemoveBrokerParameters.java."""

    PARAMS = (*_GOAL_PARAMS, *_EXECUTION_PARAMS,
              Param("brokerid", "csv_int", required=True),
              Param("throttle_removed_broker", "bool", default=True),
              Param("destination_broker_ids", "csv_int"))

    @staticmethod
    def _no_overlap(values: dict) -> None:
        dests = set(values.get("destination_broker_ids") or ())
        removed = set(values.get("brokerid") or ())
        if dests & removed:
            raise ParameterError(
                f"brokers {sorted(dests & removed)} cannot be both removed "
                "and destinations")
    validators = (_no_overlap,)


class DemoteBrokerParameters(EndpointParameters):
    """ref DemoteBrokerParameters.java."""

    PARAMS = (*_EXECUTION_PARAMS,
              Param("brokerid", "csv_int", required=True),
              Param("skip_urp_demotion", "bool", default=True),
              Param("exclude_follower_demotion", "bool", default=True),
              Param("exclude_recently_demoted_brokers", "bool",
                    default=False),
              Param("verbose", "bool", default=False))


class FixOfflineReplicasParameters(EndpointParameters):
    """ref FixOfflineReplicasParameters.java."""

    PARAMS = (*_GOAL_PARAMS, *_EXECUTION_PARAMS)


class TopicConfigurationParameters(EndpointParameters):
    """ref TopicConfigurationParameters.java +
    TopicReplicationFactorChangeParameters.java."""

    PARAMS = (*_GOAL_PARAMS, *_EXECUTION_PARAMS,
              Param("topic", "string", required=True),
              Param("replication_factor", "int", required=True, min_value=1),
              Param("skip_rack_awareness_check", "bool", default=False))


class RemoveDisksParameters(EndpointParameters):
    """ref RemoveDisksParameters.java."""

    PARAMS = (*_EXECUTION_PARAMS,
              Param("brokerid_and_logdirs", "string", required=True))


class RightsizeParameters(EndpointParameters):
    """ref RightsizeParameters.java."""

    PARAMS = (Param("num_brokers_to_add", "int", min_value=1),
              Param("partition_count", "int", min_value=1),
              Param("brokerid", "csv_int"))


class AdminParameters(EndpointParameters):
    """ref AdminParameters.java + UpdateSelfHealingParameters +
    ChangeExecutionConcurrencyParameters + DropRecentBrokersParameters +
    UpdateConcurrencyAdjusterParameters."""

    PARAMS = (Param("disable_self_healing_for", "csv_str"),
              Param("enable_self_healing_for", "csv_str"),
              Param("concurrent_partition_movements_per_broker", "int",
                    min_value=1),
              Param("concurrent_intra_broker_partition_movements", "int",
                    min_value=1),
              Param("concurrent_leader_movements", "int", min_value=1),
              Param("drop_recently_removed_brokers", "bool", default=False),
              Param("drop_recently_demoted_brokers", "bool", default=False),
              Param("disable_concurrency_adjuster_for", "csv_str"),
              Param("enable_concurrency_adjuster_for", "csv_str"),
              Param("min_isr_based_concurrency_adjustment", "bool"))

    @staticmethod
    def _not_both(values: dict) -> None:
        both = (set(values.get("enable_self_healing_for") or ())
                & set(values.get("disable_self_healing_for") or ()))
        if both:
            raise ParameterError(
                f"anomaly types {sorted(both)} cannot be both enabled and "
                "disabled")
    validators = (_not_both,)


class ReviewParameters(EndpointParameters):
    """ref ReviewParameters.java."""

    PARAMS = (Param("approve", "csv_int"),
              Param("discard", "csv_int"))

    @staticmethod
    def _some_action(values: dict) -> None:
        if not values.get("approve") and not values.get("discard"):
            raise ParameterError("review requires approve= and/or discard=")
        both = set(values.get("approve") or ()) & set(
            values.get("discard") or ())
        if both:
            raise ParameterError(
                f"review ids {sorted(both)} cannot be both approved and "
                "discarded")
    validators = (_some_action,)


class StopProposalParameters(EndpointParameters):
    """ref StopProposalParameters.java."""

    PARAMS = (Param("force_stop", "bool", default=False),
              Param("stop_external_agent", "bool", default=True))


class SimulateParameters(EndpointParameters):
    """What-if scenario sweep (no reference analog — this build's
    /simulate endpoint). Exactly one of ``sweep`` (expanded over alive
    brokers) or ``scenarios`` (a JSON list of scenario objects; see
    whatif/spec.py) must be given. Scenario-body validation happens in
    the whatif layer — this class only gates the transport shape."""

    PARAMS = (Param("sweep", "enum", choices=("N1", "N2")),
              Param("scenarios", "string"))

    @staticmethod
    def _exactly_one(values: dict) -> None:
        if bool(values.get("sweep")) == bool(values.get("scenarios")):
            raise ParameterError(
                "simulate requires exactly one of 'sweep' (N1|N2) or "
                "'scenarios' (JSON list)")
    validators = (_exactly_one,)


class PauseResumeParameters(EndpointParameters):
    """ref PauseResumeParameters.java (reason is in COMMON_PARAMS)."""


class FleetParameters(EndpointParameters):
    """``GET /fleet`` — the fleet summary takes only the common params
    (json=false renders the fixed-width table)."""


class FleetRebalanceParameters(EndpointParameters):
    """``POST /fleet/rebalance`` — a forced fleet tick; proposals land
    in the member caches, execution stays per-cluster."""


class ForecastParameters(EndpointParameters):
    """``GET /forecast`` — the fitted-trajectory summary and cached
    sweep report (json=false renders the fixed-width horizon table)."""


class HistoryParameters(EndpointParameters):
    """``GET /history`` — the control-plane flight recorder
    (core/events.py). Filters narrow the journal read; ``since_seq``
    makes polling incremental (json=false renders the fixed-width
    event table)."""

    PARAMS = (Param("category", "csv_str"),
              Param("severity", "enum",
                    choices=("INFO", "WARN", "ERROR")),
              Param("since_seq", "int", default=0, min_value=0),
              Param("limit", "int", default=256, min_value=1))


class ForecastRefreshParameters(EndpointParameters):
    """``POST /forecast`` — force a refit from the current window
    history plus one fresh trajectory sweep. Purely host-side fitting
    + a dry-run scoring dispatch; provisioning actions stay behind
    rightsize / the capacity-forecast detector."""


#: endpoint -> parameter class (ref CruiseControlEndPoint -> Parameters
#: wiring in KafkaCruiseControlServlet)
ENDPOINT_PARAMETERS: dict[str, type[EndpointParameters]] = {
    "state": StateParameters,
    "load": LoadParameters,
    "partition_load": PartitionLoadParameters,
    "proposals": ProposalsParameters,
    "kafka_cluster_state": KafkaClusterStateParameters,
    "user_tasks": UserTasksParameters,
    "bootstrap": BootstrapParameters,
    "train": TrainParameters,
    "review_board": ReviewBoardParameters,
    "permissions": PermissionsParameters,
    "openapi": OpenApiParameters,
    "rebalance": RebalanceParameters,
    "add_broker": AddBrokerParameters,
    "remove_broker": RemoveBrokerParameters,
    "demote_broker": DemoteBrokerParameters,
    "fix_offline_replicas": FixOfflineReplicasParameters,
    "topic_configuration": TopicConfigurationParameters,
    "remove_disks": RemoveDisksParameters,
    "rightsize": RightsizeParameters,
    "admin": AdminParameters,
    "review": ReviewParameters,
    "stop_proposal_execution": StopProposalParameters,
    "pause_sampling": PauseResumeParameters,
    "resume_sampling": PauseResumeParameters,
    "simulate": SimulateParameters,
    "fleet": FleetParameters,
    "fleet_rebalance": FleetRebalanceParameters,
    "forecast": ForecastParameters,
    "forecast_refresh": ForecastRefreshParameters,
    "history": HistoryParameters,
}


def parse_endpoint_params(endpoint: str, query: dict[str, list[str]]
                          ) -> ParsedParams:
    """Parse + validate one request's query params for ``endpoint``.
    Raises :class:`ParameterError` (HTTP 400) on unknown/invalid input."""
    cls = ENDPOINT_PARAMETERS.get(endpoint)
    if cls is None:
        raise ParameterError(f"unknown endpoint {endpoint}")
    return cls.parse(endpoint, query)
