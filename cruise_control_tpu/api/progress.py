"""Operation progress tracking (ref ``servlet/.../async/progress/
OperationProgress.java`` + step classes like ``OptimizationForGoal``,
``WaitingForClusterModel``): an append-only list of named steps with
completion percentages, attached to every async operation and rendered in
``/user_tasks`` and in-flight responses."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class ProgressStep:
    description: str
    start_ms: int
    completed_percent: float = 0.0
    end_ms: int | None = None

    def to_json(self) -> dict:
        return {"step": self.description,
                "completionPercentage": round(self.completed_percent, 2),
                "timeInMs": ((self.end_ms or int(time.time() * 1000))
                             - self.start_ms)}


class OperationProgress:
    def __init__(self) -> None:
        self._steps: list[ProgressStep] = []
        self._lock = threading.Lock()

    def add_step(self, description: str) -> ProgressStep:
        with self._lock:
            now = int(time.time() * 1000)
            if self._steps and self._steps[-1].end_ms is None:
                self._steps[-1].end_ms = now
                self._steps[-1].completed_percent = 100.0
            step = ProgressStep(description, now)
            self._steps.append(step)
            return step

    def finish(self) -> None:
        with self._lock:
            if self._steps and self._steps[-1].end_ms is None:
                self._steps[-1].end_ms = int(time.time() * 1000)
                self._steps[-1].completed_percent = 100.0

    def to_json(self) -> list[dict]:
        with self._lock:
            return [s.to_json() for s in self._steps]
