"""Two-step request review flow (ref ``servlet/purgatory/Purgatory.java:42``).

When ``two.step.verification.enabled`` is on, POST requests land in the
purgatory as PENDING_REVIEW; a reviewer approves or discards them via the
REVIEW endpoint (``applyReview`` ``:234``); an approved request id can then
be submitted once (``submit`` ``:169``)."""

from __future__ import annotations

import enum
import itertools
import threading
import time
from dataclasses import dataclass, field


class ReviewStatus(enum.Enum):
    """ref purgatory/ReviewStatus.java."""

    PENDING_REVIEW = "PENDING_REVIEW"
    APPROVED = "APPROVED"
    SUBMITTED = "SUBMITTED"
    DISCARDED = "DISCARDED"


_VALID = {
    ReviewStatus.PENDING_REVIEW: {ReviewStatus.APPROVED,
                                  ReviewStatus.DISCARDED},
    ReviewStatus.APPROVED: {ReviewStatus.SUBMITTED, ReviewStatus.DISCARDED},
    ReviewStatus.SUBMITTED: set(),
    ReviewStatus.DISCARDED: set(),
}


@dataclass
class RequestInfo:
    review_id: int
    endpoint: str
    params: dict
    submitter: str
    status: ReviewStatus = ReviewStatus.PENDING_REVIEW
    reason: str = ""
    submitted_ms: int = field(default_factory=lambda: int(time.time() * 1000))

    def to_json(self) -> dict:
        return {"Id": self.review_id, "EndPoint": self.endpoint,
                "Status": self.status.value, "Reason": self.reason,
                "SubmitterAddress": self.submitter,
                "SubmissionTimeMs": self.submitted_ms}


class Purgatory:
    def __init__(self, retention_ms: int = 7 * 24 * 3600 * 1000,
                 max_requests: int = 25) -> None:
        self._requests: dict[int, RequestInfo] = {}
        self._ids = itertools.count()
        self._lock = threading.RLock()
        self.retention_ms = retention_ms
        #: pending-request cap (ref two.step.purgatory.max.requests)
        self.max_requests = max_requests

    def add(self, endpoint: str, params: dict, submitter: str) -> RequestInfo:
        """ref maybeAddToPurgatory :115."""
        with self._lock:
            pending = sum(1 for r in self._requests.values()
                          if r.status is ReviewStatus.PENDING_REVIEW)
            if pending >= self.max_requests:
                raise ValueError(
                    f"purgatory is full ({pending} pending requests >= "
                    f"two.step.purgatory.max.requests={self.max_requests}); "
                    "review or discard pending requests first")
            info = RequestInfo(next(self._ids), endpoint, params, submitter)
            self._requests[info.review_id] = info
            return info

    def apply_review(self, approve: set[int], discard: set[int],
                     reason: str = "") -> dict[int, RequestInfo]:
        """ref applyReview :234."""
        with self._lock:
            touched = {}
            for rid in approve | discard:
                info = self._requests.get(rid)
                if info is None:
                    raise KeyError(f"no request with review id {rid}")
                target = (ReviewStatus.APPROVED if rid in approve
                          else ReviewStatus.DISCARDED)
                if target not in _VALID[info.status]:
                    raise ValueError(
                        f"request {rid} is {info.status.value}; cannot "
                        f"{target.value}")
                info.status = target
                info.reason = reason
                touched[rid] = info
            return touched

    def get(self, review_id: int, endpoint: str | None = None) -> RequestInfo:
        """Read an approved request WITHOUT consuming it — callers validate
        the replayed request first, then :meth:`submit` (a replay typo
        must not burn the approval).

        ``endpoint``, when given, must match the endpoint the request was
        reviewed for (ref Purgatory.java:179-184: a review id is bound to
        one endpoint; replaying it against another would execute an action
        that was never reviewed)."""
        with self._lock:
            info = self._requests.get(review_id)
            if info is None:
                raise KeyError(f"no request with review id {review_id}")
            if ReviewStatus.SUBMITTED not in _VALID[info.status]:
                raise ValueError(
                    f"request {review_id} is {info.status.value}, not APPROVED")
            if endpoint is not None and info.endpoint != endpoint:
                raise ValueError(
                    f"request {review_id} was reviewed for endpoint "
                    f"{info.endpoint}, not {endpoint}")
            return info

    def submit(self, review_id: int,
               endpoint: str | None = None) -> RequestInfo:
        """Mark an approved request submitted, returning it for execution
        (ref submit :169)."""
        with self._lock:
            info = self.get(review_id, endpoint)
            info.status = ReviewStatus.SUBMITTED
            return info

    def restore_approval(self, review_id: int) -> None:
        """Roll a just-submitted request back to APPROVED. ONLY for the
        dispatcher's scheduling failure path: when the task manager
        rejects the execution (capacity 429) after submit() already
        consumed the approval, the "back off and retry" contract requires
        the approval to survive — the request never actually ran. No
        reference equivalent (the reference 500s before this can arise)."""
        with self._lock:
            info = self._requests.get(review_id)
            if info is not None and info.status is ReviewStatus.SUBMITTED:
                info.status = ReviewStatus.APPROVED

    def review_board(self) -> list[RequestInfo]:
        with self._lock:
            now = int(time.time() * 1000)
            stale = [rid for rid, r in self._requests.items()
                     if now - r.submitted_ms > self.retention_ms]
            for rid in stale:
                del self._requests[rid]
            return sorted(self._requests.values(),
                          key=lambda r: r.review_id)
