"""Async user task management (ref ``servlet/UserTaskManager.java:69``).

Long-running requests get a ``User-Task-ID`` UUID; the work runs on an
executor pool as an :class:`OperationFuture`; clients poll the same
endpoint (or ``/user_tasks``) with the header until the future completes.
Completed tasks are retained for a configurable time so late polls still
see the result (ref completed-task retention ``UserTaskManager.java``).
"""

from __future__ import annotations

import enum
import threading
import time
import uuid as uuidlib
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable

from .progress import OperationProgress


class TooManyUserTasksError(RuntimeError):
    """Active-user-task capacity exhausted. The server maps this to HTTP
    429 — a deliberate improvement over the reference, whose equivalent
    RuntimeException (``UserTaskManager.java:496``) surfaces as a 500;
    429 tells clients to back off and retry rather than report a server
    fault. ``retry_after_s`` rides the ``Retry-After`` response header
    so shedding is an instruction, not just a rejection."""

    def __init__(self, message: str, *, retry_after_s: int = 1) -> None:
        super().__init__(message)
        self.retry_after_s = max(1, int(retry_after_s))


class TaskState(enum.Enum):
    """ref UserTaskManager.TaskState."""

    ACTIVE = "Active"
    COMPLETED = "Completed"
    COMPLETED_WITH_ERROR = "CompletedWithError"


@dataclass
class UserTaskInfo:
    user_task_id: str
    endpoint: str
    request_url: str
    start_ms: int
    future: Future
    progress: OperationProgress = field(default_factory=OperationProgress)

    @property
    def state(self) -> TaskState:
        if not self.future.done():
            return TaskState.ACTIVE
        return (TaskState.COMPLETED_WITH_ERROR if self.future.exception()
                else TaskState.COMPLETED)

    def to_json(self) -> dict:
        return {"UserTaskId": self.user_task_id,
                "Status": self.state.value,
                "RequestURL": self.request_url,
                "StartMs": self.start_ms,
                "Progress": self.progress.to_json()}


class UserTaskManager:
    def __init__(self, max_active_tasks: int = 25,
                 completed_task_retention_ms: int = 24 * 3600 * 1000,
                 num_threads: int = 8,
                 max_cached_completed: int = 100,
                 registry=None) -> None:
        from ..core.sensors import MetricRegistry
        self._tasks: dict[str, UserTaskInfo] = {}
        self._lock = threading.RLock()
        self._pool = ThreadPoolExecutor(max_workers=num_threads,
                                        thread_name_prefix="user-task")
        self.max_active_tasks = max_active_tasks
        self.retention_ms = completed_task_retention_ms
        #: count cap on retained completed tasks (ref
        #: max.cached.completed.user.tasks): oldest evicted first, on top
        #: of the time-based retention. One pool here — the reference's
        #: per-scope monitor/admin caches are a deliberate deviation
        #: (docs/deviations.md §8).
        self.max_cached_completed = max_cached_completed
        #: backpressure meters: queue depth (active = queued + running —
        #: the cap bounds BOTH, queues can never grow without bound) and
        #: the shed rate an operator alerts on.
        self.registry = registry or MetricRegistry()
        name = MetricRegistry.name
        self.registry.gauge(name("UserTasks", "active-depth"),
                            self.active_count)
        self._rejections = self.registry.meter(
            name("UserTasks", "rejected-rate"))

    def active_count(self) -> int:
        """Active tasks = running + queued behind the pool: the bounded
        quantity ``max_active_tasks`` caps."""
        with self._lock:
            return sum(1 for t in self._tasks.values()
                       if t.state is TaskState.ACTIVE)

    def _ensure_capacity_locked(self) -> None:
        active = sum(1 for t in self._tasks.values()
                     if t.state is TaskState.ACTIVE)
        if active >= self.max_active_tasks:
            self._rejections.mark()
            raise TooManyUserTasksError(
                f"too many active user tasks ({active})")

    def ensure_capacity(self) -> None:
        """Raise TooManyUserTasksError if a new submission would be
        rejected right now. For callers that must fail BEFORE consuming
        a one-shot resource (a two-step approval): the pre-check narrows
        the window, and submit() re-checks authoritatively."""
        with self._lock:
            self._expire_completed()
            self._ensure_capacity_locked()

    def submit(self, endpoint: str, request_url: str,
               fn: Callable[[OperationProgress], Any],
               user_task_id: str | None = None) -> UserTaskInfo:
        """Create (or return the existing) task for this id (ref
        getOrCreateUserTask: resubmitting with the same User-Task-ID header
        reattaches rather than rerunning)."""
        with self._lock:
            self._expire_completed()
            if user_task_id and user_task_id in self._tasks:
                return self._tasks[user_task_id]
            self._ensure_capacity_locked()
            tid = user_task_id or str(uuidlib.uuid4())
            progress = OperationProgress()
            future = self._pool.submit(fn, progress)
            info = UserTaskInfo(user_task_id=tid, endpoint=endpoint,
                                request_url=request_url,
                                start_ms=int(time.time() * 1000),
                                future=future, progress=progress)
            self._tasks[tid] = info
            return info

    def get(self, user_task_id: str) -> UserTaskInfo | None:
        with self._lock:
            return self._tasks.get(user_task_id)

    def all_tasks(self) -> list[UserTaskInfo]:
        with self._lock:
            self._expire_completed()
            return sorted(self._tasks.values(), key=lambda t: t.start_ms)

    def _expire_completed(self) -> None:
        now = int(time.time() * 1000)
        stale = [tid for tid, t in self._tasks.items()
                 if t.state is not TaskState.ACTIVE
                 and now - t.start_ms > self.retention_ms]
        for tid in stale:
            del self._tasks[tid]
        done = [(t.start_ms, tid) for tid, t in self._tasks.items()
                if t.state is not TaskState.ACTIVE]
        if len(done) > self.max_cached_completed:
            for _, tid in sorted(done)[:len(done)
                                       - self.max_cached_completed]:
                del self._tasks[tid]

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
