"""OpenAPI 3 spec for the REST surface.

The reference assembles its spec from ``src/main/resources/yaml/base.yaml``
plus one yaml per endpoint (23 files under ``yaml/endpoints/``) and serves
swagger-ui from ``webroot/``. Here the spec is generated from the same
parameter tables the dispatcher uses, so it cannot drift from the server,
and is served as JSON at ``GET /kafkacruisecontrol/openapi``.
"""

from __future__ import annotations

_COMMON_ASYNC_PARAMS = [
    ("dryrun", "boolean", "compute proposals only, do not execute"),
    ("goals", "string", "comma-separated goal class names to run"),
    ("kafka_assigner", "boolean",
     "use the kafka-assigner emulation goal set"),
    ("excluded_topics", "string", "comma-separated topics to exclude"),
    ("waived_hard_goals", "string",
     "named hard goals exempted from the off-chain audit "
     "(framework extension; in-chain hard goals still gate)"),
    ("fast_mode", "boolean", "reduced-effort search"),
    ("exclude_brokers_for_leadership", "string", "comma-separated ids"),
    ("exclude_brokers_for_replica_move", "string", "comma-separated ids"),
    ("destination_broker_ids", "string", "comma-separated ids"),
    ("ignore_proposal_cache", "boolean", "bypass the precompute cache"),
    ("get_response_timeout_s", "number",
     "long-poll timeout before a 202 progress response"),
    ("review_id", "integer", "approved review id (two-step verification)"),
]

#: endpoint -> (method, summary, extra params)
ENDPOINTS: dict[str, tuple[str, str, list[tuple[str, str, str]]]] = {
    "state": ("get", "Monitor/executor/analyzer/anomaly-detector state; "
                     "every response carries ServerRole (leader|standby "
                     "+ fencing epoch — docs/operations.md §HA)",
              [("substates", "string", "comma-separated subset")]),
    "load": ("get", "Per-broker load snapshot", []),
    "partition_load": ("get", "Per-partition resource load, sorted",
                       [("resource", "string", "CPU|NW_IN|NW_OUT|DISK"),
                        ("start", "integer", "first entry"),
                        ("entries", "integer", "max entries")]),
    "proposals": ("get", "Cached or freshly computed rebalance proposals",
                  [("ignore_proposal_cache", "boolean", "recompute")]),
    "kafka_cluster_state": ("get", "Kafka-level partition/replica state", []),
    "user_tasks": ("get", "Recent/active async user tasks", []),
    "review_board": ("get", "Two-step-verification review queue", []),
    "permissions": ("get", "Roles of the authenticated principal", []),
    "openapi": ("get", "This OpenAPI 3 document", []),
    "bootstrap": ("get", "Replay historic samples into the monitor",
                  [("start", "integer", "epoch ms"),
                   ("end", "integer", "epoch ms")]),
    "train": ("get", "Fit the (bytes-in, bytes-out) -> CPU regression", []),
    "rebalance": ("post", "Compute and optionally execute a rebalance",
                  _COMMON_ASYNC_PARAMS),
    "add_broker": ("post", "Move load onto new brokers",
                   [("brokerid", "string", "comma-separated ids"),
                    *_COMMON_ASYNC_PARAMS]),
    "remove_broker": ("post", "Drain brokers before decommission",
                      [("brokerid", "string", "comma-separated ids"),
                       *_COMMON_ASYNC_PARAMS]),
    "fix_offline_replicas": ("post", "Move offline replicas to live brokers",
                             _COMMON_ASYNC_PARAMS),
    "demote_broker": ("post", "Move leadership off brokers",
                      [("brokerid", "string", "comma-separated ids"),
                       *_COMMON_ASYNC_PARAMS]),
    "topic_configuration": ("post", "Change topic replication factor",
                            [("topic", "string", "topic name or pattern"),
                             ("replication_factor", "integer", "target RF"),
                             *_COMMON_ASYNC_PARAMS]),
    "rightsize": ("post", "Provisioner-driven cluster rightsizing", []),
    "remove_disks": ("post", "Drain specific log dirs",
                     [("brokerid_and_logdirs", "string",
                       "<id>-<logdir>[,...]"), *_COMMON_ASYNC_PARAMS]),
    "stop_proposal_execution": ("post", "Stop the ongoing execution", []),
    "pause_sampling": ("post", "Pause metric sampling",
                       [("reason", "string", "audit note")]),
    "resume_sampling": ("post", "Resume metric sampling",
                        [("reason", "string", "audit note")]),
    "admin": ("post", "Runtime toggles (self-healing, concurrency)",
              [("disable_self_healing_for", "string", "anomaly types"),
               ("enable_self_healing_for", "string", "anomaly types"),
               ("concurrent_partition_movements_per_broker", "integer", ""),
               ("concurrent_leader_movements", "integer", "")]),
    "review": ("post", "Approve/discard parked requests",
               [("approve", "string", "comma-separated review ids"),
                ("discard", "string", "comma-separated review ids")]),
    "simulate": ("post", "What-if scenario sweep: score hypothetical "
                         "failures, growth and capacity changes",
                 [("sweep", "string", "N1|N2 broker-loss sweep over "
                                      "alive brokers"),
                  ("scenarios", "string",
                   "JSON list of scenario objects (broker_loss, "
                   "broker_add, capacity_resize, load_scale, "
                   "topic_add); also accepted as a JSON request body")]),
    "trace": ("get", "Chrome trace-event JSON of the span ring buffer "
                     "(Perfetto-loadable)", []),
    "devicestats": ("get", "Device runtime stats: compile lifecycle, "
                           "host<->device transfer bytes, device memory "
                           "and padding waste",
                    [("json", "boolean",
                      "false renders the fixed-width text table")]),
    "fleet": ("get", "Fleet summary: per-cluster balance score, proposal "
                     "freshness and N-1 risk from the batched control "
                     "plane (also at /fleet)", []),
    "fleet_rebalance": ("post", "Force one fleet tick: every member "
                                "recomputes through the batched [C] "
                                "dispatch and re-caches its proposals; "
                                "execution stays per-cluster (also at "
                                "/fleet/rebalance)", []),
    "forecast": ("get", "Fitted per-topic load trajectories + the "
                        "projected-horizon sweep report (risk, capacity "
                        "pressure and time-to-breach per horizon x "
                        "quantile; docs/forecasting.md)", []),
    "forecast_refresh": ("post", "Refit forecasts from the current "
                                 "window history and run one trajectory "
                                 "sweep now (also POST /forecast); "
                                 "host-side fitting + a dry-run scoring "
                                 "dispatch — provisioning stays behind "
                                 "rightsize / the detector", []),
    "history": ("get", "Control-plane flight recorder: the causal "
                       "decision journal (core/events.py) with "
                       "category/severity/since_seq filters; replicas "
                       "serve the leader's streamed journal merged with "
                       "their own (docs/observability.md)", []),
}


def api_explorer_html(base_path: str = "/kafkacruisecontrol") -> str:
    """Self-contained HTML API explorer served at the web root (the
    stand-in for the reference's swagger-ui ``webroot/`` — this
    environment cannot ship swagger's JS assets, so the page renders the
    same endpoint/parameter tables directly)."""
    from .parameters import ENDPOINT_PARAMETERS
    rows = []
    for name, (method, summary, extra) in sorted(ENDPOINTS.items()):
        cls = ENDPOINT_PARAMETERS.get(name)
        declared = sorted(cls.specs()) if cls is not None else []
        params = ", ".join(declared) or "—"
        rows.append(
            f"<tr><td><code>{method.upper()}</code></td>"
            f"<td><code>{base_path}/{name}</code></td>"
            f"<td>{summary}</td><td><small>{params}</small></td></tr>")
    return f"""<!doctype html>
<html><head><meta charset="utf-8"><title>cruise-control-tpu API</title>
<style>
 body {{ font: 14px/1.5 system-ui, sans-serif; margin: 2rem auto;
        max-width: 72rem; padding: 0 1rem; color: #1a1a1a; }}
 table {{ border-collapse: collapse; width: 100%; }}
 th, td {{ text-align: left; padding: .4rem .6rem;
           border-bottom: 1px solid #ddd; vertical-align: top; }}
 code {{ background: #f4f4f4; padding: 0 .25rem; border-radius: 3px; }}
</style></head><body>
<h1>cruise-control-tpu</h1>
<p>TPU-native Cruise Control. Machine-readable spec:
<a href="{base_path}/openapi">{base_path}/openapi</a> · state:
<a href="{base_path}/state">{base_path}/state</a></p>
<table><tr><th>Method</th><th>Path</th><th>Summary</th>
<th>Parameters</th></tr>
{"".join(rows)}
</table>
<p><small>Async POSTs return a <code>User-Task-ID</code> header; poll by
re-issuing the request with that header. See docs/rest-api.md.</small></p>
</body></html>"""


#: Param.kind -> OpenAPI schema (csv kinds are comma-separated strings
#: on the wire).
_KIND_SCHEMA = {"bool": "boolean", "int": "integer", "double": "number",
                "string": "string", "csv_str": "string",
                "csv_int": "string"}


def _declared_params(endpoint: str, descriptions: dict[str, str]
                     ) -> list[dict]:
    """Parameter objects generated from the SAME typed specs the
    dispatcher validates with (api/parameters.py) — names, types, enum
    choices, defaults, required flags and minimums cannot drift from the
    server's actual contract."""
    from .parameters import ENDPOINT_PARAMETERS
    cls = ENDPOINT_PARAMETERS.get(endpoint)
    if cls is None:
        return []
    out = []
    for p in cls.specs().values():
        if p.kind == "enum":
            schema: dict = {"type": "string",
                            "enum": [str(c) for c in p.choices]}
        else:
            schema = {"type": _KIND_SCHEMA.get(p.kind, "string")}
        if p.kind in ("csv_str", "csv_int"):
            schema["description"] = "comma-separated list"
        if p.default is not None:
            schema["default"] = p.default
        if p.min_value is not None:
            schema["minimum"] = p.min_value
        out.append({"name": p.name, "in": "query",
                    "required": bool(p.required),
                    "description": descriptions.get(p.name, ""),
                    "schema": schema})
    return out


#: Response body schemas for the main result shapes (ref the response
#: classes under servlet/response/). version=1 wraps every JSON body.
_SCHEMAS = {
    "OptimizationResult": {
        "type": "object",
        "properties": {
            "version": {"type": "integer"},
            "summary": {"type": "object",
                        "description": "proposal counts by action type"},
            "goalSummary": {"type": "array", "items": {
                "type": "object", "properties": {
                    "goal": {"type": "string"},
                    "hard": {"type": "boolean"},
                    "violationBefore": {"type": "number"},
                    "violationAfter": {"type": "number"},
                    "optimizationDurationMs": {"type": "number"},
                    "status": {"type": "string",
                               "enum": ["NO-ACTION", "FIXED", "VIOLATED"]},
                }}},
            "violatedGoalsBefore": {"type": "array",
                                    "items": {"type": "string"}},
            "violatedGoalsAfter": {"type": "array",
                                   "items": {"type": "string"}},
            "proposals": {"type": "array", "items": {
                "type": "object", "properties": {
                    "topic": {"type": "string"},
                    "partition": {"type": "integer"},
                    "oldLeader": {"type": "integer"},
                    "oldReplicas": {"type": "array",
                                    "items": {"type": "integer"}},
                    "newReplicas": {"type": "array",
                                    "items": {"type": "integer"}},
                }}},
            "provisionResponse": {"type": "object", "nullable": True},
        }},
    "ProgressResponse": {
        "type": "object",
        "properties": {
            "version": {"type": "integer"},
            "progress": {"type": "array", "items": {"type": "object"}},
            "userTaskId": {"type": "string"},
        }},
    "ErrorResponse": {
        "type": "object",
        "properties": {
            "version": {"type": "integer"},
            "errorMessage": {"type": "string"},
        }},
    "ReviewResult": {
        "type": "object",
        "description": "request parked for two-step review",
        "properties": {
            "version": {"type": "integer"},
            "reviewResult": {"type": "object", "properties": {
                "Id": {"type": "integer"},
                "EndPoint": {"type": "string"},
                "Status": {"type": "string"},
                "Reason": {"type": "string"},
                "SubmitterAddress": {"type": "string"},
                "SubmissionTimeMs": {"type": "integer"},
            }}}},
    "WhatIfReport": {
        "type": "object",
        "description": "per-scenario what-if scorecards "
                       "(whatif/engine.py WhatIfReport)",
        "properties": {
            "version": {"type": "integer"},
            "numScenarios": {"type": "integer"},
            "goals": {"type": "array", "items": {"type": "string"}},
            "durationMs": {"type": "number"},
            "staleModel": {"type": "boolean"},
            "riskiest": {"type": "string", "nullable": True},
            "maxRisk": {"type": "number"},
            "scenarios": {"type": "array", "items": {
                "type": "object", "properties": {
                    "scenario": {"type": "object",
                                 "description": "the declarative spec "
                                                "echoed back"},
                    "name": {"type": "string"},
                    "risk": {"type": "number",
                             "description": "[0, 1] composite risk"},
                    "violatedGoals": {"type": "array",
                                      "items": {"type": "string"}},
                    "violatedHardGoals": {"type": "array",
                                          "items": {"type": "string"}},
                    "capacityPressure": {"type": "number"},
                    "unavailablePartitions": {"type": "integer"},
                    "offlineReplicas": {"type": "integer"},
                    "headroom": {"type": "object",
                                 "description": "per-resource remaining "
                                                "usable capacity + worst "
                                                "broker fraction"},
                    "worstBroker": {},
                }}},
        }},
    "ForecastReport": {
        "type": "object",
        "description": "fitted-trajectory summary + projected-horizon "
                       "sweep (forecast/engine.py ForecastReport; "
                       "docs/forecasting.md)",
        "properties": {
            "version": {"type": "integer"},
            "enabled": {"type": "boolean"},
            "horizonsMs": {"type": "array", "items": {"type": "integer"}},
            "quantiles": {"type": "array", "items": {"type": "number"}},
            "fits": {"type": "integer"},
            "sweeps": {"type": "integer"},
            "storePath": {"type": "string", "nullable": True},
            "fittedTopics": {"type": "integer", "nullable": True},
            "fittedAtMs": {"type": "integer", "nullable": True},
            "worstBacktestMape": {
                "type": "number", "nullable": True,
                "description": "worst 1-window-holdout relative error "
                               "over fitted topics"},
            "timeToBreachMs": {
                "type": "integer", "nullable": True,
                "description": "estimated ms until projected capacity "
                               "pressure crosses 1.0 (null = no breach "
                               "inside the scored horizons)"},
            "lastSweepMs": {"type": "integer", "nullable": True},
            "topics": {"type": "object",
                       "description": "per-topic fit summary (degrade "
                                      "rung, backtest error, per-window "
                                      "trend)"},
            "report": {"type": "object", "nullable": True, "properties": {
                "generatedAtMs": {"type": "integer"},
                "durationMs": {"type": "number"},
                "staleModel": {"type": "boolean"},
                "timeToBreachMs": {"type": "integer", "nullable": True},
                "breachHorizonMs": {"type": "integer", "nullable": True},
                "breachQuantile": {"type": "number", "nullable": True},
                "baseline": {"type": "object", "nullable": True},
                "horizons": {"type": "array", "items": {
                    "type": "object", "properties": {
                        "horizonMs": {"type": "integer"},
                        "quantile": {"type": "number"},
                        "risk": {"type": "number"},
                        "capacityPressure": {"type": "number"},
                        "violatedGoals": {"type": "array",
                                          "items": {"type": "string"}},
                        "violatedHardGoals": {"type": "array",
                                              "items": {"type": "string"}},
                        "headroom": {"type": "object"},
                        "worstBroker": {},
                        "maxFactor": {"type": "number"},
                        "scenario": {"type": "string"},
                    }}},
            }},
        }},
    "EventHistory": {
        "type": "object",
        "description": "flight-recorder journal read (core/events.py "
                       "EventJournal); events are causally linked via "
                       "cause -> seq",
        "properties": {
            "version": {"type": "integer"},
            "node": {"type": "string", "nullable": True},
            "role": {"type": "string"},
            "lastSeq": {"type": "integer"},
            "numEvents": {"type": "integer"},
            "dropped": {"type": "integer"},
            "capacity": {"type": "integer"},
            "events": {"type": "array", "items": {
                "type": "object", "properties": {
                    "seq": {"type": "integer"},
                    "tsMs": {"type": "integer"},
                    "category": {"type": "string"},
                    "action": {"type": "string"},
                    "severity": {"type": "string",
                                 "enum": ["info", "warn", "error"]},
                    "epoch": {"type": "integer", "nullable": True},
                    "spanId": {"type": "string", "nullable": True},
                    "cause": {"type": "integer", "nullable": True,
                              "description": "seq of the causing event"},
                    "node": {"type": "string", "nullable": True},
                    "detail": {"type": "object", "nullable": True},
                }}},
        }},
    "TraceEvents": {
        "type": "object",
        "description": "Chrome trace-event JSON (chrome://tracing / "
                       "Perfetto); spans from the process ring buffer",
        "properties": {
            "traceEvents": {"type": "array", "items": {"type": "object"}},
            "displayTimeUnit": {"type": "string"},
        }},
    "DeviceStats": {
        "type": "object",
        "description": "device-runtime ledger "
                       "(core/runtime_obs.py DeviceStatsCollector)",
        "properties": {
            "version": {"type": "integer"},
            "enabled": {"type": "boolean"},
            "compile": {"type": "object", "properties": {
                "totalEvents": {"type": "integer"},
                "aotEvents": {"type": "integer"},
                "recompileEvents": {
                    "type": "integer",
                    "description": "compiles for already-compiled shape "
                                   "buckets — nonzero on a warm path "
                                   "means a pass-signature change"},
                "byProgram": {"type": "object",
                              "description": "per tracked program: "
                                             "compiles, aotCompiles, "
                                             "dispatches, shapeBuckets"},
                "recentEvents": {"type": "array",
                                 "items": {"type": "object"}},
            }},
            "transfers": {"type": "object", "properties": {
                "h2dBytesTotal": {"type": "integer"},
                "d2hBytesTotal": {"type": "integer"},
                "lastCycle": {"type": "object", "nullable": True},
            }},
            "memory": {"type": "object",
                       "description": "live/peak bytes; source names the "
                                      "backend path (device_memory_stats "
                                      "on TPU/GPU, live_arrays on CPU)"},
            "padding": {"type": "object", "nullable": True},
            "budget": {
                "type": "object",
                "description": "standing against the configured "
                               "padding/HBM budgets "
                               "(device.padding.waste.budget.pct / "
                               "device.hbm.budget.bytes; docs/scaling.md)",
                "properties": {
                    "paddingWastePct": {"type": "number",
                                        "nullable": True},
                    "paddingWasteBudgetPct": {"type": "number",
                                              "nullable": True},
                    "peakBytes": {"type": "integer", "nullable": True},
                    "hbmBudgetBytes": {"type": "integer",
                                       "nullable": True},
                    "paddingOverBudget": {"type": "boolean"},
                    "hbmOverBudget": {"type": "boolean"},
                }},
            "resident": {
                "type": "object", "nullable": True,
                "description": "device-resident model state "
                               "(model/resident.py): epoch bumps on "
                               "structural full rebuilds; metric-only "
                               "cycles report lastUpdate=delta with "
                               "lastDeltaRows/lastDeltaBytes",
                "properties": {
                    "epoch": {"type": "integer"},
                    "fullRebuilds": {"type": "integer"},
                    "deltaCycles": {"type": "integer"},
                    "noopCycles": {"type": "integer"},
                    "lastUpdate": {"type": "string", "nullable": True},
                    "lastDeltaRows": {"type": "integer"},
                    "lastDeltaBytes": {"type": "integer"},
                    "lastFullBytes": {"type": "integer"},
                    "shapes": {"type": "object"},
                }},
            "proposalFreshness": {
                "type": "object",
                "description": "proposal-cache freshness vs the "
                               "proposals.freshness.target.ms SLO: lagMs "
                               "is how long the current model generation "
                               "has gone unanswered (0 = cache valid), "
                               "ageMs how old the cached result is",
                "properties": {
                    "valid": {"type": "boolean"},
                    "cacheId": {"type": "string", "nullable": True},
                    "ageMs": {"type": "integer", "nullable": True},
                    "lagMs": {"type": "integer", "nullable": True},
                    "targetMs": {"type": "integer", "nullable": True},
                    "computations": {"type": "integer"},
                    "breaches": {"type": "integer"},
                }},
            "fleet": {
                "type": "object", "nullable": True,
                "description": "fleet control plane (fleet/registry.py): "
                               "cluster count, current shape bucket and "
                               "the last batched dispatch's wall clock; "
                               "null when fleet.enabled=false",
                "properties": {
                    "clusterCount": {"type": "integer"},
                    "ticks": {"type": "integer"},
                    "bucket": {"type": "object", "nullable": True},
                    "lastDispatchMs": {"type": "number",
                                       "nullable": True},
                    "lastTickMs": {"type": "integer", "nullable": True},
                }},
            "population": {
                "type": "object", "nullable": True,
                "description": "multi-objective population search "
                               "(parallel/population.py; docs/search.md)"
                               ": last run's joint-scoring snapshot — "
                               "null when search.population=0",
                "properties": {
                    "size": {"type": "integer"},
                    "requested": {"type": "integer"},
                    "devices": {"type": "integer"},
                    "objective": {"type": "string"},
                    "winner": {"type": "integer"},
                    "winnerIsAnchor": {"type": "boolean"},
                    "paretoFrontSize": {"type": "integer"},
                    "paretoRanks": {"type": "array",
                                    "items": {"type": "integer"}},
                    "weightedScores": {"type": "array",
                                       "items": {"type": "number"}},
                    "movesPerMember": {"type": "array",
                                       "items": {"type": "integer"}},
                    "perGoalAcceptance": {"type": "array",
                                          "items": {"type": "array"}},
                    "survivorPerms": {"type": "array",
                                      "items": {"type": "array"}},
                }},
            "tuning": {
                "type": "object", "nullable": True,
                "description": "tuned-search-schedule store "
                               "(analyzer/tuning.py; docs/search.md): "
                               "per-shape-bucket SearchConfig overrides "
                               "+ tuner trial history — null when "
                               "search.tuning.enabled=false",
                "properties": {
                    "version": {"type": "integer"},
                    "path": {"type": "string"},
                    "buckets": {"type": "object"},
                }},
            "snapshot": {
                "type": "object", "nullable": True,
                "description": "crash-safe serving-state snapshot "
                               "(core/snapshot.py): write cadence + the "
                               "per-reason restore-refusal counters "
                               "(corrupt / version-skew / stale / "
                               "cluster-mismatch) an operator alerts on "
                               "— null when snapshot.path is unset",
                "properties": {
                    "path": {"type": "string"},
                    "intervalMs": {"type": "integer"},
                    "maxAgeMs": {"type": "integer", "nullable": True},
                    "writes": {"type": "integer"},
                    "writeFailures": {"type": "integer"},
                    "restores": {"type": "integer"},
                    "restoreFallbacks": {"type": "object"},
                    "lastWriteMs": {"type": "integer", "nullable": True},
                    "bytes": {"type": "integer", "nullable": True},
                }},
            "ha": {
                "type": "object",
                "description": "leader/standby role readout "
                               "(core/leader.py; also on every /state "
                               "response as ServerRole): the fencing "
                               "epoch is the monotonic token every "
                               "executor mutation is stamped under",
                "properties": {
                    "enabled": {"type": "boolean"},
                    "role": {"type": "string",
                             "enum": ["leader", "standby"]},
                    "identity": {"type": "string"},
                    "leaderId": {"type": "string", "nullable": True},
                    "fencingEpoch": {"type": "integer", "nullable": True},
                    "observedEpoch": {"type": "integer",
                                      "nullable": True},
                    "leaseUntilMs": {"type": "integer", "nullable": True},
                    "takeovers": {"type": "integer"},
                }},
        }},
    "FleetSummary": {
        "type": "object",
        "description": "per-cluster fleet readout (fleet/registry.py): "
                       "balance score = fraction of chain goals "
                       "satisfied, freshness = the member cache's SLO "
                       "view, risk = the batched N-1 sweep's verdict",
        "properties": {
            "enabled": {"type": "boolean"},
            "numClusters": {"type": "integer"},
            "ticks": {"type": "integer"},
            "lastTickMs": {"type": "integer", "nullable": True},
            "bucket": {"type": "object", "nullable": True},
            "lastDispatchMs": {"type": "number", "nullable": True},
            "clusters": {"type": "array", "items": {
                "type": "object",
                "properties": {
                    "clusterId": {"type": "string"},
                    "ready": {"type": "boolean"},
                    "generation": {"type": "integer", "nullable": True},
                    "balanceScore": {"type": "number"},
                    "violatedGoals": {"type": "array",
                                      "items": {"type": "string"}},
                    "violatedHardGoals": {"type": "array",
                                          "items": {"type": "string"}},
                    "numProposals": {"type": "integer"},
                    "numMoves": {"type": "integer"},
                    "staleModel": {"type": "boolean"},
                    "freshness": {"type": "object"},
                    "risk": {"type": "object", "nullable": True},
                    "lastError": {"type": "string", "nullable": True},
                }}},
        }},
}

_OPTIMIZATION_ENDPOINTS = {"rebalance", "add_broker", "remove_broker",
                           "fix_offline_replicas", "demote_broker",
                           "topic_configuration", "proposals"}


def openapi_spec(base_path: str = "/kafkacruisecontrol") -> dict:
    # Imported here (not at module top) to keep this module importable
    # standalone; server.py only loads openapi lazily, so no cycle either
    # way — but the endpoint behavior sets live in server.py.
    from .server import ASYNC_ENDPOINTS, NO_REVIEW_REQUIRED

    def _ref(name: str) -> dict:
        return {"content": {"application/json": {"schema": {
            "$ref": f"#/components/schemas/{name}"}}}}

    paths: dict[str, dict] = {}
    for name, (method, summary, extra) in ENDPOINTS.items():
        descriptions = {pname: desc for pname, _ptype, desc in extra}
        params = _declared_params(name, descriptions)
        ok: dict = {"description": "completed result (JSON; with "
                                   "json=false, a text/plain fixed-width "
                                   "table instead)"}
        if name in _OPTIMIZATION_ENDPOINTS:
            ok.update(_ref("OptimizationResult"))
        elif name == "simulate":
            ok.update(_ref("WhatIfReport"))
        elif name == "trace":
            ok.update(_ref("TraceEvents"))
        elif name == "devicestats":
            ok.update(_ref("DeviceStats"))
        elif name in ("fleet", "fleet_rebalance"):
            ok.update(_ref("FleetSummary"))
        elif name in ("forecast", "forecast_refresh"):
            ok.update(_ref("ForecastReport"))
        elif name == "history":
            ok.update(_ref("EventHistory"))
        # JSON is the documented default body (json defaults true): every
        # 200 advertises application/json — a typed $ref where one
        # exists, a generic object otherwise.
        ok.setdefault("content", {}).setdefault(
            "application/json", {"schema": {"type": "object"}})
        # json=false renders a plaintext table for the same 200 (ref the
        # response classes' writeOutputStream path).
        ok["content"]["text/plain"] = {"schema": {"type": "string"}}
        responses = {
            "200": ok,
            "400": {"description": "invalid parameters",
                    **_ref("ErrorResponse")},
        }
        # 202 only where it can actually happen, with the body it
        # actually carries: async endpoints long-poll (ProgressResponse);
        # reviewable POSTs may park (ReviewResult); sync GETs never 202.
        is_async = name in ASYNC_ENDPOINTS
        reviewable = method == "post" and name not in NO_REVIEW_REQUIRED
        if is_async and reviewable:
            responses["202"] = {
                "description": "accepted (poll with the User-Task-ID "
                               "header) or parked for review (two-step "
                               "verification)",
                "content": {"application/json": {"schema": {"oneOf": [
                    {"$ref": "#/components/schemas/ProgressResponse"},
                    {"$ref": "#/components/schemas/ReviewResult"}]}}}}
        elif is_async:
            responses["202"] = {
                "description": "accepted; poll with the User-Task-ID "
                               "header",
                **_ref("ProgressResponse")}
        elif reviewable:
            responses["202"] = {
                "description": "parked for review (two-step "
                               "verification)",
                **_ref("ReviewResult")}
        if is_async:
            # Task-capacity pushback (UserTaskManager overflow): back
            # off and retry. Async endpoints only — sync requests never
            # enter the task manager.
            responses["429"] = {
                "description": "too many active user tasks; back off "
                               "and retry",
                **_ref("ErrorResponse")}
        op = {
            "summary": summary,
            "operationId": name,
            "parameters": params,
            "responses": responses,
        }
        paths[f"{base_path}/{name}"] = {method: op}
    return {
        "openapi": "3.0.3",
        "info": {"title": "cruise-control-tpu",
                 "description": "TPU-native Cruise Control REST API "
                                "(reference parity: CruiseControlEndPoint)",
                 "version": "2.0"},
        "paths": paths,
        "components": {
            "schemas": _SCHEMAS,
            "securitySchemes": {
                "basicAuth": {"type": "http", "scheme": "basic"},
                "bearerAuth": {"type": "http", "scheme": "bearer",
                               "bearerFormat": "JWT"},
            }},
    }
