"""OpenAPI 3 spec for the REST surface.

The reference assembles its spec from ``src/main/resources/yaml/base.yaml``
plus one yaml per endpoint (23 files under ``yaml/endpoints/``) and serves
swagger-ui from ``webroot/``. Here the spec is generated from the same
parameter tables the dispatcher uses, so it cannot drift from the server,
and is served as JSON at ``GET /kafkacruisecontrol/openapi``.
"""

from __future__ import annotations

_COMMON_ASYNC_PARAMS = [
    ("dryrun", "boolean", "compute proposals only, do not execute"),
    ("goals", "string", "comma-separated goal class names to run"),
    ("kafka_assigner", "boolean",
     "use the kafka-assigner emulation goal set"),
    ("excluded_topics", "string", "comma-separated topics to exclude"),
    ("fast_mode", "boolean", "reduced-effort search"),
    ("exclude_brokers_for_leadership", "string", "comma-separated ids"),
    ("exclude_brokers_for_replica_move", "string", "comma-separated ids"),
    ("destination_broker_ids", "string", "comma-separated ids"),
    ("ignore_proposal_cache", "boolean", "bypass the precompute cache"),
    ("get_response_timeout_s", "number",
     "long-poll timeout before a 202 progress response"),
    ("review_id", "integer", "approved review id (two-step verification)"),
]

#: endpoint -> (method, summary, extra params)
ENDPOINTS: dict[str, tuple[str, str, list[tuple[str, str, str]]]] = {
    "state": ("get", "Monitor/executor/analyzer/anomaly-detector state",
              [("substates", "string", "comma-separated subset")]),
    "load": ("get", "Per-broker load snapshot", []),
    "partition_load": ("get", "Per-partition resource load, sorted",
                       [("resource", "string", "CPU|NW_IN|NW_OUT|DISK"),
                        ("start", "integer", "first entry"),
                        ("entries", "integer", "max entries")]),
    "proposals": ("get", "Cached or freshly computed rebalance proposals",
                  [("ignore_proposal_cache", "boolean", "recompute")]),
    "kafka_cluster_state": ("get", "Kafka-level partition/replica state", []),
    "user_tasks": ("get", "Recent/active async user tasks", []),
    "review_board": ("get", "Two-step-verification review queue", []),
    "permissions": ("get", "Roles of the authenticated principal", []),
    "bootstrap": ("get", "Replay historic samples into the monitor",
                  [("start", "integer", "epoch ms"),
                   ("end", "integer", "epoch ms")]),
    "train": ("get", "Fit the (bytes-in, bytes-out) -> CPU regression", []),
    "rebalance": ("post", "Compute and optionally execute a rebalance",
                  _COMMON_ASYNC_PARAMS),
    "add_broker": ("post", "Move load onto new brokers",
                   [("brokerid", "string", "comma-separated ids"),
                    *_COMMON_ASYNC_PARAMS]),
    "remove_broker": ("post", "Drain brokers before decommission",
                      [("brokerid", "string", "comma-separated ids"),
                       *_COMMON_ASYNC_PARAMS]),
    "fix_offline_replicas": ("post", "Move offline replicas to live brokers",
                             _COMMON_ASYNC_PARAMS),
    "demote_broker": ("post", "Move leadership off brokers",
                      [("brokerid", "string", "comma-separated ids"),
                       *_COMMON_ASYNC_PARAMS]),
    "topic_configuration": ("post", "Change topic replication factor",
                            [("topic", "string", "topic name or pattern"),
                             ("replication_factor", "integer", "target RF"),
                             *_COMMON_ASYNC_PARAMS]),
    "rightsize": ("post", "Provisioner-driven cluster rightsizing", []),
    "remove_disks": ("post", "Drain specific log dirs",
                     [("brokerid_and_logdirs", "string",
                       "<id>-<logdir>[,...]"), *_COMMON_ASYNC_PARAMS]),
    "stop_proposal_execution": ("post", "Stop the ongoing execution", []),
    "pause_sampling": ("post", "Pause metric sampling",
                       [("reason", "string", "audit note")]),
    "resume_sampling": ("post", "Resume metric sampling",
                        [("reason", "string", "audit note")]),
    "admin": ("post", "Runtime toggles (self-healing, concurrency)",
              [("disable_self_healing_for", "string", "anomaly types"),
               ("enable_self_healing_for", "string", "anomaly types"),
               ("concurrent_partition_movements_per_broker", "integer", ""),
               ("concurrent_leader_movements", "integer", "")]),
    "review": ("post", "Approve/discard parked requests",
               [("approve", "string", "comma-separated review ids"),
                ("discard", "string", "comma-separated review ids")]),
}


def openapi_spec(base_path: str = "/kafkacruisecontrol") -> dict:
    paths: dict[str, dict] = {}
    for name, (method, summary, extra) in ENDPOINTS.items():
        params = [{
            "name": pname, "in": "query", "required": False,
            "description": desc, "schema": {"type": ptype},
        } for pname, ptype, desc in extra]
        op = {
            "summary": summary,
            "operationId": name,
            "parameters": params,
            "responses": {
                "200": {"description": "completed result (JSON)"},
                "202": {"description":
                        "accepted; poll with the User-Task-ID header"},
            },
        }
        if method == "post":
            op["responses"]["202"]["description"] += (
                " or parked for review (two-step verification)")
        paths[f"{base_path}/{name}"] = {method: op}
    return {
        "openapi": "3.0.3",
        "info": {"title": "cruise-control-tpu",
                 "description": "TPU-native Cruise Control REST API "
                                "(reference parity: CruiseControlEndPoint)",
                 "version": "2.0"},
        "paths": paths,
        "components": {"securitySchemes": {
            "basicAuth": {"type": "http", "scheme": "basic"},
            "bearerAuth": {"type": "http", "scheme": "bearer",
                           "bearerFormat": "JWT"},
        }},
    }
