"""Event-loop web engine — the second server implementation (ref the
reference's ``KafkaCruiseControlVertxApp`` next to the Jetty servlet app;
both engines there share one request-handling layer, as both engines here
share :func:`~cruise_control_tpu.api.server.route_request`).

Architecture mirrors the Vert.x model on asyncio: a single event loop
accepts connections and parses HTTP/1.1; the blocking application work
(goal optimization, monitor reads) is handed to a worker thread pool
(``run_in_executor`` — Vert.x's ``executeBlocking``) so a long rebalance
never stalls the accept loop. The loop runs in a daemon thread so the
engine exposes the same synchronous ``start()/stop()/port`` surface as the
threading engine and the two are drop-in interchangeable behind
``webserver.engine``.
"""

from __future__ import annotations

import asyncio
import threading
from concurrent.futures import ThreadPoolExecutor

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 16 * 1024 * 1024


class AsyncHttpEngine:
    def __init__(self, app, host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        self.app = app
        self.host = host
        self._requested_port = port
        self._ssl = ssl_context
        self._loop: asyncio.AbstractEventLoop | None = None
        self._stop: asyncio.Event | None = None
        self._thread: threading.Thread | None = None
        self._bound = threading.Event()
        self._port: int | None = None
        self._bind_error: BaseException | None = None
        # Own worker pool (not the loop's default executor): asyncio.run's
        # shutdown would otherwise block on in-flight blocking requests,
        # hanging stop() behind a long rebalance. shutdown(wait=False)
        # gives the same semantics as the threading engine's shutdown —
        # in-flight handlers finish on daemon threads.
        self._pool = ThreadPoolExecutor(max_workers=32,
                                        thread_name_prefix="cc-aio-worker")

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cc-http-aio")
        self._thread.start()
        if not self._bound.wait(timeout=30):
            raise RuntimeError("asyncio web engine failed to bind")
        if self._bind_error is not None:
            raise self._bind_error

    def stop(self) -> None:
        if self._loop is not None and self._stop is not None:
            self._loop.call_soon_threadsafe(self._stop.set)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._pool.shutdown(wait=False, cancel_futures=True)

    @property
    def port(self) -> int:
        self._bound.wait(timeout=30)
        if self._port is None:
            raise RuntimeError("asyncio web engine is not bound")
        return self._port

    # ------------------------------------------------------------ internals
    def _run(self) -> None:
        asyncio.run(self._main())

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        try:
            server = await asyncio.start_server(
                self._serve_client, self.host, self._requested_port,
                ssl=self._ssl)
        except BaseException as e:
            # Surface EADDRINUSE etc. from start() instead of a silent
            # daemon-thread death + 30 s timeout.
            self._bind_error = e
            self._bound.set()
            raise
        self._port = server.sockets[0].getsockname()[1]
        self._bound.set()
        async with server:
            await self._stop.wait()

    async def _serve_client(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        from .server import route_request
        peer = (writer.get_extra_info("peername") or ("?",))[0]
        try:
            while True:
                request_line = await reader.readline()
                if not request_line:
                    return
                parts = request_line.decode("latin-1").split()
                if len(parts) < 3:
                    return
                method, raw_path = parts[0].upper(), parts[1]
                headers: dict[str, str] = {}
                total = 0
                while True:
                    line = await reader.readline()
                    total += len(line)
                    if total > MAX_HEADER_BYTES:
                        return
                    if line in (b"\r\n", b"\n", b""):
                        break
                    name, _, value = line.decode("latin-1").partition(":")
                    headers[name.strip().lower()] = value.strip()
                error = None
                try:
                    length = int(headers.get("content-length", 0) or 0)
                except ValueError:
                    error = (400, b'{"version": 1, "errorMessage": '
                                  b'"bad Content-Length"}')
                    length = 0
                if error is None and "chunked" in headers.get(
                        "transfer-encoding", "").lower():
                    # No chunked decoding: mis-reading the body would
                    # corrupt the keep-alive stream — refuse loudly.
                    error = (411, b'{"version": 1, "errorMessage": '
                                  b'"Length Required (chunked transfer '
                                  b'encoding is not supported)"}')
                if length > MAX_BODY_BYTES:
                    return
                body = await reader.readexactly(length) if length else b""
                if error is not None:
                    status, data = error
                    ctype, extra = "application/json", {}
                elif method not in ("GET", "POST", "OPTIONS"):
                    status, ctype, data, extra = 405, "application/json", \
                        b'{"version": 1, "errorMessage": "bad method"}', {}
                else:
                    # Blocking application work off the event loop
                    # (Vert.x executeBlocking analog).
                    status, ctype, data, extra = \
                        await asyncio.get_running_loop().run_in_executor(
                            self._pool, route_request, self.app, method,
                            raw_path, headers, body, peer)
                hdrs = [f"HTTP/1.1 {status} CC",
                        f"Content-Type: {ctype}",
                        f"Content-Length: {len(data)}"]
                hdrs += [f"{k}: {v}" for k, v in extra.items()]
                keep = headers.get("connection", "keep-alive").lower()
                hdrs.append(f"Connection: {keep}")
                writer.write(("\r\n".join(hdrs) + "\r\n\r\n").encode("latin-1"))
                writer.write(data)
                await writer.drain()
                if self.app.accesslog:
                    from .server import _ACCESS_LOG
                    _ACCESS_LOG.info("%s %s %s -> %d", peer, method,
                                     raw_path, status)
                if keep == "close":
                    return
        except (asyncio.IncompleteReadError, ConnectionError):
            return
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass
