"""Write-path admission control: per-principal token-bucket rate limits.

The serving plane's POST surface used to take every request straight
into the task queues — under a flood the only backpressure was the
active-task cap, shared across every caller, so one noisy principal
could starve the rest and the operator had no per-source throttle at
all. :class:`AdmissionController` sits between ``check_access`` (which
resolves the :class:`~cruise_control_tpu.api.security.Principal`) and
dispatch: every POST draws one token from its principal's bucket, and an
empty bucket answers **429 + ``Retry-After``** (the seconds until the
next token — shedding is an instruction to back off, never a 5xx).

Buckets refill continuously at ``rate_per_s`` up to ``burst``; the
bucket map is LRU-bounded (``max_principals``) so an attacker minting
principal names cannot grow host memory. Everything is metered under the
``Admission.*`` sensor group — throttle rate, admitted count, live
principal count — so a shedding tier is visible on ``/metrics`` before
users notice.

Read paths (GET) are never admission-gated: reads scale through the
render cache and the replica tier (core/replication.py), writes through
this throttle + the bounded task queues (api/tasks.py).
"""

from __future__ import annotations

import math
import threading
import time as _time
from collections import OrderedDict

#: sensor group for the admission series (``Admission.*``).
ADMISSION_SENSOR = "Admission"


class AdmissionLimitError(Exception):
    """A principal's token bucket is empty: the server maps this to
    429 with ``Retry-After: retry_after_s``."""

    def __init__(self, message: str, *, retry_after_s: int,
                 principal: str) -> None:
        super().__init__(message)
        self.retry_after_s = max(1, int(retry_after_s))
        self.principal = principal


class _Bucket:
    """One principal's continuously-refilling token bucket."""

    __slots__ = ("tokens", "stamp_ms")

    def __init__(self, burst: float, now_ms: int) -> None:
        self.tokens = float(burst)
        self.stamp_ms = int(now_ms)

    def take(self, now_ms: int, rate_per_s: float,
             burst: float) -> float:
        """Draw one token. Returns 0.0 on admission, else the seconds
        until a token will be available."""
        elapsed_s = max(0, now_ms - self.stamp_ms) / 1000.0
        self.tokens = min(burst, self.tokens + elapsed_s * rate_per_s)
        self.stamp_ms = int(now_ms)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / rate_per_s


class AdmissionController:
    """Per-principal write admission for one serving process.

    Thread-safe; shared by every server thread. ``now_ms`` is injectable
    for deterministic tests (defaults to wall clock)."""

    def __init__(self, *, rate_per_s: float = 5.0, burst: int = 10,
                 max_principals: int = 1024, now_ms=None,
                 registry=None) -> None:
        from ..core.sensors import MetricRegistry
        if rate_per_s <= 0:
            raise ValueError("admission rate must be positive")
        self.rate_per_s = float(rate_per_s)
        self.burst = float(max(1, burst))
        self.max_principals = int(max_principals)
        self._now_ms = now_ms or (lambda: int(_time.time() * 1000))
        self._lock = threading.Lock()
        #: principal name -> bucket, LRU-evicted at max_principals
        self._buckets: OrderedDict[str, _Bucket] = OrderedDict()
        self.registry = registry or MetricRegistry()
        name = MetricRegistry.name
        g = ADMISSION_SENSOR
        self._admitted = self.registry.counter(name(g, "admitted"))
        self._throttled = self.registry.meter(name(g, "throttled-rate"))
        self.registry.gauge(name(g, "principals"),
                            lambda: len(self._buckets))

    def admit(self, principal: str, now_ms: int | None = None) -> None:
        """Draw one token for ``principal`` or raise
        :class:`AdmissionLimitError` with the back-off hint. One bucket
        per principal: a flooding caller exhausts only its own budget —
        everyone else's tokens are untouched."""
        now = int(now_ms if now_ms is not None else self._now_ms())
        with self._lock:
            bucket = self._buckets.get(principal)
            if bucket is None:
                bucket = _Bucket(self.burst, now)
                self._buckets[principal] = bucket
                while len(self._buckets) > self.max_principals:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(principal)
            wait_s = bucket.take(now, self.rate_per_s, self.burst)
        if wait_s > 0:
            self._throttled.mark()
            raise AdmissionLimitError(
                f"principal {principal!r} exceeded the write admission "
                f"rate ({self.rate_per_s:g}/s, burst {self.burst:g})",
                retry_after_s=math.ceil(wait_s), principal=principal)
        self._admitted.inc()

    def to_json(self) -> dict:
        with self._lock:
            principals = len(self._buckets)
        return {
            "ratePerS": self.rate_per_s,
            "burst": self.burst,
            "principals": principals,
            "admitted": self._admitted.count,
            "throttled": self._throttled.count,
        }
