"""The product facade: one object wiring monitor -> analyzer -> executor.

Rebuild of ``KafkaCruiseControl.java:78`` (constructor wiring ``:112-129``,
``startUp()`` ``:221-227``). Every REST endpoint's business logic lives
here as a synchronous method the user-task pool invokes; the HTTP layer
only parses parameters and serializes results.
"""

from __future__ import annotations

import fnmatch
import json
import logging
import threading
import time as _time

import numpy as np

from ..analyzer import (OptimizationOptions, SearchConfig, TpuGoalOptimizer,
                        goals_by_name)
from ..analyzer.optimizer import OptimizerResult
from ..executor import (Executor, ExecutorConfig, OngoingExecutionError)
from ..model.cpu_regression import LinearRegressionModelParameters
from ..model.flat import (broker_replica_counts, broker_leader_counts,
                          broker_utilization)
from ..model.stats import stats_summary
from ..monitor import (LoadMonitor, LoadMonitorTaskRunner,
                       ModelCompletenessRequirements)
from ..core.metricdef import BrokerMetric
from ..core.resources import Resource
from ..core.retry import RetryPolicy
from ..executor.kafka_admin import RETRYABLE_ADMIN_ERRORS
from .precompute import ProposalCache
from .progress import OperationProgress

LOG = logging.getLogger(__name__)


class KafkaCruiseControl:
    """ref KafkaCruiseControl.java:78."""

    def __init__(self, admin, monitor: LoadMonitor,
                 task_runner: LoadMonitorTaskRunner | None = None,
                 optimizer: TpuGoalOptimizer | None = None,
                 executor: Executor | None = None,
                 detector=None,
                 options_generator=None,
                 cpu_model: LinearRegressionModelParameters | None = None,
                 now_ms=None, admin_retry: RetryPolicy | None = None,
                 sleep_ms=None, cluster_id: str | None = None) -> None:
        self.admin = admin
        self.monitor = monitor
        self.task_runner = task_runner
        self.optimizer = optimizer or TpuGoalOptimizer()
        self.executor = executor or Executor(admin)
        self.detector = detector
        # OptimizationOptionsGenerator plugin (ref
        # DefaultOptimizationOptionsGenerator), installed on the optimizer
        # — the single source of truth — so the proposal cache and
        # detectors, which call optimize() directly, go through it too.
        if options_generator is not None:
            self.optimizer.options_generator = options_generator
        #: goal names the self-healing fix paths optimize with (ref
        #: self.healing.goals; None/empty = the default chain). The
        #: anomaly fix() methods read this; serve.py wires it from config
        #: after validating it covers the registered hard goals (the
        #: reference's startup sanity check).
        self.self_healing_goals: list[str] | None = None
        #: ref replication.factor.self.healing.skip.rack.awareness.check:
        #: RF self-healing waives the rack-awareness audit when set
        #: (clusters without reliable rack metadata).
        self.rf_self_healing_skip_rack_check: bool = False
        self._now_ms = now_ms or (lambda: int(_time.time() * 1000))
        #: shared backoff policy for the facade's direct admin reads —
        #: one transient AdminTimeoutError must not fail a whole REST
        #: request (the executor carries its own copy for write paths).
        #: serve.py wires it from the admin.retry.* keys; the chaos
        #: harness passes the engine's sleep so retries stay on the
        #: simulated clock.
        self.admin_retry = admin_retry or RetryPolicy()
        self._admin_sleep_ms = sleep_ms
        #: opt-out for the stale-model execution gate: when True,
        #: non-dryrun operations may act on stale-served models (see
        #: StaleClusterModelError; operators who prefer availability
        #: over topology freshness during sample outages)
        self.allow_stale_execution = False
        #: this stack's cluster identity (fleet.cluster.id when the fleet
        #: layer is on): scopes the proposal cache so a fleet tick can
        #: never serve another member's proposals through this facade.
        self.cluster_id = cluster_id
        self.proposal_cache = ProposalCache(monitor, self.optimizer,
                                            now_ms=self._now_ms,
                                            cache_id=cluster_id)
        #: fleet registry (fleet/registry.py) when the fleet control
        #: plane is enabled — serves /fleet and /fleet/rebalance and the
        #: fleet section of /devicestats. None = single-cluster mode.
        self.fleet = None
        #: what-if scenario engine scoring hypothetical topologies with
        #: the SAME goal chain the optimizer serves — /simulate and the
        #: resilience detector share its compiled sweep programs.
        from ..whatif import WhatIfEngine
        self.whatif = WhatIfEngine(
            goals=self.optimizer.goals,
            constraint=self.optimizer.constraint,
            tracer=self.optimizer.tracer,
            collector=self.optimizer.collector,
            mesh=self.optimizer.mesh,
            # Scenario re-pads must land on the same shape buckets the
            # monitor builds with, or BrokerAdd/TopicAdd growth compiles
            # off-bucket sweep variants.
            partition_pad_multiple=monitor.config.partition_pad_multiple,
            broker_pad_multiple=monitor.config.broker_pad_multiple)
        #: forecast engine (forecast/engine.py): fits per-topic load
        #: trajectories from the monitor's window history and scores
        #: them through the SAME what-if engine — /forecast, the
        #: capacity-forecast detector and the ``forecast`` scenario
        #: source of /simulate all share this one instance (one fit,
        #: one compiled sweep program set). serve.py reconfigures it
        #: from the forecast.* keys and wires the persistence store.
        from ..forecast import ForecastEngine
        self.forecast = ForecastEngine(
            monitor, self.whatif, tracer=self.optimizer.tracer,
            collector=self.optimizer.collector, now_ms=self._now_ms)
        # Shared with the metrics processor so a TRAIN-fitted regression
        # feeds CPU estimation for samples that lack broker CPU.
        self.cpu_model = cpu_model or LinearRegressionModelParameters()
        self._lock = threading.RLock()
        #: goal-name tuple -> memoized goal-scoped optimizer (see
        #: :meth:`_optimizer_for`); insertion-ordered for LRU eviction.
        self._goal_optimizers: dict[tuple, TpuGoalOptimizer] = {}
        #: merged self-metric view over the wired subsystems (each owns a
        #: private registry so independent stacks in one process never share
        #: sensor state — ref KafkaCruiseControl.java:112 threading one
        #: dropwizardMetricRegistry through every constructor; here the
        #: facade is the aggregation point instead). Resolved at scrape
        #: time so a detector attached after construction is included.
        from ..core.sensors import CompositeRegistry, MetricRegistry

        #: extra per-layer registries merged into the scrape view (the
        #: web app appends its servlet-request sensors here).
        self.extra_registries: list = []

        # Facade-owned sensors: the retried-admin-read meter must be
        # scrape-visible like the executor's (silent degradation is the
        # failure mode the robustness layer exists to prevent).
        self._own_registry = MetricRegistry()
        self._admin_retries = self._own_registry.meter(
            MetricRegistry.name("KafkaCruiseControl", "admin-retry-rate"))
        self.extra_registries.append(self._own_registry)

        #: span tracer serving /trace and /state?substates=tracing — the
        #: optimizer's tracer (the process default unless overridden), so
        #: every subsystem wired with the default shares one buffer and a
        #: single dump covers the whole monitor→model→optimize→execute
        #: loop. Its Span.* timers join the scrape view (CompositeRegistry
        #: dedupes by identity, so shared tracers emit once).
        self.tracer = self.optimizer.tracer
        self.extra_registries.append(self.tracer.registry)

        #: control-plane flight recorder (core/events.py): the causal
        #: decision journal every subsystem records into — serves
        #: /history, rides /trace as instant events, streams to read
        #: replicas, and persists through the snapshot payload. Always
        #: constructed (appends are cheap and `enabled=False` no-ops
        #: them); serve.py reconfigures it from the events.* keys. Its
        #: EventJournal.* counters join the scrape view.
        from ..core.events import EventJournal
        self.journal = EventJournal(tracer=self.tracer,
                                    now_ms=self._now_ms)
        self.extra_registries.append(self.journal.registry)
        self.executor.journal = self.journal
        #: SLO burn-rate evaluator (core/slo.py), wired by serve.py from
        #: the slo.* keys; None = no SLO evaluation. ha_tick drives it
        #: so standbys (which run no detector loop) still evaluate the
        #: standby-staleness objective.
        self.slo = None
        #: (plan object, journal seq) pairs for the last few served
        #: plans — the propose→serve causality link (a cached entry's
        #: plan-selected event is recorded once, then every serve of
        #: that same result names it as cause).
        self._recent_plans: list = []
        #: journal seq last shipped on the replication stream — the
        #: publisher's delta cursor.
        self._streamed_journal_seq = 0
        #: id() of the last journaled population-stats dict (one
        #: population-winner event per optimize run, not per serve).
        self._journaled_pop_id = None

        #: device-runtime ledger serving /devicestats and the DeviceStats
        #: substate of /state — the optimizer's collector (the process
        #: default unless overridden), shared by every subsystem wired
        #: with the default, so one dump covers all compiled programs.
        #: Its DeviceRuntime.* sensors join the scrape view (identity-
        #: deduped like the tracer's).
        self.device_stats = self.optimizer.collector
        self.extra_registries.append(self.device_stats.registry)

        #: proposal-freshness sensors (ProposalCache.freshness-*-ms
        #: gauges + the SLO-breach meter) join the scrape view.
        self.extra_registries.append(self.proposal_cache.registry)

        #: Forecast.* sensors (fit/sweep timers, topics-fitted,
        #: backtest-mape, time-to-breach-ms gauges) join the scrape view.
        self.extra_registries.append(self.forecast.registry)

        #: startup pre-warm thread (see :meth:`start_prewarm`).
        self._prewarm_thread: threading.Thread | None = None
        self._prewarm_stop = threading.Event()

        #: crash-safe snapshot manager (core/snapshot.py) — wire via
        #: :meth:`attach_snapshotter`. None = snapshots disabled.
        self.snapshotter = None
        #: leader elector (core/leader.py) — wire via
        #: :meth:`attach_elector`. None = single-process mode: this
        #: process is unconditionally the leader.
        self.elector = None
        #: snapshot-delta streaming session (core/replication.py) — wire
        #: via :meth:`attach_replication_channel`. None = the standby
        #: refreshes by snapshot mtime-poll (the pre-streaming path).
        self.replication = None
        #: proposal-cache (generation, seq) last shipped on the stream —
        #: the publisher's dedup key, so the full cached result is only
        #: re-serialized when the entry actually moved.
        self._streamed_proposals_key = None
        #: device move scheduler (executor/schedule.py), built lazily on
        #: the first execution with ``executor.device.scheduling`` on —
        #: shares the optimizer's collector/tracer so its programs ride
        #: the same recompile gate and span view.
        self._move_scheduler = None
        #: last forecast-deferral outcome (counts + topic sets) for the
        #: /devicestats executor section; None until a deferral-enabled
        #: execution ran.
        self._last_deferral: dict | None = None

        def _registries():
            regs = [self.optimizer.registry, self.monitor.registry,
                    self.executor.registry, self.whatif.registry]
            if self.detector is not None and hasattr(self.detector,
                                                     "registry"):
                regs.append(self.detector.registry)
            fetcher = getattr(self.task_runner, "fetcher", None)
            if fetcher is not None and hasattr(fetcher, "registry"):
                regs.append(fetcher.registry)
            if self.fleet is not None:
                # Member registries arrive cluster-namespaced (the
                # LOCAL cluster's monitor is deduped by identity above;
                # remote members render as cc_<cluster>_*).
                from ..core.sensors import NamespacedRegistry as _NR
                regs.extend(r for r in self.fleet.scrape_registries()
                            if not isinstance(r, _NR)
                            or r.inner is not self.monitor.registry)
            return regs + list(self.extra_registries)

        self.registry = CompositeRegistry(_registries)

        #: serving-tier render cache (api/rendercache.py): per-endpoint
        #: immutable pre-serialized response snapshots keyed on the
        #: lock-free change counters (monitor generation, resident epoch,
        #: registry shape). Lives on the facade — both web engines route
        #: through it — and the precompute refresher tick re-publishes
        #: the auto-refresh set so hot entries stay warm.
        from .rendercache import RenderCache
        self.rendercache = RenderCache()
        self.extra_registries.append(self.rendercache.registry)
        self._register_render_endpoints()
        self.proposal_cache.on_tick.append(self.rendercache.refresh)

    def _register_render_endpoints(self) -> None:
        """Wire the read-tier endpoints into the render cache.

        Key model: ``base_key`` is the cheap lock-free triple (model
        generation, resident epoch, scrape-surface shape) every response
        body depends on; endpoints whose bytes can move without those
        counters (executor phase inside /state, live meter values inside
        /metrics) default to ``ttl_ms=0`` (cache OFF — tier-1 stacks and
        single-user CLIs always see fresh bytes) and are flipped to a
        ttl micro-cache by ``rendercache.enable()`` on serving/bench
        stacks. /proposals is exact: its body is a pure function of the
        published proposal-cache entry, so the (generation, entry seq)
        key alone bounds staleness and it caches everywhere."""
        from .rendercache import Uncacheable
        rc = self.rendercache

        def base_key() -> tuple:
            resident = getattr(self.monitor, "resident", None)
            return (self.monitor.generation,
                    resident.epoch if resident is not None else -1,
                    self.registry.mutation_count)

        def serving_entry():
            """Generation-valid entry — or, on a replication follower,
            the newest replicated entry: its age is policed by the
            bounded-staleness read gate, not by generation strictness
            (the follower's generation rides the stream ahead of the
            leader's last proposal export)."""
            e = self.proposal_cache.fast_entry()
            if e is None and self._follower_serving():
                e = self.proposal_cache.latest_entry()
            return e

        def proposals_key() -> tuple:
            e = serving_entry()
            if e is None:
                raise Uncacheable("proposal cache cold or stale")
            return (e.generation, e.seq)

        def proposals_payload() -> dict:
            e = serving_entry()
            if e is None:
                raise Uncacheable("proposal cache cold or stale")
            # The servlet response shape (server.py builds the same dict
            # on the uncached path); lazy import to dodge the cycle.
            from .server import _optimization_response
            return _optimization_response(e.result, None)

        rc.register("proposals", proposals_key, proposals_payload,
                    ttl_ms=None, plaintext=True, auto_refresh=True)
        rc.register("state", base_key, lambda: self.state(None),
                    ttl_ms=0, plaintext=True, auto_refresh=True)
        rc.register("kafka_cluster_state", base_key,
                    lambda: self.kafka_cluster_state(), ttl_ms=0,
                    plaintext=True)
        rc.register("load", base_key, lambda: self.load(), ttl_ms=0,
                    plaintext=True)
        rc.register("devicestats",
                    lambda: base_key() + (self.device_stats.cycle_seq,),
                    self.device_stats_json, ttl_ms=0, plaintext=True,
                    auto_refresh=True)
        rc.register("fleet", base_key, self.fleet_summary, ttl_ms=0,
                    plaintext=True)
        rc.register("forecast", base_key, self.forecast_json, ttl_ms=0,
                    plaintext=True)
        rc.register("metrics", lambda: (self.registry.mutation_count,),
                    self.registry.expose_text,
                    content_type="text/plain; version=0.0.4; charset=utf-8",
                    ttl_ms=0, raw=True)
        rc.register("trace", base_key,
                    lambda: json.dumps(self.trace_json()),
                    ttl_ms=0, raw=True)
        # /history is deliberately NOT render-cached: its filters are
        # per-request and the journal is already a lock-cheap ring read.

        def explorer_payload() -> str:
            from .openapi import api_explorer_html
            return api_explorer_html()

        rc.register("explorer", lambda: (), explorer_payload,
                    content_type="text/html; charset=utf-8",
                    ttl_ms=None, raw=True)

    def _admin_read(self, fn, *args):
        """Run a read-only admin RPC under the shared retry policy:
        transient timeouts back off and re-attempt (metered on the
        facade's `admin-retry-rate` and logged — silent degradation is
        the failure mode this PR exists to prevent), fatal errors surface
        on the first try."""
        def on_retry(attempt, delay_ms, exc):
            self._admin_retries.mark()
            LOG.warning(
                "facade admin read %s failed transiently (%s: %s); retry "
                "%d in %d ms", getattr(fn, "__name__", fn),
                type(exc).__name__, exc, attempt + 1, delay_ms)
        return self.admin_retry.call(fn, *args,
                                     retry_on=RETRYABLE_ADMIN_ERRORS,
                                     sleep_ms=self._admin_sleep_ms,
                                     now_ms=self._now_ms,
                                     on_retry=on_retry)

    # ----------------------------------------------------------- lifecycle
    def start_up(self, precompute_interval_s: float = 30.0,
                 start_precompute: bool = True,
                 skip_loading: bool = False,
                 freshness_target_ms: int = 0,
                 start_prewarm: bool = False,
                 precompute_watch_only: bool = False) -> None:
        """ref startUp() KafkaCruiseControl.java:221-227.
        ``skip_loading`` bypasses sample-store replay (ref
        skip.loading.samples). ``freshness_target_ms`` arms the proposal
        freshness SLO (proposals.freshness.target.ms; 0 = plain interval
        refresher); ``start_prewarm`` launches the background startup
        pre-warm (prewarm.on.start). ``precompute_watch_only`` keeps the
        freshness/breach accounting but never recomputes — the fleet
        mode, where the registry's batched tick refills the cache."""
        # Snapshot restore FIRST — before the refresher could race a
        # recompute and before prewarm: a restored resident model +
        # generation-valid cache means prewarm's model build rides the
        # resident buffers and the first /proposals is a cache read.
        if self.snapshotter is not None:
            self.restore_from_snapshot()
        if self.task_runner is not None and \
                self.task_runner.state.value == "NOT_STARTED":
            self.task_runner.start(self._now_ms(), skip_loading=skip_loading)
        if start_precompute:
            self.proposal_cache.start_refresher(
                precompute_interval_s, self._now_ms,
                freshness_target_ms=freshness_target_ms,
                watch_only=precompute_watch_only)
        if start_prewarm:
            self.start_prewarm()
        if self.detector is not None:
            self.detector.start_detection()

    def shutdown(self) -> None:
        if self.fleet is not None:
            self.fleet.stop()
        self.proposal_cache.stop()
        self._prewarm_stop.set()
        if self._prewarm_thread is not None:
            self._prewarm_thread.join(timeout=5)
            self._prewarm_thread = None
        if self.detector is not None:
            self.detector.stop_detection()
        # Clean shutdown: persist one final snapshot (the restart serves
        # warm from it) and hand leadership off immediately instead of
        # letting the lease run out under a standby.
        if self.snapshotter is not None and (self.elector is None
                                             or self.elector.is_leader()):
            self.snapshotter.write(self._now_ms(), self.snapshot_payload())
        if self.elector is not None:
            self.elector.resign(self._now_ms())

    def prewarm(self) -> dict:
        """Pre-warm the serving path's compiled programs: build one
        cluster model (the resident path's first full upload), compile
        the resident delta-ingest bucket, and AOT-compile the default
        goal chain for the model's shapes — all landing in the versioned
        persistent compilation cache (``.jax_cache/v<N>``), so
        steady-state metric-only cycles dispatch with ZERO compiles.
        Raises (NotEnoughValidWindows) while the monitor lacks sample
        history; :meth:`start_prewarm` retries in the background."""
        from ..utils.platform import enable_compilation_cache
        enable_compilation_cache()
        result = self.monitor.cluster_model(self._now_ms())
        resident = getattr(self.monitor, "resident", None)
        if resident is not None:
            resident.warmup()
        # The proposal cache's options select the chain the steady-state
        # refresher actually serves.
        self.optimizer.warmup(result.model, result.metadata,
                              self.proposal_cache.options)
        return {"status": "warmed", "generation": result.generation}

    def start_prewarm(self, poll_interval_s: float = 2.0) -> None:
        """Background startup pre-warm: retry :meth:`prewarm` until the
        monitor has enough sample history, then exit. Daemon thread;
        stopped by :meth:`shutdown`."""
        if self._prewarm_thread is not None and \
                self._prewarm_thread.is_alive():
            return
        # Fresh stop event per start: an orphan loop from a previous
        # start (shutdown's join timed out mid-prewarm) still holds its
        # own — already set — event and exits at its next wait, so a
        # restart can never leave two loops compiling concurrently.
        stop = threading.Event()
        self._prewarm_stop = stop

        def loop():
            from ..monitor import NotEnoughValidWindowsException
            logged_unexpected = False
            # Every failed attempt pays the model build's admin describe
            # sweeps before it can raise — back off exponentially (cap
            # 60s) so an hours-long warm-in (1h windows) doesn't hammer
            # the cluster admin endpoints every 2s.
            delay = poll_interval_s
            while not stop.wait(delay):
                try:
                    self.prewarm()
                    LOG.info("startup pre-warm complete: serving path "
                             "compiled ahead of first request")
                    return
                except NotEnoughValidWindowsException:
                    pass       # monitor still warming in: retry, backed off
                except Exception:
                    # Non-transient failures must be visible (a silently
                    # cold serving path defeats prewarm.on.start); log the
                    # first with traceback, keep retrying quietly.
                    if not logged_unexpected:
                        logged_unexpected = True
                        LOG.warning("startup pre-warm failed (will keep "
                                    "retrying, backed off)", exc_info=True)
                delay = min(delay * 2, 60.0)

        self._prewarm_thread = threading.Thread(target=loop, daemon=True,
                                                name="startup-prewarm")
        self._prewarm_thread.start()

    # ------------------------------------------------- snapshot + HA role
    def attach_snapshotter(self, snapshotter) -> None:
        """Wire a :class:`~cruise_control_tpu.core.snapshot.
        SnapshotManager`: its ``Snapshot.*`` sensors join the scrape
        view; ``start_up`` restores from it, ``ha_tick`` writes on
        cadence, ``shutdown`` writes a final snapshot."""
        self.snapshotter = snapshotter
        snapshotter.journal = self.journal
        self.extra_registries.append(snapshotter.registry)

    def attach_elector(self, elector) -> None:
        """Wire a :class:`~cruise_control_tpu.core.leader.LeaderElector`:
        one leader owns optimization + execution, this process's
        executor is fenced under its epoch, and the ``HA.*`` sensors
        join the scrape view."""
        self.elector = elector
        self.executor.fence = elector
        elector.journal = self.journal
        self.extra_registries.append(elector.registry)

    def attach_replication_channel(self, channel, *, node_id: str,
                                   max_staleness_ms: int = 5_000,
                                   poll_wait_ms: int = 0,
                                   coalesce_ms: int = 0,
                                   ledger: list | None = None):
        """Wire snapshot-delta streaming over ``channel`` (a
        :class:`~cruise_control_tpu.core.replication.ReplicationChannel`
        or an :class:`~cruise_control_tpu.core.replication.
        HttpReplicationClient`): ``ha_tick`` publishes frames when
        leading and follows the stream when standing by (replacing the
        snapshot mtime-poll), replica reads gate on the bounded-
        staleness contract (:meth:`read_refusal`), and the
        ``Replication.*`` sensors join the scrape view. Returns the
        session."""
        from ..core.replication import ReplicationSession
        resident = getattr(self.monitor, "resident", None)
        if resident is not None:
            resident.enable_delta_capture()
        # Follower serving path: with no local sample flow, model reads
        # serve the stream-fed resident state (stale-flagged — the
        # execution gate still refuses to act on it).
        if hasattr(self.monitor, "serve_from_resident"):
            self.monitor.serve_from_resident = True
        session = ReplicationSession(
            node_id=node_id, channel=channel,
            clocks=self._replication_clocks,
            build_frame=self._build_replication_frame,
            fencing_epoch=lambda: (self.elector.epoch
                                   if self.elector is not None else 0),
            apply_frame=self._apply_replication_frame,
            resync=self._replication_resync,
            on_fence=(self.elector.observe_epoch_floor
                      if self.elector is not None else None),
            max_staleness_ms=max_staleness_ms,
            poll_wait_ms=poll_wait_ms, coalesce_ms=coalesce_ms,
            ledger=ledger, now_ms=self._now_ms)
        session.journal = self.journal
        if self.journal.node is None:
            # Journal rows need a node identity the moment this process
            # joins a multi-process topology (replica-vs-leader
            # provenance on /history); serve.py may have set one already.
            self.journal.node = node_id
        self.replication = session
        self.extra_registries.append(session.registry)
        if getattr(channel, "registry", None) is not None \
                and channel.registry is not session.registry:
            self.extra_registries.append(channel.registry)
        return session

    def _replication_clocks(self) -> dict:
        """The logical-clock tuple the stream is keyed on — exactly the
        counters the render cache keys already derive from, so a replica
        that applied a frame serves byte-identical cached GETs."""
        resident = getattr(self.monitor, "resident", None)
        entry = self.proposal_cache.fast_entry()
        return {
            "generation": self.monitor.generation,
            "residentEpoch": (resident.epoch
                              if resident is not None else -1),
            "residentIngest": (resident.ingest_seq
                               if resident is not None else -1),
            "mutationCount": self.registry.mutation_count,
            "proposalSeq": (entry.seq if entry is not None else None),
            # Journal-only decisions (a refusal, a heal outcome) must
            # still publish a frame — replicas serve /history locally.
            "journalSeq": self.journal.last_seq,
        }

    def _build_replication_frame(self) -> dict | None:
        """Leader-side frame body: the resident delta entries captured
        since the last publish, the proposal-cache export when its entry
        moved, and the monitor generation. ``None`` when there is
        genuinely nothing to say."""
        resident = getattr(self.monitor, "resident", None)
        body = None
        if resident is not None:
            entries, overflow = resident.drain_deltas()
            if overflow:
                # Capture overflow lost deltas: ship a structural marker
                # so followers resync instead of silently diverging.
                entries = [{"structural": True,
                            "ingest": resident.ingest_seq,
                            "epoch": resident.epoch}]
            if entries:
                body = {"entries": entries, "epoch": resident.epoch,
                        "ingest": resident.ingest_seq}
        proposals = None
        entry = self.proposal_cache.fast_entry()
        key = ((entry.generation, entry.seq)
               if entry is not None else None)
        if key is not None and key != self._streamed_proposals_key:
            proposals = self.proposal_cache.export_state()
            self._streamed_proposals_key = key
        # Journal delta since the last shipped seq: replicas apply the
        # leader's decisions into their own ring and serve /history
        # locally (fence-checked with the rest of the frame).
        journal_delta = self.journal.export_delta(
            self._streamed_journal_seq)
        if journal_delta:
            self._streamed_journal_seq = max(
                e["seq"] for e in journal_delta)
        # Clock-only movement (generation bump, registry shape) still
        # publishes: followers key their render caches off the counters.
        return {
            "clusterId": self.cluster_id,
            "generation": self.monitor.generation,
            "resident": body,
            "proposalCache": proposals,
            "journal": journal_delta or None,
        }

    def _apply_replication_frame(self, frame: dict) -> str:
        """Follower-side domain apply. Gap-safe by construction: a
        delta entry that is not contiguously applicable (structural
        marker, epoch bump, ingest mismatch) answers ``"resync"`` and
        the session falls back to the full snapshot."""
        if frame.get("clusterId") not in (None, self.cluster_id):
            return "skipped"      # another cluster's stream — never apply
        applied = False
        resident = getattr(self.monitor, "resident", None)
        body = frame.get("resident")
        if body is not None and resident is not None:
            for entry in body.get("entries", ()):
                if int(entry.get("ingest", 0)) <= resident.ingest_seq:
                    continue      # already covered by the snapshot
                if not resident.apply_delta(entry):
                    return "resync"
                applied = True
        generation = frame.get("generation")
        if generation is not None \
                and generation > self.monitor.generation:
            self.monitor.seed_generation(generation)
            applied = True
        proposals = frame.get("proposalCache")
        if proposals is not None:
            self.proposal_cache.restore_state(proposals)
            applied = True
        journal_delta = frame.get("journal")
        if journal_delta:
            if self.journal.apply_remote(
                    journal_delta, source_node=frame.get("node")):
                applied = True
        return "applied" if applied else "skipped"

    def _replication_resync(self) -> int | None:
        """Full-snapshot bootstrap/resync for the stream follower.
        Returns the leader-clock ms the restored state is fresh as of,
        or None when no newer snapshot was restorable (the session stays
        in SYNCING/RESYNC and retries next tick)."""
        if self.snapshotter is None:
            return None
        now = self._now_ms()
        if not self.snapshotter.newer_snapshot_available():
            return None
        if not self.restore_from_snapshot(now):
            return None
        staleness = self.snapshotter._last_staleness_ms or 0
        return now - staleness

    def _follower_serving(self) -> bool:
        """True when this process serves reads FROM the stream (a
        replication follower): cached proposals serve by newest
        replicated entry instead of recomputing, and model reads fall
        back to the resident state when local sample history is short
        (monitor._serve_resident)."""
        return (self.replication is not None
                and self.replication.role != "leader")

    def read_refusal(self) -> dict | None:
        """The replica read gate: ``None`` when this process may serve
        GETs (always, without replication wired — the pre-streaming
        standby contract is unchanged), else the bounded-staleness
        refusal descriptor (server.py maps it to 503 + ``Retry-After``
        with the leader's identity in the JSON body)."""
        if self.replication is None:
            return None
        refusal = self.replication.read_refusal(self._now_ms())
        if refusal is not None:
            refusal["leaderId"] = (self.elector.leader_id()
                                   if self.elector is not None else None)
        return refusal

    def ha_role(self) -> str:
        """``leader`` (single-process mode included) or ``standby``."""
        if self.elector is None:
            return "leader"
        return "leader" if self.elector.is_leader() else "standby"

    def ha_json(self) -> dict:
        """The role readout served on ``/state`` (ServerRole) and
        ``/devicestats`` (ha section)."""
        if self.elector is None:
            return {"enabled": False, "role": "leader", "leaderId": None,
                    "fencingEpoch": None}
        return {"enabled": True, **self.elector.to_json()}

    def _refuse_if_not_leader(self) -> None:
        """Execution gate shared by every non-dryrun path: standby
        replicas serve reads only — execution endpoints answer 503 with
        the leader's identity (server.py maps NotLeaderError)."""
        if self.elector is not None and not self.elector.is_leader():
            from ..core.leader import NotLeaderError
            self.journal.record(
                "execute", "refused-not-leader", severity="warn",
                detail={"leaderId": self.elector.leader_id()})
            raise NotLeaderError(
                "this process is a standby replica; execution is owned "
                f"by the leader ({self.elector.leader_id() or 'unknown'})",
                leader_id=self.elector.leader_id())

    def snapshot_payload(self) -> dict:
        """Everything a restarted process needs to serve warm — the
        composition core/snapshot.py persists. Host-side data only plus
        the (picklable) cached OptimizerResult; no live object graphs."""
        resident = getattr(self.monitor, "resident", None)
        resident_state = (resident.export_state()
                          if resident is not None else None)
        return {
            "clusterId": self.cluster_id,
            "generation": self.monitor.generation,
            "resident": ({"epoch": resident_state[0],
                          "arrays": resident_state[1],
                          "ingestSeq": resident.ingest_seq}
                         if resident_state is not None else None),
            "proposalCache": self.proposal_cache.export_state(),
            "fencingEpoch": (self.elector.epoch
                             if self.elector is not None else 0),
            "journal": self.journal.export_state(),
        }

    def restore_from_snapshot(self, now_ms: int | None = None) -> bool:
        """Apply the persisted snapshot so this process serves warm:
        seed the monitor generation, rebuild the resident device buffers
        from the host mirrors (bit-identical by construction), install
        the cached proposals (stale-flagged: served immediately, but the
        stale-execution gate refuses to ACT on them until a live model
        build confirms the topology — how a stale snapshot trips the
        refusal), and raise the fencing-epoch floor. Returns True when a
        snapshot was applied; corrupt/version-skewed/stale files are
        metered + logged by the manager and this returns False (cold
        path)."""
        if self.snapshotter is None:
            return False
        now = now_ms if now_ms is not None else self._now_ms()

        def _validate(payload):
            if payload.get("clusterId") != self.cluster_id:
                return ("cluster-mismatch",
                        f"snapshot was taken for cluster "
                        f"{payload.get('clusterId')!r}, this process "
                        f"serves {self.cluster_id!r}")
            return None

        payload = self.snapshotter.restore(now, validate=_validate)
        if payload is None:
            return False
        self.monitor.seed_generation(payload.get("generation", 0))
        resident = getattr(self.monitor, "resident", None)
        res_state = payload.get("resident")
        if resident is not None and res_state is not None:
            resident.restore(res_state["epoch"], res_state["arrays"],
                             ingest_seq=res_state.get("ingestSeq"))
        cache_state = payload.get("proposalCache")
        if cache_state is not None:
            self.proposal_cache.restore_state(cache_state)
        if self.elector is not None:
            self.elector.observe_epoch_floor(
                payload.get("fencingEpoch", 0))
        journal_state = payload.get("journal")
        if journal_state:
            # Merge (never replace): the restoring process's own events —
            # including the restore-refusal trail that may have preceded
            # this successful restore — stay in its ring.
            self.journal.restore_state(journal_state)
        LOG.info(
            "restored serving state from snapshot: generation %s, "
            "resident %s, cached proposals %s (generation %s) — serving "
            "warm; execution stays gated until a live model build",
            payload.get("generation"),
            "restored" if (resident is not None and res_state) else "none",
            "restored" if cache_state else "none",
            cache_state["generation"] if cache_state else None)
        return True

    def ha_tick(self, now_ms: int | None = None) -> str:
        """One serving-loop HA round: run the election, write the
        cadenced snapshot when leading, refresh from the leader's newer
        snapshot when standing by. Returns the current role. Cheap
        no-op when neither snapshots nor HA are wired."""
        now = now_ms if now_ms is not None else self._now_ms()
        role = (self.elector.tick(now) if self.elector is not None
                else "leader")
        if self.slo is not None:
            # Rides ha_tick (not only the detector loop) so standby
            # processes evaluate the standby-staleness objective too;
            # interval-throttled internally.
            self.slo.evaluate(now)
        self.journal.maybe_persist(now)
        if self.replication is not None:
            # Streaming mode: the leader publishes delta frames (and
            # still writes the cadenced full snapshot — it remains the
            # bootstrap/resync path); the standby follows the stream
            # instead of mtime-polling the file.
            if role == "leader" and self.snapshotter is not None:
                self.snapshotter.maybe_write(now, self.snapshot_payload)
            self.replication.tick(now, role)
        elif self.snapshotter is not None:
            if role == "leader":
                self.snapshotter.maybe_write(now, self.snapshot_payload)
            elif (self.snapshotter.standby_should_poll(now)
                  and self.snapshotter.newer_snapshot_available()):
                # Standby: serve the leader's latest published state.
                # The fast-poll throttle (interval/4, or immediately on
                # a local-process peer write) keeps the stat() cadence
                # bounded without widening the staleness window.
                self.restore_from_snapshot(now)
        return role

    # ------------------------------------------------------ goal-based ops
    #: LRU bound on memoized goal-scoped optimizers — goal lists come from
    #: request parameters, so without a cap a client cycling goal subsets
    #: would accumulate compiled XLA chains without limit.
    MAX_GOAL_OPTIMIZERS = 16

    def _optimizer_for(self, goals: list[str] | None,
                       constraint=None) -> "TpuGoalOptimizer":
        """Memoize goal-scoped optimizers by goal-name tuple so repeated
        requests naming the same custom goals reuse one compiled-chain
        cache instead of paying a fresh XLA compile per request (the
        persistent disk cache only softens that; the in-process jit
        dispatch cache is per-optimizer). Shares the server optimizer's
        registry so goal-scoped proposal timings surface on /metrics.

        ``constraint`` overrides the balancing constraint (the
        goal-violation detector's relaxed-threshold chain); everything
        else — options generator, mesh, branches, registered hard
        goals — is inherited from the server optimizer either way."""
        if not goals and constraint is None:
            return self.optimizer
        cst = constraint or self.optimizer.constraint
        key = (tuple(goals or ()), cst)
        with self._lock:
            opt = self._goal_optimizers.pop(key, None)
            if opt is None:
                opt = TpuGoalOptimizer(
                    goals=(goals_by_name(goals, cst) if goals else None),
                    constraint=cst,
                    config=self.optimizer.config,
                    options_generator=self.optimizer.options_generator,
                    registry=self.optimizer.registry,
                    mesh=self.optimizer.mesh,
                    branches=self.optimizer.branches,
                    population=self.optimizer.population,
                    tuned_store=self.optimizer.tuned_store,
                    hard_goal_names=self.optimizer.hard_goal_names)
            self._goal_optimizers[key] = opt   # re-insert = most recent
            while len(self._goal_optimizers) > self.MAX_GOAL_OPTIMIZERS:
                self._goal_optimizers.pop(
                    next(iter(self._goal_optimizers)))
            return opt

    def _optimize(self, progress: OperationProgress | None,
                  goals: list[str] | None,
                  options: OptimizationOptions,
                  requirements: ModelCompletenessRequirements | None = None,
                  spec_mutator=None) -> OptimizerResult:
        with self.device_stats.cycle("propose"):
            return self._optimize_cycle(progress, goals, options,
                                        requirements, spec_mutator)

    def _optimize_cycle(self, progress, goals, options, requirements,
                        spec_mutator) -> OptimizerResult:
        """Body of :meth:`_optimize`, bracketed by a device-stats cycle so
        /devicestats' lastCycle covers the FULL propose cycle — the model
        build's host->device upload included, not just the optimizer's own
        dispatches (the optimizer's inner cycle no-ops under this one)."""
        if progress:
            progress.add_step("WaitingForClusterModel")
        result = self.monitor.cluster_model(self._now_ms(), requirements)
        original_placement = None
        if spec_mutator is not None:
            # Only mutator flows materialize the (lazy) spec object graph;
            # plain rebalance/proposals ride the flat arrays straight from
            # the dense pipeline.
            spec = result.spec
            # Proposals must capture the full live->final change, so
            # remember the LIVE placement before the mutator rewrites the
            # spec (an RF change adds/drops replicas pre-optimization; a
            # diff against the mutated model would silently drop the RF
            # change for partitions the optimizer leaves in place).
            original_placement = {(p.topic, p.partition): list(p.replicas)
                                  for p in spec.partitions}
            spec = spec_mutator(spec)
            from ..model.spec import flatten_spec
            model, metadata = flatten_spec(spec)
        else:
            model, metadata = result.model, result.metadata
        # Goal-scoped requests inherit the server's balancing constraint —
        # a request naming goals must not silently optimize against
        # default thresholds (ref goalsByPriority resolution reusing the
        # configured BalancingConstraint).
        opt = self._optimizer_for(goals)
        if progress:
            progress.add_step("OptimizationProposalCandidateComputation")
        on_goal = ((lambda name: progress.add_step(f"OptimizationForGoal-"
                                                   f"{name}"))
                   if progress else None)
        res = opt.optimize(model, metadata, options, on_goal_start=on_goal)
        if original_placement is not None:
            from dataclasses import replace as _dc_replace

            from ..model.proposals import diff_proposals_vs_placement
            mutated_keys = {(p.topic, p.partition) for p in spec.partitions
                            if list(p.replicas) != original_placement.get(
                                (p.topic, p.partition))}
            res = _dc_replace(res, proposals=diff_proposals_vs_placement(
                original_placement, model, res.final_model, metadata,
                mutated_keys))
        if result.stale:
            from dataclasses import replace as _dc_replace
            res = _dc_replace(res, stale_model=True)
        return res

    def _refuse_stale_execution(self, source_stale: bool) -> None:
        """The stale-model execution gate, shared by EVERY non-dryrun
        path (inter-broker via _maybe_execute, intra-broker via
        remove_disks): stale models are fine to LOOK at (dryrun, /load,
        proposals) but not to ACT on — their topology predates the
        dropout, so executing moves computed from them can target dead
        brokers/disks or undo post-cache changes. Checked two ways: the
        caller says whether ITS source model was stale-served, and the
        monitor is asked whether live sample flow is broken RIGHT NOW (a
        total dropout freezes the model generation, so cached proposals
        can stay "valid" without any model build flagging staleness)."""
        if not self.allow_stale_execution and (
                source_stale
                or self.monitor.history_stale(self._now_ms())):
            from ..monitor import StaleClusterModelError
            self.journal.record(
                "execute", "refused-stale-model", severity="warn",
                detail={"sourceStale": bool(source_stale)})
            raise StaleClusterModelError(
                "refusing non-dryrun execution against a stale cluster "
                "model (source model stale-served: "
                f"{source_stale}); wait for sample history to recover "
                "or set allow_stale_execution")

    def _maybe_execute(self, res: OptimizerResult, dryrun: bool,
                       uuid: str, progress: OperationProgress | None,
                       **executor_kwargs):
        if dryrun:
            return None
        # Leadership BEFORE the empty-proposal no-op: a standby must 503
        # every execution request (telling the client where the leader
        # is), not silently succeed when the plan happens to be empty.
        self._refuse_if_not_leader()
        if not res.proposals:
            return None
        self._refuse_stale_execution(res.stale_model)
        proposals = list(res.proposals)
        cfg = self.executor.config
        if cfg.forecast_deferral_enabled:
            proposals = self._apply_forecast_deferral(proposals,
                                                      executor_kwargs)
            if not proposals:
                return None
        if cfg.device_scheduling and "schedule" not in executor_kwargs:
            schedule = self._device_schedule(proposals, executor_kwargs)
            if schedule is not None:
                executor_kwargs["schedule"] = schedule
                stats = dict(schedule.stats)
                self.journal.record(
                    "execute", "schedule-built",
                    severity=("warn" if stats.get("unrepaired_violations")
                              else "info"),
                    detail={k: stats.get(k) for k in
                            ("batches", "moves", "repair_rounds",
                             "boundaries_audited",
                             "unrepaired_violations")
                            if k in stats})
        if progress:
            progress.add_step("ExecutingProposals")
        return self.executor.execute_proposals(proposals, uuid=uuid,
                                               **executor_kwargs)

    def _apply_forecast_deferral(self, proposals, executor_kwargs):
        """PR 13 follow-up: drop heals the forecast predicts obsolete and
        front-load leadership for projected-hot topics. Median projection
        at the configured horizon — deferral is a central-tendency call,
        not a tail-risk one (the quantile sweep stays a /forecast
        analysis surface). No fit yet -> defer nothing (never block an
        execution on forecast availability)."""
        from ..executor.schedule import forecast_filter
        cfg = self.executor.config
        try:
            scenario = self.forecast.trajectory_scenario(
                cfg.forecast_deferral_horizon_ms, 0.5)
        except ValueError:
            return proposals
        kept, deferred, hot = forecast_filter(
            proposals, scenario,
            shrink_below=cfg.forecast_deferral_shrink_factor,
            hot_above=cfg.forecast_hot_factor)
        hot_moving = hot & {p.topic for p in kept}
        self._last_deferral = {
            "deferredMoves": len(deferred),
            "deferredTopics": sorted({p.topic for p in deferred}),
            "hotTopics": sorted(hot_moving),
            "horizonMs": cfg.forecast_deferral_horizon_ms,
        }
        if deferred:
            LOG.info(
                "forecast deferral: holding %d move(s) on %d topic(s) "
                "projected below x%.2f at horizon %dms",
                len(deferred), len(self._last_deferral["deferredTopics"]),
                cfg.forecast_deferral_shrink_factor,
                cfg.forecast_deferral_horizon_ms)
        if hot_moving:
            executor_kwargs.setdefault("leadership_priority_topics",
                                       hot_moving)
        return kept

    def _device_schedule(self, proposals, executor_kwargs):
        """Build the device-side :class:`MoveSchedule` for this
        execution. Any failure degrades to the host greedy planner (the
        documented degrade path) — scheduling is an optimization, never
        an availability dependency."""
        if not any(p.has_replica_action for p in proposals):
            return None
        from ..executor.concurrency import ExecutionConcurrencyManager
        from ..executor.schedule import DeviceMoveScheduler
        from ..executor.strategy import StrategyContext, strategy_chain
        cfg = self.executor.config
        try:
            result = self.monitor.cluster_model(self._now_ms(), None)
            model, metadata = result.model, result.metadata
            goals = self.optimizer._audit_goals_for([], metadata,
                                                    OptimizationOptions())
            cc = cfg.concurrency
            if executor_kwargs.get("concurrency_overrides"):
                from dataclasses import replace as _dc_replace
                cc = _dc_replace(cc,
                                 **executor_kwargs["concurrency_overrides"])
            # Sizes for the strategy order + per-batch ETA: the model's
            # disk load, restricted to the partitions actually moving.
            keys = {(p.topic, p.partition) for p in proposals}
            disk = np.asarray(model.leader_load)[:, 3]
            sizes = {k: float(disk[i])
                     for i, k in enumerate(metadata.partition_keys)
                     if k in keys}
            ctx = StrategyContext(partition_size_mb=sizes)
            names = (executor_kwargs.get("strategy_names")
                     or list(cfg.default_strategy_names) or None)
            if self._move_scheduler is None:
                self._move_scheduler = DeviceMoveScheduler(
                    collector=self.optimizer.collector,
                    tracer=self.optimizer.tracer)
            return self._move_scheduler.schedule(
                proposals, ExecutionConcurrencyManager(cc),
                model=model, metadata=metadata, goals=goals,
                capacity_threshold=self.optimizer.constraint
                .capacity_threshold,
                strategy=strategy_chain(names), strategy_context=ctx,
                throttle_bytes=(
                    executor_kwargs.get("throttle_bytes")
                    or cfg.default_replication_throttle_bytes),
                bandwidth_mb_per_batch=cfg.schedule_bandwidth_mb_per_batch,
                max_repair_rounds=cfg.schedule_max_repair_rounds)
        except Exception:
            LOG.exception("device move scheduling failed; degrading to "
                          "the host greedy planner")
            return None

    def rebalance(self, goals: list[str] | None = None, dryrun: bool = True,
                  options: OptimizationOptions | None = None, uuid: str = "",
                  progress: OperationProgress | None = None,
                  ignore_proposal_cache: bool = False, **executor_kwargs):
        """ref RebalanceRunnable.java:30 (cache path :92-121)."""
        options = options or OptimizationOptions()
        use_cache = (not ignore_proposal_cache and goals is None
                     and options == OptimizationOptions())
        if use_cache:
            res = self.proposal_cache.get(self._now_ms())
            # The cache computes with skip_hard_goal_check; a rebalance
            # keeps the reference's strict semantics.
            if res.violated_hard_goals and not options.skip_hard_goal_check:
                from ..analyzer import OptimizationFailureError
                raise OptimizationFailureError(
                    f"hard goals still violated: {res.violated_hard_goals}",
                    res)
        else:
            res = self._optimize(progress, goals, options)
        exec_res = self._maybe_execute(res, dryrun, uuid, progress,
                                       **executor_kwargs)
        return res, exec_res

    def add_brokers(self, broker_ids: list[int], dryrun: bool = True,
                    goals: list[str] | None = None, uuid: str = "",
                    progress: OperationProgress | None = None,
                    options: OptimizationOptions | None = None,
                    **executor_kwargs):
        """Move load onto the new brokers (ref AddBrokersRunnable; new
        brokers become the only allowed destinations). ``options`` carries
        the request's goal options; the destination restriction is imposed
        on top."""
        from dataclasses import replace as _dc_replace

        def mark_new(spec):
            for b in spec.brokers:
                if b.broker_id in set(broker_ids):
                    b.new = True
            return spec
        options = _dc_replace(options or OptimizationOptions(),
                              destination_broker_ids=frozenset(broker_ids))
        res = self._optimize(progress, goals, options,
                             spec_mutator=mark_new)
        exec_res = self._maybe_execute(res, dryrun, uuid, progress,
                                       **executor_kwargs)
        return res, exec_res

    def remove_brokers(self, broker_ids: list[int], dryrun: bool = True,
                       goals: list[str] | None = None, uuid: str = "",
                       progress: OperationProgress | None = None,
                       destination_broker_ids: frozenset[int] | None = None,
                       options: OptimizationOptions | None = None,
                       **executor_kwargs):
        """Drain the given brokers (ref RemoveBrokersRunnable: demoted to
        dead state so every replica becomes a must-move;
        ``destination_broker_ids`` restricts where drained replicas may
        land, ref DESTINATION_BROKER_IDS_PARAM)."""
        from dataclasses import replace as _dc_replace
        removed = set(broker_ids)

        def mark_dead(spec):
            for b in spec.brokers:
                if b.broker_id in removed:
                    b.alive = False
            return spec
        options = options or OptimizationOptions()
        if destination_broker_ids:
            options = _dc_replace(
                options,
                destination_broker_ids=frozenset(destination_broker_ids))
        res = self._optimize(progress, goals, options,
                             spec_mutator=mark_dead)
        exec_res = self._maybe_execute(res, dryrun, uuid, progress,
                                       removed_brokers=removed,
                                       **executor_kwargs)
        return res, exec_res

    def demote_brokers(self, broker_ids: list[int], dryrun: bool = True,
                       uuid: str = "",
                       progress: OperationProgress | None = None,
                       options: OptimizationOptions | None = None,
                       skip_urp_demotion: bool = True,
                       exclude_follower_demotion: bool = True,
                       **executor_kwargs):
        """Move leadership (and preferred-leader order) off the brokers
        (ref DemoteBrokerRunnable + PreferredLeaderElectionGoal).

        ``skip_urp_demotion`` (ref SKIP_URP_DEMOTION_PARAM, default true)
        pins under-replicated partitions in place — shuffling leadership
        of a partition already missing replicas risks unavailability.
        ``exclude_follower_demotion`` (ref EXCLUDE_FOLLOWER_DEMOTION_PARAM,
        default true) keeps follower replicas' preferred order; when false
        the demoted brokers also sink to the end of every replica list."""
        from dataclasses import replace as _dc_replace
        demoted = set(broker_ids)

        # The URP exclusion must gate the *spec mutation*, not just the
        # optimizer: excluded_partitions only stops the search engine from
        # proposing moves, but every partition whose preferred order the
        # mutator rewrites is force-diffed against the live placement and
        # executed. Compute the pinned set first so the mutator leaves
        # under-replicated partitions entirely alone (ref
        # DemotionHelper / SKIP_URP_DEMOTION semantics).
        options = options or OptimizationOptions()
        excluded_parts = set(options.excluded_partitions)
        if skip_urp_demotion:
            excluded_parts |= {
                tp for tp, info in self._admin_read(
                    self.admin.describe_partitions).items()
                if len(info.isr) < len(info.replicas)}

        def mark_demoted(spec):
            for b in spec.brokers:
                if b.broker_id in demoted:
                    b.demoted = True
            for p in spec.partitions:
                if (p.topic, p.partition) in excluded_parts:
                    continue  # pinned (URP or caller-excluded): no rewrite
                # Demoted brokers also lose *preferred* leadership: rotate
                # them out of the head of the replica list.
                if p.replicas and p.replicas[0] in demoted:
                    alive = [r for r in p.replicas if r not in demoted]
                    if alive:
                        head = alive[0]
                        rest = [r for r in p.replicas if r != head]
                        p.replicas = [head, *rest]
                if not exclude_follower_demotion and p.replicas:
                    # Follower demotion: demoted brokers sink to the tail
                    # of the preferred order (relative order preserved).
                    p.replicas = ([r for r in p.replicas
                                   if r not in demoted]
                                  + [r for r in p.replicas if r in demoted])
            return spec

        options = _dc_replace(
            options,
            excluded_brokers_for_leadership=(
                options.excluded_brokers_for_leadership
                | frozenset(broker_ids)),
            excluded_partitions=frozenset(excluded_parts))
        res = self._optimize(progress,
                             ["PreferredLeaderElectionGoal"],
                             options,
                             spec_mutator=mark_demoted)
        exec_res = self._maybe_execute(res, dryrun, uuid, progress,
                                       demoted_brokers=demoted,
                                       **executor_kwargs)
        return res, exec_res

    def fix_offline_replicas(self, dryrun: bool = True, uuid: str = "",
                             goals: list[str] | None = None,
                             progress: OperationProgress | None = None,
                             options: OptimizationOptions | None = None,
                             **executor_kwargs):
        """ref FixOfflineReplicasRunnable: offline replicas are must-moves
        in the analyzer already; this runs the chain and executes."""
        res = self._optimize(progress, goals,
                             options or OptimizationOptions())
        exec_res = self._maybe_execute(res, dryrun, uuid, progress,
                                       **executor_kwargs)
        return res, exec_res

    def update_topic_configuration(self, topic_pattern: str, target_rf: int,
                                   dryrun: bool = True, uuid: str = "",
                                   progress: OperationProgress | None = None,
                                   options: OptimizationOptions | None = None,
                                   goals: list[str] | None = None,
                                   **executor_kwargs):
        """Replication-factor change (ref UpdateTopicConfigurationRunnable +
        ClusterModel.createOrDeleteReplicas :962): adjust each matched
        partition's replica list rack-aware, then rebalance."""
        if target_rf < 1:
            raise ValueError(
                f"replication_factor must be >= 1, got {target_rf}")

        def change_rf(spec):
            by_broker = {b.broker_id: b for b in spec.brokers}
            alive = [b for b in spec.brokers if b.alive]
            counts = {b.broker_id: 0 for b in alive}
            for p in spec.partitions:
                for r in p.replicas:
                    if r in counts:
                        counts[r] += 1
            for p in spec.partitions:
                if not fnmatch.fnmatch(p.topic, topic_pattern):
                    continue
                replicas = list(p.replicas)
                while len(replicas) > target_rf:
                    # Drop the last (least-preferred, never the leader).
                    gone = replicas.pop()
                    counts[gone] = counts.get(gone, 1) - 1
                racks_used = {by_broker[r].rack for r in replicas
                              if r in by_broker}
                while len(replicas) < target_rf:
                    # Least-loaded alive broker, new rack first (ref
                    # rack-aware replica addition).
                    candidates = [b for b in alive
                                  if b.broker_id not in replicas]
                    if not candidates:
                        raise ValueError(
                            f"not enough brokers for RF {target_rf}")
                    fresh = [b for b in candidates
                             if b.rack not in racks_used]
                    pool = fresh or candidates
                    pick = min(pool, key=lambda b: counts[b.broker_id])
                    replicas.append(pick.broker_id)
                    counts[pick.broker_id] += 1
                    racks_used.add(pick.rack)
                p.replicas = replicas
                # The preferred order must stay a permutation of the new
                # replica set: keep surviving entries' relative order,
                # append additions at the end (Kafka's semantics when the
                # assignment list changes).
                if p.preferred_replicas is not None:
                    kept = [r for r in p.preferred_replicas if r in replicas]
                    kept.extend(r for r in replicas if r not in kept)
                    p.preferred_replicas = kept
            return spec
        res = self._optimize(progress, goals,
                             options or OptimizationOptions(),
                             spec_mutator=change_rf)
        exec_res = self._maybe_execute(res, dryrun, uuid, progress,
                                       **executor_kwargs)
        return res, exec_res

    # ----------------------------------------------------------- get ops
    def _journal_plan(self, res: OptimizerResult) -> int | None:
        """Journal the plan-selection decision ONCE per distinct result
        object (cached entries serve the same object repeatedly), so
        every later served event chains back to one plan-selected seq.
        Identity scan over ≤8 recent plans: O(1) on the warm path."""
        for r, s in self._recent_plans:
            if r is res:
                return s
        if not self.journal.enabled:
            return None
        if res.violated_hard_goals:
            self.journal.record(
                "optimizer", "hard-goal-violation", severity="warn",
                detail={"violated": [str(g)
                                     for g in res.violated_hard_goals]})
        seq = self.journal.record(
            "optimizer", "plan-selected",
            detail={"numProposals": len(res.proposals),
                    "staleModel": bool(res.stale_model)})
        pop = getattr(self.optimizer, "last_population_stats", None)
        if pop is not None and id(pop) != self._journaled_pop_id:
            self._journaled_pop_id = id(pop)
            self.journal.record(
                "optimizer", "population-winner", cause=seq,
                detail={"winner": pop.get("winner"),
                        "winnerIsAnchor": pop.get("winnerIsAnchor"),
                        "size": pop.get("size"),
                        "paretoFrontSize": pop.get("paretoFrontSize")})
        self._recent_plans.append((res, seq))
        del self._recent_plans[:-8]
        return seq

    def _journal_propose(self, res: OptimizerResult, source: str) -> None:
        """The propose→serve causality pair on the serving path."""
        if not self.journal.enabled:
            return
        self.journal.record(
            "propose", "served", cause=self._journal_plan(res),
            detail={"source": source,
                    "numProposals": len(res.proposals),
                    "staleModel": bool(res.stale_model)})

    def proposals(self, ignore_cache: bool = False,
                  goals: list[str] | None = None,
                  progress: OperationProgress | None = None) -> OptimizerResult:
        """ref ProposalsRunnable / getProposals KafkaCruiseControl.java:534.
        A proposals read is a dry-run measurement either way: unfixable hard
        goals are a finding served with the provision verdict, like the
        cache path. A request naming ``goals`` always computes fresh — the
        cache only holds default-chain results."""
        if ignore_cache or goals:
            res = self._optimize(progress, goals,
                                 OptimizationOptions(
                                     skip_hard_goal_check=True))
            self._journal_propose(res, "fresh")
            return res
        if self._follower_serving():
            # Replication follower: never recompute — serve the newest
            # replicated entry (stale-flagged at restore, so the
            # execution gate refuses to act on it) and let the
            # bounded-staleness read gate police its age.
            e = self.proposal_cache.latest_entry()
            if e is not None:
                self._journal_propose(e.result, "replicated-cache")
                return e.result
        res = self.proposal_cache.get(self._now_ms())
        self._journal_propose(res, "cache")
        return res

    def simulate(self, payload: dict) -> dict:
        """What-if scenario sweep over the live cluster model (the
        ``/simulate`` endpoint). ``payload`` is the declarative spec —
        ``{"sweep": "N1"|"N2"}`` or ``{"scenarios": [...]}`` — parsed and
        validated before any device work. Purely a read: the hypothetical
        models exist only inside the sweep's device program, and the
        proposal cache is never touched (its scenario guard enforces
        this, see ProposalCache.store)."""
        from ..whatif import alive_broker_ids, parse_scenarios
        result = self.monitor.cluster_model(self._now_ms())
        scenarios = parse_scenarios(
            payload, alive_broker_ids(result.model, result.metadata),
            # {"type": "forecast"} sources resolve through the fitted
            # per-topic forecasts into concrete TrajectoryScale specs.
            forecaster=self.forecast.trajectory_scenario)
        report = self.whatif.sweep(result.model, result.metadata,
                                   scenarios, stale_model=result.stale)
        return report.to_json()

    def forecast_json(self) -> dict:
        """``GET /forecast``: the fitted-trajectory summary and the
        cached sweep report (computed on first call; POST /forecast
        forces a refit + fresh sweep)."""
        self.forecast.maybe_refresh(self._now_ms())
        return self.forecast.report_json()

    def forecast_refresh(self) -> dict:
        """``POST /forecast``: refit forecasts from the current window
        history and run one trajectory sweep NOW. A monitor with no
        aggregated windows yet is a client-retryable not-ready state —
        HTTP 400, as rest-api.md documents — not a server fault."""
        from ..core.aggregator import NotEnoughValidWindowsError
        now = self._now_ms()
        try:
            self.forecast.refresh(now)
            self.forecast.sweep(now)
        except NotEnoughValidWindowsError as e:
            raise ValueError(
                f"no aggregated windows to fit forecasts from yet "
                f"({e}); retry once the monitor has sampled at least "
                f"one window") from e
        return self.forecast.report_json()

    def load(self, populate_disk_info: bool = False,
             capacity_only: bool = False) -> dict:
        """Broker-level load stats (ref LoadRunnable -> BrokerStats).
        ``populate_disk_info`` adds per-logdir disk usage (ref
        POPULATE_DISK_INFO_PARAM); ``capacity_only`` reports capacities
        without requiring load data (ref CAPACITY_ONLY_PARAM)."""
        result = self.monitor.cluster_model(
            self._now_ms(),
            populate_replica_placement_only=capacity_only)
        model = result.model
        counts = np.asarray(broker_replica_counts(model))
        leaders = np.asarray(broker_leader_counts(model))
        caps = np.asarray(model.broker_capacity)
        util = (None if capacity_only
                else np.asarray(broker_utilization(model)))
        disk_by_broker: dict[int, dict[str, float]] = {}
        if populate_disk_info:
            sizes = {tp: i.size_mb
                     for tp, i in self._admin_read(
                         self.admin.describe_partitions).items()}
            for (t, p, b), d in self._admin_read(
                    self.admin.describe_replica_log_dirs).items():
                disk_by_broker.setdefault(b, {})
                disk_by_broker[b][d] = (disk_by_broker[b].get(d, 0.0)
                                        + sizes.get((t, p), 0.0))
        hosts = result.spec.brokers
        brokers = []
        for i, b in enumerate(hosts):
            row = {
                "Broker": b.broker_id, "Rack": b.rack,
                "BrokerState": "ALIVE" if b.alive else "DEAD",
                "Replicas": int(counts[i]), "Leaders": int(leaders[i]),
                "Capacity": {r.name: float(caps[i, int(r)])
                             for r in Resource},
            }
            if util is not None:
                row.update({
                    "CpuPct": float(util[i, Resource.CPU]),
                    "NwInRate": float(util[i, Resource.NW_IN]),
                    "NwOutRate": float(util[i, Resource.NW_OUT]),
                    "DiskMB": float(util[i, Resource.DISK])})
            if populate_disk_info:
                row["DiskState"] = {
                    d: round(mb, 3) for d, mb in sorted(
                        disk_by_broker.get(b.broker_id, {}).items())}
            brokers.append(row)
        return {"brokers": brokers,
                "summary": (None if capacity_only
                            else stats_summary(model)),
                "generation": result.generation}

    def partition_load(self, resource: str = "DISK", start: int = 0,
                       max_entries: int = 2**31,
                       topic_pattern: str | None = None,
                       broker_ids: list[int] | None = None,
                       max_load: bool = False) -> list[dict]:
        """ref PartitionLoadRunnable: partitions sorted by a resource.
        ``topic_pattern`` / ``broker_ids`` filter rows (ref TOPIC_PARAM,
        BROKER_ID_PARAM); ``max_load`` scores each partition by its
        max-window load instead of the window average (ref MAX_LOAD_PARAM
        -> Load.expectedUtilizationFor(max))."""
        result = self.monitor.cluster_model(self._now_ms())
        res_idx = int(Resource[resource.upper()])
        wanted_brokers = set(broker_ids or ())
        rows = []
        for p in result.spec.partitions:
            if topic_pattern and not fnmatch.fnmatch(p.topic, topic_pattern):
                continue
            if wanted_brokers and not (wanted_brokers & set(p.replicas)):
                continue
            load = list(p.leader_load)
            if max_load:
                windows = result.partition_windows.get(
                    (p.topic, p.partition))
                if windows is not None and windows.size:
                    # KafkaMetric 0-3 line up with the Resource axis.
                    load = [float(np.max(windows[r])) for r in range(4)]
            rows.append({
                "topic": p.topic, "partition": p.partition,
                "leader": p.replicas[0] if p.replicas else -1,
                "followers": list(p.replicas[1:]),
                "CPU": load[0], "NW_IN": load[1],
                "NW_OUT": load[2], "DISK": load[3],
            })
        rows.sort(key=lambda r: -r[Resource(res_idx).name])
        return rows[start:start + max_entries]

    def kafka_cluster_state(self, verbose: bool = False,
                            topic_pattern: str | None = None) -> dict:
        """ref KafkaClusterStateRequest: topology + replica health.
        ``verbose`` adds per-partition leader/replicas/ISR detail (ref
        KafkaClusterState.writeKafkaClusterState verbose sections);
        ``topic_pattern`` scopes the partition view (ref TOPIC_PARAM)."""
        parts = self._admin_read(self.admin.describe_partitions)
        if topic_pattern:
            parts = {tp: i for tp, i in parts.items()
                     if fnmatch.fnmatch(tp[0], topic_pattern)}
        alive = self._admin_read(self.admin.describe_cluster)
        under_replicated = [list(tp) for tp, i in parts.items()
                            if len(i.isr) < len(i.replicas)]
        offline = [list(tp) for tp, i in parts.items()
                   if any(not alive.get(b, False) for b in i.replicas)]
        leader_count: dict[int, int] = {}
        replica_count: dict[int, int] = {}
        for i in parts.values():
            leader_count[i.leader] = leader_count.get(i.leader, 0) + 1
            for b in i.replicas:
                replica_count[b] = replica_count.get(b, 0) + 1
        return {"KafkaBrokerState": {
                    "IsController": {},
                    "Summary": {"Brokers": len(alive),
                                "Alive": sum(alive.values())},
                    "LeaderCountByBrokerId": leader_count,
                    "ReplicaCountByBrokerId": replica_count},
                "KafkaPartitionState": {
                    "UnderReplicatedPartitions": under_replicated,
                    "OfflinePartitions": offline,
                    "TotalPartitions": len(parts),
                    **({"Partitions": [
                        {"topic": i.topic, "partition": i.partition,
                         "leader": i.leader, "replicas": list(i.replicas),
                         "in-sync": sorted(i.isr),
                         "size-MB": round(i.size_mb, 3)}
                        for i in sorted(parts.values(),
                                        key=lambda i: (i.topic, i.partition))
                    ]} if verbose else {})}}

    def device_stats_json(self) -> dict:
        """The full ``/devicestats`` payload: the device-runtime ledger
        plus the resident-state section (epoch, last delta rows/bytes),
        the proposal-freshness readout, and — when the fleet control
        plane is on — the fleet section (cluster count, shape bucket,
        last batched-dispatch wall clock)."""
        payload = self.device_stats.to_json()
        resident = getattr(self.monitor, "resident", None)
        payload["resident"] = (resident.to_json()
                               if resident is not None else None)
        payload["proposalFreshness"] = self.proposal_cache.freshness_json(
            self._now_ms())
        payload["fleet"] = (self.fleet.stats_json()
                            if self.fleet is not None else None)
        # Forecast-engine snapshot (fit counts, worst backtest error,
        # last sweep's time-to-breach) — always present; dashboards poll
        # unconditionally.
        payload["forecast"] = self.forecast.stats_json()
        # Population-search snapshot (last run's joint-scoring readout —
        # Pareto front size, per-goal acceptance across the population)
        # and the tuned-schedule store's per-bucket fields + trial
        # history. None when the respective mode is off — dashboards
        # poll unconditionally.
        payload["population"] = getattr(self.optimizer,
                                        "last_population_stats", None)
        store = getattr(self.optimizer, "tuned_store", None)
        payload["tuning"] = store.to_json() if store is not None else None
        # Crash-safety + HA readouts (null-safe: dashboards poll
        # unconditionally whether or not the layer is wired).
        payload["snapshot"] = (self.snapshotter.to_json()
                               if self.snapshotter is not None else None)
        payload["ha"] = self.ha_json()
        payload["replication"] = (self.replication.to_json()
                                  if self.replication is not None
                                  else None)
        # Device-scheduled execution readout: the last pipelined run's
        # batch/poll/verify counters plus the last forecast-deferral
        # outcome. Null until the first scheduled execution — dashboards
        # poll unconditionally.
        stats = getattr(self.executor, "last_schedule_stats", None)
        payload["executor"] = (
            None if stats is None and self._last_deferral is None
            else {"schedule": stats, "forecastDeferral": self._last_deferral})
        return payload

    # ------------------------------------------------- flight recorder
    def trace_json(self) -> dict:
        """``GET /trace``: the Chrome-trace export — spans from the
        tracer plus the journal's decisions as instant ("i") events on
        the same perf_counter timeline, so a decision row sits visually
        between the spans that produced it."""
        trace = self.tracer.to_chrome_trace()
        trace["traceEvents"] = list(trace.get("traceEvents", ())) + \
            self.journal.chrome_instant_events(self.tracer._epoch)
        return trace

    def history_json(self, categories: list[str] | None = None,
                     severity: str | None = None, since_seq: int = 0,
                     limit: int = 256) -> dict:
        """``GET /history``: the filtered decision journal. Served
        locally on EVERY role — a read replica answers from the journal
        it applied off the leader's stream (plus its own local events),
        which is what makes post-failover forensics possible when the
        old leader is gone."""
        out = self.journal.history_json(
            categories=categories, min_severity=severity,
            since_seq=since_seq, limit=limit)
        out["role"] = self.ha_role()
        return out

    # -------------------------------------------------------- fleet ops
    def fleet_summary(self) -> dict:
        """``GET /fleet``: per-cluster balance/freshness/risk summary.
        With the fleet layer off this is an honest ``enabled: false``
        rather than an error — dashboards poll it unconditionally."""
        if self.fleet is None:
            return {"enabled": False, "numClusters": 0, "clusters": []}
        return self.fleet.summary_json(self._now_ms())

    def fleet_rebalance(self) -> dict:
        """``POST /fleet/rebalance``: force one fleet tick now (every
        member recomputes and re-caches); execution stays per-cluster."""
        if self.fleet is None:
            raise ValueError(
                "fleet control plane is disabled (fleet.enabled=false)")
        return self.fleet.rebalance(self._now_ms())

    def state(self, substates: list[str] | None = None) -> dict:
        """ref GetStateRunnable -> CruiseControlState with substates."""
        wanted = {s.lower() for s in (substates or
                                      ["monitor", "executor", "analyzer",
                                       "anomaly_detector"])}
        # Role metadata rides EVERY state response (like "version"): a
        # client must be able to tell a standby from the leader without
        # knowing to ask for it (the HA runbook's first diagnostic).
        out: dict = {"ServerRole": self.ha_json()}
        # Numeric self-metrics snapshot (ref the JMX-exposed Dropwizard
        # registry; substates=sensors scopes a response to just these).
        if "sensors" in wanted:
            out["Sensors"] = self.registry.to_json()
        # Recent-span snapshot (the /trace ring buffer, span-record form;
        # the Chrome trace-event export lives on /trace itself).
        if "tracing" in wanted:
            out["Tracing"] = self.tracer.to_json()
        # Device-runtime ledger: compile lifecycle, transfers, memory,
        # padding (the /devicestats payload, embedded for one-call
        # dashboards).
        if "device_stats" in wanted or "devicestats" in wanted:
            out["DeviceStats"] = self.device_stats_json()
        if "monitor" in wanted:
            mon = self.monitor.state(self._now_ms()).to_json()
            if self.task_runner is not None:
                mon["taskRunner"] = self.task_runner.state_json()
            out["MonitorState"] = mon
        if "executor" in wanted:
            out["ExecutorState"] = self.executor.state_json()
        if "analyzer" in wanted:
            now = self._now_ms()
            out["AnalyzerState"] = {
                "isProposalReady": self.proposal_cache.valid(),
                "readyGoals": [g.name for g in self.optimizer.goals],
                "proposalFreshnessAgeMs":
                    self.proposal_cache.freshness_age_ms(now),
                "proposalFreshnessLagMs":
                    self.proposal_cache.freshness_lag_ms(now)}
        if "anomaly_detector" in wanted and self.detector is not None:
            out["AnomalyDetectorState"] = self.detector.state_json()
        return out

    # ------------------------------------------------------- admin-ish ops
    def stop_proposal_execution(self, force: bool = False,
                                stop_external_agent: bool = False) -> None:
        self.executor.stop_execution(force=force,
                                     stop_external_agent=stop_external_agent)

    def stop_ongoing_and_wait(self, timeout_s: float = 60.0) -> bool:
        """Preempt the in-flight execution and wait for the executor to
        release (the shared stop-then-wait used by
        stop_ongoing_execution requests and maintenance-event
        preemption). Returns True when the executor is idle."""
        import time as _t
        if self.executor.has_ongoing_execution():
            self.stop_proposal_execution()
            deadline = _t.monotonic() + timeout_s
            while (self.executor.has_ongoing_execution()
                   and _t.monotonic() < deadline):
                _t.sleep(0.05)
        return not self.executor.has_ongoing_execution()

    def pause_sampling(self, reason: str = "") -> None:
        if self.task_runner is None:
            raise RuntimeError("no sampling task runner configured")
        self.task_runner.pause(reason)

    def resume_sampling(self, reason: str = "") -> None:
        if self.task_runner is None:
            raise RuntimeError("no sampling task runner configured")
        self.task_runner.resume(reason)

    def bootstrap(self, start_ms: int, end_ms: int) -> int:
        if self.task_runner is None:
            raise RuntimeError("no sampling task runner configured")
        return self.task_runner.bootstrap(start_ms, end_ms)

    def train(self, now_ms: int | None = None) -> dict:
        """Feed broker (bytes-in, bytes-out) -> CPU observations into the
        linear regression (ref TrainRunnable + LinearRegressionModelParameters).
        Runs under the task runner's TRAINING state when a runner is wired
        (ref LoadMonitorTaskRunner.java:57-58)."""
        import contextlib
        guard = (self.task_runner.training() if self.task_runner is not None
                 else contextlib.nullcontext())
        with guard:
            stats = self.monitor.broker_window_stats(
                now_ms or self._now_ms())
            for _, values in stats.items():
                for w in range(values.shape[1]):
                    self.cpu_model.add_observation(
                        values[BrokerMetric.LEADER_BYTES_IN, w],
                        values[BrokerMetric.LEADER_BYTES_OUT, w],
                        values[BrokerMetric.CPU_USAGE, w])
            self.cpu_model.fit()
        return self.cpu_model.to_json()

    def remove_disks(self, broker_id_logdirs: dict[int, list[str]],
                     dryrun: bool = True, uuid: str = "",
                     progress: OperationProgress | None = None,
                     **executor_kwargs) -> dict:
        """Drain the given logdirs onto their brokers' surviving disks
        (ref RemoveDisksRunnable; the intra-broker kernel with the doomed
        disks' capacity zeroed)."""
        from ..analyzer.intra import intra_broker_rebalance
        result = self.monitor.cluster_model(self._now_ms())
        res = intra_broker_rebalance(
            result.model, result.metadata, self.admin,
            self.monitor.capacity_resolver,
            drained_disks=broker_id_logdirs)
        out = {"numIntraBrokerMoves": len(res.moves),
               "goalSummary": res.goal_summary(),
               "capacityViolation": {"before": res.capacity_violation_before,
                                     "after": res.capacity_violation_after},
               "balanceViolation": {"before": res.balance_violation_before,
                                    "after": res.balance_violation_after},
               "iterations": res.iterations,
               "moves": [m.to_json() for m in res.moves]}
        if not dryrun:
            self._refuse_if_not_leader()
        if not dryrun and res.moves:
            self._refuse_stale_execution(result.stale)
            if progress:
                progress.add_step("ExecutingIntraBrokerMoves")
            exec_res = self.executor.execute_proposals(
                [], intra_broker_moves=res.moves, uuid=uuid,
                **executor_kwargs)
            out["executionResult"] = {"succeeded": exec_res.succeeded,
                                      "numDeadTasks": exec_res.num_dead_tasks}
        return out

    def rebalance_disks(self, dryrun: bool = True, uuid: str = "",
                        progress: OperationProgress | None = None,
                        **executor_kwargs) -> dict:
        """Intra-broker disk balance (ref rebalance with the intra-broker
        goal list)."""
        return self.remove_disks({}, dryrun=dryrun, uuid=uuid,
                                 progress=progress, **executor_kwargs)

    def rightsize(self, **kwargs) -> dict:
        """ref RightsizeRunnable -> Provisioner; concrete provisioning is
        the detector layer's BasicProvisioner acting on the current
        optimization's provision verdict."""
        if (self.detector is None
                or getattr(self.detector, "provisioner", None) is None):
            return {"provisionerState": "No provisioner configured"}
        from ..monitor import NotEnoughValidWindowsException
        try:
            res = self.proposal_cache.get(self._now_ms())
        except (NotEnoughValidWindowsException, TimeoutError) as e:
            return {"provisionerState": "NOT_READY", "reason": str(e)}
        recs = (res.provision_response.recommendations
                if res.provision_response is not None else [])
        return self.detector.provisioner.rightsize(recommendations=recs,
                                                   **kwargs)
