"""API layer (L6): REST endpoints, async user tasks, purgatory review flow,
security, the product facade and the proposal precompute cache (ref
``servlet/`` + ``KafkaCruiseControl.java``)."""

from .facade import KafkaCruiseControl
from .precompute import ProposalCache
from .progress import OperationProgress
from .purgatory import Purgatory, ReviewStatus
from .openapi import openapi_spec
from .security import (AllowAllSecurityProvider, AuthorizationError,
                       BasicSecurityProvider, JwtSecurityProvider, Principal,
                       Role, SpnegoSecurityProvider,
                       TrustedProxySecurityProvider, check_access)
from .server import CruiseControlApp
from .tasks import TaskState, UserTaskManager

__all__ = ["KafkaCruiseControl", "ProposalCache", "OperationProgress",
           "Purgatory", "ReviewStatus", "AllowAllSecurityProvider",
           "AuthorizationError", "BasicSecurityProvider",
           "JwtSecurityProvider", "Principal", "Role",
           "SpnegoSecurityProvider",
           "TrustedProxySecurityProvider", "check_access", "openapi_spec",
           "CruiseControlApp", "TaskState", "UserTaskManager"]
