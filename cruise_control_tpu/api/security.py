"""Pluggable request security (ref ``servlet/security/``).

The reference ships HTTP Basic, JWT, SPNEGO and trusted-proxy providers
over a VIEWER/USER/ADMIN role model (``DefaultRoleSecurityProvider.java``,
``UserPermissionsManager.java``). Endpoint-to-role mapping follows the
reference: GET state/load/proposals = VIEWER, kafka-admin POSTs = USER,
admin/review = ADMIN.
"""

from __future__ import annotations

import base64
import enum
from dataclasses import dataclass
from typing import Protocol


class Role(enum.Enum):
    VIEWER = 1
    USER = 2
    ADMIN = 3


#: endpoint name -> minimum role (ref DefaultRoleSecurityProvider roles)
ENDPOINT_MIN_ROLE: dict[str, Role] = {
    "state": Role.VIEWER, "load": Role.VIEWER, "partition_load": Role.VIEWER,
    "proposals": Role.VIEWER, "kafka_cluster_state": Role.VIEWER,
    "user_tasks": Role.VIEWER, "review_board": Role.VIEWER,
    "permissions": Role.VIEWER,
    "rebalance": Role.USER, "add_broker": Role.USER,
    "remove_broker": Role.USER, "demote_broker": Role.USER,
    "fix_offline_replicas": Role.USER, "topic_configuration": Role.USER,
    "rightsize": Role.USER, "remove_disks": Role.USER,
    "stop_proposal_execution": Role.USER, "pause_sampling": Role.USER,
    "resume_sampling": Role.USER, "bootstrap": Role.USER, "train": Role.USER,
    "admin": Role.ADMIN, "review": Role.ADMIN,
}


class AuthorizationError(PermissionError):
    def __init__(self, message: str, status: int = 401):
        super().__init__(message)
        self.status = status


@dataclass
class Principal:
    name: str
    role: Role


class SecurityProvider(Protocol):
    """ref SecurityProvider.java."""

    def authenticate(self, headers: dict[str, str]) -> Principal: ...


class AllowAllSecurityProvider:
    """Security disabled (webserver.security.enable=false, the default)."""

    def authenticate(self, headers) -> Principal:
        return Principal("anonymous", Role.ADMIN)


class BasicSecurityProvider:
    """HTTP Basic auth against a static credentials map (ref
    BasicSecurityProvider.java + the auth-file format)."""

    def __init__(self, users: dict[str, tuple[str, Role]]):
        """``users``: name -> (password, role)."""
        self.users = users

    def authenticate(self, headers: dict[str, str]) -> Principal:
        auth = headers.get("authorization", headers.get("Authorization", ""))
        if not auth.startswith("Basic "):
            raise AuthorizationError("missing basic auth credentials", 401)
        try:
            raw = base64.b64decode(auth[6:]).decode()
            name, _, password = raw.partition(":")
        except Exception:
            raise AuthorizationError("malformed basic auth header", 401)
        entry = self.users.get(name)
        if entry is None or entry[0] != password:
            raise AuthorizationError("bad credentials", 401)
        return Principal(name, entry[1])


class TrustedProxySecurityProvider:
    """Trusted-proxy auth: requests from listed proxies carry the acting
    principal in a header (ref security/trustedproxy/)."""

    def __init__(self, trusted_proxies: set[str],
                 principal_header: str = "doAs",
                 role: Role = Role.USER):
        self.trusted_proxies = trusted_proxies
        # The HTTP layer lowercases header names before dispatch.
        self.principal_header = principal_header.lower()
        self.role = role

    def authenticate(self, headers: dict[str, str]) -> Principal:
        proxy = headers.get("x-forwarded-by", "")
        if proxy not in self.trusted_proxies:
            raise AuthorizationError(f"untrusted proxy {proxy!r}", 403)
        name = headers.get(self.principal_header, "")
        if not name:
            raise AuthorizationError("missing doAs principal", 401)
        return Principal(name, self.role)


def check_access(provider: SecurityProvider, endpoint: str,
                 headers: dict[str, str]) -> Principal:
    principal = provider.authenticate(headers)
    required = ENDPOINT_MIN_ROLE.get(endpoint, Role.ADMIN)
    if principal.role.value < required.value:
        raise AuthorizationError(
            f"{principal.name} ({principal.role.name}) lacks "
            f"{required.name} for {endpoint}", 403)
    return principal
