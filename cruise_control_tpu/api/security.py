"""Pluggable request security (ref ``servlet/security/``).

The reference ships HTTP Basic, JWT, SPNEGO and trusted-proxy providers
over a VIEWER/USER/ADMIN role model (``DefaultRoleSecurityProvider.java``,
``UserPermissionsManager.java``). Endpoint-to-role mapping follows the
reference: GET state/load/proposals = VIEWER, kafka-admin POSTs = USER,
admin/review = ADMIN.
"""

from __future__ import annotations

import base64
import enum
from dataclasses import dataclass
from typing import Protocol


class Role(enum.Enum):
    VIEWER = 1
    USER = 2
    ADMIN = 3


#: endpoint name -> minimum role (ref DefaultRoleSecurityProvider roles)
ENDPOINT_MIN_ROLE: dict[str, Role] = {
    "state": Role.VIEWER, "load": Role.VIEWER, "partition_load": Role.VIEWER,
    "proposals": Role.VIEWER, "kafka_cluster_state": Role.VIEWER,
    "user_tasks": Role.VIEWER, "review_board": Role.VIEWER,
    "permissions": Role.VIEWER, "openapi": Role.VIEWER,
    # simulate is a pure read (dry-run what-if analysis), VIEWER like
    # proposals despite being a POST.
    "simulate": Role.VIEWER,
    # fleet summary is a read; a forced fleet recompute is USER-level
    # like rebalance (it only refreshes member caches, never executes).
    "fleet": Role.VIEWER, "fleet_rebalance": Role.USER,
    # forecast report is a read; forcing a refit + sweep is USER-level
    # like fleet_rebalance (compute, never execution).
    "forecast": Role.VIEWER, "forecast_refresh": Role.USER,
    # the flight recorder is a read-only forensic surface
    "history": Role.VIEWER,
    "rebalance": Role.USER, "add_broker": Role.USER,
    "remove_broker": Role.USER, "demote_broker": Role.USER,
    "fix_offline_replicas": Role.USER, "topic_configuration": Role.USER,
    "rightsize": Role.USER, "remove_disks": Role.USER,
    "stop_proposal_execution": Role.USER, "pause_sampling": Role.USER,
    "resume_sampling": Role.USER, "bootstrap": Role.USER, "train": Role.USER,
    "admin": Role.ADMIN, "review": Role.ADMIN,
}


class AuthorizationError(PermissionError):
    def __init__(self, message: str, status: int = 401,
                 challenge: str | None = None):
        super().__init__(message)
        self.status = status
        #: WWW-Authenticate challenge the 401 response should carry so
        #: conforming clients (curl --negotiate, browsers) retry with
        #: credentials.
        self.challenge = challenge


@dataclass
class Principal:
    name: str
    role: Role


class SecurityProvider(Protocol):
    """ref SecurityProvider.java."""

    def authenticate(self, headers: dict[str, str]) -> Principal: ...


class AllowAllSecurityProvider:
    """Security disabled (webserver.security.enable=false, the default)."""

    def authenticate(self, headers) -> Principal:
        return Principal("anonymous", Role.ADMIN)


class BasicSecurityProvider:
    """HTTP Basic auth against a static credentials map (ref
    BasicSecurityProvider.java + the auth-file format)."""

    #: challenge attached to every 401 from this provider (RFC 7235)
    default_challenge = 'Basic realm="cruisecontrol"'

    def __init__(self, users: dict[str, tuple[str, Role]]):
        """``users``: name -> (password, role)."""
        self.users = users

    def authenticate(self, headers: dict[str, str]) -> Principal:
        auth = headers.get("authorization", headers.get("Authorization", ""))
        if not auth.startswith("Basic "):
            raise AuthorizationError("missing basic auth credentials", 401,
                                     challenge='Basic realm="cruisecontrol"')
        try:
            raw = base64.b64decode(auth[6:]).decode()
            name, _, password = raw.partition(":")
        except Exception:
            raise AuthorizationError("malformed basic auth header", 401)
        entry = self.users.get(name)
        if entry is None or entry[0] != password:
            raise AuthorizationError("bad credentials", 401)
        return Principal(name, entry[1])


class JwtSecurityProvider:
    """JWT bearer-token auth (ref ``security/jwt/JwtSecurityProvider`` +
    ``JwtAuthenticator``): HS256-signed tokens carrying the principal in
    ``sub`` and the role in a configurable claim. The reference validates
    RS256 tokens minted by an SSO service; with no crypto dependencies in
    this environment the shared-secret HMAC variant keeps the same token
    shape, expiry, and claim mapping."""

    #: challenge attached to every 401 from this provider (RFC 7235)
    default_challenge = "Bearer"

    def __init__(self, secret: bytes | str, *, role_claim: str = "role",
                 default_role: Role = Role.VIEWER,
                 now_s: "Callable[[], float] | None" = None,
                 max_token_age_s: float | None = None,
                 expected_audiences: "list[str] | None" = None,
                 cookie_name: str | None = None):
        import time
        self.secret = secret.encode() if isinstance(secret, str) else secret
        self.role_claim = role_claim
        self.default_role = default_role
        self._now_s = now_s or time.time
        #: hard cap on token lifetime from ``iat``; tokens older than this
        #: are rejected even if their ``exp`` lies further out.
        self.max_token_age_s = max_token_age_s
        #: accepted aud values (ref jwt.expected.audiences; empty = any)
        self.expected_audiences = list(expected_audiences or ())
        #: cookie carrying the token besides the Bearer header (ref
        #: jwt.cookie.name / JwtAuthenticator cookie extraction)
        self.cookie_name = cookie_name

    @staticmethod
    def _b64url_decode(part: str) -> bytes:
        pad = -len(part) % 4
        return base64.urlsafe_b64decode(part + "=" * pad)

    @classmethod
    def encode(cls, secret: bytes | str, claims: dict) -> str:
        """Mint a token (test/ops helper — the reference relies on an
        external issuer)."""
        import hashlib
        import hmac
        import json
        secret = secret.encode() if isinstance(secret, str) else secret

        def enc(obj) -> str:
            raw = json.dumps(obj, separators=(",", ":")).encode()
            return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

        signing = f"{enc({'alg': 'HS256', 'typ': 'JWT'})}.{enc(claims)}"
        sig = hmac.new(secret, signing.encode(), hashlib.sha256).digest()
        return (signing + "."
                + base64.urlsafe_b64encode(sig).rstrip(b"=").decode())

    def authenticate(self, headers: dict[str, str]) -> Principal:
        import hashlib
        import hmac
        import json
        auth = headers.get("authorization", headers.get("Authorization", ""))
        token = auth[7:].strip() if auth.startswith("Bearer ") else ""
        if not token and self.cookie_name:
            # ref JwtAuthenticator: the token may arrive in a cookie.
            for part in headers.get("cookie", "").split(";"):
                name, _, value = part.strip().partition("=")
                if name == self.cookie_name and value:
                    token = value
                    break
        if not token:
            raise AuthorizationError("missing bearer token", 401,
                                     challenge="Bearer")
        parts = token.split(".")
        if len(parts) != 3:
            raise AuthorizationError("malformed JWT", 401)
        try:
            header = json.loads(self._b64url_decode(parts[0]))
            claims = json.loads(self._b64url_decode(parts[1]))
            sig = self._b64url_decode(parts[2])
        except Exception:
            raise AuthorizationError("malformed JWT", 401)
        if header.get("alg") != "HS256":
            raise AuthorizationError(
                f"unsupported JWT alg {header.get('alg')!r}", 401)
        expect = hmac.new(self.secret,
                          f"{parts[0]}.{parts[1]}".encode(),
                          hashlib.sha256).digest()
        if not hmac.compare_digest(sig, expect):
            raise AuthorizationError("bad JWT signature", 401)
        now = self._now_s()

        def _ts(claim: str, required: bool) -> float | None:
            v = claims.get(claim)
            if v is None:
                if required:
                    raise AuthorizationError(
                        f"JWT missing required {claim} claim", 401)
                return None
            try:
                return float(v)
            except (TypeError, ValueError):
                raise AuthorizationError(f"malformed JWT {claim} claim", 401)

        # A token without exp would be valid forever (irrevocable if the
        # shared secret leaks), so exp is mandatory here even though RFC 7519
        # makes it optional.
        if now >= _ts("exp", required=True):
            raise AuthorizationError("JWT expired", 401)
        nbf = _ts("nbf", required=False)
        if nbf is not None and now < nbf:
            raise AuthorizationError("JWT not yet valid (nbf)", 401)
        if self.max_token_age_s is not None:
            iat = _ts("iat", required=True)
            if now - iat > self.max_token_age_s:
                raise AuthorizationError("JWT exceeds max token age", 401)
        if self.expected_audiences:
            aud = claims.get("aud")
            auds = set(aud if isinstance(aud, list) else [aud]
                       if aud is not None else [])
            if not auds & set(self.expected_audiences):
                raise AuthorizationError(
                    "JWT aud claim matches no expected audience", 401)
        name = claims.get("sub")
        if not name:
            raise AuthorizationError("JWT missing sub claim", 401)
        role_raw = claims.get(self.role_claim)
        try:
            role = (Role[role_raw.upper()] if isinstance(role_raw, str)
                    else self.default_role)
        except KeyError:
            raise AuthorizationError(f"unknown role {role_raw!r}", 403)
        return Principal(name, role)


class SpnegoSecurityProvider:
    """SPNEGO/Kerberos auth (ref ``security/spnego/``). Requires a GSSAPI
    implementation; this environment ships none, so construction is gated
    with a clear error instead of failing deep inside a request. When a
    ``gssapi`` module is available, tokens from the ``Authorization:
    Negotiate <token>`` header are accepted for the configured service
    principal."""

    #: challenge attached to every 401 from this provider (RFC 7235)
    default_challenge = "Negotiate"

    def __init__(self, service_principal: str,
                 role: Role = Role.USER):
        try:
            import gssapi
        except ImportError as e:
            raise RuntimeError(
                "SpnegoSecurityProvider requires the 'gssapi' package "
                "(Kerberos); install it or use webserver.security.provider="
                "basic|jwt|trustedproxy") from e
        self.service_principal = service_principal
        self.role = role
        # Acquire acceptor credentials once: resolves the principal and
        # reads the keytab at startup (bad configs fail loudly here, not
        # as per-request 401s).
        self._server_name = gssapi.Name(
            service_principal, name_type=gssapi.NameType.hostbased_service)
        self._creds = gssapi.Credentials(usage="accept",
                                         name=self._server_name)

    def authenticate(self, headers: dict[str, str]) -> Principal:
        import base64 as _b64

        import gssapi
        auth = headers.get("authorization", "")
        if not auth.startswith("Negotiate "):
            raise AuthorizationError("missing Negotiate token", 401,
                                     challenge="Negotiate")
        # Decode/handshake failures are authentication failures (401),
        # like every other provider — not 400/500 leaks of raw errors.
        try:
            token = _b64.b64decode(auth[10:])
            ctx = gssapi.SecurityContext(creds=self._creds, usage="accept")
            ctx.step(token)
            if not ctx.complete:
                raise AuthorizationError("incomplete SPNEGO handshake", 401,
                                         challenge="Negotiate")
            return Principal(str(ctx.initiator_name), self.role)
        except AuthorizationError:
            raise
        except Exception as e:
            raise AuthorizationError(f"SPNEGO authentication failed: "
                                     f"{type(e).__name__}", 401,
                                     challenge="Negotiate")


class TrustedProxySecurityProvider:
    """Trusted-proxy auth: requests from listed proxies carry the acting
    principal in a header (ref security/trustedproxy/)."""

    def __init__(self, trusted_proxies: set[str],
                 principal_header: str = "doAs",
                 role: Role = Role.USER,
                 ip_regex: str | None = None):
        import re
        self.trusted_proxies = trusted_proxies
        # The HTTP layer lowercases header names before dispatch.
        self.principal_header = principal_header.lower()
        self.role = role
        #: source-address gate (ref trusted.proxy.services.ip.regex): the
        #: proxy must ALSO connect from a matching address when set.
        self.ip_pattern = re.compile(ip_regex) if ip_regex else None

    def authenticate(self, headers: dict[str, str]) -> Principal:
        proxy = headers.get("x-forwarded-by", "")
        if proxy not in self.trusted_proxies:
            raise AuthorizationError(f"untrusted proxy {proxy!r}", 403)
        if self.ip_pattern is not None:
            # The HTTP layer records the peer address under this pseudo
            # header (never forwarded — set from the socket).
            addr = headers.get("x-cc-peer-address", "")
            if not self.ip_pattern.fullmatch(addr):
                raise AuthorizationError(
                    f"proxy address {addr!r} not allowed by "
                    "trusted.proxy.services.ip.regex", 403)
        name = headers.get(self.principal_header, "")
        if not name:
            raise AuthorizationError("missing doAs principal", 401)
        return Principal(name, self.role)


def check_access(provider: SecurityProvider, endpoint: str,
                 headers: dict[str, str]) -> Principal:
    principal = provider.authenticate(headers)
    required = ENDPOINT_MIN_ROLE.get(endpoint, Role.ADMIN)
    if principal.role.value < required.value:
        raise AuthorizationError(
            f"{principal.name} ({principal.role.name}) lacks "
            f"{required.name} for {endpoint}", 403)
    return principal
