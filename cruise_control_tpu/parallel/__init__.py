"""Multi-chip scale-out for the analyzer search.

The reference copes with model size by *shrinking the problem* (proposal
cache, fast mode, topic exclusion — SURVEY.md §5.7); it never parallelizes
the search. Here the partition axis of the flattened model shards across a
``jax.sharding.Mesh`` and XLA inserts the collectives: per-broker aggregates
are scatter-adds from sharded [P, R] arrays into replicated [B1, ...] rows
(an implicit psum), and candidate top-k runs shard-local then gathers.
"""

from ._compat import shard_map
from .batching import ProgramCache, pad_model_to, pow2_bucket, round_up
from .branches import (BRANCH_AXIS, make_branch_mesh, make_branched_search,
                       select_best)
from .population import (POPULATION_AXIS, make_population_mesh,
                         make_population_search, population_layout,
                         select_plan)
from .sharding import (PARTITION_AXIS, host_array_shardings, make_mesh,
                       mesh_fingerprint, model_shardings,
                       resolve_mesh_devices, scenario_batch_shardings,
                       shard_model, sharded_state_shardings)

__all__ = ["PARTITION_AXIS", "make_mesh", "mesh_fingerprint",
           "model_shardings", "resolve_mesh_devices", "shard_model",
           "shard_map", "sharded_state_shardings", "host_array_shardings",
           "scenario_batch_shardings", "BRANCH_AXIS", "make_branch_mesh",
           "make_branched_search", "select_best",
           "POPULATION_AXIS", "make_population_mesh",
           "make_population_search", "population_layout", "select_plan",
           "ProgramCache", "pad_model_to", "pow2_bucket", "round_up"]
