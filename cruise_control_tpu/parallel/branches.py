"""Multi-slice scale-out: independent search branches over a device mesh.

SURVEY §5.8(b): within a slice, the partition axis shards over ICI
(:mod:`.sharding`); *across* slices — where DCN latency would throttle the
per-iteration broker-aggregate all-reduces — the right decomposition is
independent *search branches*: every slice runs the full goal-chain search
on a replicated model with its own PRNG stream, and the best final state
by lexicographic violation wins. This replaces the reference's
proposal-precompute thread pool (``num.proposal.precompute.threads``,
``GoalOptimizer.java:112-119`` — N goal-chain runs on cloned models, best
result cached) with N device-resident branches.

Implemented with ``shard_map`` over a ``branch`` mesh axis: inputs
replicate, each branch derives its seed from ``axis_index``, and no
collective crosses branches until the final violation comparison — so
branch divergence (different per-branch iteration counts) is legal and
DCN sees exactly one sync at the end.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..analyzer.constraint import SearchConfig
from ..analyzer.engine import make_chain_step
from ..analyzer.goals import GoalKernel
from ._compat import shard_map

BRANCH_AXIS = "branch"


def make_branch_mesh(n_branches: int | None = None) -> Mesh:
    """One mesh axis over slices/devices, one branch per entry.

    On real multi-slice hardware pass the per-slice device groups; on a
    single host this fans branches across local devices.
    """
    devices = jax.devices()
    n = n_branches or len(devices)
    if len(devices) < n:
        raise ValueError(f"need {n} devices for {n} branches, "
                         f"have {len(devices)}")
    return Mesh(np.asarray(devices[:n]), (BRANCH_AXIS,))


def make_branched_search(goals: Sequence[GoalKernel], cfg: SearchConfig,
                         mesh: Mesh, collector=None):
    """Build ``run(state, ctx, key) -> (states, violations)`` where branch
    ``i`` holds ``states[i]`` (leading branch dim) and
    ``violations[i, g]`` its final per-goal residuals. Use
    :func:`select_best` to pick the winner.

    The jitted program registers with the device-runtime collector
    (``collector=None`` = the process default) as ``branched-search``, so
    its compiles and dispatches show on /devicestats like every other
    program in the repo."""
    chain = make_chain_step(goals, cfg)

    def branch(state, ctx, key):
        idx = jax.lax.axis_index(BRANCH_AXIS)
        st, stack = chain(state, ctx, jax.random.fold_in(key, idx))
        # Leading branch dim of size 1 per shard -> global [n_branches, ...]
        return (jax.tree.map(lambda x: x[None], st), stack[None])

    def run(state, ctx, key):
        in_specs = (jax.tree.map(lambda _: P(), state),
                    jax.tree.map(lambda _: P(), ctx), P())
        out_specs = (jax.tree.map(lambda _: P(BRANCH_AXIS), state),
                     P(BRANCH_AXIS))
        fn = shard_map(branch, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs)
        return fn(state, ctx, key)

    from ..core.runtime_obs import default_collector
    return (collector or default_collector()).track(
        f"branched-search-x{mesh.devices.size}", jax.jit(run))


def checked_violations(violations, what: str = "branched search"
                       ) -> np.ndarray:
    """Fetch a [N, G] violation matrix, failing loudly on NaN residuals.
    A NaN means a broken goal kernel, and NaN compares False both ways so
    any sort below could silently serve the broken plan — this is the
    shared guard for every best-of-N selection (branches AND the
    population search), matching the sequential walk's self-check."""
    v = np.asarray(jax.device_get(violations))   # [N, n_goals]
    if np.isnan(v).any():
        bad = sorted(set(np.nonzero(np.isnan(v))[0].tolist()))
        raise RuntimeError(
            f"{what} produced NaN violations on members {bad}")
    return v


_checked_violations = checked_violations


def audit_violation_count(audit_eval, member_state) -> int:
    """Number of audited hard goals a plan leaves violated — the ONE
    definition of the audit verdict used for best-of-N selection (the
    branched search and the population search both rank on it; the
    ulp-aware cutoff is ``GoalResult.satisfied``'s rule, 1e-6 + 1e-6 *
    scale). ``audit_eval(state) -> (f32[A] violations, f32[A] scales)``
    is the optimizer's jitted audit program; evaluated host-side per
    candidate plan — plan counts are device counts, so this is a
    handful of tiny dispatches."""
    av, scales = jax.device_get(audit_eval(member_state))
    av = np.asarray(av, dtype=np.float64)
    tol = 1e-6 + 1e-6 * np.asarray(scales, dtype=np.float64)
    return int((av > tol).sum())


def select_best(states, violations):
    """Pick the branch whose violation stack wins lexicographically
    (earlier goals dominate — same ordering the sequential chain
    enforces); ties break toward the lower branch index so results stay
    deterministic."""
    v = _checked_violations(violations)
    order = sorted(range(v.shape[0]), key=lambda i: (tuple(v[i]), i))
    best = order[0]
    state = jax.tree.map(lambda x: x[best], states)
    return state, best, v[best]


def select_best_audited(states, violations, audit_eval):
    """Like :func:`select_best`, but the off-chain hard-goal audit
    DOMINATES the ordering: a branch with fewer audit-violated hard
    goals wins even when another branch edges it lexicographically on
    chain residuals — otherwise the winner could fail the hard-goal gate
    while a passing plan existed in the same shard_map run.

    ``audit_eval(branch_state) -> (f32[A] violations, f32[A] scales)``
    (the optimizer's jitted audit program); evaluated per branch on the
    host side — branch counts are device counts, so this is a handful of
    tiny dispatches."""
    v = _checked_violations(violations)
    keys = []
    for i in range(v.shape[0]):
        bstate = jax.tree.map(lambda x, _i=i: x[_i], states)
        num_bad = audit_violation_count(audit_eval, bstate)
        keys.append((num_bad, tuple(v[i]), i))
    best = min(keys)[-1]
    state = jax.tree.map(lambda x: x[best], states)
    return state, best, v[best]
