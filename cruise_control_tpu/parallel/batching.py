"""Shared shape-bucketed batched-program machinery.

Both batched device subsystems — the what-if scenario engine
(``whatif/engine.py``, a vmapped ``[S, ...]`` scenario axis) and the
fleet control plane (``fleet/engine.py``, a cluster-sharded ``[C, ...]``
axis) — follow the same recipe: pad the batch axis to a bucket multiple
so nearby batch sizes reuse one compiled program, key the program on
(shapes, bucket, goal binding), cache a bounded number of compiled
variants behind a lock shared by request threads and background
detectors, and host-side re-pad the flat model when a batch outgrows the
live model's padding slack. This module is that recipe, lifted out of
the what-if engine so the fleet path consumes the identical machinery
instead of a second copy.
"""

from __future__ import annotations

import threading

import numpy as np


def round_up(n: int, multiple: int) -> int:
    """Next multiple of ``multiple`` at or above ``n`` (minimum one
    bucket: a zero/negative count still compiles a real program)."""
    if n <= 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


def pow2_bucket(n: int) -> int:
    """Next power of two at or above ``n`` (minimum 1) — the bucket rule
    shared by the population search's K axis and the tuner's shape
    buckets: geometric buckets keep the compiled-program (and tuned-
    config) count logarithmic in the sizes a long-lived process sees."""
    return 1 << max(int(n) - 1, 0).bit_length()


class ProgramCache:
    """Bounded, thread-safe compiled-program cache.

    Get-or-create holds the lock across the build so two racing first
    callers (an HTTP request thread and a background detector — the
    what-if engine's steady state; the fleet tick and a forced
    ``/fleet/rebalance``) converge on ONE program object instead of each
    paying a full XLA compile. FIFO-bounded like the optimizer's
    audit-fn cache: cache keys can carry per-topic bind masks, so an
    evolving topic set must not accumulate compiled programs forever.
    An evicted program still in use keeps working through its holder's
    reference; the next requester just rebuilds it.
    """

    def __init__(self, capacity: int = 8) -> None:
        self.capacity = capacity
        self._programs: dict = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)

    def get_or_build(self, key, build):
        """Return the cached program for ``key``, building (under the
        lock) and caching it on a miss."""
        with self._lock:
            program = self._programs.get(key)
            if program is None:
                program = build()
                self._programs[key] = program
                while len(self._programs) > self.capacity:
                    self._programs.pop(next(iter(self._programs)))
            return program


def pad_model_to(model, new_B: int, new_P: int, new_R: int):
    """Host-side re-pad of a ``FlatClusterModel`` to larger padded shapes
    (``new_B`` brokers x ``new_P`` partitions x ``new_R`` replica slots).

    The shared math behind the what-if engine's scenario re-pad (a
    BrokerAdd/TopicAdd batch outgrowing the live model's padding slack)
    and the fleet layer's shape-bucket stacking (heterogeneous member
    clusters padded to one fleet bucket). New broker rows arrive invalid
    (masked out of every reduction), new partition rows empty (replica
    slots on the sentinel), so the padded model scores bit-identically to
    the original. Costs one numpy round-trip + a metered re-upload; a
    no-op when the shapes already match.
    """
    from ..model.flat import FlatClusterModel
    B = model.num_brokers_padded
    P, R = model.replica_broker.shape
    if (new_B, new_P, new_R) == (B, P, R):
        return model
    if new_B < B or new_P < P or new_R < R:
        raise ValueError(
            f"pad_model_to cannot shrink: have ({B}, {P}, {R}), "
            f"asked for ({new_B}, {new_P}, {new_R})")

    rb = np.asarray(model.replica_broker)
    out_rb = np.full((new_P, new_R), new_B, np.int32)
    # The empty-slot sentinel is the one-past-last broker row, so it moves
    # with the broker padding: every old-sentinel entry must be rewritten.
    out_rb[:P, :R] = np.where(rb == B, new_B, rb)

    def pad_p(arr, fill):
        arr = np.asarray(arr)
        out = np.full((new_P,) + arr.shape[1:], fill, arr.dtype)
        out[:P] = arr
        return out

    def pad_b(arr, fill):
        arr = np.asarray(arr)
        out = np.full((new_B,) + arr.shape[1:], fill, arr.dtype)
        out[:B] = arr
        return out

    pref = np.tile(np.arange(new_R, dtype=np.int32), (new_P, 1))
    pref[:P, :R] = np.asarray(model.replica_pref_pos)
    offline = np.zeros((new_P, new_R), bool)
    offline[:P, :R] = np.asarray(model.replica_offline)
    return FlatClusterModel.from_numpy(
        replica_broker=out_rb,
        leader_load=pad_p(model.leader_load, 0.0),
        follower_load=pad_p(model.follower_load, 0.0),
        partition_topic=pad_p(model.partition_topic, -1),
        partition_valid=pad_p(model.partition_valid, False),
        replica_offline=offline,
        replica_pref_pos=pref,
        broker_capacity=pad_b(model.broker_capacity, 0.0),
        broker_rack=pad_b(model.broker_rack, 0),
        broker_host=pad_b(model.broker_host, 0),
        broker_set=pad_b(model.broker_set, -1),
        broker_alive=pad_b(model.broker_alive, False),
        broker_new=pad_b(model.broker_new, False),
        broker_demoted=pad_b(model.broker_demoted, False),
        broker_broken_disk=pad_b(model.broker_broken_disk, False),
        broker_valid=pad_b(model.broker_valid, False))
