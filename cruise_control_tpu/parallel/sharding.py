"""Mesh + sharding layout for the flattened cluster model.

Layout decision (the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives):

- Partition-indexed arrays ([P] / [P, R] / [P, 4]) shard over the mesh axis
  ``"p"`` — the partition axis is the big one (1M at LinkedIn scale) and
  every per-replica computation is independent along it.
- Broker-indexed arrays ([B1] / [B1, 4]) replicate: B is ~1000x smaller than
  P, every candidate scoring step reads arbitrary broker rows (gathers), and
  the scatter-add that builds them from sharded replica loads becomes an XLA
  all-reduce over ICI — exactly the psum the hand-written version would do.
- Scalars and candidate batches replicate.

The same layout serves single-chip (trivial mesh) and multi-slice (mesh over
DCN: keep "p" inside a slice so the per-iteration all-reduce of two [B1, 4]
rows rides ICI; only the per-goal boundary syncs cross DCN).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..model.flat import FlatClusterModel

PARTITION_AXIS = "p"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"(set --xla_force_host_platform_device_count for CPU tests)")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (PARTITION_AXIS,))


def _spec_for(leaf: jax.Array, num_partitions_padded: int) -> P:
    """Partition-axis leaves shard on dim 0; everything else replicates."""
    if leaf.ndim >= 1 and leaf.shape[0] == num_partitions_padded:
        return P(PARTITION_AXIS, *([None] * (leaf.ndim - 1)))
    return P()


def model_shardings(model: FlatClusterModel, mesh: Mesh):
    """Pytree of NamedShardings matching :class:`FlatClusterModel` leaves."""
    Ppad = model.num_partitions_padded
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, _spec_for(leaf, Ppad)), model)


def shard_model(model: FlatClusterModel, mesh: Mesh) -> FlatClusterModel:
    """Place the model on the mesh (partition axis sharded)."""
    return jax.device_put(model, model_shardings(model, mesh))


def sharded_state_shardings(state, mesh: Mesh, num_partitions_padded: int):
    """Shardings for a :class:`..analyzer.state.SearchState` pytree."""
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, _spec_for(leaf, num_partitions_padded)),
        state)
