"""Mesh + sharding layout for the flattened cluster model.

Layout decision (the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives):

- Partition-indexed arrays ([P] / [P, R] / [P, 4]) shard over the mesh axis
  ``"p"`` — the partition axis is the big one (1M at LinkedIn scale) and
  every per-replica computation is independent along it.
- Broker-indexed arrays ([B1] / [B1, 4]) replicate: B is ~1000x smaller than
  P, every candidate scoring step reads arbitrary broker rows (gathers), and
  the scatter-add that builds them from sharded replica loads becomes an XLA
  all-reduce over ICI — exactly the psum the hand-written version would do.
- Scalars and candidate batches replicate.

The same layout serves single-chip (trivial mesh) and multi-slice (mesh over
DCN: keep "p" inside a slice so the per-iteration all-reduce of two [B1, 4]
rows rides ICI; only the per-goal boundary syncs cross DCN).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..model.flat import FlatClusterModel

PARTITION_AXIS = "p"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"(set --xla_force_host_platform_device_count for CPU tests)")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (PARTITION_AXIS,))


def resolve_mesh_devices(n: int) -> int:
    """Resolve the ``search.mesh.devices`` config value to a concrete
    device count: ``-1`` means "all visible devices", positive values
    clamp to what jax exposes, ``0`` stays 0 (no mesh)."""
    if n == 0:
        return 0
    available = len(jax.devices())
    return available if n < 0 else min(n, available)


def mesh_fingerprint(mesh: Mesh | None):
    """Hashable identity of a mesh for program-cache keys (None = no
    mesh). Device objects themselves are process-stable but their hash
    is not guaranteed across jax versions; the string ids are."""
    if mesh is None:
        return None
    return tuple(str(d) for d in mesh.devices.flat)


def _spec_for(leaf: jax.Array, num_partitions_padded: int) -> P:
    """Partition-axis leaves shard on dim 0; everything else replicates."""
    if leaf.ndim >= 1 and leaf.shape[0] == num_partitions_padded:
        return P(PARTITION_AXIS, *([None] * (leaf.ndim - 1)))
    return P()


def model_shardings(model: FlatClusterModel, mesh: Mesh):
    """Pytree of NamedShardings matching :class:`FlatClusterModel` leaves."""
    Ppad = model.num_partitions_padded
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, _spec_for(leaf, Ppad)), model)


def shard_model(model: FlatClusterModel, mesh: Mesh) -> FlatClusterModel:
    """Place the model on the mesh (partition axis sharded)."""
    return jax.device_put(model, model_shardings(model, mesh))


def sharded_state_shardings(state, mesh: Mesh, num_partitions_padded: int):
    """Shardings for a :class:`..analyzer.state.SearchState` pytree."""
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, _spec_for(leaf, num_partitions_padded)),
        state)


def host_array_shardings(arrays: dict, mesh: Mesh,
                         num_partitions_padded: int) -> dict:
    """NamedShardings for a ``FlatClusterModel.from_numpy`` kwarg dict of
    HOST arrays — same layout rule as :func:`model_shardings` (partition
    axis shards, broker axis replicates), applied before the upload so a
    full rebuild ships per-device shards instead of one monolithic array
    followed by a device-side reshard."""
    return {name: NamedSharding(mesh, _spec_for(a, num_partitions_padded))
            for name, a in arrays.items()}


def scenario_batch_shardings(mesh: Mesh, num_partitions_padded: int, tree):
    """Shardings for the what-if engine's per-scenario parameter arrays:
    ``[S, P]``-shaped leaves shard the partition axis (dim 1, the big
    one); the scenario axis and every broker-indexed parameter replicate
    — the vmapped sweep then partitions exactly like the goal passes."""
    def spec(leaf):
        if leaf.ndim >= 2 and leaf.shape[1] == num_partitions_padded:
            return P(None, PARTITION_AXIS, *([None] * (leaf.ndim - 2)))
        return P()
    return jax.tree.map(lambda l: NamedSharding(mesh, spec(l)), tree)
