"""Multi-objective population search: K candidate plans evolve in ONE
jitted program, scored jointly over every goal.

The sequential optimizer walks the goal chain once; the branched search
(:mod:`.branches`) runs N independent full chains and keeps the
lexicographic best. This module is the next step (PAPERS.md:
"Multi-Objective Optimization of Consumer Group Autoscaling", arxiv
2402.06085): a *population* of K candidate plans where

- every member runs the goal-chain walk — the UNMODIFIED pass functions
  from the process-wide compiled chain (``CompiledGoalChain._pass_fns``,
  the same functions the sequential path compiled), so each member's
  moves come from exactly the engine's top-k / cross-product / conflict
  machinery;
- between polish generations the whole population is scored JOINTLY over
  all goals — the violation stack, scale-normalized, reduced to a
  weighted sum or a dominance-count Pareto rank
  (``analyzer.engine.weighted_objective`` / ``pareto_ranks``) — and
  truncation selection reseeds the losers from the survivors; an adopted
  plan keeps evolving under its slot's own PRNG stream, so lineages
  diverge again immediately;
- the served plan is the multi-objective winner (host-side
  :func:`select_plan`, hard-goal audit verdicts dominating like
  ``branches.select_best_audited``).

**Anchor guarantee**: member 0 always runs the exact sequential schedule
— same key stream (``key`` itself, not a fold), never adopts another
member's state (``perm[0] == 0``), per-goal polish skip decisions
identical to the host loop's — so ``K=1`` degenerates to the sequential
chain walk bit for bit (tier-1 gated), and because member 0 is always in
the final selection pool, the winner can never score worse than the
sequential plan under the configured objective.

The population axis rides the same machinery as the branched search:
``shard_map`` over a member mesh axis fans members across devices, an
inner ``lax.map`` packs multiple members per device (real control flow —
no vmap batching rewrite, the fleet lesson), and the compiled programs
live in the shared :class:`.batching.ProgramCache`. K rounds up to the
next power of two (:func:`.batching.pow2_bucket`) so nearby population
sizes share one compiled program; the extra slots run as additional
explorers.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..analyzer.constraint import PopulationConfig, SearchConfig
from ..analyzer.engine import (pareto_ranks, violation_stack,
                               weighted_objective)
from ..analyzer.goals import GoalKernel
from ._compat import shard_map
from .batching import pow2_bucket
from .branches import audit_violation_count, checked_violations

POPULATION_AXIS = "member"

#: PRNG stream salt for members > 0 (member 0 uses the request key
#: verbatim — the anchor's stream must equal the sequential walk's).
#: Distinct from the engine's internal fold_in salts (70_000 drain,
#: 50_000 fused polish, 1000-series polish rounds).
_MEMBER_KEY_SALT = 90_000


def population_layout(size: int, device_cap: int | None = None
                      ) -> tuple[int, int, int]:
    """(devices D, members-per-device k, K bucket) for a K-member
    population: K rounds up to the next power of two (the K-bucket —
    nearby sizes reuse one compiled program), members fan out over up to
    ``device_cap`` devices, the remainder packs via the inner
    ``lax.map``. Powers of two keep the split even, so no padding slots
    exist — every slot is a real explorer."""
    cap = device_cap if device_cap is not None else len(jax.devices())
    K = pow2_bucket(max(int(size), 1))
    D = min(max(cap, 1), K)
    while K % D:
        D -= 1          # K is a power of two: lands on a power of two
    return D, K // D, K


def make_population_mesh(num_devices: int) -> Mesh:
    """One mesh axis over the local devices, like ``make_branch_mesh``
    but under the population's own axis name."""
    devices = jax.devices()
    if len(devices) < num_devices:
        raise ValueError(f"need {num_devices} devices, "
                         f"have {len(devices)}")
    return Mesh(np.asarray(devices[:num_devices]), (POPULATION_AXIS,))


def n_survivors(size: int, fraction: float) -> int:
    """Survivor count for a K-member generation: ``ceil(K * fraction)``
    clamped to ``[1, K-1]`` (for K > 1). The upper clamp matters: slot 0
    is force-anchored to the sequential lineage AFTER the survivor
    round-robin, so only K-1 slots are free — with K survivors the
    top-ranked plan would hold ONLY slot 0 and be silently discarded by
    the anchor override. Capping at K-1 guarantees every survivor
    (including the rank winner at slot ``n_surv``) keeps at least one
    slot."""
    if size <= 1:
        return 1
    return max(1, min(math.ceil(size * fraction), size - 1))


def _member_key(key: jax.Array, m: jax.Array) -> jax.Array:
    # Member 0 is the anchor: ITS stream is the request key itself, so
    # its walk/polish keys equal the sequential loop's fold_in series.
    return jnp.where(m == 0, key,
                     jax.random.fold_in(key, _MEMBER_KEY_SALT + m))


def make_population_search(pass_fns: Sequence, goals: Sequence[GoalKernel],
                           cfg: SearchConfig, pop_cfg: PopulationConfig,
                           mesh: Mesh, k_per_dev: int, collector=None):
    """Build ``run(state, ctx, key)`` — the whole population search as one
    jitted program (single device dispatch + single host fetch per
    optimize, like the fused chain).

    ``pass_fns`` must be the compiled chain's raw pass functions
    (``CompiledGoalChain._pass_fns`` — the process-wide shared-chain
    registry stays the one source of pass identity, exactly as the fleet
    walk consumes them).

    Returns, for ``K = mesh.devices.size * k_per_dev`` members:

    - ``states``: final SearchStates stacked on a leading [K] axis (left
      on device; the winner is gathered after host-side selection),
    - ``aux``: ``(offline.any(), f32[G] scales, f32[G] initial stack)``
      — the sequential path's pre-pass readings, computed once,
    - ``iters``: i32[K, G] per-member per-goal iteration totals,
    - ``walk_bounds``: f32[K, G, G] — row i is slot m's plan's violation
      stack after walk pass i (the sequential boundary bookkeeping;
      histories follow adoptions, so a slot always carries its CURRENT
      plan's lineage),
    - ``polish_rows``: f32[R, K, G] round-end stacks (R polish rounds),
    - ``moves``: i32[K] cumulative moves applied per member,
    - ``accepted``: i32[K, G] per-member per-goal accepted-move counts,
    - ``perms``: i32[R, K] the survivor permutation applied before each
      polish generation (slot i's plan came from slot ``perms[r, i]``),
    - ``ranks``: i32[K] final dominance-count Pareto ranks,
    - ``weighted``: f32[K] final weighted-objective scores.

    Everything the host needs rides this one program's outputs — the
    population telemetry adds ZERO device syncs beyond the sequential
    path's end-of-chain fetch (gated in tests/test_tracing.py).
    """
    goals = tuple(goals)
    pass_fns = tuple(pass_fns)
    G = len(goals)
    D = int(mesh.devices.size)
    K = D * int(k_per_dev)
    R = cfg.polish_passes + 1 if cfg.polish_passes else 0
    polish_eps = min(cfg.epsilon, 1e-6)
    hard_mask = np.asarray([g.hard for g in goals], bool)
    n_surv = n_survivors(K, pop_cfg.survivor_fraction)
    use_pareto = pop_cfg.objective == "pareto"

    def _member_walk(state, ctx, mkey):
        """The sequential walk, one member: every pass in chain order,
        keys fold_in(mkey, i) — identical to the host loop's
        ``_walk_passes(chain, range(G), ...)`` schedule."""
        iters, bounds, moves = [], [], []
        for i, run_pass in enumerate(pass_fns):
            state, it, stack, mv = run_pass(state, ctx,
                                            jax.random.fold_in(mkey, i))
            iters.append(it)
            bounds.append(stack)
            moves.append(mv)
        return (state, jnp.stack(iters), jnp.stack(bounds),
                jnp.stack(moves))

    def _member_polish(state, ctx, mkey, boundary, rnd):
        """One polish round, one member — the sequential loop's exact
        semantics: skip decisions use the ROUND-START boundary (frozen),
        keys fold_in(mkey, 1000*(rnd+1)+i), ``~(x <= eps)`` keeps NaN
        residuals in the todo set, and a round whose starting boundary is
        fully converged runs nothing (the host loop's ``break``)."""
        round_do = jnp.any(~(boundary <= polish_eps))
        prev_stack = boundary
        iters, moves = [], []
        for i, run_pass in enumerate(pass_fns):
            todo = round_do & ~(boundary[i] <= polish_eps)

            def _do(st, _p=run_pass, _i=i):
                return _p(st, ctx,
                          jax.random.fold_in(mkey, 1000 * (rnd + 1) + _i))

            def _skip(st, _prev=prev_stack):
                return (st, jnp.zeros((), jnp.int32), _prev,
                        st.moves_applied)

            state, it, stack, mv = jax.lax.cond(todo, _do, _skip, state)
            prev_stack = stack
            iters.append(it)
            moves.append(mv)
        return state, jnp.stack(iters), prev_stack, jnp.stack(moves)

    def _rep_specs(tree):
        return jax.tree.map(lambda _: P(), tree)

    def _pop_specs(tree):
        return jax.tree.map(lambda _: P(POPULATION_AXIS), tree)

    def _walk_sm(state, ctx, key):
        """shard_map'd walk: inputs replicate, each device evolves its
        k_per_dev members via lax.map, outputs stack on the global [K]
        member axis (the branches.py recipe with an inner member pack)."""
        def body(state, ctx, key):
            d = jax.lax.axis_index(POPULATION_AXIS)

            def one(j):
                m = d * k_per_dev + j
                return _member_walk(state, ctx, _member_key(key, m))

            return jax.lax.map(one, jnp.arange(k_per_dev))

        out_struct = jax.eval_shape(
            lambda s, c, k: _member_walk(s, c, k), state, ctx, key)
        return shard_map(
            body, mesh=mesh,
            in_specs=(_rep_specs(state), _rep_specs(ctx), P()),
            out_specs=_pop_specs(out_struct))(state, ctx, key)

    def _polish_sm(states, ctx, boundary, key, rnd):
        def body(states, ctx, boundary, key):
            d = jax.lax.axis_index(POPULATION_AXIS)

            def one(t):
                j, st, bnd = t
                m = d * k_per_dev + j
                return _member_polish(st, ctx, _member_key(key, m), bnd,
                                      rnd)

            return jax.lax.map(one, (jnp.arange(k_per_dev), states,
                                     boundary))

        state1 = jax.tree.map(lambda x: jax.ShapeDtypeStruct(
            x.shape[1:], x.dtype), states)
        bnd1 = jax.ShapeDtypeStruct(boundary.shape[1:], boundary.dtype)
        out_struct = jax.eval_shape(
            lambda s, c, b, k: _member_polish(s, c, k, b, rnd),
            state1, ctx, bnd1, key)
        return shard_map(
            body, mesh=mesh,
            in_specs=(_pop_specs(states), _rep_specs(ctx),
                      P(POPULATION_AXIS), P()),
            out_specs=_pop_specs(out_struct))(states, ctx, boundary, key)

    def _scores(boundary, moves, scales):
        weighted = weighted_objective(
            boundary, scales, hard_mask, hard_weight=pop_cfg.hard_weight,
            move_weight=pop_cfg.move_weight, moves=moves)
        ranks = pareto_ranks(boundary, scales)
        return ranks, weighted

    def _survivor_perm(boundary, moves, scales):
        """Truncation selection: rank by (Pareto rank when configured,)
        weighted score with index tie-break, top n_surv survive, slot i
        adopts survivor[i mod n_surv] — and slot 0 is ALWAYS re-anchored
        to its own lineage (the sequential anchor never adopts; n_surv
        <= K-1, see ``n_survivors``, so the override can never evict the
        rank winner's only slot)."""
        ranks, weighted = _scores(boundary, moves, scales)
        primary = (ranks.astype(jnp.float32) if use_pareto
                   else jnp.zeros_like(weighted))
        order = jnp.lexsort((jnp.arange(K), weighted, primary))
        survivors = order[:n_surv]
        perm = survivors[jnp.arange(K) % n_surv]
        return perm.at[0].set(0)

    def run(state, ctx, key):
        # The sequential path's pre-pass aux readings, computed ONCE for
        # the shared initial state (all members start from the request
        # model) — same definition as CompiledGoalChain._aux_impl.
        aux = (state.offline.any(),
               jnp.stack([g.violation_scale(state, ctx) for g in goals]),
               violation_stack(goals, state, ctx))
        scales = aux[1]
        states, iters, walk_bounds, mv_walk = _walk_sm(state, ctx, key)
        boundary = walk_bounds[:, -1, :]                        # [K, G]
        accepted = mv_walk - jnp.concatenate(
            [jnp.zeros((K, 1), mv_walk.dtype), mv_walk[:, :-1]], axis=1)
        moves = mv_walk[:, -1]                                  # [K]
        perms, rows = [], []
        for rnd in range(R):
            # Generation boundary: joint multi-objective scoring over the
            # whole population, truncation selection, survivor adoption.
            # The gather between shard_map regions reshards at the jit
            # level (XLA inserts the collective); all per-member
            # accounting follows its plan's lineage.
            perm = _survivor_perm(boundary, moves, scales)
            states = jax.tree.map(lambda x: x[perm], states)
            boundary, iters = boundary[perm], iters[perm]
            accepted, moves = accepted[perm], moves[perm]
            # History follows the plan's LINEAGE: after every adoption the
            # per-slot walk rows and earlier round rows are re-permuted
            # too, so slot m's history is always its current plan's own
            # history (the winner's trajectory reads straight off slot
            # ``best`` — tiny [K, G] arrays, negligible cost).
            walk_bounds = walk_bounds[perm]
            rows = [r[perm] for r in rows]
            states, it_r, b_r, mv_r = _polish_sm(states, ctx, boundary,
                                                 key, rnd)
            accepted = accepted + mv_r - jnp.concatenate(
                [moves[:, None], mv_r[:, :-1]], axis=1)
            moves = mv_r[:, -1]
            iters = iters + it_r
            boundary = b_r
            perms.append(perm)
            rows.append(boundary)
        ranks, weighted = _scores(boundary, moves, scales)
        polish_rows = (jnp.stack(rows) if rows
                       else jnp.zeros((0, K, G), jnp.float32))
        perm_arr = (jnp.stack(perms) if perms
                    else jnp.zeros((0, K), jnp.int32))
        return (states, aux, iters, walk_bounds, polish_rows, moves,
                accepted, perm_arr, ranks, weighted)

    # No donation: the initial state fans out to K member copies, so its
    # buffer is never reusable in place (jit would warn on every call).
    from ..core.runtime_obs import default_collector
    return (collector or default_collector()).track(
        f"population-search-x{K}", jax.jit(run))


def select_plan(states, stacks, moves, ranks, weighted,
                pop_cfg: PopulationConfig, audit_eval=None):
    """Pick the served plan from the population: hard-goal audit verdicts
    dominate (a gate-passing plan beats any gate-failing one — the
    ``select_best_audited`` rule), then the configured joint objective
    (Pareto rank when ``objective="pareto"``), then the weighted score,
    then the lexicographic stack, ties toward the lower slot (slot 0 is
    the sequential anchor, so "no worse than sequential" holds under the
    configured objective by construction).

    ``stacks``/``moves``/``ranks``/``weighted`` are the already-fetched
    host copies; only the winner's state is gathered off the device.
    Returns ``(state, winner_index, winner_stack)``."""
    v = checked_violations(stacks, "population search")
    ranks = np.asarray(ranks)
    weighted = np.asarray(weighted)
    moves = np.asarray(moves)
    keys = []
    for m in range(v.shape[0]):
        num_bad = 0
        if audit_eval is not None:
            mstate = jax.tree.map(lambda x, _m=m: x[_m], states)
            num_bad = audit_violation_count(audit_eval, mstate)
        primary = (int(ranks[m]) if pop_cfg.objective == "pareto" else 0)
        keys.append((num_bad, primary, float(weighted[m]), tuple(v[m]),
                     int(moves[m]), m))
    best = min(keys)[-1]
    state = jax.tree.map(lambda x: x[best], states)
    return state, best, v[best]
