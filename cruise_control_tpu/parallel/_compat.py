"""jax version compatibility for the parallel layer.

The ``shard_map`` entry point moved out of ``jax.experimental`` in
jax 0.8 and renamed its replication-checker kwarg (``check_rep`` ->
``check_vma``) on the way. This shim is the ONE place that reasoning
lives: every module that needs shard_map imports :func:`shard_map` from
here (enforced by a lint test in ``tests/test_parallel.py`` — a second
copy of the try/except would drift the kwarg handling the moment the
next rename lands).
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map   # jax >= 0.8
    _CHECK_KW = "check_vma"
except ImportError:   # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"


def shard_map(fn, **kwargs):
    """``jax.shard_map`` with the replication checker OFF under the
    version-correct kwarg name. The callers here derive per-shard
    behavior from ``axis_index`` (branch seeds), which makes outputs
    intentionally non-replicated — the checker would reject them."""
    kwargs[_CHECK_KW] = False
    return _shard_map(fn, **kwargs)
