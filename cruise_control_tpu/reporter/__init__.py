"""Broker metrics reporter agent (L0) — rebuild of
``cruise-control-metrics-reporter``: raw metric types + records
(:mod:`.metrics`), the metrics-topic transport (:mod:`.transport`), and the
per-broker harvesting agent (:mod:`.agent`)."""

from .agent import (BrokerMetricsSource, MetricsReporterAgent,
                    SimClusterMetricsSource)
from .metrics import CruiseControlMetric, MetricScope, RawMetricType
from .transport import MetricsTransport

__all__ = ["BrokerMetricsSource", "MetricsReporterAgent",
           "SimClusterMetricsSource", "CruiseControlMetric", "MetricScope",
           "RawMetricType", "MetricsTransport"]
