"""Metrics transport: the stand-in for the ``__CruiseControlMetrics`` topic.

The reference's agent produces serialized metric records to a Kafka topic
the sampler later consumes (``CruiseControlMetricsReporter.java:65`` /
``CruiseControlMetricsReporterSampler.java:93``). This in-process transport
keeps the same produce/poll contract (append-only log, time-ranged reads,
serialized records) so the agent -> sampler pipeline is exercised end to
end; a Kafka-backed implementation would swap in a producer/consumer pair
behind the same two methods.
"""

from __future__ import annotations

import threading

from .metrics import CruiseControlMetric


class MetricsTransport:
    def __init__(self, retention_ms: int | None = None):
        self._records: list[tuple[int, bytes]] = []   # (time_ms, serialized)
        self._lock = threading.Lock()
        self._retention_ms = retention_ms

    def produce(self, metric: CruiseControlMetric) -> None:
        with self._lock:
            self._records.append((metric.time_ms, metric.serialize()))

    def produce_all(self, metrics) -> None:
        for m in metrics:
            self.produce(m)

    def poll(self, start_ms: int, end_ms: int) -> list[CruiseControlMetric]:
        """Records with start_ms <= time < end_ms (the sampler's window)."""
        with self._lock:
            if self._retention_ms is not None and self._records:
                horizon = self._records[-1][0] - self._retention_ms
                self._records = [r for r in self._records if r[0] >= horizon]
            return [CruiseControlMetric.deserialize(raw)
                    for t, raw in self._records if start_ms <= t < end_ms]

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)
