"""Raw metric records emitted by the broker agent (L0).

Rebuild of ``cruise-control-metrics-reporter``'s metric model
(``metricsreporter/metric/RawMetricType.java:27`` — 43 types across
BROKER / TOPIC / PARTITION scopes — and ``CruiseControlMetric.java`` with
its Broker/Topic/PartitionMetric subclasses + ``MetricSerde.java``).
The monitor's processor consumes these records; the agent produces them.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass


class MetricScope(enum.Enum):
    BROKER = "BROKER"
    TOPIC = "TOPIC"
    PARTITION = "PARTITION"


class RawMetricType(enum.Enum):
    """ref RawMetricType.java:27+ (43 types; value = stable wire id)."""

    # --- broker scope -----------------------------------------------------
    ALL_TOPIC_BYTES_IN = 0
    ALL_TOPIC_BYTES_OUT = 1
    ALL_TOPIC_REPLICATION_BYTES_IN = 2
    ALL_TOPIC_REPLICATION_BYTES_OUT = 3
    ALL_TOPIC_FETCH_REQUEST_RATE = 4
    ALL_TOPIC_PRODUCE_REQUEST_RATE = 5
    ALL_TOPIC_MESSAGES_IN_PER_SEC = 6
    BROKER_CPU_UTIL = 7
    BROKER_PRODUCE_REQUEST_RATE = 8
    BROKER_CONSUMER_FETCH_REQUEST_RATE = 9
    BROKER_FOLLOWER_FETCH_REQUEST_RATE = 10
    BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT = 11
    BROKER_REQUEST_QUEUE_SIZE = 12
    BROKER_RESPONSE_QUEUE_SIZE = 13
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX = 14
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN = 15
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 16
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 17
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 18
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 19
    BROKER_PRODUCE_TOTAL_TIME_MS_MAX = 20
    BROKER_PRODUCE_TOTAL_TIME_MS_MEAN = 21
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MAX = 22
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MEAN = 23
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MAX = 24
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MEAN = 25
    BROKER_PRODUCE_LOCAL_TIME_MS_MAX = 26
    BROKER_PRODUCE_LOCAL_TIME_MS_MEAN = 27
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MAX = 28
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN = 29
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MAX = 30
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN = 31
    BROKER_LOG_FLUSH_RATE = 32
    BROKER_LOG_FLUSH_TIME_MS_MAX = 33
    BROKER_LOG_FLUSH_TIME_MS_MEAN = 34
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH = 35
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_999TH = 36
    BROKER_LOG_FLUSH_TIME_MS_50TH = 37
    BROKER_LOG_FLUSH_TIME_MS_999TH = 38
    # --- topic scope ------------------------------------------------------
    TOPIC_BYTES_IN = 39
    TOPIC_BYTES_OUT = 40
    TOPIC_REPLICATION_BYTES_IN = 41
    TOPIC_REPLICATION_BYTES_OUT = 42
    TOPIC_FETCH_REQUEST_RATE = 43
    TOPIC_PRODUCE_REQUEST_RATE = 44
    TOPIC_MESSAGES_IN_PER_SEC = 45
    # --- partition scope --------------------------------------------------
    PARTITION_SIZE = 46

    @property
    def scope(self) -> MetricScope:
        v = self.value
        if v <= 38:
            return MetricScope.BROKER
        if v <= 45:
            return MetricScope.TOPIC
        return MetricScope.PARTITION


@dataclass(frozen=True)
class CruiseControlMetric:
    """One raw metric record (ref CruiseControlMetric.java + the
    BrokerMetric/TopicMetric/PartitionMetric subclasses, collapsed into one
    record with optional topic/partition fields)."""

    metric_type: RawMetricType
    time_ms: int
    broker_id: int
    value: float
    topic: str | None = None
    partition: int | None = None

    def __post_init__(self):
        scope = self.metric_type.scope
        if scope is MetricScope.TOPIC and self.topic is None:
            raise ValueError(f"{self.metric_type.name} requires a topic")
        if scope is MetricScope.PARTITION and (self.topic is None
                                               or self.partition is None):
            raise ValueError(f"{self.metric_type.name} requires topic+partition")

    # -- wire format (ref MetricSerde.java, JSON instead of binary) --------
    def serialize(self) -> bytes:
        d = {"t": self.metric_type.value, "ts": self.time_ms,
             "b": self.broker_id, "v": self.value}
        if self.topic is not None:
            d["topic"] = self.topic
        if self.partition is not None:
            d["p"] = self.partition
        return json.dumps(d).encode()

    @classmethod
    def deserialize(cls, raw: bytes) -> "CruiseControlMetric":
        d = json.loads(raw.decode())
        return cls(metric_type=RawMetricType(d["t"]), time_ms=d["ts"],
                   broker_id=d["b"], value=d["v"], topic=d.get("topic"),
                   partition=d.get("p"))
