"""The broker metrics reporter agent (L0).

Rebuild of ``CruiseControlMetricsReporter.java:65``: runs alongside each
broker, harvests its metrics on an interval, and produces
:class:`CruiseControlMetric` records to the metrics transport. The
reference plugs into Kafka's ``MetricsReporter`` and reads the Yammer
registry; here the agent reads a :class:`BrokerMetricsSource` (implemented
by ``SimulatedKafkaCluster`` views or any object exposing the same
per-broker numbers) — the harvest/serialize/produce loop and record schema
are the parity surface.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol

from .metrics import CruiseControlMetric, RawMetricType
from .transport import MetricsTransport


class BrokerMetricsSource(Protocol):
    """What the agent reads from its broker each interval."""

    def broker_stats(self, broker_id: int) -> dict[str, float]:
        """e.g. cpu_util, bytes_in/out, replication bytes, request rates."""
        ...

    def topic_stats(self, broker_id: int) -> dict[str, dict[str, float]]:
        """topic -> {bytes_in, bytes_out, replication_bytes_in, ...} for
        partitions led on this broker."""
        ...

    def partition_sizes(self, broker_id: int) -> dict[tuple[str, int], float]:
        """(topic, partition) -> size MB for replicas hosted on this broker."""
        ...


_BROKER_STAT_TYPES = {
    "cpu_util": RawMetricType.BROKER_CPU_UTIL,
    "bytes_in": RawMetricType.ALL_TOPIC_BYTES_IN,
    "bytes_out": RawMetricType.ALL_TOPIC_BYTES_OUT,
    "replication_bytes_in": RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN,
    "replication_bytes_out": RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT,
    "produce_request_rate": RawMetricType.ALL_TOPIC_PRODUCE_REQUEST_RATE,
    "fetch_request_rate": RawMetricType.ALL_TOPIC_FETCH_REQUEST_RATE,
    "messages_in_rate": RawMetricType.ALL_TOPIC_MESSAGES_IN_PER_SEC,
    "request_handler_idle_percent":
        RawMetricType.BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT,
    "request_queue_size": RawMetricType.BROKER_REQUEST_QUEUE_SIZE,
    "log_flush_rate": RawMetricType.BROKER_LOG_FLUSH_RATE,
    "log_flush_time_ms": RawMetricType.BROKER_LOG_FLUSH_TIME_MS_MEAN,
    "log_flush_time_ms_999": RawMetricType.BROKER_LOG_FLUSH_TIME_MS_999TH,
}

_TOPIC_STAT_TYPES = {
    "bytes_in": RawMetricType.TOPIC_BYTES_IN,
    "bytes_out": RawMetricType.TOPIC_BYTES_OUT,
    "replication_bytes_in": RawMetricType.TOPIC_REPLICATION_BYTES_IN,
    "messages_in_rate": RawMetricType.TOPIC_MESSAGES_IN_PER_SEC,
}


@dataclass
class MetricsReporterAgent:
    """One agent instance per broker (ref CruiseControlMetricsReporter)."""

    broker_id: int
    source: BrokerMetricsSource
    transport: MetricsTransport
    reporting_interval_ms: int = 60_000
    _last_report_ms: int = -1

    def maybe_report(self, now_ms: int) -> int:
        """Harvest + produce if the interval elapsed; returns #records
        produced (ref the reporter's scheduled ``run()``)."""
        if (self._last_report_ms >= 0
                and now_ms - self._last_report_ms < self.reporting_interval_ms):
            return 0
        self._last_report_ms = now_ms
        return self.report(now_ms)

    def report(self, now_ms: int) -> int:
        records: list[CruiseControlMetric] = []
        stats = self.source.broker_stats(self.broker_id)
        for key, mtype in _BROKER_STAT_TYPES.items():
            if key in stats:
                records.append(CruiseControlMetric(
                    mtype, now_ms, self.broker_id, float(stats[key])))
        for topic, tstats in self.source.topic_stats(self.broker_id).items():
            for key, mtype in _TOPIC_STAT_TYPES.items():
                if key in tstats:
                    records.append(CruiseControlMetric(
                        mtype, now_ms, self.broker_id, float(tstats[key]),
                        topic=topic))
        for (topic, partition), size in self.source.partition_sizes(
                self.broker_id).items():
            records.append(CruiseControlMetric(
                RawMetricType.PARTITION_SIZE, now_ms, self.broker_id,
                float(size), topic=topic, partition=partition))
        self.transport.produce_all(records)
        return len(records)


class SimClusterMetricsSource:
    """Adapts a :class:`SimulatedKafkaCluster` + synthetic per-partition
    rates into the agent's metrics source (what a real broker's Yammer
    registry provides)."""

    def __init__(self, cluster, rates):
        """``rates``: (topic, partition) -> (bytes_in, bytes_out)."""
        self.cluster = cluster
        self.rates = rates

    def _led(self, broker_id: int):
        return [info for info in self.cluster.describe_partitions().values()
                if info.leader == broker_id]

    def broker_stats(self, broker_id: int) -> dict[str, float]:
        led = self._led(broker_id)
        bytes_in = sum(self.rates.get(i.tp, (0, 0))[0] for i in led)
        bytes_out = sum(self.rates.get(i.tp, (0, 0))[1] for i in led)
        repl_in = sum(self.rates.get(i.tp, (0, 0))[0]
                      for i in self.cluster.describe_partitions().values()
                      if broker_id in i.replicas and i.leader != broker_id)
        sim = self.cluster.broker_metrics(broker_id)
        return {"cpu_util": 0.001 * (bytes_in + bytes_out),
                "bytes_in": bytes_in, "bytes_out": bytes_out,
                "replication_bytes_in": repl_in,
                "request_queue_size": sim.get("request_queue_size", 0.0),
                "log_flush_time_ms": sim.get("log_flush_time_ms", 0.0)}

    def topic_stats(self, broker_id: int) -> dict[str, dict[str, float]]:
        out: dict[str, dict[str, float]] = {}
        for info in self._led(broker_id):
            r = self.rates.get(info.tp, (0.0, 0.0))
            t = out.setdefault(info.topic, {"bytes_in": 0.0, "bytes_out": 0.0})
            t["bytes_in"] += r[0]
            t["bytes_out"] += r[1]
        return out

    def partition_sizes(self, broker_id: int) -> dict[tuple[str, int], float]:
        return {info.tp: info.size_mb
                for info in self.cluster.describe_partitions().values()
                if broker_id in info.replicas}
