from .platform import respect_env_platforms

__all__ = ["respect_env_platforms"]
