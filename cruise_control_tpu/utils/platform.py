"""Platform selection workaround for hijacked JAX configs.

The deployment environment boots a TPU-tunnel ("axon") PJRT backend from a
``sitecustomize`` hook that imports jax at interpreter start and rewrites
``jax.config.jax_platforms`` to ``"axon,cpu"`` — overriding whatever
``JAX_PLATFORMS`` the caller exported. When the tunnel is unhealthy this
hangs every ``jax.devices()`` deep in ``make_c_api_client``.

:func:`respect_env_platforms` restores the contract that the env var wins:
call it before the first array op in any entry-point script.
"""

from __future__ import annotations

import logging
import os

LOG = logging.getLogger(__name__)

#: Version stamp keying the persistent-compilation-cache directory.
#:
#: XLA's cache key covers input shapes and the traced computation, but a
#: repo-level *pass-signature* change (a new output in every goal pass, a
#: donation change, a jax upgrade quirk) leaves thousands of stale
#: entries in place and silently recompiles everything exactly once per
#: shape — unpredictably, mid-serving (the PR 3 incident: the
#: ``(state, iters, stack, moves)`` signature change invalidated every
#: pre-PR3 entry). Keying the directory by a repo-owned version makes
#: that cost explicit and predictable: bump this constant in any PR that
#: changes a jitted program's signature, and the repayment happens in
#: one planned warmup instead of mixing stale and fresh entries.
#:
#: v2: this PR (device-runtime observability) — the collector changes no
#: program signatures, but the versioning scheme itself starts here, so
#: pre-existing unversioned entries are left behind in the old root.
JIT_CACHE_VERSION = 2

#: log the resolved cache dir exactly once per process (every entry
#: point funnels through enable_compilation_cache, often repeatedly).
_CACHE_LOGGED = False


def respect_env_platforms() -> str | None:
    """Make ``JAX_PLATFORMS`` authoritative over the snapshotted config.

    Returns the platform list now in effect (or None if untouched). Safe to
    call repeatedly; must run before the first backend initialization to
    have any effect on device selection.
    """
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return None
    import jax
    have = jax.config.jax_platforms
    if have != want:
        jax.config.update("jax_platforms", want)
    return want


def probe_default_backend(timeout_s: float = 120.0) -> str | None:
    """Initialize the default JAX backend in a *subprocess* with a timeout.

    Returns the default platform name ("tpu"/"cpu"/...) or None if backend
    init hangs or fails — which happens whenever the axon tunnel relay is
    down. Callers use this to fall back to CPU instead of hanging forever.
    """
    import subprocess
    import sys
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, timeout=timeout_s,
                             text=True)
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        return None
    for line in out.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1]
    return None


#: Repo-local persistent XLA compilation cache. A 15-goal chain costs
#: ~20-40 min of XLA compile on TPU the first time; the cache turns every
#: later process's cold start into a disk read. Kept inside the repo tree
#: (gitignored) because this deployment must not write outside it.
DEFAULT_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Turn on JAX's persistent compilation cache (idempotent).

    Must run before the first compilation to catch everything, but is safe
    any time. Returns the cache directory in use, or None when no writable
    location exists (cache disabled, never a startup crash — the package
    dir is read-only under system installs).

    The resolved root is suffixed ``v<JIT_CACHE_VERSION>`` so a
    pass-signature change repays its compiles predictably (one planned
    warmup into a fresh directory) instead of mixing stale entries with
    fresh ones; the resolved dir + version is logged once per process.
    """
    import tempfile
    candidates = [c for c in (
        cache_dir, os.environ.get("JAX_COMPILATION_CACHE_DIR"),
        DEFAULT_CACHE_DIR,
        os.path.join(tempfile.gettempdir(), "cruise_control_tpu_xla_cache"),
    ) if c]
    for root in candidates:
        d = os.path.join(root, f"v{JIT_CACHE_VERSION}")
        try:
            os.makedirs(d, exist_ok=True)
            probe = os.path.join(d, ".writable")
            with open(probe, "w", encoding="utf-8"):
                pass
            os.unlink(probe)
        except OSError:
            continue
        import jax
        jax.config.update("jax_compilation_cache_dir", d)
        # Cache everything that took meaningful compile time; the default
        # (1 s + min entry size) skips the many small passes a chain has.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        global _CACHE_LOGGED
        if not _CACHE_LOGGED:
            _CACHE_LOGGED = True
            LOG.info("persistent XLA compilation cache: %s "
                     "(JIT_CACHE_VERSION=%d)", d, JIT_CACHE_VERSION)
        return d
    return None


def ensure_live_backend(timeout_s: float = 120.0) -> str:
    """Probe the default backend; fall back to CPU if it is unreachable.

    Must be called before the first array op. Returns the platform in use.
    Also enables the persistent compilation cache — every entry point that
    cares about backend health cares about cold-start latency too.
    """
    want = respect_env_platforms()
    import jax
    enable_compilation_cache()
    if want and want.split(",")[0].strip() == "cpu":
        # Operator explicitly pinned CPU: probing the default backend
        # would only measure the dead-tunnel import hang (the axon PJRT
        # plugin blocks at discovery even when it will never be
        # selected) — 120 s of startup latency for an answer the env
        # already gave. Normalized: callers prefix-match on "cpu".
        return "cpu"
    platform = probe_default_backend(timeout_s)
    if platform is None:
        jax.config.update("jax_platforms", "cpu")
        return "cpu (fallback: default backend unreachable)"
    return platform
