"""Platform selection workaround for hijacked JAX configs.

The deployment environment boots a TPU-tunnel ("axon") PJRT backend from a
``sitecustomize`` hook that imports jax at interpreter start and rewrites
``jax.config.jax_platforms`` to ``"axon,cpu"`` — overriding whatever
``JAX_PLATFORMS`` the caller exported. When the tunnel is unhealthy this
hangs every ``jax.devices()`` deep in ``make_c_api_client``.

:func:`respect_env_platforms` restores the contract that the env var wins:
call it before the first array op in any entry-point script.
"""

from __future__ import annotations

import os


def respect_env_platforms() -> str | None:
    """Make ``JAX_PLATFORMS`` authoritative over the snapshotted config.

    Returns the platform list now in effect (or None if untouched). Safe to
    call repeatedly; must run before the first backend initialization to
    have any effect on device selection.
    """
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return None
    import jax
    have = jax.config.jax_platforms
    if have != want:
        jax.config.update("jax_platforms", want)
    return want


def probe_default_backend(timeout_s: float = 120.0) -> str | None:
    """Initialize the default JAX backend in a *subprocess* with a timeout.

    Returns the default platform name ("tpu"/"cpu"/...) or None if backend
    init hangs or fails — which happens whenever the axon tunnel relay is
    down. Callers use this to fall back to CPU instead of hanging forever.
    """
    import subprocess
    import sys
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, timeout=timeout_s,
                             text=True)
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        return None
    for line in out.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1]
    return None


def ensure_live_backend(timeout_s: float = 120.0) -> str:
    """Probe the default backend; fall back to CPU if it is unreachable.

    Must be called before the first array op. Returns the platform in use.
    """
    respect_env_platforms()
    import jax
    platform = probe_default_backend(timeout_s)
    if platform is None:
        jax.config.update("jax_platforms", "cpu")
        return "cpu (fallback: default backend unreachable)"
    return platform
