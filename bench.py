"""Benchmark: rebalance-proposal wall-clock, TPU batched search vs greedy.

Scenario #2 from BASELINE.md: synthetic 100-broker / 20K-partition cluster
with skewed placement, ReplicaDistribution + resource UsageDistribution
goals. The baseline is a host-side sequential greedy implementing the same
goal semantics (the stand-in for the reference's GoalOptimizer greedy loop,
which published no numbers — BASELINE.md).

Prints ONE JSON line:
  {"metric": ..., "value": <warm wall-clock s>, "unit": "s",
   "vs_baseline": <greedy_s / tpu_s speedup>}
Diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np

NUM_BROKERS = 100
NUM_PARTITIONS = 20_000
RF = 2
GOALS = ["ReplicaDistributionGoal", "DiskUsageDistributionGoal",
         "NetworkInboundUsageDistributionGoal",
         "NetworkOutboundUsageDistributionGoal"]

#: BASELINE.md scenario table: #3 = 1K x 200K full default chain,
#: #4 = 10K x 1M (the <30 s north-star target). Greedy at these sizes runs
#: for hours, so the scale scenarios report vs_baseline against the 30 s
#: target instead of a greedy run.
SCALE_SCENARIOS = {
    #: swaps: per-scenario swap-candidate batch — 512 cuts scenario 3's
    #: topic-matched swap tail (TopicReplicaDistribution 56 -> 38 iters,
    #: -26% warm), but CROWDS OUT leadership candidates in scenario 4's
    #: leader-driven NW_OUT pass (38 -> 128 iters measured), so #4 keeps
    #: the default batch.
    3: dict(brokers=1000, partitions=200_000, rf=2, goals=None,
            metric="rebalance_proposal_wall_clock_1kx200k", target_s=30.0,
            k=1024, swaps=512),
    # Candidate batch scaled with the move budget AND the platform: a
    # 10K x 1M skew needs ~500K moves, so 1K-candidate iterations are
    # iteration-bound (~400 iters, 78 s CPU). 4K candidates cut the
    # iteration count ~4x, but the apply stage's [M, M] conflict/guard
    # matmuls grow quadratically — nearly free on the MXU, dominant on
    # CPU (measured 144 s) — so the batch is sized per backend.
    #
    # waive: the 4 distribution goals cannot preserve strict
    # rack-awareness (count/usage moves ignore racks), so that single
    # audit is waived — every OTHER registered hard goal (replica +
    # 4 resource capacities) is audited post-optimization and GATES the
    # row; the ``fullchain`` variant runs the entire default chain with
    # nothing waived.
    #
    # fullchain_swaps: the FULL default chain's swap-heavy passes
    # (TopicReplicaDistribution, the leadership tails) dominate at
    # 10K x 1M — swaps=512 halves the warm CPU row to 113.6 s
    # (226.1 s default batch; Topic 28 -> 19 iters, LeaderBytesIn
    # 51 -> 34 — BASELINE.md round-5 section). The 4-goal variant
    # KEEPS the default batch: its leader-driven NW_OUT pass
    # measurably regresses under a large swap batch (round-4 A/B,
    # 38 -> 128 iters).
    4: dict(brokers=10_000, partitions=1_000_000, rf=2, goals=GOALS,
            metric="rebalance_proposal_wall_clock_10kx1m", target_s=30.0,
            k=1024, k_tpu=4096, waive=("RackAwareGoal",),
            fullchain_swaps=512),
}


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def emit(metric: str, value, unit: str, vs_baseline, *, vs_target=None,
         vs_greedy=None, mesh_devices=None) -> None:
    """The one JSON line the driver records. ``platform`` self-certifies
    where the number was measured (tpu vs cpu fallback) so a BENCH artifact
    can never silently pass off a fallback run as a TPU result.

    ``vs_baseline`` keeps the driver's historical field, but its meaning
    varied by scenario (target/wall-clock for the scale rows, greedy/tpu
    for scenario 2) — so the row now also carries the unambiguous fields:
    ``vs_target`` = scenario time budget / measured wall-clock (>1 means
    under budget), ``vs_greedy`` = host-greedy wall-clock / measured
    wall-clock (>1 means faster than the sequential baseline). A scenario
    without the corresponding comparison leaves the field null."""
    import jax
    row = {
        "metric": metric, "value": value, "unit": unit,
        "vs_baseline": vs_baseline,
        "platform": jax.devices()[0].platform,
    }
    if vs_target is not None:
        row["vs_target"] = vs_target
    if vs_greedy is not None:
        row["vs_greedy"] = vs_greedy
    if mesh_devices is not None:
        # Scale-tier rows: 0 = unsharded, N = N-way partition-axis mesh
        # (sharded and unsharded captures of one metric must never read
        # as the same series).
        row["mesh_devices"] = mesh_devices
    print(json.dumps(row), flush=True)


#: windows ingested for the monitor→model stage bench.
MODEL_BUILD_WINDOWS = 4


def run_model_build_bench(num_brokers: int = NUM_BROKERS,
                          num_partitions: int = NUM_PARTITIONS, *,
                          emit_row: bool = True, repeats: int = 2) -> dict:
    """Monitor→model stage wall-clock: aggregate + ``cluster_model``
    through the dense whole-pool pipeline vs the retained per-entity
    reference path, on the same ingested sample history. Model parity is
    asserted before any number is reported — a wrong fast model must fail
    loudly, not win the row. Emits the ``model_build_wall_clock`` JSON
    line (value = dense seconds, vs_baseline = legacy/dense speedup)."""
    from cruise_control_tpu.core.metricdef import partition_metric_def
    from cruise_control_tpu.executor import SimulatedKafkaCluster
    from cruise_control_tpu.monitor import LoadMonitor, MonitorConfig

    window_ms = 1000
    sim = SimulatedKafkaCluster()
    for b in range(num_brokers):
        sim.add_broker(b)
    num_topics = max(num_partitions // 100, 1)
    for p in range(num_partitions):
        sim.add_partition(f"t{p % num_topics}", p,
                          [p % num_brokers, (p + 1) % num_brokers],
                          size_mb=50.0 + (p % 100))
    monitors = {
        mode: LoadMonitor(sim, MonitorConfig(
            num_windows=MODEL_BUILD_WINDOWS, window_ms=window_ms,
            min_samples_per_window=1, dense_pipeline=dense))
        for mode, dense in (("dense", True), ("legacy", False))}
    mdef = partition_metric_def()
    keys = sorted(sim.describe_partitions())
    P = len(keys)
    rng = np.random.default_rng(11)
    for w in range(MODEL_BUILD_WINDOWS + 1):
        vals = np.abs(rng.normal(10.0, 3.0, size=(P, mdef.size())))
        # Sparsity: every 7th partition is only sampled every third
        # window, so the extrapolation ladder (AVG_ADJACENT /
        # NO_VALID_EXTRAPOLATION) is on the measured path.
        keep = np.ones(P, bool)
        keep[::7] = (w % 3 == 0)
        ents = [k for k, kp in zip(keys, keep) if kp]
        times = np.full(len(ents), w * window_ms + 100, np.int64)
        for m in monitors.values():
            m.partition_aggregator.add_samples_dense(ents, times,
                                                     vals[keep])
    now_ms = (MODEL_BUILD_WINDOWS + 1) * window_ms

    def timed(monitor):
        best, res = float("inf"), None
        for _ in range(repeats):
            t0 = time.monotonic()
            res = monitor.cluster_model(now_ms)
            best = min(best, time.monotonic() - t0)
        return best, res

    legacy_s, res_l = timed(monitors["legacy"])
    dense_s, res_d = timed(monitors["dense"])
    for name in ("replica_broker", "leader_load", "follower_load",
                 "partition_topic", "partition_valid", "replica_offline",
                 "replica_pref_pos"):
        a = np.asarray(getattr(res_d.model, name))
        b = np.asarray(getattr(res_l.model, name))
        if not np.array_equal(a, b):
            raise RuntimeError(
                f"dense/legacy monitor pipeline mismatch in model.{name}")
    if res_d.metadata.partition_keys != res_l.metadata.partition_keys:
        raise RuntimeError("dense/legacy monitor metadata mismatch")
    speedup = legacy_s / dense_s if dense_s > 0 else None
    log(f"model build ({num_brokers}x{num_partitions}): dense {dense_s:.3f}s"
        f" legacy {legacy_s:.3f}s speedup "
        + (f"{speedup:.1f}x" if speedup is not None else "n/a"))
    if emit_row:
        emit("model_build_wall_clock", round(dense_s, 3), "s",
             round(speedup, 3) if speedup else None)
    return {"dense_s": dense_s, "legacy_s": legacy_s, "speedup": speedup,
            "partitions": P}


def run_whatif_n1_bench(num_brokers: int = NUM_BROKERS,
                        num_partitions: int = NUM_PARTITIONS, *,
                        goal_names: list | None = None, repeats: int = 3,
                        rebuild_samples: int = 3,
                        single_samples: int = 20,
                        emit_row: bool = True, gate: bool = True) -> dict:
    """What-if N-1 sweep wall-clock: every single-broker loss scored by
    the full goal stack in ONE vmapped device program, vs evaluating the
    same scenarios one at a time the pre-whatif way — per scenario,
    rebuild the hypothetical model host-side (spec mutation +
    flatten_spec, exactly how the facade's add/remove/demote dry-runs
    construct hypothetical topologies) and score it with one device
    dispatch. ``rebuild_samples`` rebuilds are timed and extrapolated to
    the full sweep (per-scenario rebuild cost is constant).

    The gate requires warm-batch >= 5x over N x rebuild-and-score. The
    log also reports the batched-vs-single-DISPATCH ratio (same engine,
    unpadded S=1 program, model already flat): on CPU the sweep is
    compute-bound so that ratio hovers near 1; the batch's win there is
    eliminating N rebuild+dispatch round-trips, and on TPU the scenario
    axis rides the vector units.
    """
    from cruise_control_tpu.analyzer import goals_by_name
    from cruise_control_tpu.model.spec import (BrokerSpec, ClusterSpec,
                                               flatten_spec)
    from cruise_control_tpu.whatif import LoadScale, WhatIfEngine, n1_sweep
    goals = goals_by_name(goal_names or GOALS)
    # Spec-based build: the rebuild baseline needs the spec path, and the
    # batched engine gets the identical flattened model.
    spec = build_spec(num_brokers=num_brokers,
                      num_partitions=num_partitions)
    model, md = flatten_spec(spec)
    eng = WhatIfEngine(goals=goals)
    scenarios = n1_sweep(md.broker_ids)
    S = len(scenarios)
    t0 = time.monotonic()
    report = eng.sweep(model, md, scenarios)
    cold_s = time.monotonic() - t0
    warm_s = float("inf")
    for _ in range(repeats):
        t0 = time.monotonic()
        report = eng.sweep(model, md, scenarios)
        warm_s = min(warm_s, time.monotonic() - t0)
    assert report.num_scenarios == S

    # Single-dispatch baseline: same engine, scenario axis unpadded, one
    # device program per scenario on the already-flat model.
    eng1 = WhatIfEngine(goals=goals, scenario_pad_multiple=1)
    sub = scenarios[:single_samples] if single_samples else scenarios
    eng1.sweep(model, md, [scenarios[0]])        # compile the S=1 program
    t0 = time.monotonic()
    singles = [eng1.sweep(model, md, [s]).outcomes[0] for s in sub]
    dispatch_s = (time.monotonic() - t0) * (S / len(sub))
    # Parity: the batch and the singles must agree on what is violated —
    # a fast sweep that scores differently is worthless.
    for got, single in zip(report.outcomes, singles):
        if got.violated_goals != single.violated_goals:
            raise RuntimeError(
                f"whatif batched/single mismatch on {got.scenario.name}: "
                f"{got.violated_goals} vs {single.violated_goals}")

    # Rebuild baseline: host-side model rebuild per scenario + one
    # scoring dispatch (the status-quo hypothetical-evaluation path).
    t0 = time.monotonic()
    for scn in scenarios[:rebuild_samples]:
        dead = set(scn.brokers)
        spec_s = ClusterSpec(
            brokers=[BrokerSpec(b.broker_id, rack=b.rack, host=b.host,
                                capacity=b.capacity,
                                alive=b.broker_id not in dead)
                     for b in spec.brokers],
            partitions=spec.partitions)
        model_s, md_s = flatten_spec(spec_s)
        eng1.sweep(model_s, md_s, [LoadScale(1.0)])
    rebuild_s = (time.monotonic() - t0) * (S / rebuild_samples)

    speedup = rebuild_s / warm_s if warm_s > 0 else None
    vs_dispatch = dispatch_s / warm_s if warm_s > 0 else None
    scn_per_s = S / warm_s if warm_s > 0 else 0.0
    log(f"whatif N-1 sweep ({num_brokers}x{num_partitions}, {S} scenarios,"
        f" {len(goals)} goals): cold {cold_s:.2f}s warm {warm_s:.3f}s "
        f"({scn_per_s:.0f} scenarios/s); sequential rebuild+score "
        f"{rebuild_s:.1f}s ({speedup:.1f}x), single-dispatch "
        f"{dispatch_s:.2f}s ({vs_dispatch:.2f}x)")
    if gate and (speedup is None or speedup < 5.0):
        raise RuntimeError(
            f"whatif batching gate: batched sweep only "
            f"{speedup if speedup is None else round(speedup, 2)}x faster "
            f"than {S} sequential rebuild+score evaluations (need >= 5x)")
    if emit_row:
        emit("whatif_n1_sweep_wall_clock", round(warm_s, 3), "s",
             round(speedup, 3) if speedup else None)
    return {"cold_s": cold_s, "warm_s": warm_s, "rebuild_s": rebuild_s,
            "dispatch_s": dispatch_s, "speedup": speedup,
            "vs_dispatch": vs_dispatch, "scenarios": S,
            "scenarios_per_s": scn_per_s}


def run_fleet_propose_bench(num_clusters: int = 16,
                            num_brokers: int = NUM_BROKERS,
                            num_partitions: int = NUM_PARTITIONS, *,
                            goal_names: list | None = None,
                            repeats: int = 3, seed: int = 3,
                            emit_row: bool = True, gate: bool = True
                            ) -> dict:
    """Fleet-scale batched propose (ISSUE 10): ``num_clusters`` member
    clusters optimized by ONE cluster-sharded device dispatch
    (fleet/engine.py — each device runs the unmodified single-cluster
    goal chain over its slice of the ``[C, ...]`` axis) vs the
    status-quo: looping the warm single-cluster ``optimize`` over the
    same member models, one at a time.

    Three always-on gates ride every run (any scale — they are
    deterministic correctness, not performance):

    - **bit-identical parity**: the fleet dispatch's proposals must equal
      the sequential loop's, member by member, byte for byte;
    - **zero warm recompiles**: repeat fleet dispatches after the first
      must compile nothing on the device-runtime ledger;
    - **one dispatch group**: homogeneous members must never silently
      split into per-group dispatches (that would fake the amortization).

    The ``>= 5x`` clusters/s gate is judged at bench scale only
    (16 x 100x20k on CPU; ``gate=False`` for the tier-1 smoke): the win
    is real device-level concurrency, so it needs real (or forced-host)
    devices — scenario 6 forces 16 virtual CPU devices before jax
    initializes."""
    import jax
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer import (OptimizationOptions,
                                             SearchConfig,
                                             TpuGoalOptimizer,
                                             goals_by_name)
    from cruise_control_tpu.core.runtime_obs import default_collector
    from cruise_control_tpu.fleet import FleetModel, FleetOptimizer
    from cruise_control_tpu.model.spec import flatten_spec
    goals = goals_by_name(goal_names or GOALS)
    spec = build_spec(num_brokers=num_brokers,
                      num_partitions=num_partitions)
    model, md = flatten_spec(spec)
    # Per-cluster load variation: same topology, deterministically
    # scaled loads — heterogeneous enough that every member's search
    # does real distinct work, homogeneous enough for one dispatch
    # group.
    members = []
    for c in range(num_clusters):
        f = jnp.float32(1.0 + 0.01 * c)
        members.append((f"cluster-{c:02d}",
                        model.replace(leader_load=model.leader_load * f,
                                      follower_load=model.follower_load
                                      * f), md))
    fleet = FleetModel.stack(members)
    opt = TpuGoalOptimizer(
        goals=goals,
        config=SearchConfig(num_replica_candidates=512,
                            num_dest_candidates=16, apply_per_iter=512,
                            max_iters_per_goal=512))
    fleet_opt = FleetOptimizer(opt)
    opts = OptimizationOptions(seed=seed, skip_hard_goal_check=True)

    # Sequential baseline: the existing warm single-cluster path looped
    # over the members (compile once on member 0, then time the loop).
    opt.optimize(fleet.members[0].model, fleet.members[0].metadata, opts)
    t0 = time.monotonic()
    seq_results = [opt.optimize(m.model, m.metadata, opts)
                   for m in fleet.members]
    seq_s = time.monotonic() - t0

    t0 = time.monotonic()
    fleet_results = fleet_opt.propose(fleet, opts)        # cold
    cold_s = time.monotonic() - t0
    if fleet_opt._groups_gauge_val != 1:
        raise RuntimeError(
            f"fleet bench split into {fleet_opt._groups_gauge_val} "
            "dispatch groups — homogeneous members must share ONE "
            "compiled program")
    collector = default_collector()
    before = collector.snapshot()
    warm_s = float("inf")
    for _ in range(repeats):
        t0 = time.monotonic()
        fleet_results = fleet_opt.propose(fleet, opts)
        warm_s = min(warm_s, time.monotonic() - t0)
    after = collector.snapshot()
    recompiles = (after["compileEvents"] + after["aotCompileEvents"]
                  - before["compileEvents"] - before["aotCompileEvents"])
    if recompiles:
        raise RuntimeError(
            f"fleet warm-recompile gate: {recompiles} compile events "
            f"across {repeats} warm fleet dispatches (expected 0)")
    for m, fr, sr in zip(fleet.members, fleet_results, seq_results):
        if [p.to_json() for p in fr.proposals] \
                != [p.to_json() for p in sr.proposals] \
                or fr.num_moves != sr.num_moves:
            raise RuntimeError(
                f"fleet parity gate: {m.cluster_id} batched proposals "
                "differ from the sequential per-cluster propose")

    clusters_per_s = num_clusters / warm_s if warm_s > 0 else 0.0
    speedup = seq_s / warm_s if warm_s > 0 else None
    log(f"fleet propose ({num_clusters} x {num_brokers}x{num_partitions},"
        f" {len(goals)} goals, {len(jax.devices())} devices): cold "
        f"{cold_s:.2f}s warm {warm_s:.3f}s ({clusters_per_s:.1f} "
        f"clusters/s); sequential loop {seq_s:.2f}s "
        f"({'n/a' if speedup is None else f'{speedup:.1f}x'}); "
        "parity bit-identical, 0 warm recompiles")
    if gate and (speedup is None or speedup < 5.0):
        raise RuntimeError(
            f"fleet batching gate: batched propose only "
            f"{speedup if speedup is None else round(speedup, 2)}x over "
            f"{num_clusters} sequential per-cluster proposes (need >= 5x)")
    if emit_row:
        emit("fleet_propose_clusters_per_s", round(clusters_per_s, 3),
             "clusters/s", round(speedup, 3) if speedup else None)
    return {"cold_s": cold_s, "warm_s": warm_s, "seq_s": seq_s,
            "speedup": speedup, "clusters_per_s": clusters_per_s,
            "clusters": num_clusters, "recompiles": recompiles,
            "devices": len(jax.devices())}


#: documented move-count tolerance for the multi-objective A/B gate: the
#: population winner may spend up to this factor of the sequential
#: chain's moves reaching its (no-worse) violation stacks. docs/search.md.
MULTIOBJ_MOVE_TOLERANCE = 1.5
#: documented quality tolerance (scale-NORMALIZED weighted-objective
#: units): tuned-schedule quality may not exceed the fixed schedule's by
#: more than this — mirrors the tuner's own 1.02x feasibility band on
#: residuals that are ~O(1) normalized when not fully converged.
MULTIOBJ_QUALITY_TOL = 0.05


def run_multiobj_propose_bench(num_brokers: int = NUM_BROKERS,
                               num_partitions: int = NUM_PARTITIONS, *,
                               goal_names: list | None = None,
                               population: int = 4,
                               tune_trials: int = 4, tune_rungs: int = 2,
                               repeats: int = 3, seed: int = 3,
                               store_path: str | None = None,
                               emit_row: bool = True, gate: bool = True
                               ) -> dict:
    """Tuned multi-objective population search vs the fixed-schedule
    sequential chain (ISSUE 11). Three stages:

    1. **baseline**: the sequential goal chain under the DEFAULT
       ``SearchConfig`` — the fixed schedule every untuned process
       serves — compile+warm, then best-of-``repeats`` warm propose;
    2. **offline tuning**: successive-halving over the schedule space
       (``analyzer/tuning.py``) on this very scenario, winner persisted
       per shape bucket into the TunedConfigStore (the store a serving
       process loads via ``search.tuning.enabled``);
    3. **tuned population propose**: ``search.population=K`` under the
       tuned schedule — every member the full chain on its own device
       stream, joint weighted scoring, anchor member 0.

    Emitted rows: ``multiobj_propose_wall_clock`` (tuned population warm
    propose; vs_baseline/vs_greedy = fixed-schedule sequential warm /
    tuned population warm — >1 means the learned schedule beats the
    fixed one) and ``proposal_quality_delta`` (tuned population final
    weighted objective minus sequential's, scale-normalized units —
    <= 0 means no quality given up).

    Always-on gates (any scale): zero warm recompiles on the population
    path, quality delta within MULTIOBJ_QUALITY_TOL, move count within
    MULTIOBJ_MOVE_TOLERANCE of sequential. The wall-clock >= 1x gate is
    judged at bench scale only (``gate=False`` for the tier-1 smoke) —
    population concurrency needs real (or forced-host) devices, which
    scenario 7 forces like the fleet scenario does."""
    import jax

    from cruise_control_tpu.analyzer import (OptimizationOptions,
                                             SearchConfig,
                                             TpuGoalOptimizer,
                                             TunedConfigStore, autotune,
                                             goals_by_name, plan_quality)
    from cruise_control_tpu.core.runtime_obs import default_collector
    from cruise_control_tpu.model.spec import flatten_spec

    names = goal_names or GOALS
    spec = build_spec(num_brokers=num_brokers,
                      num_partitions=num_partitions)
    model, md = flatten_spec(spec)
    opts = OptimizationOptions(seed=seed, skip_hard_goal_check=True)
    base = SearchConfig()
    # ONE scoring convention across the tuner's feasibility test, these
    # gates, and the population A/B tests (analyzer/tuning.plan_quality).
    quality = plan_quality

    # 1. Fixed-schedule sequential baseline.
    seq_opt = TpuGoalOptimizer(goals=goals_by_name(names), config=base)
    seq_opt.optimize(model, md, opts)                  # compile + warm
    seq_s, seq_res = float("inf"), None
    for _ in range(repeats):
        t0 = time.monotonic()
        seq_res = seq_opt.optimize(model, md, opts)
        seq_s = min(seq_s, time.monotonic() - t0)
    seq_q = quality(seq_res)

    # 2. Offline tuning into the persisted store (the expensive half —
    # each candidate schedule compiles its own chain; logged, not gated:
    # tuning cost is paid offline, never on the serving path).
    store = TunedConfigStore(store_path)
    t0 = time.monotonic()
    fields, history, bucket = autotune(
        model, md, base=base, store=store, trials=tune_trials,
        rungs=tune_rungs, seed=seed, goals=goals_by_name(names),
        options=opts)
    tune_s = time.monotonic() - t0
    log(f"multiobj tuning: {len(history)} trials in {tune_s:.1f}s -> "
        f"bucket {bucket} fields {fields or '(incumbent schedule kept)'}")

    # 3. Tuned population propose (K members, anchor = sequential
    # schedule under the TUNED config).
    pop_opt = TpuGoalOptimizer(goals=goals_by_name(names), config=base,
                               tuned_store=store, population=population)
    t0 = time.monotonic()
    pop_opt.optimize(model, md, opts)                  # compile + warm
    cold_s = time.monotonic() - t0
    collector = default_collector()
    before = collector.snapshot()
    pop_s, pop_res = float("inf"), None
    for _ in range(repeats):
        t0 = time.monotonic()
        pop_res = pop_opt.optimize(model, md, opts)
        pop_s = min(pop_s, time.monotonic() - t0)
    after = collector.snapshot()
    recompiles = (after["compileEvents"] + after["aotCompileEvents"]
                  - before["compileEvents"] - before["aotCompileEvents"])
    if recompiles:
        raise RuntimeError(
            f"multiobj warm-recompile gate: {recompiles} compile events "
            f"across {repeats} warm population proposes (expected 0)")
    pop_q = quality(pop_res)
    quality_delta = pop_q - seq_q
    if quality_delta > MULTIOBJ_QUALITY_TOL:
        raise RuntimeError(
            f"multiobj quality gate: tuned population objective {pop_q:.4f}"
            f" worse than fixed-schedule sequential {seq_q:.4f} by "
            f"{quality_delta:.4f} (> {MULTIOBJ_QUALITY_TOL})")
    # max(.., 1): a 0-move sequential baseline (already-balanced
    # scenario) must not turn the multiplicative tolerance into "any
    # population move fails" — same floor the tuner's feasibility test
    # uses.
    if pop_res.num_moves > max(seq_res.num_moves, 1) \
            * MULTIOBJ_MOVE_TOLERANCE:
        raise RuntimeError(
            f"multiobj move gate: population plan spends "
            f"{pop_res.num_moves} moves vs sequential "
            f"{seq_res.num_moves} (tolerance {MULTIOBJ_MOVE_TOLERANCE}x)")
    speedup = seq_s / pop_s if pop_s > 0 else None
    pop_stats = (pop_res.telemetry or {}).get("population", {})
    log(f"multiobj propose ({num_brokers}x{num_partitions}, "
        f"{len(names)} goals, K={pop_stats.get('size')}, "
        f"{len(jax.devices())} devices): fixed-seq warm {seq_s:.3f}s, "
        f"tuned population cold {cold_s:.2f}s warm {pop_s:.3f}s "
        f"({'n/a' if speedup is None else f'{speedup:.2f}x'}); quality "
        f"delta {quality_delta:+.4f}, moves {pop_res.num_moves} vs "
        f"{seq_res.num_moves}, winner {pop_stats.get('winner')} "
        f"(front {pop_stats.get('paretoFrontSize')}), 0 warm recompiles")
    if gate and (speedup is None or speedup < 1.0):
        raise RuntimeError(
            f"multiobj wall-clock gate: tuned population warm propose "
            f"{pop_s:.3f}s did not beat the fixed-schedule sequential "
            f"warm propose {seq_s:.3f}s (need >= 1x)")
    if emit_row:
        emit("multiobj_propose_wall_clock", round(pop_s, 3), "s",
             round(speedup, 3) if speedup else None,
             vs_greedy=round(speedup, 3) if speedup else None)
        emit("proposal_quality_delta", round(quality_delta, 6),
             "normalized-objective", None)
    return {"seq_s": seq_s, "cold_s": cold_s, "pop_s": pop_s,
            "speedup": speedup, "tune_s": tune_s,
            "tuned_fields": fields, "bucket": bucket,
            "trials": len(history),
            "seq_quality": seq_q, "pop_quality": pop_q,
            "quality_delta": quality_delta,
            "seq_moves": seq_res.num_moves, "pop_moves": pop_res.num_moves,
            "population": pop_stats, "recompiles": recompiles,
            "devices": len(jax.devices())}


#: bench-scale backtest-accuracy bar for the forecast fit (the ISSUE-13
#: acceptance gate, judged on clean synthetic diurnal+growth traces at
#: every scale — it is a deterministic model-quality bound, not a
#: wall-clock number). docs/forecasting.md §Accuracy.
FORECAST_MAPE_BUDGET = 0.15


def run_forecast_sweep_bench(num_clusters: int = 4,
                             num_brokers: int = NUM_BROKERS,
                             num_partitions: int = NUM_PARTITIONS, *,
                             goal_names: list | None = None,
                             history_windows: int = 96,
                             repeats: int = 3, emit_row: bool = True,
                             gate: bool = True) -> dict:
    """Forecast pipeline (ISSUE 13): host-side per-topic trajectory
    fitting over a synthetic diurnal+growth window history, then the
    fitted (horizon x quantile) grid scored across ``num_clusters``
    fleet members as ONE ``[C, S]`` batched trajectory dispatch
    (fleet/engine.py ``sweep_trajectories``) vs the status quo: looping
    the warm single-cluster ``WhatIfEngine`` sweep per member.

    Three always-on gates (deterministic at any scale):

    - **backtest accuracy**: worst 1-window-holdout MAPE over the fitted
      topics stays <= ``FORECAST_MAPE_BUDGET`` (the traces are clean
      diurnal + linear growth — the acceptance-criteria shapes);
    - **scoring parity**: every fleet row must match the single-cluster
      sweep of the same scenario (the summary rows round to 4 decimals);
    - **zero warm recompiles**: repeat fleet trajectory dispatches after
      the first compile nothing on the device-runtime ledger.

    The ``>= 1x`` wall-clock bar vs the sequential loop is judged at
    bench scale only (``gate=False`` for the tier-1 toy smoke). Emits
    ``forecast_backtest_mape`` + ``forecast_sweep_wall_clock``."""
    import jax
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer import TpuGoalOptimizer, goals_by_name
    from cruise_control_tpu.core.runtime_obs import default_collector
    from cruise_control_tpu.fleet import FleetModel, FleetOptimizer
    from cruise_control_tpu.forecast import fit_topic_forecasts
    from cruise_control_tpu.model.spec import flatten_spec
    from cruise_control_tpu.whatif import TrajectoryScale, WhatIfEngine
    from cruise_control_tpu.workload import diurnal_growth_series
    goals = goals_by_name(goal_names or GOALS)
    spec = build_spec(num_brokers=num_brokers,
                      num_partitions=num_partitions)
    model, md = flatten_spec(spec)

    # --- fit stage: 1-minute windows, 24-window (diurnal) seasonality.
    # Each live topic gets a deterministic level + growth + diurnal
    # trace with mild noise — the acceptance-criteria trace shapes at
    # fleet topic count, generated through the workload pattern package
    # (seed 13, byte-identical to the builder this bench used to inline;
    # tests/test_workload.py pins that equivalence).
    window_ms = 60_000
    W, K = history_windows, 24
    topics = sorted(md.topic_index)
    series = diurnal_growth_series(topics, W, day_windows=K, seed=13)
    t0 = time.monotonic()
    fits = fit_topic_forecasts(series, window_ms,
                               seasonal_period_ms=K * window_ms,
                               min_history_windows=3, fitted_at_ms=0)
    fit_s = time.monotonic() - t0
    mape = fits.worst_backtest_mape()
    if mape is None or mape > FORECAST_MAPE_BUDGET:
        raise RuntimeError(
            f"forecast backtest gate: worst 1-window-holdout MAPE "
            f"{mape} over {len(fits)} topics exceeds "
            f"{FORECAST_MAPE_BUDGET} on clean diurnal+growth traces")

    # --- sweep stage: the +1h/+6h/+24h x p50/p90 grid, factors from the
    # fit, scored across C members in one [C, S] dispatch.
    grid = [TrajectoryScale(horizon_ms=h, quantile=q,
                            factors=tuple(sorted(
                                fits.factors(h, q).items())))
            for h in (3_600_000, 21_600_000, 86_400_000)
            for q in (0.5, 0.9)]
    S = len(grid)
    members = []
    for c in range(num_clusters):
        f = jnp.float32(1.0 + 0.01 * c)
        members.append((f"cluster-{c:02d}",
                        model.replace(leader_load=model.leader_load * f,
                                      follower_load=model.follower_load
                                      * f), md))
    fleet = FleetModel.stack(members)
    fleet_opt = FleetOptimizer(TpuGoalOptimizer(goals=goals))

    t0 = time.monotonic()
    out = fleet_opt.sweep_trajectories(fleet, grid)        # cold
    cold_s = time.monotonic() - t0
    collector = default_collector()
    before = collector.snapshot()
    warm_s = float("inf")
    for _ in range(repeats):
        t0 = time.monotonic()
        out = fleet_opt.sweep_trajectories(fleet, grid)
        warm_s = min(warm_s, time.monotonic() - t0)
    after = collector.snapshot()
    recompiles = (after["compileEvents"] + after["aotCompileEvents"]
                  - before["compileEvents"] - before["aotCompileEvents"])
    if recompiles:
        raise RuntimeError(
            f"forecast warm-recompile gate: {recompiles} compile events "
            f"across {repeats} warm [C={num_clusters}, S={S}] trajectory "
            "dispatches (expected 0)")

    # Sequential baseline: the warm single-cluster what-if sweep looped
    # over the members (compile once on member 0, then time the loop) —
    # doubles as the always-on scoring-parity gate.
    eng = WhatIfEngine(goals=goals)
    eng.sweep(fleet.members[0].model, fleet.members[0].metadata, grid)
    t0 = time.monotonic()
    singles = [eng.sweep(m.model, m.metadata, grid)
               for m in fleet.members]
    seq_s = time.monotonic() - t0
    for summary, single in zip(out, singles):
        for row, o in zip(summary["scenarios"], single.outcomes):
            if abs(row["risk"] - o.risk) > 1e-3 or \
                    abs(row["capacityPressure"]
                        - o.capacity_pressure) > 1e-3 or \
                    row["violatedHardGoals"] != o.violated_hard_goals:
                raise RuntimeError(
                    f"forecast parity gate: fleet row for "
                    f"{summary['clusterId']}/{row['scenario']} diverges "
                    "from the single-cluster sweep of the same scenario")

    speedup = seq_s / warm_s if warm_s > 0 else None
    log(f"forecast sweep ({num_clusters} x {num_brokers}x"
        f"{num_partitions}, {len(fits)} topics fitted in {fit_s:.2f}s "
        f"worst MAPE {mape:.4f}, {S} scenarios, "
        f"{len(jax.devices())} devices): cold {cold_s:.2f}s warm "
        f"{warm_s:.3f}s; sequential loop {seq_s:.2f}s "
        f"({'n/a' if speedup is None else f'{speedup:.1f}x'}); parity "
        "ok, 0 warm recompiles")
    if gate and (speedup is None or speedup < 1.0):
        raise RuntimeError(
            f"forecast sweep gate: batched [C, S] dispatch "
            f"{warm_s:.3f}s did not beat the sequential per-member "
            f"sweep loop {seq_s:.3f}s (need >= 1x)")
    if emit_row:
        emit("forecast_backtest_mape", round(mape, 6), "mape", None)
        emit("forecast_sweep_wall_clock", round(warm_s, 3), "s",
             round(speedup, 3) if speedup else None,
             vs_greedy=round(speedup, 3) if speedup else None)
    return {"fit_s": fit_s, "mape": mape, "topics": len(fits),
            "scenarios": S, "clusters": num_clusters,
            "cold_s": cold_s, "warm_s": warm_s, "seq_s": seq_s,
            "speedup": speedup, "recompiles": recompiles,
            "devices": len(jax.devices())}


def run_workload_regime_bench(num_brokers: int = NUM_BROKERS,
                              num_partitions: int = NUM_PARTITIONS, *,
                              goal_names: list | None = None,
                              history_windows: int = 192,
                              tune_trials: int = 0, tune_rungs: int = 2,
                              seed: int = 3,
                              store_path: str | None = None,
                              emit_row: bool = True,
                              gate: bool = True) -> dict:
    """Trace-driven workload plane (ISSUE 20), two stages:

    1. **pattern-class forecast gates** (pure host): one seeded trace
       over EVERY registered pattern class (``workload/patterns.py`` —
       steady, diurnal+growth, flash crowd, weekly, step migration,
       correlated burst, skew drift), fitted through the full degrade
       ladder (daily + weekly seasonality + residual changepoint
       truncation); the worst 1-window-holdout MAPE of every class must
       stay <= ``FORECAST_MAPE_BUDGET``. Emits one
       ``forecast_mape_<class>`` row per class.
    2. **regime-aware online tuning** (device): an untuned sequential
       propose is the quality baseline; then a ``RegimeTuningLoop``
       drives scripted aggregate series through steady -> flash crowd ->
       step migration, ensuring a tuned config per ``(bucket, regime)``
       and flipping the optimizer's ``active_regime``. After one warm-up
       pass over the phases, a second scripted pass re-optimizes in each
       regime — the device-runtime ledger must show ZERO compile events
       (tuned configs join the chain key; shifts swap cached chains).
       Gate: no phase's tuned quality regresses the untuned baseline by
       more than ``MULTIOBJ_QUALITY_TOL``. Emits
       ``proposal_quality_delta`` (worst phase) and
       ``workload_regime_recompiles``.

    ``tune_trials <= 1`` pins the incumbent schedule per regime with no
    per-candidate compiles (the tier-1 smoke mode); the bench default
    can raise it to run the real successive-halving tuner per regime."""
    import jax

    from cruise_control_tpu.analyzer import (OptimizationOptions,
                                             SearchConfig,
                                             TpuGoalOptimizer,
                                             TunedConfigStore,
                                             goals_by_name, plan_quality)
    from cruise_control_tpu.core.runtime_obs import default_collector
    from cruise_control_tpu.model.spec import flatten_spec
    from cruise_control_tpu.workload import (SPEC_REGISTRY,
                                             RegimeDetector,
                                             RegimeTuningLoop,
                                             backtest_by_class,
                                             generate_trace)

    # --- stage 1: per-class MAPE gates on one multi-class trace (two
    # topics per class, 1-minute windows, 24-window days, 8-day span so
    # the weekly rung has >= one full cycle of history).
    window_ms, day_windows = 60_000, 24
    specs = list(SPEC_REGISTRY.values())
    wl_topics = [f"wl-{i:03d}" for i in range(2 * len(specs))]
    t0 = time.monotonic()
    trace = generate_trace(specs, wl_topics,
                           num_windows=history_windows,
                           window_ms=window_ms, seed=13,
                           day_windows=day_windows)
    mapes = backtest_by_class(
        trace, seasonal_period_ms=day_windows * window_ms,
        week_period_ms=7 * day_windows * window_ms,
        changepoint_min_shift=6.0)
    fit_s = time.monotonic() - t0
    for cls, mape in sorted(mapes.items()):
        if mape is None or mape > FORECAST_MAPE_BUDGET:
            raise RuntimeError(
                f"workload forecast gate: pattern class {cls} worst "
                f"1-window-holdout MAPE {mape} exceeds "
                f"{FORECAST_MAPE_BUDGET}")
    log(f"workload classes ({len(wl_topics)} topics x "
        f"{history_windows} windows, fitted in {fit_s:.2f}s): " +
        ", ".join(f"{c}={m:.4f}" for c, m in sorted(mapes.items())))

    # --- stage 2: regime loop over scripted aggregate series. Each
    # series is shaped so RegimeDetector.classify returns the phase's
    # label (steady tail ~1x, flash crowd spikes 8x then decays, step
    # holds 2.5x).
    goals = goals_by_name(goal_names or GOALS)
    spec = build_spec(num_brokers=num_brokers,
                      num_partitions=num_partitions)
    model, md = flatten_spec(spec)
    opts = OptimizationOptions(seed=seed, skip_hard_goal_check=True)
    base = SearchConfig()

    flat = np.full(24, 100.0)
    phases = [
        ("steady", np.concatenate([flat, np.full(8, 105.0)])),
        ("flash_crowd", np.concatenate(
            [flat, [800.0, 700.0, 500.0, 300.0, 200.0, 150.0, 120.0,
                    105.0]])),
        ("step_migration", np.concatenate([flat, np.full(8, 250.0)])),
    ]

    untuned = TpuGoalOptimizer(goals=goals, config=base)
    untuned.optimize(model, md, opts)                  # compile + warm
    untuned_q = plan_quality(untuned.optimize(model, md, opts))

    store = TunedConfigStore(store_path)
    opt = TpuGoalOptimizer(goals=goals, config=base, tuned_store=store)
    loop = RegimeTuningLoop(opt, store, RegimeDetector(min_dwell=1),
                            trials=tune_trials, rungs=tune_rungs,
                            seed=seed, goals=goals, options=opts)
    # Warm-up pass: tune (or pin) each regime's config and compile its
    # chain once.
    for name, series in phases:
        event = loop.on_series(series, model, md)
        if loop.detector.regime != name:
            raise RuntimeError(
                f"workload regime script error: series for {name} "
                f"classified as {loop.detector.regime}")
        if event is not None and event["regime"] != name:
            raise RuntimeError(
                f"workload regime event mismatch: {event}")
        opt.optimize(model, md, opts)

    # Scripted pass: same shift sequence warm — zero compile events.
    collector = default_collector()
    before = collector.snapshot()
    qualities, regime_s = {}, float("inf")
    for name, series in phases:
        loop.on_series(series, model, md)
        t0 = time.monotonic()
        res = opt.optimize(model, md, opts)
        regime_s = min(regime_s, time.monotonic() - t0)
        qualities[name] = plan_quality(res)
    after = collector.snapshot()
    recompiles = (after["compileEvents"] + after["aotCompileEvents"]
                  - before["compileEvents"] - before["aotCompileEvents"])
    if recompiles:
        raise RuntimeError(
            f"workload regime recompile gate: {recompiles} compile "
            f"events across the warm steady -> flash_crowd -> "
            f"step_migration pass (expected 0: tuned configs join the "
            "chain key, shifts must swap cached chains)")
    quality_delta = max(q - untuned_q for q in qualities.values())
    if quality_delta > MULTIOBJ_QUALITY_TOL:
        worst = max(qualities, key=qualities.get)
        raise RuntimeError(
            f"workload regime quality gate: {worst} tuned objective "
            f"{qualities[worst]:.4f} worse than untuned {untuned_q:.4f} "
            f"by {quality_delta:.4f} (> {MULTIOBJ_QUALITY_TOL})")
    log(f"workload regime loop ({num_brokers}x{num_partitions}, "
        f"{len(goals)} goals, trials={tune_trials}, "
        f"{len(jax.devices())} devices): {len(loop.detector.shifts)} "
        f"shifts, {loop.retunes} retunes, warm propose {regime_s:.3f}s, "
        f"quality delta {quality_delta:+.4f}, 0 warm recompiles")
    if emit_row:
        for cls, mape in sorted(mapes.items()):
            emit(f"forecast_mape_{cls}", round(mape, 6), "mape", None)
        emit("proposal_quality_delta", round(quality_delta, 6),
             "normalized-objective", None)
        emit("workload_regime_recompiles", recompiles, "count", None)
    return {"mapes": mapes, "fit_s": fit_s, "topics": len(wl_topics),
            "untuned_quality": untuned_q, "qualities": qualities,
            "quality_delta": quality_delta, "recompiles": recompiles,
            "shifts": len(loop.detector.shifts),
            "retunes": loop.retunes, "regime_s": regime_s,
            "devices": len(jax.devices())}


def run_tracer_overhead_bench(num_brokers: int = 50,
                              num_partitions: int = 5_000, *,
                              goal_names: list | None = None,
                              repeats: int = 5, emit_row: bool = True,
                              gate: bool = True) -> dict:
    """Span-tracer overhead on the warm propose path: optimize wall-clock
    with the tracer enabled vs disabled (disabled = the PR-2 pipeline
    shape). Best-of-``repeats`` per mode to shed scheduler noise. Gate:
    enabled must stay within 2% of disabled — tracing that taxes the hot
    path defeats its purpose and fails the bench loudly."""
    from cruise_control_tpu.analyzer import (OptimizationOptions,
                                             SearchConfig, TpuGoalOptimizer,
                                             goals_by_name)
    from cruise_control_tpu.core.tracing import default_tracer
    model, md = build_flat_direct(num_brokers, num_partitions, RF)
    opt = TpuGoalOptimizer(
        goals=goals_by_name(goal_names or GOALS),
        config=SearchConfig(num_replica_candidates=512,
                            num_dest_candidates=16, apply_per_iter=512,
                            max_iters_per_goal=256))
    run_opts = dict(skip_hard_goal_check=True)
    opt.optimize(model, md, OptimizationOptions(seed=0, **run_opts))  # warm
    tracer = default_tracer()

    def best_of(enabled: bool) -> float:
        tracer.enabled = enabled
        t_best = float("inf")
        for _ in range(repeats):
            t0 = time.monotonic()
            opt.optimize(model, md, OptimizationOptions(seed=1, **run_opts))
            t_best = min(t_best, time.monotonic() - t0)
        return t_best

    try:
        disabled_s = best_of(False)
        enabled_s = best_of(True)
    finally:
        tracer.enabled = True
    overhead_pct = ((enabled_s - disabled_s) / disabled_s * 100.0
                    if disabled_s > 0 else 0.0)
    log(f"tracer overhead ({num_brokers}x{num_partitions}): enabled "
        f"{enabled_s:.3f}s disabled {disabled_s:.3f}s "
        f"({overhead_pct:+.2f}%)")
    if gate and overhead_pct > 2.0:
        raise RuntimeError(
            f"tracer overhead gate: {overhead_pct:.2f}% > 2% "
            f"(enabled {enabled_s:.3f}s vs disabled {disabled_s:.3f}s)")
    if emit_row:
        emit("tracer_overhead_propose_path_pct",
             round(max(overhead_pct, 0.0), 3), "%", None)
    return {"enabled_s": enabled_s, "disabled_s": disabled_s,
            "overhead_pct": overhead_pct}


def run_event_journal_overhead_bench(num_brokers: int = 50,
                                     num_partitions: int = 5_000, *,
                                     goal_names: list | None = None,
                                     repeats: int = 5,
                                     emit_row: bool = True,
                                     gate: bool = True) -> dict:
    """Flight-recorder overhead on the warm propose path: one served
    proposal = one warm optimize plus the journal rows the facade writes
    for it (optimizer/plan-selected -> propose/served, cause-linked,
    plus a detector heartbeat), A/B with the journal enabled vs
    disabled. Best-of-``repeats`` per mode to shed scheduler noise.

    Two gates. The wall-clock gate: enabled must stay within 2% of
    disabled — a recorder that taxes the propose path defeats its
    purpose. The sync gate (ALWAYS on, any scale — it is deterministic):
    the enabled serve must issue exactly as many explicit host syncs
    (jax.device_get / jax.block_until_ready) as the disabled one; the
    journal is host-side bookkeeping and must never touch the device."""
    import jax

    from cruise_control_tpu.analyzer import (OptimizationOptions,
                                             SearchConfig, TpuGoalOptimizer,
                                             goals_by_name)
    from cruise_control_tpu.core.events import EventJournal
    model, md = build_flat_direct(num_brokers, num_partitions, RF)
    opt = TpuGoalOptimizer(
        goals=goals_by_name(goal_names or GOALS),
        config=SearchConfig(num_replica_candidates=512,
                            num_dest_candidates=16, apply_per_iter=512,
                            max_iters_per_goal=256))
    run_opts = dict(skip_hard_goal_check=True)
    opt.optimize(model, md, OptimizationOptions(seed=0, **run_opts))  # warm
    journal = EventJournal(capacity=4096, node="bench")

    def serve_once():
        res = opt.optimize(model, md, OptimizationOptions(seed=1, **run_opts))
        # The decision chain the facade journals per served proposal.
        plan = journal.record("optimizer", "plan-selected",
                              detail={"numProposals": len(res.proposals)})
        journal.record("propose", "served", cause=plan,
                       detail={"source": "fresh",
                               "numProposals": len(res.proposals)})
        journal.record("detector", "round-complete",
                       detail={"anomalies": 0})
        return res

    def best_of(enabled: bool) -> float:
        journal.enabled = enabled
        t_best = float("inf")
        for _ in range(repeats):
            t0 = time.monotonic()
            serve_once()
            t_best = min(t_best, time.monotonic() - t0)
        return t_best

    # Sync gate first: count explicit host syncs for one serve per mode.
    counts = {"n": 0}
    orig_get, orig_block = jax.device_get, jax.block_until_ready

    def counting(fn):
        def wrapped(*a, **kw):
            counts["n"] += 1
            return fn(*a, **kw)
        return wrapped

    jax.device_get = counting(orig_get)
    jax.block_until_ready = counting(orig_block)
    try:
        journal.enabled = False
        serve_once()
        syncs_disabled = counts["n"]
        counts["n"] = 0
        journal.enabled = True
        serve_once()
        syncs_enabled = counts["n"]
    finally:
        jax.device_get = orig_get
        jax.block_until_ready = orig_block
        journal.enabled = True
    if syncs_enabled != syncs_disabled:
        raise RuntimeError(
            f"journal device-sync gate: {syncs_enabled} explicit syncs "
            f"with the journal enabled vs {syncs_disabled} disabled — "
            "the flight recorder must stay pure host-side bookkeeping")

    try:
        disabled_s = best_of(False)
        enabled_s = best_of(True)
    finally:
        journal.enabled = True
    overhead_pct = ((enabled_s - disabled_s) / disabled_s * 100.0
                    if disabled_s > 0 else 0.0)
    log(f"event journal overhead ({num_brokers}x{num_partitions}): "
        f"enabled {enabled_s:.3f}s disabled {disabled_s:.3f}s "
        f"({overhead_pct:+.2f}%), {journal.last_seq} rows journaled, "
        f"{syncs_enabled} == {syncs_disabled} host syncs per serve")
    if gate and overhead_pct > 2.0:
        raise RuntimeError(
            f"event journal overhead gate: {overhead_pct:.2f}% > 2% "
            f"(enabled {enabled_s:.3f}s vs disabled {disabled_s:.3f}s)")
    if emit_row:
        emit("event_journal_overhead_propose_path_pct",
             round(max(overhead_pct, 0.0), 3), "%", None)
    return {"enabled_s": enabled_s, "disabled_s": disabled_s,
            "overhead_pct": overhead_pct,
            "syncs_enabled": syncs_enabled,
            "syncs_disabled": syncs_disabled,
            "rows": journal.last_seq}


def run_move_budget_bench(num_members: int = 16, budget: int = 96,
                          local_cap: int = 8, seed: int = 0, *,
                          emit_row: bool = True, gate: bool = True) -> dict:
    """Scenario 13: the fleet move-budget coordinator's convergence tax
    (fleet/budget.py). M member clusters all violating hard goals heal
    concurrently; each can execute at most ``local_cap`` moves per tick
    on its own (its executor concurrency cap), and the budgeted run
    additionally draws every move from ONE fleet-wide per-tick budget.
    Host-side toy dynamics on purpose: the quantity under test is the
    allocator (starvation-freedom, urgency ordering, the throughput a
    global cap costs), not the optimizer — the registry wiring is chaos-
    gated in tests/test_chaos_fleet.py.

    Three gates, all deterministic: (a) per-tick granted moves never
    exceed the budget (carry-over disabled for the gate run), (b) two
    identical runs produce the identical grant history, (c) total
    time-to-balanced under the budget stays within 1.5x of unbudgeted —
    a budget sized at ~75% of aggregate demand must throttle the burst,
    not wedge convergence."""
    from cruise_control_tpu.core.retry import deterministic_uniform
    from cruise_control_tpu.fleet import (BudgetRequest,
                                          MoveBudgetCoordinator)

    #: seeded heterogeneous backlogs: every member starts in hard-goal
    #: violation with 20..80 outstanding moves.
    def initial_backlogs():
        return {f"c{i:02d}": 20 + int(60 * deterministic_uniform(
            seed, "budget-backlog", i)) for i in range(num_members)}

    def run(budget_per_tick: int, max_ticks: int = 1_000):
        coord = MoveBudgetCoordinator(budget_per_tick=budget_per_tick,
                                      carry_max_ticks=0)
        backlog = initial_backlogs()
        history, ticks = [], 0
        while any(backlog.values()):
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"move-budget bench: no convergence in {max_ticks} "
                    f"ticks (budget {budget_per_tick})")
            requests = [
                BudgetRequest(cluster_id=cid,
                              requested=min(left, local_cap),
                              hard_violations=1,
                              # Bigger backlog = nearer forecast breach.
                              time_to_breach_ms=60_000 * local_cap
                              // max(left, 1))
                for cid, left in backlog.items() if left > 0]
            grants = coord.allocate(requests, ticks)
            history.append(tuple(sorted(
                (cid, g.granted) for cid, g in grants.items())))
            for cid, g in grants.items():
                backlog[cid] -= min(g.granted, backlog[cid])
        return ticks, history

    t0 = time.monotonic()
    unbudgeted_ticks, _ = run(0)
    budgeted_ticks, hist1 = run(budget)
    _, hist2 = run(budget)
    wall_s = time.monotonic() - t0
    worst_tick = max(sum(g for _, g in tick) for tick in hist1)
    ratio = budgeted_ticks / unbudgeted_ticks
    log(f"move budget ({num_members} members, budget {budget}, local cap "
        f"{local_cap}): balanced in {budgeted_ticks} ticks vs "
        f"{unbudgeted_ticks} unbudgeted ({ratio:.2f}x), worst tick "
        f"granted {worst_tick}/{budget}, {wall_s:.2f}s host-side")
    if gate:
        if worst_tick > budget:
            raise RuntimeError(
                f"move-budget gate: a tick granted {worst_tick} moves > "
                f"budget {budget}")
        if hist1 != hist2:
            raise RuntimeError(
                "move-budget gate: two identical runs produced different "
                "grant histories — allocation must be deterministic")
        if ratio > 1.5:
            raise RuntimeError(
                f"move-budget gate: time-to-balanced ratio {ratio:.2f}x "
                f"> 1.5x unbudgeted ({budgeted_ticks} vs "
                f"{unbudgeted_ticks} ticks)")
    if emit_row:
        emit("fleet_move_budget_time_to_balanced_ratio", round(ratio, 3),
             "x", 1.5)
    return {"budgeted_ticks": budgeted_ticks,
            "unbudgeted_ticks": unbudgeted_ticks, "ratio": ratio,
            "worst_tick_granted": worst_tick, "budget": budget}


def run_device_stats_bench(num_brokers: int = NUM_BROKERS,
                           num_partitions: int = NUM_PARTITIONS, *,
                           goal_names: list | None = None, cycles: int = 3,
                           repeats: int = 3, emit_row: bool = True,
                           gate: bool = True) -> dict:
    """Device-runtime observability rows on the warm propose path.

    Three numbers, all read off the DeviceStatsCollector:

    - ``warm_recompile_count`` — compile events across ``cycles`` warm
      propose cycles AFTER one warmup optimize. ALWAYS gated == 0 (every
      scale): a warm cycle that still compiles is exactly the silent
      recompile storm this instrumentation exists to catch.
    - ``transfer_bytes_per_cycle`` — h2d+d2h bytes of one warm cycle
      (min over cycles; the model is device-resident, so this is the
      walk's result fetches + the proposal diff's host reads).
    - ``padding_waste_pct`` — partition-axis padding waste of the bench
      model (the shape-bucket tax item 5 of the roadmap pays at 10Kx1M).

    Plus the same <2% overhead A/B bar the tracer bench set: collector
    enabled vs disabled on the warm path (``gate`` controls only this
    wall-clock gate — it is noise-bound at toy scale)."""
    from cruise_control_tpu.analyzer import (OptimizationOptions,
                                             SearchConfig, TpuGoalOptimizer,
                                             goals_by_name)
    from cruise_control_tpu.core.runtime_obs import default_collector
    model, md = build_flat_direct(num_brokers, num_partitions, RF)
    opt = TpuGoalOptimizer(
        goals=goals_by_name(goal_names or GOALS),
        config=SearchConfig(num_replica_candidates=512,
                            num_dest_candidates=16, apply_per_iter=512,
                            max_iters_per_goal=256))
    collector = default_collector()
    run_opts = dict(skip_hard_goal_check=True)
    opt.optimize(model, md, OptimizationOptions(seed=0, **run_opts))  # warm
    snap = collector.snapshot()
    per_cycle_bytes = []
    for i in range(cycles):
        opt.optimize(model, md, OptimizationOptions(seed=1 + i, **run_opts))
        per_cycle_bytes.append(collector.last_cycle["transferBytes"])
    after = collector.snapshot()
    recompiles = ((after["compileEvents"] + after["aotCompileEvents"])
                  - (snap["compileEvents"] + snap["aotCompileEvents"]))
    transfer_bytes = min(per_cycle_bytes)
    padding = collector.padding_from_model(model)

    def best_of(enabled: bool) -> float:
        collector.enabled = enabled
        t_best = float("inf")
        for r in range(repeats):
            t0 = time.monotonic()
            opt.optimize(model, md,
                         OptimizationOptions(seed=100 + r, **run_opts))
            t_best = min(t_best, time.monotonic() - t0)
        return t_best

    try:
        disabled_s = best_of(False)
        enabled_s = best_of(True)
    finally:
        collector.enabled = True
    overhead_pct = ((enabled_s - disabled_s) / disabled_s * 100.0
                    if disabled_s > 0 else 0.0)
    log(f"device stats ({num_brokers}x{num_partitions}): "
        f"{recompiles} recompiles over {cycles} warm cycles, "
        f"{transfer_bytes} transfer bytes/cycle, padding waste "
        f"{padding['partitionWastePct']}% partitions / "
        f"{padding['brokerWastePct']}% brokers; collector overhead "
        f"{overhead_pct:+.2f}% (enabled {enabled_s:.3f}s / disabled "
        f"{disabled_s:.3f}s)")
    if recompiles != 0:
        raise RuntimeError(
            f"warm-recompile gate: {recompiles} compile events across "
            f"{cycles} warm propose cycles (want 0) — a warm path that "
            "recompiles is the failure mode this collector exists to "
            "catch; see /devicestats recentEvents for the programs")
    if gate and overhead_pct > 2.0:
        raise RuntimeError(
            f"device-stats collector overhead gate: {overhead_pct:.2f}% "
            f"> 2% (enabled {enabled_s:.3f}s vs disabled "
            f"{disabled_s:.3f}s)")
    if emit_row:
        emit("warm_recompile_count", recompiles, "compiles", None)
        emit("transfer_bytes_per_cycle", transfer_bytes, "bytes", None)
        emit("padding_waste_pct", padding["partitionWastePct"], "%", None)
    return {"recompiles": recompiles, "transfer_bytes": transfer_bytes,
            "padding": padding, "overhead_pct": overhead_pct,
            "enabled_s": enabled_s, "disabled_s": disabled_s}


def run_resident_delta_bench(num_brokers: int = NUM_BROKERS,
                             num_partitions: int = NUM_PARTITIONS, *,
                             churn_pct: float = 1.0, cycles: int = 3,
                             emit_row: bool = True, gate: bool = True
                             ) -> dict:
    """Resident-state rows: metric-only delta cycles vs the full-rebuild
    upload on the monitor→model path.

    A monitor with the resident state on ingests a stable synthetic
    workload; each warm cycle then changes ``churn_pct`` of partitions
    (the "sliver of metric windows" case the resident path exists for)
    and rebuilds. Reported:

    - ``resident_delta_cycle_wall_clock`` — best metric-only cycle
      (aggregate + assembly + delta scatter), vs_baseline = the full
      rebuild+upload cycle over it.
    - ``resident_delta_h2d_bytes_per_cycle`` — the delta payload bytes,
      vs_baseline = full-model upload bytes over it. **Gated >= 10x at
      bench scale** (the acceptance bar; delta-bucket padding makes the
      ratio meaningless on toy shapes, so the smoke gate passes
      gate=False).

    Always asserted, every scale: delta cycles touch EXACTLY the churned
    rows (exact-diff parity), bump no epoch, and — after one
    ``resident.warmup()`` — compile nothing.
    """
    from cruise_control_tpu.core.metricdef import partition_metric_def
    from cruise_control_tpu.core.runtime_obs import default_collector
    from cruise_control_tpu.executor import SimulatedKafkaCluster
    from cruise_control_tpu.monitor import LoadMonitor, MonitorConfig

    window_ms = 1000
    sim = SimulatedKafkaCluster()
    for b in range(num_brokers):
        sim.add_broker(b)
    num_topics = max(num_partitions // 100, 1)
    for p in range(num_partitions):
        sim.add_partition(f"t{p % num_topics}", p,
                          [p % num_brokers, (p + 1) % num_brokers],
                          size_mb=50.0 + (p % 100))
    monitor = LoadMonitor(sim, MonitorConfig(
        num_windows=MODEL_BUILD_WINDOWS, window_ms=window_ms,
        min_samples_per_window=1))
    resident = monitor.resident
    assert resident is not None
    mdef = partition_metric_def()
    keys = sorted(sim.describe_partitions())
    P = len(keys)
    # Integer values: window means over identical values are exact, so
    # only the churned rows ever produce a changed load row.
    vals = ((np.arange(P * mdef.size(), dtype=np.float64)
             .reshape(P, mdef.size()) % 97) + 1.0)
    next_w = 0

    def ingest(v, windows=1):
        nonlocal next_w
        for _ in range(windows):
            times = np.full(P, next_w * window_ms + 100, np.int64)
            monitor.partition_aggregator.add_samples_dense(keys, times, v)
            next_w += 1

    ingest(vals, windows=MODEL_BUILD_WINDOWS + 1)
    t0 = time.monotonic()
    monitor.cluster_model(next_w * window_ms)
    full_s = time.monotonic() - t0
    assert resident.last_update == "full" and resident.epoch == 1
    full_bytes = resident.last_full_bytes
    resident.warmup()                  # pre-compile the delta bucket

    churn_n = max(int(P * churn_pct / 100.0), 1)
    churn_rows = np.arange(churn_n)
    collector = default_collector()
    snap = collector.snapshot()
    delta_s, delta_bytes_per_cycle = float("inf"), []
    for c in range(cycles):
        vals = vals.copy()
        vals[churn_rows] += 1.0 + c
        # Two windows so the changed window rolls out of the in-flight
        # slot (the aggregator never serves the current window).
        ingest(vals, windows=2)
        t0 = time.monotonic()
        monitor.cluster_model(next_w * window_ms)
        delta_s = min(delta_s, time.monotonic() - t0)
        if resident.last_update != "delta" or resident.epoch != 1:
            raise RuntimeError(
                f"metric-only cycle {c} left the delta path: "
                f"update={resident.last_update} epoch={resident.epoch}")
        if resident.last_delta_rows != churn_n:
            raise RuntimeError(
                f"delta touched {resident.last_delta_rows} rows, expected "
                f"exactly the {churn_n} churned rows — the exact-diff "
                "parity contract is broken")
        delta_bytes_per_cycle.append(resident.last_delta_bytes)
    after = collector.snapshot()
    recompiles = ((after["compileEvents"] + after["aotCompileEvents"])
                  - (snap["compileEvents"] + snap["aotCompileEvents"]))
    if recompiles != 0:
        raise RuntimeError(
            f"resident delta cycles compiled {recompiles} programs after "
            "warmup (want 0) — see /devicestats recentEvents")
    epoch_after_deltas = resident.epoch
    # WARM full-rebuild baseline: the first build above was cold
    # (first-touch aggregation + allocation); re-measure the full
    # rebuild+upload cycle warm so the wall-clock comparison is
    # like-for-like with the warm delta cycles.
    resident.invalidate()
    t0 = time.monotonic()
    monitor.cluster_model(next_w * window_ms)
    full_s = min(full_s, time.monotonic() - t0)
    assert resident.last_update == "full"
    delta_bytes = min(delta_bytes_per_cycle)
    ratio = full_bytes / delta_bytes if delta_bytes else None
    log(f"resident delta ({num_brokers}x{num_partitions}, "
        f"{churn_n} rows/cycle churn): delta cycle {delta_s:.3f}s vs full "
        f"{full_s:.3f}s; h2d {delta_bytes} bytes/cycle vs full upload "
        f"{full_bytes} bytes ({ratio:.1f}x smaller)")
    if gate and (ratio is None or ratio < 10.0):
        raise RuntimeError(
            f"resident h2d gate: delta payload {delta_bytes} bytes is only "
            f"{ratio:.1f}x smaller than the {full_bytes}-byte full upload "
            "(want >= 10x)")
    if emit_row:
        emit("resident_delta_cycle_wall_clock", round(delta_s, 3), "s",
             round(full_s / delta_s, 3) if delta_s > 0 else None)
        emit("resident_delta_h2d_bytes_per_cycle", delta_bytes, "bytes",
             round(ratio, 1) if ratio else None)
    return {"full_s": full_s, "delta_s": delta_s,
            "full_bytes": full_bytes, "delta_bytes": delta_bytes,
            "rows_per_cycle": churn_n, "ratio": ratio,
            "recompiles": recompiles, "epoch": epoch_after_deltas}


def run_chaos_recovery_bench(*, seed: int = 11, emit_row: bool = True,
                             max_steps: int = 200) -> dict:
    """Recovery time under the canonical chaos scenario: a broker dies
    mid-run and the detector→optimizer→executor loop drains and restores
    it. Value = simulated steps from the observed crash to restored
    balancedness (healthy, fully-replicated, executor idle) — tracked so
    a regression in the heal path (slower detection, stuck teardown,
    extra execution rounds) fails review like a perf regression. Fully
    deterministic in ``seed``; invariants gate the row (a recovery that
    loses replicas must fail the bench, not report a fast number)."""
    from cruise_control_tpu.chaos import (ChaosHarness, check_invariants,
                                          snapshot_topology)
    h = ChaosHarness(seed=seed)
    base = snapshot_topology(h.sim)
    h.warmup()
    s0 = h.engine.step
    h.engine.schedule(s0 + 2, "kill_broker", broker=1)
    h.engine.schedule(s0 + 9, "restart_broker", broker=1)
    h.steps_until(lambda: not h.sim.describe_cluster().get(1, True), 20,
                  what="scheduled broker kill")
    t0 = time.monotonic()
    steps = h.steps_until(h.healed, max_steps, what="post-crash recovery")
    wall_s = time.monotonic() - t0
    problems = check_invariants(h.sim, base, h.executor)
    if problems:
        raise RuntimeError("chaos recovery bench violated invariants "
                           f"(seed={seed}): " + "; ".join(problems))
    log(f"chaos recovery (seed={seed}): {steps} steps crash->balanced "
        f"({wall_s:.1f}s wall, {h.detector.num_self_healing_started} "
        "fixes)")
    if emit_row:
        emit("chaos_recovery_steps", steps, "steps", None)
    return {"steps": steps, "seed": seed, "wall_s": wall_s}


class _LatencyAdmin:
    """Admin proxy charging a fixed wall-clock RTT per RPC against the
    simulated cluster. The latency burns OUTSIDE the lock (network time —
    the part a pipelined executor can overlap); the sim call itself is
    serialized (the sim is not thread-safe). With the executor's
    ``sleep_ms`` bound to the sim clock (near-zero wall), measured wall
    time is (RPC rounds x RTT) minus whatever the pipeline overlaps —
    exactly the quantity scenario 11 compares."""

    concurrent_safe = True

    def __init__(self, sim, latency_s: float):
        self._sim = sim
        self._latency_s = latency_s
        self._latency_lock = threading.Lock()
        self.calls = 0

    def __getattr__(self, name):
        inner = getattr(self._sim, name)
        if not callable(inner):
            return inner

        def call(*args, **kwargs):
            time.sleep(self._latency_s)
            with self._latency_lock:
                self.calls += 1
                return inner(*args, **kwargs)
        return call


class _BenchFlippingFence:
    """Elector stand-in deposing the executor after N fence checks —
    mid-pipeline, between batch admission and completion."""

    def __init__(self, flips_after: int):
        self.epoch = 7
        self._checks = 0
        self._flips_after = flips_after

    def is_current(self, token) -> bool:
        self._checks += 1
        return self._checks <= self._flips_after

    def leader_id(self) -> str:
        return "bench-successor"


def run_executor_schedule_bench(*, num_brokers: int = 8,
                                partitions: int = 48,
                                size_mb: float = 200.0,
                                rate_mb_s: float = 25.0,
                                rpc_latency_ms: float = 4.0,
                                chaos: bool = True, chaos_seed: int = 11,
                                chaos_max_steps: int = 200,
                                emit_row: bool = True,
                                gate: bool = True) -> dict:
    """Scenario 11: device-scheduled pipelined execution vs the greedy
    sequential per-batch executor, identical sim + identical RPC tax.

    Both sides drive the same follower-rotation plan through a
    ``SimulatedKafkaCluster`` wrapped in :class:`_LatencyAdmin` (fixed
    wall RTT per admin RPC, sim calls serialized, latency overlappable).
    Copy time runs on the *sim* clock (free wall), so wall-clock measures
    exactly what the pipelined phase optimizes: RPC rounds and their
    overlap. The greedy baseline re-plans per batch and polls every
    progress interval; the scheduled side admits precomputed batches,
    skips ETA-covered polls and overlaps the poll round's reads.

    **Gated** (the acceptance bar):

    - ``executor_moves_per_s`` >= 3x the greedy baseline;
    - zero hard-goal violations at every batch boundary
      (``unrepaired_violations == 0`` from the on-device audit);
    - zero warm recompiles across the scheduled run (schedule build +
      pipelined batches share one compiled program);
    - scheduled and greedy runs converge to the SAME final placement
      with zero verify failures;
    - a mid-pipeline fence flip aborts without cancelling in-flight
      copies, releases the reservation, and the drained cluster passes
      ``check_invariants`` (fencing ledger clean);
    - with ``chaos=True``: ``time_to_balanced_steps`` on the canonical
      crash-recovery scenario is no worse than greedy, with the device
      path provably engaged (schedule stats present)."""
    from cruise_control_tpu.analyzer.goals import goals_by_name
    from cruise_control_tpu.core.runtime_obs import default_collector
    from cruise_control_tpu.executor import (
        ConcurrencyConfig, DeviceMoveScheduler,
        ExecutionConcurrencyManager, Executor, ExecutorConfig, SimClock,
        SimulatedKafkaCluster)
    from cruise_control_tpu.executor.strategy import StrategyContext
    from cruise_control_tpu.model.proposals import ExecutionProposal
    from cruise_control_tpu.model.spec import (BrokerSpec, ClusterSpec,
                                               PartitionSpec, flatten_spec)

    def make_sim(size=size_mb, rate=rate_mb_s):
        sim = SimulatedKafkaCluster()
        for b in range(num_brokers):
            sim.add_broker(b, rate_mb_s=rate, logdirs=("logdir0",
                                                       "logdir1"))
        for p in range(partitions):
            sim.add_partition(f"t{p % 4}", p,
                              [p % num_brokers, (p + 1) % num_brokers],
                              size_mb=size)
        return sim

    def rotation(sim):
        out = []
        for (topic, part), info in sorted(sim.describe_partitions()
                                          .items()):
            reps = list(info.replicas)
            out.append(ExecutionProposal(
                topic, part, old_leader=info.leader,
                old_replicas=tuple(reps),
                new_replicas=(reps[0], (reps[1] + 1) % num_brokers)))
        return out

    def make_executor(sim, latency_s):
        admin = _LatencyAdmin(sim, latency_s)
        clock = SimClock(sim)
        cfg = ExecutorConfig(progress_check_interval_ms=100,
                             min_progress_check_interval_ms=100,
                             concurrency=cc,
                             concurrency_adjuster_enabled=False)
        return Executor(admin, cfg, now_ms=clock.now_ms,
                        sleep_ms=clock.sleep_ms), admin

    latency_s = rpc_latency_ms / 1000.0
    cc = ConcurrencyConfig(num_concurrent_partition_movements_per_broker=2)
    # The audit gates HARD goals (capacity): a rotation plan's transient
    # replica-count imbalance is inherent to move ordering — no batching
    # repairs it — while blowing a capacity ceiling mid-plan is exactly
    # the failure the boundary audit exists to catch. Disk capacity is
    # sized tight: steady state ~2400 MB/broker, worst legal transient
    # +2 in-flight copies (the per-broker cap) = ~2800 MB; capacity 4500
    # at the default 0.8 disk threshold gives a 3600 MB usable ceiling —
    # clears the legal transient, catches a pile-up.
    goals = tuple(goals_by_name(["ReplicaCapacityGoal",
                                 "DiskCapacityGoal"]))
    sim_a, sim_b = make_sim(), make_sim()
    props_a, props_b = rotation(sim_a), rotation(sim_b)
    # Spec mirror of the sim for the boundary hard-goal audit.
    spec = ClusterSpec(
        brokers=[BrokerSpec(b, rack=f"r{b}",
                            capacity=(1e6, 1e6, 1e6, 4500.0))
                 for b in range(num_brokers)],
        partitions=[PartitionSpec(t, p, list(info.replicas),
                                  leader_load=(1.0, 1.0, 1.0, size_mb))
                    for (t, p), info in
                    sorted(sim_a.describe_partitions().items())])
    model, md = flatten_spec(spec)
    ctx = StrategyContext(partition_size_mb={
        (p.topic, p.partition): size_mb for p in props_a})
    throttle = int(rate_mb_s * 1e6)
    scheduler = DeviceMoveScheduler()

    def build_schedule():
        return scheduler.schedule(
            props_a, ExecutionConcurrencyManager(cc), model=model,
            metadata=md, goals=goals, strategy_context=ctx,
            throttle_bytes=throttle)

    build_schedule()                 # cold: first-fit + audit compiles
    collector = default_collector()
    before = collector.snapshot()
    ex_a, _ = make_executor(sim_a, latency_s)
    t0 = time.monotonic()
    sched = build_schedule()         # warm: in the timed window
    res_a = ex_a.execute_proposals(props_a, uuid="bench-sched",
                                   schedule=sched,
                                   throttle_bytes=throttle)
    sched_wall = time.monotonic() - t0
    after = collector.snapshot()
    recompiles = (after["compileEvents"] + after["aotCompileEvents"]
                  - before["compileEvents"] - before["aotCompileEvents"])
    stats = ex_a.last_schedule_stats

    ex_b, _ = make_executor(sim_b, latency_s)
    t0 = time.monotonic()
    res_b = ex_b.execute_proposals(props_b, uuid="bench-greedy",
                                   throttle_bytes=throttle)
    greedy_wall = time.monotonic() - t0

    moves = sched.num_moves
    sched_mps = moves / sched_wall if sched_wall > 0 else float("inf")
    greedy_mps = moves / greedy_wall if greedy_wall > 0 else float("inf")
    ratio = sched_mps / greedy_mps if greedy_mps > 0 else float("inf")
    place_a = {tp: tuple(i.replicas)
               for tp, i in sim_a.describe_partitions().items()}
    place_b = {tp: tuple(i.replicas)
               for tp, i in sim_b.describe_partitions().items()}
    log(f"executor schedule bench: {moves} moves in "
        f"{len(sched.batches)} batches, rtt {rpc_latency_ms}ms | "
        f"scheduled {sched_wall:.2f}s ({sched_mps:.1f} mv/s, "
        f"{stats['polls_skipped']} polls skipped, "
        f"{stats['overlapped_rounds']} overlapped rounds) vs greedy "
        f"{greedy_wall:.2f}s ({greedy_mps:.1f} mv/s) -> {ratio:.1f}x")
    problems = []
    if not (res_a.succeeded and res_b.succeeded):
        problems.append("a side failed: scheduled="
                        f"{res_a.succeeded} greedy={res_b.succeeded}")
    if place_a != place_b:
        problems.append("scheduled and greedy final placements diverge")
    if stats["verify_failures"]:
        problems.append(f"{stats['verify_failures']} verify failures")
    if sched.stats["unrepaired_violations"]:
        problems.append(f"{sched.stats['unrepaired_violations']} "
                        "hard-goal violations at batch boundaries")
    if recompiles:
        problems.append(f"{recompiles} warm recompiles across the "
                        "scheduled run (expected 0)")

    # Mid-pipeline fence flip: abort without cancel RPCs, reservation
    # released, ledger + invariants clean once the successor's copies
    # drain on the sim clock.
    from cruise_control_tpu.chaos import check_invariants, snapshot_topology
    sim_f = make_sim(size=500.0, rate=5.0)           # long copies
    props_f = rotation(sim_f)
    base_f = snapshot_topology(sim_f)
    sched_f = scheduler.schedule(props_f, ExecutionConcurrencyManager(cc))
    clock_f = SimClock(sim_f)
    ex_f = Executor(sim_f,
                    ExecutorConfig(progress_check_interval_ms=100,
                                   concurrency=cc,
                                   concurrency_adjuster_enabled=False),
                    now_ms=clock_f.now_ms, sleep_ms=clock_f.sleep_ms)
    ex_f.fence = _BenchFlippingFence(flips_after=3)
    ex_f.execute_proposals(props_f, uuid="bench-fence", schedule=sched_f)
    if ex_f._fencing_aborts.count != 1:
        problems.append("fence flip did not abort the pipelined phase "
                        f"exactly once ({ex_f._fencing_aborts.count})")
    if ex_f.has_ongoing_execution():
        problems.append("reservation still held after fenced abort")
    if not sim_f.list_partition_reassignments():
        problems.append("fenced abort cancelled in-flight reassignments "
                        "(they belong to the successor)")
    for _ in range(400):                             # drain on sim time
        clock_f.sleep_ms(1000)
        if not sim_f.list_partition_reassignments():
            break
    problems += check_invariants(sim_f, base_f, ex_f)

    # Chaos comparison: canonical crash-recovery scenario, greedy vs
    # device-scheduled facade path; steps-to-balanced must not regress.
    steps_greedy = steps_sched = None
    if chaos:
        from cruise_control_tpu.chaos import ChaosHarness

        def chaos_steps(device_scheduling):
            h = ChaosHarness(seed=chaos_seed)
            h.executor.config.device_scheduling = device_scheduling
            base = snapshot_topology(h.sim)
            h.warmup()
            s0 = h.engine.step
            h.engine.schedule(s0 + 2, "kill_broker", broker=1)
            h.engine.schedule(s0 + 9, "restart_broker", broker=1)
            h.steps_until(
                lambda: not h.sim.describe_cluster().get(1, True), 20,
                what="scheduled broker kill")
            steps = h.steps_until(h.healed, chaos_max_steps,
                                  what="post-crash recovery")
            bad = check_invariants(h.sim, base, h.executor)
            if bad:
                raise RuntimeError(
                    "executor schedule bench: chaos leg "
                    f"(device={device_scheduling}) violated invariants: "
                    + "; ".join(bad))
            return steps, h

        steps_greedy, _ = chaos_steps(False)
        steps_sched, h_sched = chaos_steps(True)
        log(f"chaos time_to_balanced: scheduled {steps_sched} steps vs "
            f"greedy {steps_greedy} steps (seed={chaos_seed})")
        if h_sched.executor.last_schedule_stats is None:
            problems.append("device scheduling never engaged during the "
                            "chaos heal (degraded to greedy silently)")

    # Structural always-on gates raise regardless of ``gate`` — only the
    # wall-clock ratio and the chaos step comparison are scale-dependent.
    if problems:
        raise RuntimeError("executor schedule bench always-on gates: "
                           + "; ".join(problems))
    if gate and steps_sched is not None and steps_sched > steps_greedy:
        raise RuntimeError(
            f"time_to_balanced gate: {steps_sched} steps scheduled vs "
            f"{steps_greedy} greedy (must not regress)")
    if gate and ratio < 3.0:
        raise RuntimeError(
            f"executor_moves_per_s gate: scheduled {sched_mps:.1f} mv/s "
            f"is only {ratio:.1f}x greedy {greedy_mps:.1f} mv/s "
            "(want >= 3x)")
    if emit_row:
        emit("executor_moves_per_s", round(sched_mps, 1), "moves/s",
             round(ratio, 2), vs_greedy=round(ratio, 2))
        if steps_sched is not None:
            emit("time_to_balanced_steps", steps_sched, "steps",
                 round(steps_greedy / steps_sched, 2)
                 if steps_sched else None,
                 vs_greedy=round(steps_greedy / steps_sched, 2)
                 if steps_sched else None)
    return {"moves": moves, "batches": len(sched.batches),
            "sched_wall_s": sched_wall, "greedy_wall_s": greedy_wall,
            "sched_moves_per_s": sched_mps,
            "greedy_moves_per_s": greedy_mps, "ratio": ratio,
            "polls_skipped": stats["polls_skipped"],
            "polls_performed": stats["polls_performed"],
            "overlapped_rounds": stats["overlapped_rounds"],
            "recompiles": recompiles,
            "unrepaired_violations":
                sched.stats["unrepaired_violations"],
            "steps_greedy": steps_greedy, "steps_sched": steps_sched}


def run_snapshot_restore_bench(num_brokers: int = NUM_BROKERS,
                               num_partitions: int = NUM_PARTITIONS, *,
                               goal_names: list | None = None,
                               emit_row: bool = True, gate: bool = True
                               ) -> dict:
    """Restart-warmth row: restore-to-warm-serve from a crash-safe
    snapshot vs the cold start path, on the served facade at bench scale.

    Process 1 (the "pre-crash" control plane) ingests a synthetic
    workload, pays the honest cold start — ``prewarm()`` (model build +
    resident warmup + AOT goal-chain compile) plus the first
    ``proposals()`` computation — and writes one snapshot. Process 2 (the
    "restart") shares no monitor state: a fresh monitor with ZERO sample
    history restores the snapshot and serves. Reported:

    - ``snapshot_restore_wall_clock`` — restore + first warm
      ``/proposals`` serve; vs_baseline = cold start over it. **Gated
      >= 5x at bench scale** (the acceptance bar; toy smoke runs pass
      gate=False because the suite's shared compiled chains make the
      cold path artificially cheap there).

    Always asserted, every scale: the restored process serves proposals
    BIT-IDENTICAL to the pre-crash ones, generation-valid (zero new
    cache computations), with ZERO compile events across restore+serve
    (read off the /devicestats collector), and the restored result stays
    stale-flagged (execution gated until a live model build)."""
    import os
    import tempfile

    from cruise_control_tpu.api.facade import KafkaCruiseControl
    from cruise_control_tpu.core.metricdef import partition_metric_def
    from cruise_control_tpu.core.snapshot import SnapshotManager
    from cruise_control_tpu.analyzer import (SearchConfig, TpuGoalOptimizer,
                                             goals_by_name)
    from cruise_control_tpu.executor import SimulatedKafkaCluster
    from cruise_control_tpu.monitor import LoadMonitor, MonitorConfig

    window_ms = 1000
    windows = 4
    num_topics = max(num_partitions // 100, 1)

    def build_sim():
        sim = SimulatedKafkaCluster()
        for b in range(num_brokers):
            sim.add_broker(b)
        for p in range(num_partitions):
            # Skewed onto 20% of brokers so proposals carry real moves.
            pool = max(num_brokers // 5, 2) if p % 2 == 0 else num_brokers
            sim.add_partition(f"t{p % num_topics}", p,
                              [p % pool, (p + 1) % pool],
                              size_mb=50.0 + (p % 100))
        return sim

    def build_stack(sim, optimizer, *, ingest: bool):
        monitor = LoadMonitor(sim, MonitorConfig(
            num_windows=windows, window_ms=window_ms,
            min_samples_per_window=1))
        if ingest:
            mdef = partition_metric_def()
            keys = sorted(sim.describe_partitions())
            P = len(keys)
            vals = ((np.arange(P * mdef.size(), dtype=np.float64)
                     .reshape(P, mdef.size()) % 97) + 1.0)
            for w in range(windows + 1):
                times = np.full(P, w * window_ms + 100, np.int64)
                monitor.partition_aggregator.add_samples_dense(keys, times,
                                                               vals)
        now = (windows + 1) * window_ms
        return KafkaCruiseControl(sim, monitor, optimizer=optimizer,
                                  now_ms=lambda: now)

    opt = TpuGoalOptimizer(
        goals=goals_by_name(goal_names or GOALS[:3]),
        config=SearchConfig(num_replica_candidates=512,
                            num_dest_candidates=16, apply_per_iter=512,
                            max_iters_per_goal=256))
    sim = build_sim()

    # --- process 1: the honest cold start, then one snapshot write.
    facade1 = build_stack(sim, opt, ingest=True)
    t0 = time.monotonic()
    facade1.prewarm()
    pre = facade1.proposals()
    cold_s = time.monotonic() - t0
    snap_dir = tempfile.mkdtemp(prefix="cc-snap-bench-")
    snap_path = os.path.join(snap_dir, "cc.snapshot")
    facade1.attach_snapshotter(SnapshotManager(snap_path))
    written = facade1.snapshotter.write(facade1._now_ms(),
                                        facade1.snapshot_payload())
    if not written:
        raise RuntimeError("snapshot write failed; see log")

    # --- process 2: fresh monitor, zero samples, restore + serve.
    facade2 = build_stack(sim, opt, ingest=False)
    facade2.attach_snapshotter(SnapshotManager(snap_path))
    collector = facade2.device_stats
    snap = collector.snapshot()
    t0 = time.monotonic()
    if not facade2.restore_from_snapshot():
        raise RuntimeError("snapshot restore refused; see log")
    served = facade2.proposals()
    restore_s = time.monotonic() - t0
    after = collector.snapshot()

    recompiles = ((after["compileEvents"] + after["aotCompileEvents"]
                   + after["recompileEvents"])
                  - (snap["compileEvents"] + snap["aotCompileEvents"]
                     + snap["recompileEvents"]))
    if recompiles != 0:
        raise RuntimeError(
            f"restored warm path compiled {recompiles} programs (want 0) "
            "— restore must compose with the persistent cache; see "
            "/devicestats recentEvents")
    identical = ([p.to_json() for p in served.proposals]
                 == [p.to_json() for p in pre.proposals])
    if not identical:
        raise RuntimeError(
            "restored process served different proposals than the "
            "pre-crash process — the bit-identical restore contract is "
            "broken")
    if facade2.proposal_cache.num_computations != \
            facade1.proposal_cache.num_computations:
        raise RuntimeError(
            "restore was not generation-valid: the restored cache "
            "recomputed instead of serving the snapshot entry")
    if not served.stale_model:
        raise RuntimeError("restored proposals must stay stale-flagged "
                           "(execution gated until a live model build)")
    speedup = cold_s / restore_s if restore_s > 0 else None
    log(f"snapshot restore ({num_brokers}x{num_partitions}): "
        f"restore-to-warm-serve {restore_s:.3f}s vs cold start "
        f"{cold_s:.2f}s ({speedup:.1f}x); snapshot "
        f"{facade1.snapshotter.to_json()['bytes']} bytes, 0 compiles "
        "on the restored path")
    if gate and (speedup is None or speedup < 5.0):
        raise RuntimeError(
            f"snapshot restore gate: {restore_s:.3f}s is only "
            f"{speedup:.1f}x faster than the {cold_s:.2f}s cold start "
            "(want >= 5x)")
    if emit_row:
        emit("snapshot_restore_wall_clock", round(restore_s, 3), "s",
             round(speedup, 1) if speedup else None)
    return {"cold_s": cold_s, "restore_s": restore_s, "speedup": speedup,
            "recompiles": recompiles, "identical": identical,
            "snapshot_bytes": facade1.snapshotter.to_json()["bytes"]}


def run_api_throughput_bench(num_brokers: int = 50,
                             num_partitions: int = 5_000, *,
                             threads: int = 8, duration_s: float = 2.0,
                             goal_names: list | None = None,
                             emit_row: bool = True, gate: bool = True
                             ) -> dict:
    """Heavy-traffic read tier: closed-loop mixed GET traffic against a
    warm served stack, render cache ON vs OFF (the per-request-render
    baseline). Real HTTP (keep-alive, ``threads`` client threads) over
    the stock threading engine; mix = GET /proposals + /state +
    /devicestats round-robin.

    Reported:

    - ``api_requests_per_s`` — cached read throughput; vs_baseline =
      cached / per-request-render. **Gated >= 5x at bench scale** (toy
      smoke runs pass gate=False: tiny response bodies make the
      baseline's re-render artificially cheap there).
    - ``api_read_p99_ms`` — cached read p99 latency; vs_baseline =
      baseline p99 over it.

    Always asserted, every scale: ZERO device dispatches attributable
    to cached reads (compile events AND host<->device transfer bytes
    flat across the cached GET-only phase, read off the /devicestats
    collector), ETag-consistent responses under concurrent generation
    bumps + a trickle of POST /rebalance (one ETag never names two
    different bodies; If-None-Match answers 304 with zero body bytes),
    and zero 5xx anywhere."""
    import hashlib
    import http.client

    from cruise_control_tpu.api.facade import KafkaCruiseControl
    from cruise_control_tpu.api.server import CruiseControlApp
    from cruise_control_tpu.core.metricdef import partition_metric_def
    from cruise_control_tpu.analyzer import (SearchConfig, TpuGoalOptimizer,
                                             goals_by_name)
    from cruise_control_tpu.executor import SimulatedKafkaCluster
    from cruise_control_tpu.monitor import LoadMonitor, MonitorConfig

    window_ms = 1000
    windows = 4
    num_topics = max(num_partitions // 100, 1)
    sim = SimulatedKafkaCluster()
    for b in range(num_brokers):
        sim.add_broker(b)
    for p in range(num_partitions):
        pool = max(num_brokers // 5, 2) if p % 2 == 0 else num_brokers
        sim.add_partition(f"t{p % num_topics}", p,
                          [p % pool, (p + 1) % pool],
                          size_mb=50.0 + (p % 100))
    monitor = LoadMonitor(sim, MonitorConfig(
        num_windows=windows, window_ms=window_ms,
        min_samples_per_window=1))
    mdef = partition_metric_def()
    keys = sorted(sim.describe_partitions())
    P = len(keys)
    vals = ((np.arange(P * mdef.size(), dtype=np.float64)
             .reshape(P, mdef.size()) % 97) + 1.0)
    next_window = [0]

    def ingest_window():
        w = next_window[0]
        next_window[0] += 1
        times = np.full(P, w * window_ms + 100, np.int64)
        monitor.partition_aggregator.add_samples_dense(keys, times, vals)
        now_box[0] = (w + 1) * window_ms

    now_box = [0]
    for _ in range(windows + 1):
        ingest_window()
    opt = TpuGoalOptimizer(
        goals=goals_by_name(goal_names or GOALS[:2]),
        config=SearchConfig(num_replica_candidates=512,
                            num_dest_candidates=16, apply_per_iter=512,
                            max_iters_per_goal=256))
    facade = KafkaCruiseControl(sim, monitor, optimizer=opt,
                                now_ms=lambda: now_box[0])
    app = CruiseControlApp(facade, port=0, max_active_tasks=1024)
    app.start()
    try:
        # Warm serve: one proposal computation published; the read tier
        # under test never recomputes it (mixed phase excepted).
        facade.proposals()
        mix = ["/kafkacruisecontrol/proposals", "/kafkacruisecontrol/state",
               "/kafkacruisecontrol/devicestats"]

        def drive(label, duration, *, with_writes=False):
            """Closed-loop phase: returns (completed, statuses, lat_s,
            etag->body-hash map)."""
            stop = threading.Event()
            outs = []

            def reader(my):
                conn = http.client.HTTPConnection("127.0.0.1", app.port,
                                                  timeout=60)
                i = 0
                while not stop.is_set():
                    path = mix[i % len(mix)]
                    i += 1
                    t0 = time.monotonic()
                    try:
                        conn.request("GET", path)
                        resp = conn.getresponse()
                        body = resp.read()
                    except Exception:
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", app.port, timeout=60)
                        my["transport_errors"] += 1
                        continue
                    my["lat"].append(time.monotonic() - t0)
                    my["statuses"][resp.status] = (
                        my["statuses"].get(resp.status, 0) + 1)
                    etag = resp.getheader("ETag")
                    if etag and resp.status == 200:
                        my["pairs"].append(
                            (etag, hashlib.sha256(body).hexdigest()))
                conn.close()

            def writer(my):
                # The trickle: generation bumps (a new sampling window
                # lands) interleaved with dryrun rebalances — the write
                # traffic the cached readers must stay coherent under.
                conn = http.client.HTTPConnection("127.0.0.1", app.port,
                                                  timeout=120)
                while not stop.is_set():
                    ingest_window()
                    try:
                        conn.request(
                            "POST",
                            "/kafkacruisecontrol/rebalance?dryrun=true"
                            "&get_response_timeout_s=60")
                        resp = conn.getresponse()
                        resp.read()
                        my["statuses"][resp.status] = (
                            my["statuses"].get(resp.status, 0) + 1)
                    except Exception:
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", app.port, timeout=120)
                        my["transport_errors"] += 1
                    stop.wait(0.2)
                conn.close()

            ts = []
            for _ in range(threads):
                my = {"lat": [], "statuses": {}, "pairs": [],
                      "transport_errors": 0}
                outs.append(my)
                ts.append(threading.Thread(target=reader, args=(my,),
                                           daemon=True))
            if with_writes:
                my = {"lat": [], "statuses": {}, "pairs": [],
                      "transport_errors": 0}
                outs.append(my)
                ts.append(threading.Thread(target=writer, args=(my,),
                                           daemon=True))
            for t in ts:
                t.start()
            time.sleep(duration)
            stop.set()
            for t in ts:
                t.join(timeout=180)
            statuses: dict[int, int] = {}
            lat: list[float] = []
            etags: dict[str, set] = {}
            transport_errors = 0
            for my in outs:
                for s, n in my["statuses"].items():
                    statuses[s] = statuses.get(s, 0) + n
                lat.extend(my["lat"])
                transport_errors += my["transport_errors"]
                for etag, digest in my["pairs"]:
                    etags.setdefault(etag, set()).add(digest)
            completed = sum(n for s, n in statuses.items() if s < 500)
            bad = {s: n for s, n in statuses.items() if s >= 500}
            if bad or transport_errors:
                raise RuntimeError(
                    f"api throughput bench ({label}): {bad or ''} 5xx "
                    f"responses / {transport_errors} transport errors "
                    "(want zero)")
            torn = {e: d for e, d in etags.items() if len(d) > 1}
            if torn:
                raise RuntimeError(
                    f"api throughput bench ({label}): one ETag named "
                    f"multiple bodies (torn read): {sorted(torn)[:3]}")
            log(f"api bench phase {label}: {completed} requests in "
                f"{duration:.1f}s ({completed / duration:.0f} req/s), "
                f"statuses {statuses}")
            return completed, statuses, lat, etags

        # --- phase U: the per-request-render baseline (cache off).
        facade.rendercache.enabled = False
        drive("warm-baseline", min(duration_s / 4, 0.5))   # JIT the path
        u_done, _, u_lat, _ = drive("uncached", duration_s)

        # --- phase C: cached reads; device-dispatch accounting around it.
        facade.rendercache.enabled = True
        facade.rendercache.enable(ttl_ms=250)
        drive("warm-cached", min(duration_s / 4, 0.5))
        collector = facade.device_stats
        before = collector.snapshot()
        c_done, _, c_lat, _ = drive("cached", duration_s)
        after = collector.snapshot()
        dispatches = {k: after[k] - before[k]
                      for k in ("compileEvents", "aotCompileEvents",
                                "recompileEvents", "h2dBytes", "d2hBytes")}
        if any(dispatches.values()):
            raise RuntimeError(
                "cached GET phase touched the device: "
                f"{dispatches} (want all zero — reads must be served "
                "from published bytes)")

        # --- conditional requests: a revalidation answers 304, no body.
        conn = http.client.HTTPConnection("127.0.0.1", app.port,
                                          timeout=60)
        conn.request("GET", "/kafkacruisecontrol/proposals")
        resp = conn.getresponse()
        resp.read()
        etag = resp.getheader("ETag")
        if resp.status != 200 or not etag:
            raise RuntimeError(
                f"cached GET /proposals: {resp.status}, ETag {etag!r} "
                "(want 200 with a strong validator)")
        conn.request("GET", "/kafkacruisecontrol/proposals",
                     headers={"If-None-Match": etag})
        resp = conn.getresponse()
        body = resp.read()
        conn.close()
        if resp.status != 304 or body:
            raise RuntimeError(
                f"If-None-Match revalidation: {resp.status} with "
                f"{len(body)} body bytes (want 304, zero bytes)")

        # --- phase M: cached reads under generation bumps + dryrun
        # rebalances (coherence gates live inside drive()).
        drive("mixed", duration_s, with_writes=True)

        u_rps = u_done / duration_s
        c_rps = c_done / duration_s
        speedup = c_rps / u_rps if u_rps else None

        def p99_ms(lat):
            if not lat:
                return None
            return sorted(lat)[min(int(0.99 * len(lat)),
                                   len(lat) - 1)] * 1000.0

        u_p99, c_p99 = p99_ms(u_lat), p99_ms(c_lat)
        log(f"api read tier ({num_brokers}x{num_partitions}, {threads} "
            f"threads): {c_rps:.0f} req/s cached vs {u_rps:.0f} req/s "
            f"per-request render ({speedup:.1f}x); p99 {c_p99:.2f} ms "
            f"vs {u_p99:.2f} ms; 0 device dispatches on cached reads")
        if gate and (speedup is None or speedup < 5.0):
            raise RuntimeError(
                f"api throughput gate: cached serving is only "
                f"{speedup:.1f}x the per-request-render baseline "
                "(want >= 5x)")
        if emit_row:
            emit("api_requests_per_s", round(c_rps, 1), "req/s",
                 round(speedup, 1) if speedup else None)
            emit("api_read_p99_ms", round(c_p99, 3), "ms",
                 round(u_p99 / c_p99, 1) if c_p99 else None)
        return {"uncached_rps": u_rps, "cached_rps": c_rps,
                "speedup": speedup, "uncached_p99_ms": u_p99,
                "cached_p99_ms": c_p99, "dispatches": dispatches,
                "rendercache": facade.rendercache.to_json()}
    finally:
        app.stop()


def _fanout_topology(num_brokers: int, num_partitions: int):
    """Deterministic topology shared by the scenario-10 leader and every
    replica process: a replica restores the leader's snapshot into an
    identically-shaped stack (same broker/partition layout, same monitor
    window geometry), so snapshot + delta frames apply cleanly."""
    from cruise_control_tpu.executor import SimulatedKafkaCluster
    from cruise_control_tpu.monitor import LoadMonitor, MonitorConfig

    num_topics = max(num_partitions // 100, 1)
    sim = SimulatedKafkaCluster()
    for b in range(num_brokers):
        sim.add_broker(b)
    for p in range(num_partitions):
        pool = max(num_brokers // 5, 2) if p % 2 == 0 else num_brokers
        sim.add_partition(f"t{p % num_topics}", p,
                          [p % pool, (p + 1) % pool],
                          size_mb=50.0 + (p % 100))
    monitor = LoadMonitor(sim, MonitorConfig(
        num_windows=4, window_ms=1000, min_samples_per_window=1))
    return sim, monitor


def _fanout_replica_main(node_id, leader_port, snap_path, num_brokers,
                         num_partitions, max_staleness_ms, ready_q,
                         stop_ev):
    """Scenario-10 replica process: bootstrap from the leader's snapshot,
    follow the delta stream over HTTP (``session.tick(now, "standby")``
    on a driver thread — this process has no elector, so ``ha_tick``
    would wrongly treat it as a leader), and serve the render-cache GET
    surface on its own port. Reports (port, state) once STREAMING."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        from cruise_control_tpu.api.facade import KafkaCruiseControl
        from cruise_control_tpu.api.server import CruiseControlApp
        from cruise_control_tpu.core.replication import HttpReplicationClient
        from cruise_control_tpu.core.snapshot import SnapshotManager

        sim, monitor = _fanout_topology(num_brokers, num_partitions)
        facade = KafkaCruiseControl(sim, monitor)
        facade.attach_snapshotter(SnapshotManager(snap_path))
        session = facade.attach_replication_channel(
            HttpReplicationClient("127.0.0.1", leader_port, timeout_s=10),
            node_id=node_id, max_staleness_ms=max_staleness_ms)
        app = CruiseControlApp(facade, port=0, max_active_tasks=1024)
        app.start()
        facade.rendercache.enable(ttl_ms=250)
        stop = threading.Event()

        def follow():
            while not stop.is_set():
                try:
                    session.tick(int(time.time() * 1000), "standby")
                except Exception:
                    pass
                stop.wait(0.05)

        t = threading.Thread(target=follow, daemon=True)
        t.start()
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline and session.state != "STREAMING":
            time.sleep(0.05)
        ready_q.put(("ready", node_id, app.port, session.state))
        stop_ev.wait(600)
        stop.set()
        t.join(timeout=5)
        app.stop()
    except Exception:
        import traceback
        ready_q.put(("error", node_id, traceback.format_exc(), None))


def _fanout_client_main(port, threads, warmup_s, duration_s, out_q):
    """Scenario-10 load generator: one PROCESS per target node (client
    work in the serving process would contend on its GIL and flatten the
    fan-out signal), ``threads`` keep-alive readers inside. Counts only
    the post-warmup window; any 5xx — including a bounded-staleness 503,
    which a healthy streaming replica must never answer — fails the run
    in the parent."""
    import http.client

    mix = ["/kafkacruisecontrol/proposals", "/kafkacruisecontrol/state",
           "/kafkacruisecontrol/load"]
    outs = []

    def reader(my):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        t_count = time.monotonic() + warmup_s
        t_end = t_count + duration_s
        i = 0
        while time.monotonic() < t_end:
            path = mix[i % len(mix)]
            i += 1
            counting = time.monotonic() >= t_count
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                resp.read()
            except Exception:
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                if counting:
                    my["transport_errors"] += 1
                continue
            if counting:
                my["statuses"][resp.status] = (
                    my["statuses"].get(resp.status, 0) + 1)
        conn.close()

    ts = []
    for _ in range(threads):
        my = {"statuses": {}, "transport_errors": 0}
        outs.append(my)
        ts.append(threading.Thread(target=reader, args=(my,), daemon=True))
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=warmup_s + duration_s + 120)
    statuses: dict = {}
    transport_errors = 0
    for my in outs:
        for s, n in my["statuses"].items():
            statuses[s] = statuses.get(s, 0) + n
        transport_errors += my["transport_errors"]
    out_q.put({"port": port, "statuses": statuses,
               "transport_errors": transport_errors})


def run_replica_fanout_bench(num_brokers: int = 50,
                             num_partitions: int = 5_000, *,
                             replicas: int = 2, threads: int = 6,
                             duration_s: float = 4.0,
                             max_staleness_ms: int = 10_000,
                             goal_names: list | None = None,
                             emit_row: bool = True, gate: bool = True
                             ) -> dict:
    """Replicated serving plane (scenario 10): one leader process
    (this one) streaming snapshot deltas to ``replicas`` standby
    PROCESSES that serve the render-cache GET surface, vs the same
    aggregate client load pointed at the leader alone.

    Phases (client load always runs from ``1 + replicas`` separate
    processes so client-side GIL contention is identical in both):

    - **leader-only baseline** — every client process hammers the
      leader's port while the stream keeps flowing in the background.
    - **fan-out** — one client process per node (leader + replicas).

    Reported: ``replica_fanout_api_requests_per_s`` — aggregate fan-out
    req/s; vs_baseline = fan-out / leader-only. **Gated >= 1.8x at
    2 replicas, bench scale** (toy smokes pass gate=False). The gate
    additionally needs real parallel serving capacity — at least
    ``2 * (1 + replicas)`` host cores (one per serving node, one per
    client process); on a smaller host every process timeshares the
    same cores, fan-out measures scheduler overhead instead of scaling,
    and the gate is WAIVED with a loud log (the row still emits).

    Always asserted, every scale: zero 5xx and zero transport errors in
    every counted window — a bounded-staleness 503 is a 5xx, so this
    doubles as the staleness gate under load — plus, read off each
    replica's ``/devicestats`` AFTER the fan-out phase: state STREAMING,
    ``framesApplied > 0`` (the stream genuinely fed it), and
    ``streamLagMs <= maxStalenessMs``."""
    import http.client
    import multiprocessing
    import os
    import tempfile

    from cruise_control_tpu.api.facade import KafkaCruiseControl
    from cruise_control_tpu.api.server import CruiseControlApp
    from cruise_control_tpu.analyzer import (SearchConfig, TpuGoalOptimizer,
                                             goals_by_name)
    from cruise_control_tpu.core.metricdef import partition_metric_def
    from cruise_control_tpu.core.replication import ReplicationChannel
    from cruise_control_tpu.core.snapshot import SnapshotManager

    cores = os.cpu_count() or 1
    need = 2 * (1 + replicas)
    if gate and cores < need:
        log(f"replica fanout gate WAIVED: host has {cores} CPU cores < "
            f"{need} (one per serving node + one per client process). "
            "Every process timeshares the same cores, so fan-out would "
            "measure scheduler overhead, not serving capacity — the "
            ">= 1.8x gate is judged on the bench host.")
        gate = False

    window_ms = 1000
    sim, monitor = _fanout_topology(num_brokers, num_partitions)
    mdef = partition_metric_def()
    keys = sorted(sim.describe_partitions())
    P = len(keys)
    vals = ((np.arange(P * mdef.size(), dtype=np.float64)
             .reshape(P, mdef.size()) % 97) + 1.0)

    def ingest(t_ms):
        times = np.full(P, int(t_ms), np.int64)
        monitor.partition_aggregator.add_samples_dense(keys, times, vals)

    now = int(time.time() * 1000)
    for w in range(5, 0, -1):           # fill the window history to now
        ingest(now - w * window_ms + 100)
    opt = TpuGoalOptimizer(
        goals=goals_by_name(goal_names or GOALS[:2]),
        config=SearchConfig(num_replica_candidates=512,
                            num_dest_candidates=16, apply_per_iter=512,
                            max_iters_per_goal=256))
    facade = KafkaCruiseControl(sim, monitor, optimizer=opt)
    tmp = tempfile.mkdtemp(prefix="fanout_bench_")
    snap_path = os.path.join(tmp, "serving.snap")
    facade.attach_snapshotter(SnapshotManager(snap_path, interval_ms=500))
    facade.attach_replication_channel(
        ReplicationChannel(capacity=512), node_id="leader",
        max_staleness_ms=max_staleness_ms)
    app = CruiseControlApp(facade, port=0, max_active_tasks=1024)
    app.start()
    ctx = multiprocessing.get_context("spawn")
    stop_ev = ctx.Event()
    stop_driver = threading.Event()
    procs = []
    try:
        facade.proposals()              # published entry rides the snapshot
        facade.rendercache.enable(ttl_ms=250)
        facade.ha_tick(int(time.time() * 1000))   # first snapshot + frame

        def driver():
            # The write plane under the read tier: fresh sample windows
            # land, ha_tick publishes delta frames and the cadenced
            # snapshot — replicas must stay within the staleness bound
            # WHILE the stream moves, not on a frozen leader.
            while not stop_driver.is_set():
                ingest(int(time.time() * 1000))
                facade.ha_tick(int(time.time() * 1000))
                stop_driver.wait(0.25)

        drv = threading.Thread(target=driver, daemon=True)
        drv.start()

        ready_q = ctx.Queue()
        for i in range(replicas):
            p = ctx.Process(target=_fanout_replica_main,
                            args=(f"replica-{i}", app.port, snap_path,
                                  num_brokers, num_partitions,
                                  max_staleness_ms, ready_q, stop_ev),
                            daemon=True)
            p.start()
            procs.append(p)
        replica_ports = []
        for _ in range(replicas):
            kind, node, port, state = ready_q.get(timeout=180)
            if kind != "ready":
                raise RuntimeError(f"replica {node} died during "
                                   f"bootstrap:\n{port}")
            if state != "STREAMING":
                raise RuntimeError(f"replica {node} never reached "
                                   f"STREAMING (stuck in {state})")
            replica_ports.append(port)
        log(f"fanout bench: {replicas} replicas streaming on ports "
            f"{replica_ports} (leader {app.port})")

        def drive(label, targets):
            """One client process per target; returns aggregate req/s
            over the counted windows. Gates zero 5xx / transport errors."""
            out_q = ctx.Queue()
            cs = [ctx.Process(target=_fanout_client_main,
                              args=(port, threads, 0.5, duration_s, out_q),
                              daemon=True)
                  for port in targets]
            for c in cs:
                c.start()
            results = [out_q.get(timeout=duration_s + 300)
                       for _ in cs]
            for c in cs:
                c.join(timeout=60)
            statuses: dict = {}
            transport_errors = 0
            for r in results:
                for s, n in r["statuses"].items():
                    statuses[s] = statuses.get(s, 0) + n
                transport_errors += r["transport_errors"]
            bad = {s: n for s, n in statuses.items() if s >= 500}
            if bad or transport_errors:
                raise RuntimeError(
                    f"replica fanout bench ({label}): {bad or ''} 5xx "
                    f"responses / {transport_errors} transport errors "
                    "(want zero — a bounded-staleness 503 under load "
                    "is a contract breach on a streaming replica)")
            completed = sum(statuses.values())
            rps = completed / duration_s
            log(f"fanout bench phase {label}: {completed} requests "
                f"({rps:.0f} req/s aggregate), statuses {statuses}")
            return rps, statuses

        # --- phase L: every client process on the leader alone.
        leader_targets = [app.port] * (1 + replicas)
        base_rps, _ = drive("leader-only", leader_targets)
        # --- phase F: one client process per serving node.
        fanout_rps, statuses = drive("fan-out", [app.port] + replica_ports)

        # The staleness readout, AFTER the measured window: each replica
        # must still be streaming, genuinely delta-fed, within bound.
        replication = []
        for port in replica_ports:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            conn.request("GET", "/kafkacruisecontrol/devicestats")
            resp = conn.getresponse()
            body = json.loads(resp.read())
            conn.close()
            if resp.status != 200:
                raise RuntimeError(
                    f"replica :{port} /devicestats: {resp.status}")
            rep = body["replication"]
            replication.append(rep)
            if rep["state"] != "STREAMING":
                raise RuntimeError(
                    f"replica :{port} left the stream during the bench: "
                    f"{rep['state']}")
            if not rep["framesApplied"]:
                raise RuntimeError(
                    f"replica :{port} applied zero delta frames — it "
                    "served from the bootstrap snapshot alone")
            if rep["streamLagMs"] is None \
                    or rep["streamLagMs"] > rep["maxStalenessMs"]:
                raise RuntimeError(
                    f"replica :{port} beyond the staleness bound after "
                    f"the measured window: lag {rep['streamLagMs']} ms "
                    f"> {rep['maxStalenessMs']} ms")

        speedup = fanout_rps / base_rps if base_rps else None
        lag_ms = max(r["streamLagMs"] for r in replication)
        log(f"replica fanout ({num_brokers}x{num_partitions}, "
            f"{replicas} replicas, {threads} threads/client): "
            f"{fanout_rps:.0f} req/s aggregate vs {base_rps:.0f} req/s "
            f"leader-only ({speedup:.2f}x); max stream lag {lag_ms} ms "
            f"(bound {max_staleness_ms} ms)")
        if gate and (speedup is None or speedup < 1.8):
            raise RuntimeError(
                f"replica fanout gate: {replicas} replicas scaled the "
                f"aggregate read tier only {speedup:.2f}x over the "
                "leader alone (want >= 1.8x at 2 replicas)")
        if emit_row:
            emit("replica_fanout_api_requests_per_s", round(fanout_rps, 1),
                 "req/s", round(speedup, 2) if speedup else None)
            emit("replica_fanout_stream_lag_ms", lag_ms, "ms", None)
        return {"leader_only_rps": base_rps, "fanout_rps": fanout_rps,
                "speedup": speedup, "replicas": replicas,
                "statuses": statuses, "max_stream_lag_ms": lag_ms,
                "replication": replication}
    finally:
        stop_driver.set()
        stop_ev.set()
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
        app.stop()


def build_spec(num_brokers: int = NUM_BROKERS,
               num_partitions: int = NUM_PARTITIONS):
    from cruise_control_tpu.model.spec import (BrokerSpec, ClusterSpec,
                                               PartitionSpec)
    rng = np.random.default_rng(42)
    brokers = [BrokerSpec(broker_id=i, rack=f"r{i % 10}",
                          capacity=(100.0, 1e6, 1e6, 1e8))
               for i in range(num_brokers)]
    # Skewed placement: half the partitions crowd onto 20% of brokers.
    hot = np.arange(num_brokers // 5)
    parts = []
    for p in range(num_partitions):
        if p % 2 == 0:
            pool = hot
        else:
            pool = np.arange(num_brokers)
        reps = rng.choice(pool, size=RF, replace=False).tolist()
        load = (0.02 + 0.02 * rng.random(), 5 + 10 * rng.random(),
                8 + 15 * rng.random(), 50 + 100 * rng.random())
        parts.append(PartitionSpec(topic=f"t{p % 200}", partition=p,
                                   replicas=[int(b) for b in reps],
                                   leader_load=load))
    return ClusterSpec(brokers=brokers, partitions=parts)


def greedy_baseline(model, threshold=1.10, max_moves=60_000):
    """Sequential greedy on host arrays: same bounds semantics as the goal
    kernels (avg*(2-t)..avg*t per metric), one best move at a time."""
    from cruise_control_tpu.model.flat import replica_loads
    rb = np.asarray(model.replica_broker).copy()
    loads = np.asarray(replica_loads(model))          # [P, R, 4]
    B = model.num_brokers_padded
    valid = rb < B
    util = np.zeros((B, 4))
    np.add.at(util, rb[valid], loads[valid])
    counts = np.bincount(rb[valid], minlength=B + 1)[:B].astype(float)
    nb = NUM_BROKERS
    moves = 0
    t0 = time.monotonic()
    # Metric sequence: replica counts, then disk/nw_in/nw_out utilization.
    for metric in ("count", 3, 1, 2):
        for _ in range(max_moves):
            vals = counts[:nb] if metric == "count" else util[:nb, metric]
            avg = vals.mean()
            upper, lower = avg * threshold, avg * (2 - threshold)
            if metric == "count":
                upper = max(upper, np.ceil(avg))
                lower = min(lower, np.floor(avg))
            over = vals - upper
            src = int(np.argmax(over))
            if over[src] <= 0:
                break
            # largest movable replica on src by this metric
            on_src = (rb == src) & valid
            w = (np.ones_like(loads[..., 0]) if metric == "count"
                 else loads[..., metric])
            w = np.where(on_src, w, -np.inf)
            flat = int(np.argmax(w))
            p, r = flat // rb.shape[1], flat % rb.shape[1]
            if not np.isfinite(w[p, r]):
                break
            # best destination: lowest metric value not hosting p
            hosting = np.zeros(nb, bool)
            hosting[rb[p][valid[p]]] = True
            dv = np.where(hosting[:nb], np.inf, vals)
            dst = int(np.argmin(dv))
            if not np.isfinite(dv[dst]):
                break
            delta = loads[p, r]
            util[src] -= delta
            util[dst] += delta
            counts[src] -= 1
            counts[dst] += 1
            rb[p, r] = dst
            moves += 1
    dur = time.monotonic() - t0
    return dur, moves, util, counts


def residual(util, counts, nb, threshold=1.10):
    tot = 0.0
    for metric in ("count", 3, 1, 2):
        vals = counts[:nb] if metric == "count" else util[:nb, metric]
        avg = vals.mean()
        upper, lower = avg * threshold, avg * (2 - threshold)
        if metric == "count":
            upper = max(upper, np.ceil(avg))
            lower = min(lower, np.floor(avg))
        tot += np.maximum(vals - upper, 0).sum() + np.maximum(lower - vals, 0).sum()
    return float(tot)


def build_flat_direct(num_brokers: int, num_partitions: int, rf: int,
                      seed: int = 42, place_on: int | None = None,
                      mesh=None, return_arrays: bool = False):
    """Array-native model construction for the scale scenarios — no
    per-partition Python objects (1M PartitionSpecs would dominate the
    run). Skewed like build_spec: half the partitions crowd 20% of brokers.
    ``place_on`` restricts the initial placement to the first N brokers
    (the add-brokers variant: the rest exist empty and NEW). ``mesh``
    uploads the model as partition-axis shards (from_numpy(mesh=...) —
    the sharded full-rebuild path the 10Kx1M tier measures);
    ``return_arrays`` additionally hands back the host arrays so callers
    can re-measure the upload in isolation."""
    from cruise_control_tpu.model.flat import FlatClusterModel
    from cruise_control_tpu.model.spec import ClusterMetadata, _round_up
    rng = np.random.default_rng(seed)
    P, B = num_partitions, num_brokers
    placeB = min(place_on or B, B)
    Ppad, Bpad = _round_up(P, 128), _round_up(B, 8)
    hot = placeB // 5
    base = rng.integers(0, hot, size=P)
    cold = rng.integers(0, placeB, size=P)
    first = np.where(np.arange(P) % 2 == 0, base, cold).astype(np.int64)
    # Offsets bounded so cumulative sums stay < placeB: every partial sum
    # is distinct and nonzero mod placeB, i.e. no duplicate brokers at
    # any rf.
    step_cap = max((placeB - 1) // max(rf - 1, 1), 2)
    offsets = rng.integers(1, step_cap, size=(P, rf - 1)).cumsum(axis=1)
    rb = np.full((Ppad, rf), Bpad, np.int32)
    rb[:P, 0] = first
    rb[:P, 1:] = (first[:, None] + offsets) % placeB
    lead = np.zeros((Ppad, 4), np.float32)
    lead[:P] = np.column_stack([
        0.02 + 0.02 * rng.random(P), 5 + 10 * rng.random(P),
        8 + 15 * rng.random(P), 50 + 100 * rng.random(P)]).astype(np.float32)
    foll = lead.copy()
    foll[:, 0] *= 0.5
    foll[:, 2] = 0.0
    num_topics = max(P // 500, 1)
    ptopic = np.full(Ppad, -1, np.int32)
    ptopic[:P] = np.arange(P) % num_topics
    arrays = dict(
        replica_broker=rb,
        leader_load=lead, follower_load=foll,
        partition_topic=ptopic,
        partition_valid=np.arange(Ppad) < P,
        replica_offline=np.zeros((Ppad, rf), bool),
        replica_pref_pos=np.tile(np.arange(rf, dtype=np.int32), (Ppad, 1)),
        broker_capacity=np.tile(
            np.array([100.0, 1e6, 1e6, 1e8], np.float32), (Bpad, 1)),
        broker_rack=(np.arange(Bpad) % max(B // 10, 1)).astype(np.int32),
        broker_host=np.arange(Bpad, dtype=np.int32),
        broker_set=np.full((Bpad,), -1, np.int32),
        broker_alive=np.arange(Bpad) < B,
        broker_new=np.zeros((Bpad,), bool),
        broker_demoted=np.zeros((Bpad,), bool),
        broker_broken_disk=np.zeros((Bpad,), bool),
        broker_valid=np.arange(Bpad) < B)
    model = FlatClusterModel.from_numpy(mesh=mesh, **arrays)
    topics = [f"t{i}" for i in range(num_topics)]
    keys = [(topics[i % num_topics], i) for i in range(P)]
    metadata = ClusterMetadata(
        broker_ids=list(range(B)),
        broker_index={i: i for i in range(B)},
        topics=topics, topic_index={t: i for i, t in enumerate(topics)},
        partition_keys=keys,
        partition_index={k: i for i, k in enumerate(keys)},
        racks=[f"r{i}" for i in range(max(B // 10, 1))],
        hosts=[f"h{i}" for i in range(B)], broker_sets=[])
    if return_arrays:
        return model, metadata, arrays
    return model, metadata


def _make_mesh(n: int):
    """Build an n-device mesh for the optimizer (0/absent -> no mesh,
    -1 -> all visible devices, matching search.mesh.devices). On the
    single real TPU chip this is a 1-device mesh (a no-op layout);
    correctness of the >1-device path is covered on the virtual 8-CPU
    mesh (tests/test_parallel.py + dryrun_multichip)."""
    if not n:
        return None
    import jax
    from cruise_control_tpu.parallel import make_mesh, resolve_mesh_devices
    mesh = make_mesh(resolve_mesh_devices(n))
    log(f"  mesh: {dict(mesh.shape)} over {mesh.devices.size} "
        f"{jax.devices()[0].platform} device(s)")
    return mesh


#: padding-waste gate at the scale tiers (%): multiple-of-128 partitions
#: + multiple-of-8 brokers sit well under this at 10Kx1M (~0.006% /
#: 0%); the gate exists so a pad-bucketing regression (e.g. a
#: power-of-two floor, near-2x HBM at 1M partitions) fails the tier
#: loudly instead of silently doubling device memory.
SCALE_PADDING_BUDGET_PCT = 10.0


def run_scale_scenario(n: int, mesh_devices: int = 0,
                       variant: str = "rebalance", *,
                       brokers: int | None = None,
                       partitions: int | None = None) -> dict:
    """Scenario #3/#4 — the GATED scale tier: wall-clock of a full
    proposal computation at scale, the dense-ingest throughput feeding
    it, and the device-runtime rows (warm-cycle h2d/d2h bytes, sharded
    full-rebuild upload bytes, padding waste, peak device memory) with
    the padding/HBM budgets asserted. Always emitted: every scenario-3/4
    run carries the full row set (tpu_watch.sh records them into
    TPU_RESULTS.md / MULTICHIP artifacts).

    ``variant`` (BASELINE.md row 4 names the add/remove-broker scenarios):

    - ``rebalance`` — skewed placement, steady-state rebalance;
    - ``add_brokers`` — placement crowds the first 95% of brokers, the
      last 5% join empty and NEW (ref AddBrokerRunnable: proposals flow
      onto the new capacity);
    - ``remove_brokers`` — 1% of brokers marked dead: every replica they
      host is a must-move (ref RemoveBrokerRunnable / broker-failure
      self-healing drain);
    - ``fullchain`` — the ENTIRE default goal chain (goals=None — all 16
      registered goals incl. every hard goal, the reference's actual
      per-proposal contract, GoalOptimizer.java:458-497 +
      config/cruisecontrol.properties:96) with nothing waived: the
      north-star scale at the reference's full problem statement.

    ``brokers``/``partitions`` override the scenario's scale (the
    tier-gate smoke test runs the identical code path at a CI-sized
    cluster; the emitted metric names keep the scenario's canonical
    scale label so dashboards never mix scales).
    """
    from cruise_control_tpu.analyzer import (OptimizationOptions,
                                             SearchConfig, TpuGoalOptimizer,
                                             goals_by_name)
    from cruise_control_tpu.core.aggregator import MetricSampleAggregator
    from cruise_control_tpu.core.metricdef import partition_metric_def
    from cruise_control_tpu.core.runtime_obs import default_collector
    cfgd = dict(SCALE_SCENARIOS[n])
    if brokers is not None:
        cfgd["brokers"] = brokers
    if partitions is not None:
        cfgd["partitions"] = partitions
    mesh = _make_mesh(mesh_devices)
    collector = default_collector()
    t0 = time.monotonic()
    B = cfgd["brokers"]
    n_new = max(B // 20, 1) if variant == "add_brokers" else 0
    model, md, host_arrays = build_flat_direct(
        B, cfgd["partitions"], cfgd["rf"], place_on=(B - n_new) or None,
        mesh=mesh, return_arrays=True)
    if variant == "add_brokers":
        import jax.numpy as jnp
        new_mask = np.zeros(model.num_brokers_padded, bool)
        new_mask[B - n_new:B] = True
        model = model.replace(broker_new=jnp.asarray(new_mask))
    elif variant == "remove_brokers":
        import jax.numpy as jnp
        alive = np.asarray(model.broker_alive).copy()
        dead = np.random.default_rng(7).choice(B, size=max(B // 100, 1),
                                               replace=False)
        alive[dead] = False
        model = model.replace(broker_alive=jnp.asarray(alive))
    log(f"scenario {n} [{variant}]: build {time.monotonic() - t0:.1f}s "
        f"({B} brokers, {cfgd['partitions']} partitions"
        + (f", +{n_new} new" if variant == "add_brokers" else "")
        + (", 1% dead" if variant == "remove_brokers" else "") + ")")

    # Ingest throughput: one full round of per-partition samples through the
    # dense aggregator path (the monitor-side cost of a sampling interval).
    mdef = partition_metric_def()
    agg = MetricSampleAggregator(4, 60_000, 1, mdef)
    P = cfgd["partitions"]
    entities = md.partition_keys
    values = np.abs(np.random.default_rng(0).normal(
        10.0, 3.0, size=(P, mdef.size())))
    t0 = time.monotonic()
    agg.add_samples_dense(entities, np.full(P, 30_000, np.int64), values)
    ingest_s = time.monotonic() - t0
    log(f"  ingest: {P} samples x {mdef.size()} metrics in {ingest_s:.2f}s "
        f"({P / max(ingest_s, 1e-9) / 1e6:.2f}M samples/s)")

    goal_names = None if variant == "fullchain" else cfgd["goals"]
    goals = goals_by_name(goal_names) if goal_names else None
    # Hard-goal gating: scenario rows run with the audit ON — every
    # registered hard goal not in the chain is checked post-optimization
    # and a violation fails the bench loudly. Per-scenario waivers
    # (cfgd["waive"]) exempt goals the chain deliberately cannot
    # preserve; the fullchain variant waives nothing.
    waive = frozenset() if variant == "fullchain" \
        else frozenset(cfgd.get("waive", ()))
    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    k = cfgd.get("k_tpu", cfgd["k"]) if on_tpu else cfgd["k"]
    # Drain batch sized so a few rounds cover the whole expected move
    # count (~half the replicas in the skewed build).
    drain = max(cfgd["partitions"] // 8, 16384)
    cfg_kw = dict(num_replica_candidates=k, num_dest_candidates=16,
                  apply_per_iter=k, drain_batch=drain, drain_rounds=8,
                  max_iters_per_goal=512)
    if "swaps" in cfgd:
        # Scenario-specific override; absent = SearchConfig's default.
        cfg_kw["num_swap_candidates"] = cfgd["swaps"]
    if variant == "fullchain" and "fullchain_swaps" in cfgd:
        cfg_kw["num_swap_candidates"] = cfgd["fullchain_swaps"]
    opt = TpuGoalOptimizer(goals=goals, config=SearchConfig(**cfg_kw),
                           mesh=mesh)
    t0 = time.monotonic()
    res_cold = opt.optimize(model, md, OptimizationOptions(
        seed=0, waived_hard_goals=waive))
    cold = time.monotonic() - t0
    t0 = time.monotonic()
    res = opt.optimize(model, md, OptimizationOptions(
        seed=1, waived_hard_goals=waive))
    warm = time.monotonic() - t0
    # The optimizer brackets itself in a collector cycle, so lastCycle
    # is the warm run's h2d/d2h/compile delta (no extra syncs).
    warm_cycle = dict(collector.last_cycle or {})
    log(f"  search: cold {cold:.1f}s warm {warm:.1f}s "
        f"moves={res.num_moves} proposals={len(res.proposals)}")
    for g in res.goal_results:
        log(f"    {g.name:42s} {g.violation_before:14.1f} -> "
            f"{g.violation_after:12.1f} iters={g.iterations} "
            f"({g.duration_s:.2f}s)")
    for g in res.hard_goal_audit:
        log(f"    [audit] {g.name:34s} {g.violation_before:14.1f} -> "
            f"{g.violation_after:12.1f} "
            f"{'ok' if g.satisfied else 'VIOLATED'}")
    if waive:
        log(f"  waived hard-goal audits: {sorted(waive)}")

    # Padding + memory are read BEFORE the isolated re-upload below: the
    # gate must measure the SERVING footprint, not the bench artifact's
    # temporary second model copy.
    padding = collector.padding_from_model(model)
    memory = collector.memory_snapshot()
    # Per-device peak: the HBM budget is one device's capacity (the
    # allocator peak is already per-device; the live fallback's
    # peakDeviceLiveBytes is the worst single device).
    peak_bytes = (memory.get("allocatorPeakBytes")
                  or memory.get("peakDeviceLiveBytes") or 0)

    # Budget gates (the tier is GATED, not just reported): padding waste
    # against the tier budget always (worst of the partition/broker
    # axes, same rule as DeviceStatsCollector.budget_status); peak
    # memory when a budget is configured (CC_BENCH_HBM_BUDGET_BYTES —
    # on-chip captures set it to the HBM size, CPU hosts have no
    # meaningful ceiling). Computed locally — the serving collector's
    # configured budgets stay untouched.
    import os
    hbm_budget = int(os.environ.get("CC_BENCH_HBM_BUDGET_BYTES", "0"))
    worst_waste = max(padding["partitionWastePct"],
                      padding["brokerWastePct"])
    status = {"paddingWastePct": worst_waste,
              "paddingWasteBudgetPct": SCALE_PADDING_BUDGET_PCT,
              "peakBytes": peak_bytes,
              "hbmBudgetBytes": hbm_budget or None,
              "paddingOverBudget": worst_waste > SCALE_PADDING_BUDGET_PCT,
              "hbmOverBudget": bool(hbm_budget
                                    and peak_bytes > hbm_budget)}

    # Full-rebuild upload, measured in isolation (after the memory
    # READING above — this temporarily doubles model residency): the h2d
    # bytes and wall clock of shipping the whole model host->device
    # (per-device SHARDS under a mesh — the monolithic-upload bottleneck
    # this tier watches).
    snap = collector.snapshot()
    t0 = time.monotonic()
    from cruise_control_tpu.model.flat import FlatClusterModel
    import jax as _jax
    # Block on the WHOLE model pytree: transfers are async, and the big
    # float load planes would otherwise still be streaming when the
    # clock stops.
    _jax.block_until_ready(
        FlatClusterModel.from_numpy(mesh=mesh, **host_arrays))
    rebuild_upload_s = time.monotonic() - t0
    rebuild_h2d = collector.snapshot()["h2dBytes"] - snap["h2dBytes"]
    n_mesh = 0 if mesh is None else int(mesh.devices.size)
    log(f"  device: warm-cycle h2d {warm_cycle.get('h2dBytes')} d2h "
        f"{warm_cycle.get('d2hBytes')} bytes; full-rebuild upload "
        f"{rebuild_h2d} bytes in {rebuild_upload_s:.2f}s"
        + (f" ({n_mesh}-way sharded)" if mesh is not None
           else " (unsharded)")
        + f"; padding waste {padding['partitionWastePct']}% partitions / "
        f"{padding['brokerWastePct']}% brokers; peak mem {peak_bytes} "
        f"bytes ({memory['source']})")

    metric = cfgd["metric"] + ("" if variant == "rebalance"
                               else f"_{variant}")
    scale_tag = metric.rsplit("wall_clock_", 1)[-1]
    vs_target = round(cfgd["target_s"] / warm, 3) if warm > 0 else None
    # Every tier row carries mesh_devices so sharded (4::-1) and
    # unsharded captures of the same metric stay distinguishable in
    # TPU_RESULTS.md / dashboards.
    emit(metric, round(warm, 3), "s", vs_target, vs_target=vs_target,
         mesh_devices=n_mesh)
    emit(f"h2d_bytes_per_cycle_{scale_tag}",
         warm_cycle.get("h2dBytes"), "bytes", None, mesh_devices=n_mesh)
    emit(f"full_rebuild_h2d_bytes_{scale_tag}", rebuild_h2d, "bytes",
         None, mesh_devices=n_mesh)
    # The row records the GATED quantity (worst axis) so the captured
    # series can actually show a budget regression coming.
    emit(f"padding_waste_pct_{scale_tag}", worst_waste, "%", None,
         mesh_devices=n_mesh)
    emit(f"peak_hbm_bytes_{scale_tag}", peak_bytes, "bytes", None,
         mesh_devices=n_mesh)
    # Gates raise AFTER the rows are out: a breach run must still land
    # its data points in the capture (the regression the series exists
    # to show), and a failing exit code still fails the tier.
    if status["paddingOverBudget"]:
        raise RuntimeError(
            f"scale-tier padding gate: waste {worst_waste}% exceeds the "
            f"{SCALE_PADDING_BUDGET_PCT}% budget — check the "
            "model.*.pad.multiple knobs (docs/scaling.md)")
    if status["hbmOverBudget"]:
        raise RuntimeError(
            f"scale-tier memory gate: peak {peak_bytes} bytes "
            f"exceeds the {hbm_budget}-byte budget — shard the model "
            "(search.mesh.devices) or trim windows (docs/scaling.md "
            "degrade path)")
    return {"cold_s": cold, "warm_s": warm, "vs_target": vs_target,
            "warm_cycle": warm_cycle, "rebuild_h2d": rebuild_h2d,
            "rebuild_upload_s": rebuild_upload_s, "padding": padding,
            "peak_bytes": peak_bytes, "budget": status,
            "moves": res.num_moves, "mesh_devices": n_mesh}


def run_replan_scenario(num_requests: int = 30, mesh_devices: int = 0):
    """Scenario #5: self-healing replans at 1 req/s — each request marks a
    random broker dead and recomputes proposals (fast mode, the
    self-healing path); reports p99 latency against the 1 s sustainable-
    rate budget."""
    import jax
    from cruise_control_tpu.analyzer import (OptimizationOptions,
                                             SearchConfig, TpuGoalOptimizer,
                                             goals_by_name)
    model, md = build_flat_direct(NUM_BROKERS, NUM_PARTITIONS, RF)
    opt = TpuGoalOptimizer(
        goals=goals_by_name(GOALS),
        # fused_chain: the replan path is latency-bound (one model, small
        # passes, 1 req/s budget) — a single dispatch + sync per request
        # beats per-goal dispatches behind the tunnel's round-trip time.
        config=SearchConfig(num_replica_candidates=512,
                            num_dest_candidates=16, apply_per_iter=512,
                            max_iters_per_goal=256, fused_chain=True),
        mesh=_make_mesh(mesh_devices))
    # Warm the compiled chain once (a live server has it warm already).
    opt.optimize(model, md, OptimizationOptions(seed=0, fast_mode=True,
                                                skip_hard_goal_check=True))
    import jax.numpy as jnp
    alive0 = np.asarray(model.broker_alive)
    latencies = []
    for i in range(num_requests):
        dead = i % NUM_BROKERS
        alive = alive0.copy()
        alive[dead] = False
        failed = model.replace(broker_alive=jnp.asarray(alive))
        t0 = time.monotonic()
        res = opt.optimize(failed, md, OptimizationOptions(
            seed=i, fast_mode=True, skip_hard_goal_check=True))
        latencies.append(time.monotonic() - t0)
    lat = np.sort(np.asarray(latencies))
    p50, p99 = lat[len(lat) // 2], lat[min(int(len(lat) * 0.99),
                                           len(lat) - 1)]
    log(f"scenario 5: {num_requests} broker-failure replans "
        f"p50={p50:.2f}s p99={p99:.2f}s (last proposals={len(res.proposals)})")
    vs_target = round(1.0 / float(p99), 3) if p99 > 0 else None
    emit("broker_failure_replan_p99_100x20k", round(float(p99), 3),
         "s", vs_target, vs_target=vs_target)


def run_demo_scenario():
    """Scenario #1: the 3-broker demo with config/capacity.json through the
    stock served path — monitor samples in, default goal chain, proposals
    out. The parity baseline row of BASELINE.md."""
    from cruise_control_tpu.analyzer import OptimizationOptions
    from cruise_control_tpu.config.capacity import FileCapacityResolver
    from cruise_control_tpu.executor import SimulatedKafkaCluster
    from cruise_control_tpu.monitor import (LoadMonitor,
                                            LoadMonitorTaskRunner,
                                            MetricFetcherManager,
                                            MonitorConfig,
                                            SyntheticWorkloadSampler)
    from cruise_control_tpu.api import KafkaCruiseControl
    sim = SimulatedKafkaCluster()
    for b in range(3):
        sim.add_broker(b)
    # Skewed demo: everything leads on brokers 0/1.
    for p in range(64):
        sim.add_partition(f"demo-{p % 4}", p, [p % 2, 2],
                          size_mb=100.0 + p)
    monitor = LoadMonitor(sim, MonitorConfig(num_windows=4, window_ms=1000,
                                             min_samples_per_window=1),
                          capacity_resolver=FileCapacityResolver(
                              "config/capacity.json"))
    runner = LoadMonitorTaskRunner(
        monitor, MetricFetcherManager(SyntheticWorkloadSampler(sim)),
        sampling_interval_ms=1000)
    runner.start(-1, skip_loading=True)
    for w in range(4):
        runner.maybe_run_sampling((w + 1) * 1000 - 1)
    # fused_chain (the search.fused.chain server config): a 3-broker model
    # through a 15-goal chain is pure dispatch latency — one fused
    # dispatch per proposal run instead of one per goal.
    from cruise_control_tpu.analyzer import SearchConfig, TpuGoalOptimizer
    facade = KafkaCruiseControl(
        sim, monitor, task_runner=runner,
        optimizer=TpuGoalOptimizer(config=SearchConfig(fused_chain=True)),
        now_ms=lambda: 4000)
    t0 = time.monotonic()
    facade.rebalance(dryrun=True, options=OptimizationOptions(seed=0),
                     ignore_proposal_cache=True)
    cold = time.monotonic() - t0
    t0 = time.monotonic()
    res, _ = facade.rebalance(dryrun=True,
                              options=OptimizationOptions(seed=1),
                              ignore_proposal_cache=True)
    dur = time.monotonic() - t0
    log(f"scenario 1: 3-broker demo, cold {cold:.1f}s warm {dur:.2f}s, "
        f"{len(res.proposals)} proposals, "
        f"violated after: {res.violated_goals_after}")
    emit("rebalance_proposal_wall_clock_3broker_demo", round(dur, 3),
         "s", None)


#: set by main() once the backend probe resolves; read by the crash
#: handler below WITHOUT touching jax (a device query on a dead tunnel
#: hangs — the very failure the handler recovers from).
_RESOLVED_PLATFORM: str | None = None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scenario", type=int, default=2,
                    choices=(1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13,
                             14),
                    help="BASELINE.md scenario (1 = 3-broker demo, "
                         "2 = 100x20K vs greedy, "
                         "3 = 1Kx200K, 4 = 10Kx1M, 5 = replan p99, "
                         "6 = fleet batched propose, 16 clusters x "
                         "100x20K, 7 = tuned multi-objective population "
                         "search vs fixed-schedule sequential, 100x20K, "
                         "8 = forecast fit + [C, S] fleet trajectory "
                         "sweep, 4 clusters x 100x20K, 9 = heavy-traffic "
                         "API read tier, cached vs per-request render, "
                         "10 = replicated serving plane, 2 streaming "
                         "read replicas vs the leader alone, "
                         "11 = device-scheduled pipelined executor vs "
                         "greedy sequential per-batch execution, "
                         "12 = flight-recorder journal overhead on the "
                         "warm propose path, enabled vs disabled, "
                         "13 = fleet move-budget coordinator, budgeted "
                         "vs unbudgeted convergence, "
                         "14 = trace-driven workload plane, per-class "
                         "forecast MAPE gates + regime-aware online "
                         "tuning with zero warm recompiles)")
    ap.add_argument("--mesh", type=int, default=0,
                    help="shard the optimizer over an N-device mesh "
                         "(clamped to available devices; 0 = unsharded, "
                         "-1 = all visible devices)")
    ap.add_argument("--variant", default="rebalance",
                    choices=("rebalance", "add_brokers", "remove_brokers",
                             "fullchain"),
                    help="scale-scenario variant (scenarios 3/4; "
                         "BASELINE.md row 4 add/remove-broker scenarios; "
                         "fullchain = the entire default goal chain, "
                         "hard goals gating, nothing waived)")
    args = ap.parse_args()
    if args.variant != "rebalance" and args.scenario == 2:
        log(f"--variant {args.variant} is ignored for scenario 2")
    # Probe the default backend in a subprocess first: when the TPU tunnel is
    # down, jax.devices() would otherwise hang/crash the whole bench. Falls
    # back to CPU and still emits the JSON line (platform is logged).
    from cruise_control_tpu.utils.platform import ensure_live_backend
    platform = ensure_live_backend()
    global _RESOLVED_PLATFORM
    _RESOLVED_PLATFORM = platform
    if args.scenario in (6, 7, 8) and platform.startswith("cpu"):
        # Scenarios 6/8 shard the CLUSTER axis, scenario 7 the
        # POPULATION axis over devices; on a CPU host that concurrency
        # needs forced virtual devices, set BEFORE jax initializes
        # (real accelerators use their own).
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        count = 16 if args.scenario == 6 else 8
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={count}"
            ).strip()
    import jax
    if args.scenario != 2:
        log(f"platform: {platform} -> {jax.devices()[0].platform}")
        if args.variant != "rebalance" and args.scenario not in (3, 4):
            log(f"--variant {args.variant} is ignored for scenario "
                f"{args.scenario}: variants exist for the scale "
                "scenarios (3/4) only")
        if args.scenario == 1:
            if args.mesh:
                log("--mesh is ignored for scenario 1: the demo drives the "
                    "stock served path (facade-owned optimizer)")
            run_demo_scenario()
        elif args.scenario == 5:
            run_replan_scenario(mesh_devices=args.mesh)
        elif args.scenario == 6:
            if args.mesh:
                log("--mesh is ignored for scenario 6: the fleet "
                    "dispatch owns the device axis (cluster sharding)")
            run_fleet_propose_bench()
        elif args.scenario == 7:
            if args.mesh:
                log("--mesh is ignored for scenario 7: the population "
                    "dispatch owns the device axis (member replication)")
            run_multiobj_propose_bench()
        elif args.scenario == 8:
            if args.mesh:
                log("--mesh is ignored for scenario 8: the trajectory "
                    "dispatch owns the device axis (cluster sharding)")
            run_forecast_sweep_bench()
        elif args.scenario == 9:
            if args.mesh:
                log("--mesh is ignored for scenario 9: the read tier "
                    "serves published bytes (no device work at all)")
            run_api_throughput_bench()
        elif args.scenario == 10:
            if args.mesh:
                log("--mesh is ignored for scenario 10: the replicated "
                    "read tier is host-side HTTP serving (replica "
                    "processes pin themselves to CPU)")
            run_replica_fanout_bench()
        elif args.scenario == 11:
            if args.mesh:
                log("--mesh is ignored for scenario 11: the schedule "
                    "program batches one cluster's moves (no data "
                    "parallelism to shard)")
            run_executor_schedule_bench()
        elif args.scenario == 12:
            if args.mesh:
                log("--mesh is ignored for scenario 12: the journal is "
                    "host-side bookkeeping (no device work to shard)")
            run_event_journal_overhead_bench()
        elif args.scenario == 13:
            if args.mesh:
                log("--mesh is ignored for scenario 13: budget "
                    "allocation is host-side arithmetic (no device "
                    "work to shard)")
            run_move_budget_bench()
        elif args.scenario == 14:
            if args.mesh:
                log("--mesh is ignored for scenario 14: the regime loop "
                    "drives the sequential single-cluster chain (no "
                    "data parallelism to shard)")
            run_workload_regime_bench(tune_trials=4)
        else:
            run_scale_scenario(args.scenario, mesh_devices=args.mesh,
                               variant=args.variant)
        return
    from cruise_control_tpu.analyzer import (OptimizationOptions, SearchConfig,
                                             TpuGoalOptimizer, goals_by_name)
    from cruise_control_tpu.model.flat import broker_utilization, broker_replica_counts
    from cruise_control_tpu.model.spec import flatten_spec

    log(f"platform: {platform} -> {jax.devices()[0].platform} ({jax.devices()[0]})")
    # Host-side monitor→model stage: dense whole-pool pipeline vs the
    # per-entity reference path, emitted alongside the search metric.
    run_model_build_bench()
    # Observability tax: the span tracer must be ~free on the propose path.
    run_tracer_overhead_bench()
    # Device-runtime rows: zero warm recompiles, transfer bytes per warm
    # cycle, padding waste — and the collector's own <2% overhead A/B.
    run_device_stats_bench()
    # Resident-state rows: metric-only delta cycles must ship >=10x fewer
    # h2d bytes than the full-rebuild upload, compile nothing warm, and
    # touch exactly the churned rows.
    run_resident_delta_bench()
    # Robustness: steps from injected broker crash to restored
    # balancedness through the full heal loop.
    run_chaos_recovery_bench()
    # Crash-safety: restore-to-warm-serve from the snapshot must beat the
    # cold start >= 5x with zero compiles and bit-identical proposals.
    run_snapshot_restore_bench()
    # What-if engine: batched N-1 sweep vs sequential single-scenario
    # evaluation (>= 5x gate).
    run_whatif_n1_bench()
    t0 = time.monotonic()
    spec = build_spec()
    model, md = flatten_spec(spec)
    log(f"build+flatten: {time.monotonic() - t0:.1f}s  "
        f"({NUM_BROKERS} brokers, {NUM_PARTITIONS} partitions, rf={RF})")

    opt = TpuGoalOptimizer(
        goals=goals_by_name(GOALS),
        # fused_chain: 4 goals whose passes each run ~0.1-0.3 s — behind
        # the tunnel, per-goal dispatch overhead is a visible slice of
        # the warm number; the chain converges to 0 residual so fused
        # and per-goal modes produce identical moves.
        config=SearchConfig(num_replica_candidates=512, num_dest_candidates=16,
                            apply_per_iter=512, max_iters_per_goal=512,
                            fused_chain=True),
        mesh=_make_mesh(args.mesh))

    # Audit ON, strict rack-awareness waived: random rf-2 draws over
    # 10-rack brokers collide constantly and the 4 distribution goals
    # can't (and needn't) fix that — the replica/resource-capacity hard
    # goals still gate the row. The greedy baseline ignores racks too,
    # so the comparison stays like-for-like.
    opts = dict(waived_hard_goals=frozenset({"RackAwareGoal"}))
    t0 = time.monotonic()
    res_cold = opt.optimize(model, md, OptimizationOptions(seed=0, **opts))
    cold = time.monotonic() - t0
    t0 = time.monotonic()
    res = opt.optimize(model, md, OptimizationOptions(seed=1, **opts))
    warm = time.monotonic() - t0
    log(f"tpu search: cold {cold:.2f}s warm {warm:.2f}s "
        f"moves={res.num_moves} proposals={len(res.proposals)}")
    for g in res.goal_results:
        log(f"  {g.name:42s} {g.violation_before:12.1f} -> "
            f"{g.violation_after:10.1f} iters={g.iterations} "
            f"({g.duration_s:.2f}s)")
    for g in res.hard_goal_audit:
        log(f"  [audit] {g.name:36s} {g.violation_before:12.1f} -> "
            f"{g.violation_after:10.1f} "
            f"{'ok' if g.satisfied else 'VIOLATED'}")

    g_dur, g_moves, g_util, g_counts = greedy_baseline(model)
    g_res = residual(g_util, g_counts, NUM_BROKERS)
    our_util = np.asarray(broker_utilization(res.final_model))
    our_counts = np.asarray(broker_replica_counts(res.final_model)).astype(float)
    our_res = residual(our_util, our_counts, NUM_BROKERS)
    log(f"greedy baseline: {g_dur:.2f}s moves={g_moves} residual={g_res:.1f}")
    log(f"tpu residual: {our_res:.1f} (must be <= greedy x1.05 + eps)")

    # Quality gate (BASELINE.md: "score <= stock greedy"): a quality-losing
    # run must fail loudly, not report a flattering wall-clock number. EPS
    # absorbs cross-platform float noise only (~0.02% of one broker's
    # balance band).
    EPS = 10.0
    if our_res > g_res * 1.05 + EPS:
        raise RuntimeError(
            f"quality regression: tpu residual {our_res:.1f} > "
            f"greedy {g_res:.1f} x1.05 + {EPS}")

    vs_greedy = round(g_dur / warm, 3) if warm > 0 else None
    emit("rebalance_proposal_wall_clock_100x20k", round(warm, 3), "s",
         vs_greedy, vs_greedy=vs_greedy)


def _is_transport_death(exc: BaseException) -> bool:
    """Only backend/tunnel deaths qualify for the CPU-pinned retry — a
    deterministic failure (quality gate, hard-goal check) must stay a
    loud TPU failure, not quietly become a clean CPU row."""
    msg = str(exc).lower()
    # Transport-specific phrases only: a bare "connection" would also
    # match deterministic failures whose message merely mentions one,
    # routing a real bug into the CPU retry instead of failing loudly.
    return any(tok in msg for tok in (
        "unavailable", "deadline_exceeded",
        "socket closed", "connection reset", "connection refused",
        "connection closed", "connection aborted", "connection timed out",
        "connection error", "failed to connect",
        "device is in an invalid state"))


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:
        # The axon tunnel can die MID-RUN (after the health probe passed):
        # every device op then raises UNAVAILABLE and the bench would exit
        # with no JSON line at all. One retry, pinned to CPU — an honest
        # platform:"cpu" row beats an empty artifact. The guard env stops
        # a loop; a CPU-pinned failure is a real bug and propagates.
        import os
        import sys
        import traceback
        if os.environ.get("CC_BENCH_RETRIED"):
            raise
        if not _is_transport_death(exc):
            raise
        # Derive the platform WITHOUT a device query (jax.devices() on a
        # dead tunnel hangs in backend init). _RESOLVED_PLATFORM is None
        # when the crash predates the probe — retry on CPU then too.
        resolved = _RESOLVED_PLATFORM or ""
        if resolved.startswith("cpu"):
            raise
        traceback.print_exc()
        log("bench failed on the non-CPU backend (tunnel died mid-run?); "
            "re-running pinned to CPU")
        os.execvpe(sys.executable, [sys.executable, *sys.argv],
                   {**os.environ, "JAX_PLATFORMS": "cpu",
                    "CC_BENCH_RETRIED": "1"})
